"""Central timeouts, intervals, and env-var overrides.

Parity with reference utils/constants.py (all knobs kept, names adapted
to the TPU runtime). Every value can be overridden by an environment
variable so deployments can tune without code changes.
"""

from __future__ import annotations

import os


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# --- roles ---------------------------------------------------------------
# Worker processes are launched with this env var set; it suppresses
# master-side startup behavior (auto-launch, signal cleanup).
# Reference: distributed.py:48 (COMFYUI_IS_WORKER).
WORKER_ENV_FLAG = "CDT_IS_WORKER"
MASTER_PID_ENV = "CDT_MASTER_PID"
# Chip pinning for process-per-chip compatibility mode (the TPU analog of
# CUDA_VISIBLE_DEVICES in workers/process/lifecycle.py:33).
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"

# --- heartbeat / liveness ------------------------------------------------
# Reference utils/constants.py:43-47 (COMFYUI_HEARTBEAT_*).
HEARTBEAT_INTERVAL_SECONDS = _env_float("CDT_HEARTBEAT_INTERVAL", 5.0)
HEARTBEAT_TIMEOUT_SECONDS = _env_float("CDT_HEARTBEAT_TIMEOUT", 60.0)
# The collector waits in slices of timeout/20 so interrupts propagate fast.
COLLECTOR_WAIT_SLICES = _env_int("CDT_COLLECTOR_WAIT_SLICES", 20)

# --- payloads ------------------------------------------------------------
# Reference upscale/job_store.py:12 (COMFYUI_MAX_PAYLOAD_SIZE 50MB) and
# utils/constants.py:43 (MAX_BATCH=20 tiles per flush).
MAX_PAYLOAD_SIZE = _env_int("CDT_MAX_PAYLOAD_SIZE", 50 * 1024 * 1024)
PAYLOAD_HEADROOM = 1024 * 1024
MAX_TILE_BATCH = _env_int("CDT_MAX_BATCH", 20)
# Tiles diffused per scan step in the USDU compute core (batch-K UNet/
# VAE programs; MXU utilization knob). 1 = reference numerics
# (bit-identical to the committed goldens); >1 is allclose.
# CDT_TILE_BATCH overrides; unset defaults by platform at first use:
# CPU stays 1 (golden-exact, r1-r5 trendline comparability),
# accelerators get 8 (measured best on v5e — BENCH_NOTES r5 A/B:
# K=8 is +4.0% tiles/s over K=1).
def tile_scan_batch() -> int:
    """Platform-aware CDT_TILE_BATCH resolution. Never triggers backend
    init: the platform is only consulted when jax is already imported
    (the callers are compute paths where it always is); otherwise the
    conservative CPU default applies."""
    explicit = _env_int("CDT_TILE_BATCH", 0)
    if explicit > 0:
        return explicit
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return 1
    try:
        platform = jax.default_backend()
    except Exception:  # noqa: BLE001 - backend not ready
        return 1
    return 1 if platform == "cpu" else 8
MAX_AUDIO_PAYLOAD_BYTES = _env_int("CDT_MAX_AUDIO_PAYLOAD_BYTES", 256 * 1024 * 1024)

# --- orchestration concurrency ------------------------------------------
# Reference api/queue_orchestration.py semaphores (probe=8/prep=4/media=2)
# and utils/constants.py COMFYUI_ORCHESTRATION_* env overrides.
PROBE_CONCURRENCY = _env_int("CDT_ORCHESTRATION_PROBE_CONCURRENCY", 8)
PREP_CONCURRENCY = _env_int("CDT_ORCHESTRATION_PREP_CONCURRENCY", 4)
MEDIA_SYNC_CONCURRENCY = _env_int("CDT_ORCHESTRATION_MEDIA_CONCURRENCY", 2)
MEDIA_SYNC_TIMEOUT_SECONDS = _env_float("CDT_MEDIA_SYNC_TIMEOUT", 120.0)

# --- probes / retries ----------------------------------------------------
PROBE_TIMEOUT_SECONDS = _env_float("CDT_PROBE_TIMEOUT", 5.0)
DISPATCH_TIMEOUT_SECONDS = _env_float("CDT_DISPATCH_TIMEOUT", 30.0)
REQUEST_RETRY_COUNT = _env_int("CDT_REQUEST_RETRIES", 5)
REQUEST_RETRY_BACKOFF = _env_float("CDT_REQUEST_BACKOFF", 0.5)
WORK_PULL_RETRY_COUNT = _env_int("CDT_WORK_PULL_RETRIES", 10)
WORK_PULL_RETRY_CAP_SECONDS = _env_float("CDT_WORK_PULL_RETRY_CAP", 30.0)

# --- circuit breaker (resilience/health.py) -------------------------------
# A worker becomes SUSPECT after this many consecutive transport
# failures, QUARANTINED (circuit open: no dispatch, tiles requeued)
# at the failure threshold, and is probed again (half-open) once the
# cooldown elapses.
CIRCUIT_SUSPECT_THRESHOLD = _env_int("CDT_CIRCUIT_SUSPECT_AFTER", 2)
CIRCUIT_FAILURE_THRESHOLD = _env_int("CDT_CIRCUIT_FAILURES", 5)
CIRCUIT_COOLDOWN_SECONDS = _env_float("CDT_CIRCUIT_COOLDOWN", 30.0)

# --- watchdog (telemetry/watchdog.py) -------------------------------------
# The straggler & stall detector: a worker whose rolling-median tile
# latency exceeds STRAGGLER_FACTOR x the global rolling median (with at
# least MIN_SAMPLES completions in its window) is flagged suspect; a
# job with no completion progress for STALL seconds gets its in-flight
# tail tiles speculatively re-enqueued. CDT_WATCHDOG=0 disables the
# server's background monitor thread entirely.
WATCHDOG_INTERVAL_SECONDS = _env_float("CDT_WATCHDOG_INTERVAL", 2.0)
WATCHDOG_STRAGGLER_FACTOR = _env_float("CDT_WATCHDOG_STRAGGLER_FACTOR", 4.0)
WATCHDOG_MIN_SAMPLES = _env_int("CDT_WATCHDOG_MIN_SAMPLES", 3)
WATCHDOG_STALL_SECONDS = _env_float("CDT_WATCHDOG_STALL_SECONDS", 30.0)
WATCHDOG_LATENCY_WINDOW = _env_int("CDT_WATCHDOG_LATENCY_WINDOW", 64)

# --- scheduler control plane (scheduler/) ---------------------------------
# Admission lanes in strict priority order as "name:depth" pairs; a
# request lands in a lane by its payload's `lane` field (default
# CDT_SCHED_DEFAULT_LANE). A full lane answers HTTP 429 + Retry-After.
SCHED_LANES = os.environ.get(
    "CDT_SCHED_LANES", "interactive:64,batch:256,background:1024"
)
SCHED_DEFAULT_LANE = os.environ.get("CDT_SCHED_DEFAULT_LANE", "interactive")
# Orchestrations allowed to run concurrently; queued requests wait in
# their lane (deficit-round-robin over tenants) for a grant slot.
SCHED_MAX_ACTIVE = _env_int("CDT_SCHED_MAX_ACTIVE", 4)
# DRR quantum in cost units added per tenant visit; a tenant's actual
# replenishment is quantum x its weight (CDT_SCHED_TENANT_WEIGHTS,
# "tenantA=3,tenantB=1"; unlisted tenants weigh 1).
SCHED_QUANTUM = _env_float("CDT_SCHED_QUANTUM", 1.0)
SCHED_TENANT_WEIGHTS = os.environ.get("CDT_SCHED_TENANT_WEIGHTS", "")
# How long the queue route parks a request awaiting its grant before
# answering 429 (the client should back off and retry).
SCHED_GRANT_TIMEOUT_SECONDS = _env_float("CDT_SCHED_GRANT_TIMEOUT", 120.0)
# Cost-aware placement (scheduler/placement.py): per-worker EWMA over
# pull->submit tile latencies; a worker's pull batch scales with its
# relative speed up to MAX_PULL_BATCH (BASE_PULL_BATCH at speed 1.0).
# Inside the last TAIL_TILES of a job, suspect/slow workers are denied
# pulls so the tail lands on fast healthy participants.
SCHED_EWMA_ALPHA = _env_float("CDT_SCHED_EWMA_ALPHA", 0.25)
SCHED_MIN_SAMPLES = _env_int("CDT_SCHED_MIN_SAMPLES", 2)
SCHED_BASE_PULL_BATCH = _env_int("CDT_SCHED_BASE_PULL_BATCH", 2)
SCHED_MAX_PULL_BATCH = _env_int("CDT_SCHED_MAX_PULL_BATCH", 8)
SCHED_TAIL_TILES = _env_int("CDT_SCHED_TAIL_TILES", 2)
# A worker slower than TRIM_RATIO x the fleet's mean speed is trimmed
# from the tail (it may still pull while the queue is deep).
SCHED_TRIM_RATIO = _env_float("CDT_SCHED_TRIM_RATIO", 0.5)

# --- cross-job continuous batching + step-level preemption ----------------
# CDT_XJOB_BATCH=1 routes the elastic master/worker loops through the
# cross-job continuous-batching executor (graph/batch_executor.py) when
# the job's sampler supports step-resumable execution: tiles from
# different jobs/tenants share shape-bucketed device batches and
# premium-lane arrivals preempt running lower-lane work at step
# boundaries. 0 (default) keeps the per-job scan tier exactly.
def xjob_batch_enabled() -> bool:
    return _env_int("CDT_XJOB_BATCH", 0) == 1


# Step-level preemption master-side: 1 (default) lets the scheduler
# coordinator flag running lower-lane jobs for eviction when a
# higher-lane job arrives with outstanding work; executors checkpoint
# and release at the next step boundary. Inert while every job shares
# one lane (legacy single-lane deployments see no behavior change).
PREEMPT_ENABLED = _env_int("CDT_PREEMPT", 1)
# Brownout integration: at what shed level the brownout controller
# also EVICTS running work from shed lanes (not just rejects new
# admissions). 0 = never (default: brownout stays admission-only).
PREEMPT_BROWNOUT_LEVEL = _env_int("CDT_PREEMPT_BROWNOUT_LEVEL", 0)
# Per-job byte budget for retained preemption checkpoints on the
# master (they are volatile and never journaled); beyond it — or on
# any malformed payload — the tile recomputes from step 0, which is
# the bit-identity reference anyway.
PREEMPT_CHECKPOINT_MB = _env_int("CDT_PREEMPT_CHECKPOINT_MB", 64)


# --- device-resident hot path ---------------------------------------------
# All resolved at CALL time (tests monkeypatch the env).


def xjob_device_resident_enabled() -> bool:
    """1 (default) parks evicted batch latents on-device in the
    cross-job executor: the host checkpoint becomes a lazy spill and a
    re-grant whose payload step matches the parked latent skips the
    b64 decode + H2D re-upload entirely. 0 restores decode-from-host
    on every resume (the bit-identity reference path — the parked
    latent IS the array the checkpoint was encoded from, so both
    resume modes are byte-identical by construction)."""
    return _env_int("CDT_XJOB_DEVICE_RESIDENT", 1) == 1


def xjob_device_resident_budget_bytes() -> int:
    """Byte budget for parked device latents (CDT_XJOB_DEVICE_RESIDENT_MB,
    default 256). Past it the stash evicts oldest-first; an evicted
    entry just means that tile resumes from its host spill."""
    return _env_int("CDT_XJOB_DEVICE_RESIDENT_MB", 256) * 1024 * 1024


def device_canvas_enabled() -> bool:
    """CDT_DEVICE_CANVAS=1 routes master-local tiles through the
    on-device canvas (ops/tiles.DeviceCanvas): one composited d2h per
    flush instead of one readback per tile. Only engages when the tile
    result cache is off — cache population needs host tile bytes at
    blend time. 0 (default) keeps the host canvas paths exactly."""
    return _env_int("CDT_DEVICE_CANVAS", 0) == 1


def precision_for_lane(lane: str) -> str:
    """Precision lane for a scheduler lane: CDT_BF16_LANES is a
    comma-separated list of lane names whose jobs carry their latents
    in bfloat16 between steps ("*" = every lane). Precision joins the
    cross-job batch signature, so bf16 and f32 tiles never share a
    device batch. Default: empty (everything f32)."""
    raw = os.environ.get("CDT_BF16_LANES", "")
    lanes = {part.strip() for part in raw.split(",") if part.strip()}
    if "*" in lanes or (lane and lane in lanes):
        return "bf16"
    return "f32"

# --- request lifecycle armor (deadlines / cancel / poison / brownout) -----
# Failed delivery attempts (crash/timeout requeues) a single tile may
# accumulate before it is quarantined out of the pull set as poison —
# a payload that crashes every worker that touches it must not cascade
# quarantines across the fleet forever.
TILE_MAX_ATTEMPTS = _env_int("CDT_TILE_MAX_ATTEMPTS", 3)
# What a job does when tiles were poison-quarantined: "degrade"
# completes the job with the quarantined region blended from the base
# image; "fail" raises a terminal JobPoisoned error instead.
POISON_POLICY = os.environ.get("CDT_POISON_POLICY", "degrade")
# Default end-to-end job deadline in seconds applied when a request
# names none (0 = no default deadline), and the cap clamped onto any
# client-supplied deadline (0 = uncapped).
JOB_DEADLINE_DEFAULT_SECONDS = _env_float("CDT_JOB_DEADLINE_DEFAULT", 0.0)
JOB_DEADLINE_MAX_SECONDS = _env_float("CDT_JOB_DEADLINE_MAX", 0.0)
# Brownout load-shed controller (scheduler/brownout.py): when queue-wait
# p95 or journal-append p95 crosses its threshold, admission sheds one
# more lowest-priority lane (the top lane is never shed); levels step
# at most once per cooldown and step back down once both signals fall
# under half their thresholds.
SHED_WAIT_P95_SECONDS = _env_float("CDT_SHED_WAIT_P95", 20.0)
SHED_JOURNAL_P95_SECONDS = _env_float("CDT_SHED_JOURNAL_P95", 0.25)
SHED_WINDOW_SAMPLES = _env_int("CDT_SHED_WINDOW", 64)
SHED_COOLDOWN_SECONDS = _env_float("CDT_SHED_COOLDOWN", 5.0)

# --- elastic tile pipeline (graph/tile_pipeline.py) -----------------------
# The elastic USDU worker/master data path runs as a staged pipeline:
# pull prefetch -> device sampling -> host readback + PNG encode ->
# submit flush. CDT_PIPELINE=0 restores the serial per-tile loop.
PIPELINE_ENABLED = os.environ.get("CDT_PIPELINE", "1") != "0"
# In-flight device batches the sampler may run ahead of the I/O stage
# (queue bound). 1 keeps at most two batches materialized (one in
# readback, one dispatched) — the bf16 HBM margin from the r5 OOM
# finding; raise only on chips with headroom.
PIPELINE_DEPTH = _env_int("CDT_PIPELINE_DEPTH", 1)
# Pull prefetch: claim the next grant while the device samples the
# current one (bounded to ONE grant ahead so a crash never orphans a
# deep claim). 0 pulls synchronously between batches.
PIPELINE_PREFETCH = os.environ.get("CDT_PIPELINE_PREFETCH", "1") != "0"
# Warm the tile-processor compile during the worker's ready-poll
# window so the first pull doesn't eat the (14-40 s on TPU, r5) first
# compile. With the persistent compilation cache hot this is a cache
# load, not a compile.
WARM_COMPILE = os.environ.get("CDT_WARM_COMPILE", "1") != "0"

# --- persistent XLA compilation cache -------------------------------------
# First compiles dominate a chip session's budget (BENCH_NOTES r5:
# 14-40 s with the flash kernel); the persistent cache makes every
# process after the first skip them. CDT_COMPILE_CACHE_DIR overrides
# the location; "0"/"off" disables. The default lives under the worker
# base dir (cwd) so co-hosted master+workers share one cache.
COMPILE_CACHE_DISABLED_VALUES = ("0", "off", "none")


def compile_cache_dir() -> str | None:
    """Resolved persistent-compilation-cache directory (None = off)."""
    raw = os.environ.get("CDT_COMPILE_CACHE_DIR")
    if raw is not None:
        if raw.strip().lower() in COMPILE_CACHE_DISABLED_VALUES or not raw.strip():
            return None
        return raw
    return os.path.join(os.getcwd(), ".cdt", "compile_cache")


# --- high availability: lease, standby, failover, push grants -------------
# The active master holds an epoch-numbered lease file in the journal
# dir (durability/lease.py); a warm standby promotes itself when the
# lease has been expired this long. The TTL bounds failover time AND
# the zombie window: a fenced ex-master can keep serving at most one
# TTL after losing the lease before its next journal append raises.
LEASE_TTL_SECONDS = _env_float("CDT_LEASE_TTL", 10.0)
# Standby reconnect/lease-poll cadence while following the active
# master's replication stream (api/standby.py).
STANDBY_POLL_SECONDS = _env_float("CDT_STANDBY_POLL", 1.0)
# Per-standby replication buffer (records). Overflow marks the stream
# LOST (never drops interior records — a hole would silently desync the
# replica) and the standby re-syncs from a fresh snapshot frame.
STANDBY_BUFFER_RECORDS = _env_int("CDT_STANDBY_BUFFER", 4096)
# Consecutive transport/5xx failures against one master address before
# the worker client rotates to the next address in its list.
FAILOVER_AFTER_ERRORS = _env_int("CDT_FAILOVER_AFTER", 2)
# Push-mode grants: workers hold the /distributed/events WebSocket and
# wake on pushed grant_available frames instead of pull-polling; 0
# restores the pure pull-poll protocol (the chaos-suite fallback).
PUSH_GRANTS_ENABLED = os.environ.get("CDT_PUSH_GRANTS", "1") != "0"
# How long a push-mode worker parks on the grant signal after an empty
# pull before concluding the queue is drained (one extra wait vs the
# pull protocol's immediate exit).
PUSH_WAIT_SECONDS = _env_float("CDT_PUSH_WAIT", 1.0)

# --- region mode: quorum lease, sharded masters, autoscaler ---------------
# Quorum lease peers (durability/quorum.py): a comma-separated list of
# peer register directories (one per lease-holder node). Non-empty
# switches the master lease from the shared-filesystem flock sidecar
# to majority agreement across these registers — the standby then
# needs no shared filesystem at all. Empty keeps the file lease.
LEASE_PEERS = [
    p.strip() for p in os.environ.get("CDT_LEASE_PEERS", "").split(",")
    if p.strip()
]
# Shard map for region mode (scheduler/router.py): shards separated by
# ';', each shard a comma-separated master address list (active first,
# standbys after), e.g. "http://a:8188,http://a2:8188;http://b:8188".
# Empty = unsharded (single master, the pre-region topology).
SHARDS_SPEC = os.environ.get("CDT_SHARDS", "")
# Virtual nodes per shard on the consistent-hash ring: more vnodes =
# smoother job spread and smaller reshuffle when a shard joins/leaves.
SHARD_VNODES = _env_int("CDT_SHARD_VNODES", 64)
# Per-URL backoff for the worker client's master endpoints: after a
# failure burst an address sits out base*2^k seconds (capped) so a
# dead/lagging shard address can't throttle pulls against healthy
# ones; any response resets its schedule.
ROUTER_BACKOFF_BASE_SECONDS = _env_float("CDT_ROUTER_BACKOFF_BASE", 0.5)
ROUTER_BACKOFF_CAP_SECONDS = _env_float("CDT_ROUTER_BACKOFF_CAP", 30.0)
# Usage-driven autoscaler (scheduler/autoscale.py): 1 starts the
# control loop on masters — SLO burn alerts + measured chip-second
# demand drive launch/drain of managed local workers.
AUTOSCALE_ENABLED = _env_int("CDT_AUTOSCALE", 0) == 1
# Seconds between autoscaler evaluations (each evaluation emits one
# decision record with measured chip-second cost/benefit).
AUTOSCALE_INTERVAL_SECONDS = _env_float("CDT_AUTOSCALE_INTERVAL", 15.0)
# Managed-worker count bounds the controller may scale between.
AUTOSCALE_MIN_WORKERS = _env_int("CDT_AUTOSCALE_MIN", 1)
AUTOSCALE_MAX_WORKERS = _env_int("CDT_AUTOSCALE_MAX", 8)
# Demand/capacity ratio the controller steers toward: above it scale
# up, below half of it (sustained for the hold window) scale down.
AUTOSCALE_TARGET_UTILIZATION = _env_float("CDT_AUTOSCALE_TARGET_UTIL", 0.70)
# Low utilization must persist this long before a scale-down drains a
# worker — scale-up is immediate, scale-down is patient (thrash guard).
AUTOSCALE_DOWN_HOLD_SECONDS = _env_float("CDT_AUTOSCALE_DOWN_HOLD", 120.0)

# --- fleet observability plane (telemetry/fleet.py, telemetry/slo.py) -----
# Master toggle for the fleet plane: 0 disables the monitor thread,
# master-side sampling, and SLO evaluation entirely (the routes then
# answer with enabled=false).
FLEET_ENABLED = os.environ.get("CDT_FLEET", "1") != "0"
# Seconds between master-side sampling passes (fleet sweep + rollup +
# SLO burn-rate evaluation) — also the raw-tier resolution's natural
# cadence.
FLEET_INTERVAL_SECONDS = _env_float("CDT_FLEET_INTERVAL", 10.0)
# Minimum seconds between a worker's piggybacked telemetry snapshots
# (the snapshot rides heartbeat/request_image RPCs it already sends).
FLEET_SNAPSHOT_SECONDS = _env_float("CDT_FLEET_SNAPSHOT_SECONDS", 10.0)
# A worker that stops snapshotting for this long is evicted from the
# fleet view (all its per-worker series drop).
FLEET_TTL_SECONDS = _env_float("CDT_FLEET_TTL", 120.0)
# SLO latency targets: the tile pull->submit p95 objective and the
# journal-append objective the burn-rate alerts evaluate against.
SLO_TILE_P95_SECONDS = _env_float("CDT_SLO_TILE_P95", 5.0)
SLO_JOURNAL_P95_SECONDS = _env_float("CDT_SLO_JOURNAL_P95", 0.25)

# --- usage metering / chip-time attribution (telemetry/usage.py) ----------
# Master toggle for the attribution plane: 0 disables dispatch
# attribution records on both execution tiers and the master-side
# aggregation (the usage route then answers enabled=false).
USAGE_ENABLED = os.environ.get("CDT_USAGE", "1") != "0"
# Closing the loop into admission: 1 multiplies a request's DRR cost by
# the tenant's MEASURED chip-seconds-per-tile ratio (vs the fleet
# mean), so fair share meters what tenants actually burn instead of
# the client's estimated_tiles alone.
USAGE_COST_ENABLED = _env_int("CDT_USAGE_COST", 0) == 1
# Idle usage entries (jobs/tenants with no attribution activity for
# this long) fold into retired aggregates and their retained series
# evict — tenant-id churn must not grow master memory.
USAGE_TTL_SECONDS = _env_float("CDT_USAGE_TTL", 3600.0)

# --- device-time profiling plane (telemetry/profiling.py) -----------------
# Master toggle for the transfer ledger: 0 disables the per-dispatch
# device/host split, transfer byte accounting, and the host-tax rollup
# (the profile route then answers ledger enabled=false).
PROFILING_ENABLED = os.environ.get("CDT_PROFILING", "1") != "0"
# On-demand jax.profiler capture cap: a start request asking for more
# than this many seconds is clamped (an unstopped capture auto-stops).
PROFILE_MAX_SECONDS = _env_float("CDT_PROFILE_MAX_SECONDS", 30.0)
# Capture retention under CDT_PROFILE_DIR: prune-oldest beyond this
# many trace dirs or this many MB (never the newest capture).
PROFILE_MAX_CAPTURES = _env_int("CDT_PROFILE_MAX", 8)
PROFILE_MAX_MB = _env_float("CDT_PROFILE_MAX_MB", 512.0)
# Auto-capture: 1 lets an incident trigger (deadline / alert / poison)
# grab a short device trace alongside the debug bundle; the capture
# lasts CDT_PROFILE_AUTO_SECONDS and rides the incident writer thread.
PROFILE_AUTO_ENABLED = _env_int("CDT_PROFILE_AUTO", 0) == 1
PROFILE_AUTO_SECONDS = _env_float("CDT_PROFILE_AUTO_SECONDS", 2.0)


def profile_dir_from_env() -> str | None:
    """CDT_PROFILE_DIR resolved at call time (tests monkeypatch the
    env); empty/unset disables on-demand profiler capture — the
    incident-dir idiom."""
    raw = os.environ.get("CDT_PROFILE_DIR", "").strip()
    return raw or None


def probe_report_path() -> str | None:
    """Where bench.py persists its last accelerator-probe report (and
    GET /distributed/system_info reads it back). Resolved at call time;
    empty/"0"/"off"/"none" disables the handoff."""
    raw = os.environ.get("CDT_PROBE_REPORT", ".cdt/bench_probe.json").strip()
    if not raw or raw.lower() in CACHE_DIR_DISABLED_VALUES:
        return None
    return raw


# --- content-addressed tile result cache (cache/) -------------------------
# CDT_CACHE=1 consults the master-side tile result cache at grant time
# (hits settle straight into the job — they never ship to a worker) and
# populates it at blend/submit on both execution tiers. 0 (default)
# keeps the cache entirely out of the data path; chaos suites that
# count worker dispatches rely on the default staying off.
def cache_enabled() -> bool:
    return _env_int("CDT_CACHE", 0) == 1


# Host-RAM LRU budget for decoded tile results, in MB. Eviction is
# strict LRU by bytes; an entry larger than the whole budget is never
# RAM-resident (it still lands on disk when the disk tier is on).
CACHE_RAM_MB = _env_float("CDT_CACHE_RAM_MB", 256.0)
# Disk tier byte budget (prune-oldest by mtime past it; 0 = unbounded).
CACHE_DISK_MB = _env_float("CDT_CACHE_DISK_MB", 1024.0)
# Disk tier location; "0"/"off"/"none"/empty disables the disk tier
# (RAM-only cache). Follows the compile-cache dir idiom: resolved at
# call time so tests can monkeypatch the env.
CACHE_DIR_DISABLED_VALUES = ("0", "off", "none")


def cache_dir() -> str | None:
    """Resolved disk-tier directory for the tile cache (None = RAM-only)."""
    raw = os.environ.get("CDT_CACHE_DIR", "").strip()
    if not raw or raw.lower() in CACHE_DIR_DISABLED_VALUES:
        return None
    return raw


def cache_cost_enabled() -> bool:
    """CDT_CACHE_COST=1 discounts a job's DRR admission cost by its
    tenant's measured cache-hit share: tiles the cache index says are
    likely hits never reach a device, so charging full freight for
    them double-bills the tenant (the settle path already refunds the
    admission gap — this closes it at admission time). 0 (default)
    keeps admission cost hit-blind."""
    return _env_int("CDT_CACHE_COST", 0) == 1


def cache_cost_floor() -> float:
    """Lower bound on the cache-hit admission discount multiplier
    (default 0.25): even a tenant whose recent tiles all settled from
    cache pays at least this fraction of full cost, so a cold-cache
    burst can never ride an unbounded discount into the queue."""
    floor = _env_float("CDT_CACHE_COST_FLOOR", 0.25)
    return min(1.0, max(0.0, floor))


# --- adapter plane (adapters/) --------------------------------------------
# All resolved at CALL time (tests monkeypatch the env). The rank
# bucket set itself lives in adapters/segmented.rank_buckets (it
# validates + sorts); these are the cache/cost readers.


def adapter_cache_mb() -> float:
    """Host-RAM byte budget (MB) for decoded adapter operands
    (adapters/cache.AdapterOperandCache); strict LRU past it."""
    return _env_float("CDT_ADAPTER_CACHE_MB", 256.0)


def adapter_cold_cost() -> float:
    """DRR admission cost multiplier charged when a job's adapter
    operands are NOT resident in the operand cache. 1.0 (default)
    disables the seam — admission cost is unchanged."""
    return _env_float("CDT_ADAPTER_COLD_COST", 1.0)


def budget_tenants() -> tuple[str, ...]:
    """Comma-separated tenant list routed to the cheap lane when their
    request names no explicit lane (models/gguf quantized tiers are
    the cheap lane's intended capacity)."""
    raw = os.environ.get("CDT_BUDGET_TENANTS", "")
    return tuple(sorted({t.strip() for t in raw.split(",") if t.strip()}))


def cheap_lane() -> str:
    """Lane name budget tenants route to (default: background)."""
    return os.environ.get("CDT_CHEAP_LANE", "background").strip() or "background"


# --- live event stream (telemetry/events.py) ------------------------------
# Per-subscriber bounded queue size for /distributed/events; a consumer
# slower than the event rate loses its OLDEST events (drop-oldest) and
# is told how many via the subscription's dropped count.
EVENT_QUEUE_SIZE = _env_int("CDT_EVENT_QUEUE_SIZE", 512)

# --- incident plane (telemetry/flight.py, telemetry/incidents.py) ---------
# Always-on flight recorder: a synchronous bus tap keeps the last N
# events and span closes in cheap drop-oldest ring buffers so an
# incident bundle captured AFTER a trigger still holds the evidence
# from BEFORE it. CDT_FLIGHT=0 disables the recorder entirely.
FLIGHT_ENABLED = os.environ.get("CDT_FLIGHT", "1") != "0"
FLIGHT_EVENT_CAPACITY = _env_int("CDT_FLIGHT_EVENTS", 2048)
FLIGHT_SPAN_CAPACITY = _env_int("CDT_FLIGHT_SPANS", 2048)
# Incident debug bundles: captured into CDT_INCIDENT_DIR (unset =
# incident manager disabled, the journal-dir idiom) on alert_fired /
# poison quarantine / deadline expiry / failover / manual POST.
INCIDENT_DEBOUNCE_SECONDS = _env_float("CDT_INCIDENT_DEBOUNCE", 300.0)
# Global floor between captures regardless of trigger key — an alert
# storm across MANY distinct keys still cannot melt the disk.
INCIDENT_MIN_INTERVAL_SECONDS = _env_float("CDT_INCIDENT_MIN_INTERVAL", 10.0)
# Retention: prune-oldest beyond this many bundles or this many MB.
INCIDENT_MAX_BUNDLES = _env_int("CDT_INCIDENT_MAX", 32)
INCIDENT_MAX_MB = _env_float("CDT_INCIDENT_MAX_MB", 64.0)
# Seconds of retained fleet history pulled into a bundle around the
# trigger (the FleetRegistry ?since= window).
INCIDENT_WINDOW_SECONDS = _env_float("CDT_INCIDENT_WINDOW", 600.0)


def incident_dir_from_env() -> str | None:
    """CDT_INCIDENT_DIR resolved at call time (tests monkeypatch the
    env); empty/unset disables the incident manager."""
    raw = os.environ.get("CDT_INCIDENT_DIR", "").strip()
    return raw or None

# --- job init races ------------------------------------------------------
# Grace period a result-submission endpoint waits for the master-side queue
# to be created (reference api/job_routes.py:314-333), and the worker-side
# job-ready poll (reference upscale/modes/static.py:33-47).
JOB_INIT_GRACE_SECONDS = _env_float("CDT_JOB_INIT_GRACE", 10.0)
JOB_READY_POLL_ATTEMPTS = _env_int("CDT_JOB_READY_POLLS", 20)
JOB_READY_POLL_INTERVAL = _env_float("CDT_JOB_READY_POLL_INTERVAL", 1.0)
QUEUE_POLL_INTERVAL_SECONDS = _env_float("CDT_QUEUE_POLL_INTERVAL", 0.1)

# --- worker lifecycle ----------------------------------------------------
AUTO_LAUNCH_DELAY_SECONDS = _env_float("CDT_AUTO_LAUNCH_DELAY", 2.0)
MONITOR_POLL_INTERVAL_SECONDS = _env_float("CDT_MONITOR_POLL_INTERVAL", 2.0)
WORKER_LAUNCH_GRACE_SECONDS = _env_float("CDT_LAUNCH_GRACE", 90.0)
TUNNEL_START_TIMEOUT = _env_float("CDT_TUNNEL_START_TIMEOUT", 30.0)

# --- network -------------------------------------------------------------
DEFAULT_MASTER_PORT = _env_int("CDT_MASTER_PORT", 8188)
FIRST_WORKER_PORT = _env_int("CDT_FIRST_WORKER_PORT", 8189)
CONNECTION_POOL_LIMIT = _env_int("CDT_CONN_POOL_LIMIT", 100)
CONNECTION_POOL_PER_HOST = _env_int("CDT_CONN_POOL_PER_HOST", 30)

# --- debug ---------------------------------------------------------------
DEBUG_FLAG_TTL_SECONDS = 5.0
