"""Error hierarchy for the distributed runtime.

Parity with reference utils/exceptions.py, extended with mesh/compile
errors that only exist in the TPU runtime.
"""

from __future__ import annotations


class DistributedError(Exception):
    """Base class for all framework errors."""


class WorkerError(DistributedError):
    """A worker failed or returned an invalid response."""

    def __init__(self, message: str, worker_id: str | int | None = None):
        super().__init__(message)
        self.worker_id = worker_id


class TransientServerError(WorkerError):
    """The peer answered 5xx: it's alive but momentarily failing —
    worth retrying, unlike a 4xx rejection."""


class WorkerTimeoutError(WorkerError):
    """A worker missed its heartbeat/response deadline."""


class WorkerNotAvailableError(WorkerError):
    """A worker could not be used at dispatch/probe time (unreachable,
    or it answered with a rejection)."""


class WorkerUnreachableError(WorkerNotAvailableError):
    """Transport-level failure: the request may never have arrived.
    Only these count toward the circuit breaker — a worker that
    ANSWERED (even with a rejection) is alive."""


class JobQueueError(DistributedError):
    """Job queue state is missing or inconsistent."""


class JobCancelled(DistributedError):
    """The job reached a terminal cancelled state (client cancel or
    deadline expiry) — pending and in-flight tiles were refunded; the
    master loop unwinds instead of blending a partial canvas."""

    def __init__(self, job_id: str, reason: str = "cancel"):
        super().__init__(f"job {job_id} cancelled ({reason})")
        self.job_id = job_id
        self.reason = reason


class JobPoisoned(DistributedError):
    """CDT_POISON_POLICY=fail and at least one tile exhausted its
    attempt budget: the job terminates instead of completing with a
    degraded (base-image) region."""

    def __init__(self, job_id: str, tiles: list[int]):
        super().__init__(
            f"job {job_id} poisoned: tile(s) {sorted(tiles)} exhausted "
            "their attempt budget"
        )
        self.job_id = job_id
        self.tiles = sorted(int(t) for t in tiles)


class StaleEpoch(DistributedError):
    """An RPC carried a fencing epoch older than the store's current
    one: its authority predates a master takeover (the fencing-token
    pattern). The RPC is rejected BEFORE any mutation or journal
    append — a zombie ex-master (or a worker still holding its grants)
    cannot interleave pre-failover state into the promoted store. The
    rejection carries the current epoch so live workers can refresh
    and re-register."""

    def __init__(self, message: str, current: int = 0):
        super().__init__(message)
        self.current = int(current)


class TileCollectionError(DistributedError):
    """Collecting tile/image results failed irrecoverably."""


class ProcessError(DistributedError):
    """Worker process launch/termination failed."""


class TunnelError(DistributedError):
    """Tunnel management failed."""


class PromptValidationError(DistributedError):
    """A workflow graph failed validation before execution."""

    def __init__(self, message: str, node_errors: dict | None = None):
        super().__init__(message)
        self.node_errors = node_errors or {}


class MeshError(DistributedError):
    """TPU mesh construction or sharding layout failed."""


class CompileError(DistributedError):
    """A jitted computation failed to trace/compile."""
