"""Persistent JSON config system.

Behavior parity with reference utils/config.py: defaults merged
recursively while preserving unknown keys, an mtime-based read cache,
atomic writes (tmp + fsync + os.replace), and an asyncio-locked
transaction helper that only persists when the mutation changed
something. The schema is TPU-native: workers are addressed by TPU chip
sets / mesh slices rather than CUDA devices, and the master carries a
mesh section describing the local pod slice.
"""

from __future__ import annotations

import asyncio
import contextlib
import copy
import json
import os
import threading
from typing import Any, AsyncIterator

from . import logging as dlog
from .constants import HEARTBEAT_TIMEOUT_SECONDS
from .fsio import atomic_write_json

CONFIG_FILENAME = "tpu_config.json"

DEFAULT_CONFIG: dict[str, Any] = {
    "master": {
        "host": "",
        # Which local chips the master's own compute participant uses.
        "tpu_chips": [0],
    },
    "mesh": {
        # Logical axis names for the local slice mesh. "data" is the
        # participant axis used for seed-parallel replication; "model"
        # is used by tensor/FSDP sharded models.
        "axes": {"data": -1, "model": 1},
        # ICI topology override, e.g. [4, 2] for a v5e-8 host; -1 = auto.
        "topology": None,
    },
    "workers": [],
    "settings": {
        "debug": False,
        "auto_launch_workers": False,
        "stop_workers_on_master_exit": True,
        "master_delegate_only": False,
        "websocket_orchestration": True,
        "worker_timeout_seconds": HEARTBEAT_TIMEOUT_SECONDS,
        "probe_concurrency": 8,
        "prep_concurrency": 4,
        "media_sync_concurrency": 2,
    },
    "tunnel": {},
    "managed_processes": {},
}

# Template for entries in config["workers"]. type: "mesh" = a set of
# local chips driven in-process over ICI (the TPU-native fast path);
# "local" = a separate worker process on this host; "remote"/"cloud" =
# HTTP participants on other hosts (DCN tier).
WORKER_TEMPLATE: dict[str, Any] = {
    "id": "",
    "name": "",
    "type": "mesh",
    "host": "",
    "port": 0,
    "tpu_chips": [],
    "enabled": False,
    "extra_args": "",
}


def _package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def get_config_path() -> str:
    override = os.environ.get("CDT_CONFIG_PATH")
    if override:
        return override
    return os.path.join(_package_dir(), CONFIG_FILENAME)


def _merge_defaults(defaults: Any, loaded: Any) -> Any:
    """Recursively overlay `loaded` on `defaults`, keeping unknown keys."""
    if isinstance(defaults, dict) and isinstance(loaded, dict):
        merged = {k: copy.deepcopy(v) for k, v in defaults.items()}
        for key, value in loaded.items():
            if key in merged:
                merged[key] = _merge_defaults(merged[key], value)
            else:
                merged[key] = copy.deepcopy(value)
        return merged
    return copy.deepcopy(loaded)


class _Cache:
    def __init__(self) -> None:
        self.path: str | None = None
        self.mtime: float | None = None
        self.data: dict[str, Any] | None = None
        self.lock = threading.Lock()


_cache = _Cache()
# Transaction mutex: a threading.Lock (acquired via executor so the event
# loop never blocks) rather than an asyncio.Lock — transactions may run on
# different event loops (server loop vs asyncio.run fallbacks on compute
# threads), and an asyncio.Lock binds to whichever loop first awaits it.
_txn_lock = threading.Lock()


def load_config(path: str | None = None) -> dict[str, Any]:
    """Load config with defaults merged in; cached by file mtime."""
    path = path or get_config_path()
    with _cache.lock:
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = None
        if (
            _cache.data is not None
            and _cache.path == path
            and _cache.mtime == mtime
            and mtime is not None
        ):
            return copy.deepcopy(_cache.data)

        loaded: dict[str, Any] = {}
        if mtime is not None:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    loaded = json.load(fh)
            except (OSError, json.JSONDecodeError) as exc:
                dlog.log(f"Config load failed ({exc}); using defaults")
                loaded = {}
        merged = _merge_defaults(DEFAULT_CONFIG, loaded)
        _cache.path = path
        _cache.mtime = mtime
        _cache.data = merged
        return copy.deepcopy(merged)


def save_config(config: dict[str, Any], path: str | None = None) -> None:
    """Atomic write via the shared crash-safe recipe (utils/fsio.py:
    tmp + fsync + os.replace + directory fsync)."""
    path = path or get_config_path()
    atomic_write_json(path, config, indent=2, sort_keys=False)
    with _cache.lock:
        _cache.path = path
        try:
            _cache.mtime = os.path.getmtime(path)
        except OSError:
            _cache.mtime = None
        # Cache the defaults-merged view, not the raw input — cache hits
        # must return the same shape a fresh load would.
        _cache.data = _merge_defaults(DEFAULT_CONFIG, config)


@contextlib.contextmanager
def locked_config(path: str | None = None):
    """Synchronous locked read-modify-write on the SAME mutex as
    config_transaction; persists only if mutated. For sync callers on
    executor threads (e.g. the worker process manager's PID
    persistence) — a private lock there would not exclude the async
    transaction path and load/save interleavings could drop writes.
    """
    with _txn_lock:
        config = load_config(path)
        snapshot = copy.deepcopy(config)
        yield config
        if config != snapshot:
            save_config(config, path)


@contextlib.asynccontextmanager
async def config_transaction(path: str | None = None) -> AsyncIterator[dict[str, Any]]:
    """Locked read-modify-write; persists only if mutated.

    Usage:
        async with config_transaction() as cfg:
            cfg["settings"]["debug"] = True
    """
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, _txn_lock.acquire)
    try:
        config = load_config(path)
        snapshot = copy.deepcopy(config)
        yield config
        if config != snapshot:
            save_config(config, path)
    finally:
        _txn_lock.release()


# --- convenience accessors ----------------------------------------------

def get_setting(name: str, default: Any = None, path: str | None = None) -> Any:
    return load_config(path).get("settings", {}).get(name, default)


def get_worker_timeout_seconds(path: str | None = None) -> float:
    value = get_setting("worker_timeout_seconds", HEARTBEAT_TIMEOUT_SECONDS, path)
    try:
        value = float(value)
    except (TypeError, ValueError):
        return HEARTBEAT_TIMEOUT_SECONDS
    return value if value > 0 else HEARTBEAT_TIMEOUT_SECONDS


def is_master_delegate_only(path: str | None = None) -> bool:
    return bool(get_setting("master_delegate_only", False, path))


def get_enabled_workers(path: str | None = None) -> list[dict[str, Any]]:
    return [w for w in load_config(path).get("workers", []) if w.get("enabled")]


def _read_debug_flag() -> bool:
    return bool(get_setting("debug", False))


# Wire the hot-reloadable debug flag into the logger.
dlog.set_debug_flag_reader(_read_debug_flag)
