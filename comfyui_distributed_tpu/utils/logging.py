"""Logging with a hot-reloadable debug flag.

Parity with reference utils/logging.py: `log` always prints,
`debug_log` only when the config file's debug flag is on; the flag is
re-read from disk with a short TTL cache so toggling debug in the UI
takes effect on running processes without restarts.
"""

from __future__ import annotations

import collections
import sys
import time
from typing import Any, Callable

from .constants import DEBUG_FLAG_TTL_SECONDS

PREFIX = "[Distributed-TPU]"

# In-memory ring exposed by the master-log API endpoint (the reference
# keeps an `app.logger` buffer for the same purpose).
LOG_RING: collections.deque[str] = collections.deque(maxlen=1000)

# Reader failures escalate the effective TTL (exponential, capped) so a
# persistently broken flag source is retried occasionally instead of on
# every TTL tick, and is logged ONCE per breakage instead of silently
# swallowed forever.
_MAX_BACKOFF_MULTIPLIER = 64.0

_debug_cache: dict[str, Any] = {
    "value": False,
    "checked_at": 0.0,
    "backoff": 1.0,        # multiplier on DEBUG_FLAG_TTL_SECONDS
    "error_logged": False,
}
# Injectable so tests and the config module can supply the flag source
# without import cycles (config imports logging).
_debug_flag_reader: Callable[[], bool] | None = None


def set_debug_flag_reader(reader: Callable[[], bool] | None) -> None:
    """Install the function used to read the persistent debug flag."""
    global _debug_flag_reader
    _debug_flag_reader = reader
    _debug_cache["checked_at"] = 0.0
    _debug_cache["backoff"] = 1.0
    _debug_cache["error_logged"] = False


def is_debug_enabled(now: float | None = None) -> bool:
    now = time.monotonic() if now is None else now
    ttl = DEBUG_FLAG_TTL_SECONDS * _debug_cache["backoff"]
    if now - _debug_cache["checked_at"] >= ttl:
        _debug_cache["checked_at"] = now
        if _debug_flag_reader is not None:
            try:
                _debug_cache["value"] = bool(_debug_flag_reader())
                _debug_cache["backoff"] = 1.0
                _debug_cache["error_logged"] = False
            except Exception as exc:  # noqa: BLE001 - flag source broken
                if not _debug_cache["error_logged"]:
                    log(
                        "debug-flag reader failed "
                        f"({type(exc).__name__}: {exc}); keeping last value "
                        "and backing off"
                    )
                    _debug_cache["error_logged"] = True
                _debug_cache["backoff"] = min(
                    _debug_cache["backoff"] * 2.0, _MAX_BACKOFF_MULTIPLIER
                )
    return bool(_debug_cache["value"])


def log(message: str) -> None:
    line = f"{PREFIX} {message}"
    LOG_RING.append(line)
    print(line, file=sys.stdout, flush=True)


def debug_log(message: str) -> None:
    if is_debug_enabled():
        line = f"{PREFIX}[DEBUG] {message}"
        LOG_RING.append(line)
        print(line, file=sys.stdout, flush=True)
