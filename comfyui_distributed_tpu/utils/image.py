"""Image tensor codecs for the HTTP tier.

Arrays are channel-last float [B, H, W, C] in [0, 1] (the framework's
canonical image layout — matches both the reference's torch layout and
TPU-friendly NHWC). Conversion to PIL/PNG happens only at the HTTP
boundary; inside a slice images stay on device. Parity: reference
utils/image.py + the base64 PNG data-URL envelope of
nodes/collector.py:84-119.
"""

from __future__ import annotations

import base64
import io

import numpy as np
from PIL import Image

DATA_URL_PREFIX = "data:image/png;base64,"


def ensure_numpy(tensor) -> np.ndarray:
    """Accept jnp/np/torch-like arrays; return contiguous float32 numpy."""
    if hasattr(tensor, "detach"):  # torch tensor
        tensor = tensor.detach().cpu().numpy()
    arr = np.asarray(tensor, dtype=np.float32)
    return np.ascontiguousarray(arr)


def array_to_pil(image) -> Image.Image:
    """[H, W, C] float in [0,1] → PIL RGB(A) image."""
    arr = ensure_numpy(image)
    if arr.ndim == 4:
        if arr.shape[0] != 1:
            raise ValueError(f"expected single image, got batch {arr.shape}")
        arr = arr[0]
    if arr.ndim == 2:
        arr = arr[..., None]
    from ..native import f32_to_u8

    u8 = f32_to_u8(arr)
    if u8.shape[-1] == 1:
        return Image.fromarray(u8[..., 0], mode="L")
    mode = "RGBA" if u8.shape[-1] == 4 else "RGB"
    return Image.fromarray(u8, mode=mode)


def pil_to_array(img: Image.Image) -> np.ndarray:
    """PIL image → [H, W, C] float32 in [0,1]."""
    from ..native import u8_to_f32

    if img.mode not in ("RGB", "RGBA", "L"):
        img = img.convert("RGB")
    arr = u8_to_f32(np.asarray(img, dtype=np.uint8))
    if arr.ndim == 2:
        arr = arr[..., None]
    return arr


def encode_png(image, compress_level: int = 0) -> bytes:
    """One image → PNG bytes. compress_level=0 trades size for speed on
    the hot collector path, like the reference."""
    buf = io.BytesIO()
    array_to_pil(image).save(buf, format="PNG", compress_level=compress_level)
    return buf.getvalue()


def decode_png(data: bytes) -> np.ndarray:
    with Image.open(io.BytesIO(data)) as img:
        img.load()
        return pil_to_array(img)


def encode_image_data_url(image, compress_level: int = 0) -> str:
    return DATA_URL_PREFIX + base64.b64encode(
        encode_png(image, compress_level)
    ).decode("ascii")


def decode_image_data_url(data_url: str) -> np.ndarray:
    payload = data_url
    if payload.startswith("data:"):
        _, _, payload = payload.partition(",")
    return decode_png(base64.b64decode(payload))


def batch_to_list(batch) -> list[np.ndarray]:
    arr = ensure_numpy(batch)
    if arr.ndim == 3:
        arr = arr[None]
    return [arr[i] for i in range(arr.shape[0])]


def list_to_batch(images: list[np.ndarray]) -> np.ndarray:
    if not images:
        return np.zeros((0, 64, 64, 3), dtype=np.float32)
    return np.stack([ensure_numpy(i) for i in images], axis=0)
