"""Registry of every ``CDT_*`` environment knob the codebase reads.

This is the single source of truth that closes the loop between code,
docs, and lint:

- ``scripts/gen_config_docs.py`` renders it into ``docs/configuration.md``
  (one row per knob: name, default, subsystem, effect);
- cdt-lint checker **CDT005** statically cross-checks that every knob
  read anywhere in the package appears here, that every entry here
  appears in the generated doc, and that no entry is stale (declared
  but never read).

Keep entries alphabetical within their subsystem group; ``default`` is
the *rendered* default (what an operator sees with the env var unset),
as a string, matching the reading site's fallback.
"""

from __future__ import annotations

from typing import NamedTuple


class Knob(NamedTuple):
    name: str
    default: str
    subsystem: str
    effect: str


KNOBS: tuple[Knob, ...] = (
    # --- roles / process identity ---------------------------------------
    Knob("CDT_IS_WORKER", "unset", "roles",
         "Set on spawned worker processes; suppresses master-only startup "
         "(auto-launch, signal cleanup) and flips `python -m` into worker mode."),
    Knob("CDT_MASTER_PID", "unset", "roles",
         "Master PID a worker watches; the worker exits when that process dies."),
    Knob("CDT_HOST", "127.0.0.1", "roles",
         "Bind address for the HTTP server (pass 0.0.0.0 to serve the LAN)."),
    Knob("CDT_CLOUD", "unset", "roles",
         "Forces cloud-worker detection on hosts whose metadata probe is ambiguous."),
    # --- heartbeat / liveness -------------------------------------------
    Knob("CDT_HEARTBEAT_INTERVAL", "5.0", "liveness",
         "Seconds between worker heartbeats to the master job store."),
    Knob("CDT_HEARTBEAT_TIMEOUT", "60.0", "liveness",
         "Seconds without a heartbeat before a worker's tiles are requeued."),
    Knob("CDT_COLLECTOR_WAIT_SLICES", "20", "liveness",
         "The result collector waits in timeout/N slices so interrupts propagate fast."),
    # --- payloads --------------------------------------------------------
    Knob("CDT_MAX_PAYLOAD_SIZE", "52428800", "payloads",
         "Maximum HTTP payload bytes accepted by the API (50 MB default)."),
    Knob("CDT_MAX_BATCH", "20", "payloads",
         "Maximum tiles per submit flush from a worker."),
    Knob("CDT_MAX_AUDIO_PAYLOAD_BYTES", "268435456", "payloads",
         "Maximum decoded audio payload bytes (256 MB default)."),
    Knob("CDT_TILE_BATCH", "platform-aware (CPU 1, accelerators 8)", "payloads",
         "Tiles diffused per scan step in the USDU compute core (MXU batch K); "
         "1 is golden-exact, >1 is allclose."),
    # --- orchestration ---------------------------------------------------
    Knob("CDT_ORCHESTRATION_PROBE_CONCURRENCY", "8", "orchestration",
         "Concurrent worker liveness probes during dispatch."),
    Knob("CDT_ORCHESTRATION_PREP_CONCURRENCY", "4", "orchestration",
         "Concurrent per-worker prompt preparations during dispatch."),
    Knob("CDT_ORCHESTRATION_MEDIA_CONCURRENCY", "2", "orchestration",
         "Concurrent media-sync uploads per dispatch."),
    Knob("CDT_MEDIA_SYNC_TIMEOUT", "120.0", "orchestration",
         "Per-file media sync upload timeout in seconds."),
    Knob("CDT_PROBE_TIMEOUT", "5.0", "orchestration",
         "Worker liveness probe timeout in seconds."),
    Knob("CDT_DISPATCH_TIMEOUT", "30.0", "orchestration",
         "Per-worker prompt dispatch timeout in seconds."),
    Knob("CDT_REQUEST_RETRIES", "5", "orchestration",
         "Retry attempts for idempotent master<->worker HTTP requests."),
    Knob("CDT_REQUEST_BACKOFF", "0.5", "orchestration",
         "Base seconds for exponential retry backoff (with jitter)."),
    Knob("CDT_WORK_PULL_RETRIES", "10", "orchestration",
         "Worker-side retry attempts for tile pull requests."),
    Knob("CDT_WORK_PULL_RETRY_CAP", "30.0", "orchestration",
         "Ceiling in seconds on the pull-retry backoff."),
    # --- resilience ------------------------------------------------------
    Knob("CDT_CIRCUIT_SUSPECT_AFTER", "2", "resilience",
         "Consecutive transport failures before a worker is marked suspect."),
    Knob("CDT_CIRCUIT_FAILURES", "5", "resilience",
         "Failure threshold that opens the circuit (quarantine + tile requeue)."),
    Knob("CDT_CIRCUIT_COOLDOWN", "30.0", "resilience",
         "Seconds a quarantined worker waits before a half-open probe."),
    Knob("CDT_FAULT_PLAN", "unset", "resilience",
         "Seeded fault-injection plan (e.g. `seed=3;latency(0.2)@request_image%0.5`) "
         "wrapping HTTP transport and the job store; unset = no injection."),
    Knob("CDT_DETERMINISTIC_BLEND", "unset", "resilience",
         "`1` forces sorted-order deferred compositing so the blended canvas is "
         "bit-identical regardless of tile arrival order (chaos harness sets it)."),
    # --- request lifecycle (deadlines / cancel / poison / brownout) ------
    Knob("CDT_JOB_DEADLINE_DEFAULT", "0.0", "lifecycle",
         "Default end-to-end job deadline in seconds applied when a request "
         "names none; 0 = no default deadline."),
    Knob("CDT_JOB_DEADLINE_MAX", "0.0", "lifecycle",
         "Cap clamped onto any client-supplied `deadline_s`; 0 = uncapped."),
    Knob("CDT_POISON_POLICY", "degrade", "lifecycle",
         "`degrade` completes a job with poison-quarantined tiles blended "
         "from the base image; `fail` raises a terminal JobPoisoned error."),
    Knob("CDT_SHED_COOLDOWN", "5.0", "lifecycle",
         "Seconds between brownout level steps (hysteresis against flapping)."),
    Knob("CDT_SHED_JOURNAL_P95", "0.25", "lifecycle",
         "Journal-append p95 seconds above which the brownout controller "
         "sheds one more lowest-priority lane."),
    Knob("CDT_SHED_WAIT_P95", "20.0", "lifecycle",
         "Queue-wait p95 seconds above which the brownout controller sheds "
         "one more lowest-priority lane (the premium lane never sheds)."),
    Knob("CDT_SHED_WINDOW", "64", "lifecycle",
         "Rolling sample window for the brownout controller's p95 signals."),
    Knob("CDT_TILE_MAX_ATTEMPTS", "3", "lifecycle",
         "Failed delivery attempts (crash/timeout requeues) a tile may "
         "accumulate before it is quarantined out of the pull set as poison."),
    # --- watchdog --------------------------------------------------------
    Knob("CDT_WATCHDOG", "1", "watchdog",
         "`0` disables the server's background straggler/stall monitor thread."),
    Knob("CDT_WATCHDOG_INTERVAL", "2.0", "watchdog",
         "Seconds between watchdog evaluation steps."),
    Knob("CDT_WATCHDOG_STRAGGLER_FACTOR", "4.0", "watchdog",
         "A worker whose rolling median tile latency exceeds this multiple of the "
         "global median is flagged suspect."),
    Knob("CDT_WATCHDOG_MIN_SAMPLES", "3", "watchdog",
         "Minimum completions in a worker's window before straggler verdicts."),
    Knob("CDT_WATCHDOG_STALL_SECONDS", "30.0", "watchdog",
         "A job quiet this long with tiles in flight triggers speculative re-dispatch."),
    Knob("CDT_WATCHDOG_LATENCY_WINDOW", "64", "watchdog",
         "Rolling latency window length per worker."),
    # --- scheduler -------------------------------------------------------
    Knob("CDT_SCHED_LANES", "interactive:64,batch:256,background:1024", "scheduler",
         "Admission lanes in strict priority order as name:depth pairs; a full "
         "lane answers HTTP 429 + Retry-After."),
    Knob("CDT_SCHED_DEFAULT_LANE", "interactive", "scheduler",
         "Lane used when a queue request names none."),
    Knob("CDT_SCHED_MAX_ACTIVE", "4", "scheduler",
         "Orchestrations allowed to run concurrently; the rest wait in lanes."),
    Knob("CDT_SCHED_QUANTUM", "1.0", "scheduler",
         "Deficit-round-robin quantum (cost units) added per tenant visit."),
    Knob("CDT_SCHED_TENANT_WEIGHTS", "empty", "scheduler",
         "Per-tenant DRR weights as `tenantA=3,tenantB=1`; unlisted tenants weigh 1."),
    Knob("CDT_SCHED_GRANT_TIMEOUT", "120.0", "scheduler",
         "Seconds the queue route parks a request awaiting its grant before 429."),
    Knob("CDT_SCHED_EWMA_ALPHA", "0.25", "scheduler",
         "Smoothing factor for per-worker tile-latency speed EWMAs."),
    Knob("CDT_SCHED_MIN_SAMPLES", "2", "scheduler",
         "Samples required before a worker's speed EWMA influences placement."),
    Knob("CDT_SCHED_BASE_PULL_BATCH", "2", "scheduler",
         "Pull grant size for a speed-1.0 worker."),
    Knob("CDT_SCHED_MAX_PULL_BATCH", "8", "scheduler",
         "Ceiling on speed-scaled pull grant sizes."),
    Knob("CDT_SCHED_TAIL_TILES", "2", "scheduler",
         "Within this many remaining tiles, suspect/slow workers are denied pulls."),
    Knob("CDT_SCHED_TRIM_RATIO", "0.5", "scheduler",
         "Workers slower than this fraction of fleet mean speed are trimmed "
         "from the job tail."),
    # --- cross-job batching + step-level preemption ----------------------
    Knob("CDT_PREEMPT", "1", "scheduler",
         "Step-level preemption: a premium-lane arrival flags running "
         "lower-lane jobs for step-boundary eviction (checkpoint + requeue). "
         "Inert while every job shares one lane; `0` disables entirely."),
    Knob("CDT_PREEMPT_BROWNOUT_LEVEL", "0", "scheduler",
         "Brownout shed level at/above which running work in shed lanes is "
         "also EVICTED (not just refused admission); `0` keeps brownout "
         "admission-only."),
    Knob("CDT_PREEMPT_CHECKPOINT_MB", "64", "scheduler",
         "Per-job byte budget for volatile preemption checkpoints retained "
         "on the master; beyond it evicted tiles recompute from step 0."),
    Knob("CDT_XJOB_BATCH", "0", "scheduler",
         "`1` routes elastic master/worker loops through the cross-job "
         "continuous-batching executor (tiles from different jobs/tenants "
         "share shape-bucketed device batches; step-resumable samplers "
         "only)."),
    Knob("CDT_XJOB_DEVICE_RESIDENT", "1", "scheduler",
         "`1` parks evicted batch latents on-device in the cross-job "
         "executor: the host checkpoint becomes a lazy spill and a "
         "matching re-grant resumes without the b64 decode + h2d "
         "re-upload. `0` decodes every resume from the host checkpoint "
         "(both modes are byte-identical by construction)."),
    Knob("CDT_XJOB_DEVICE_RESIDENT_MB", "256", "scheduler",
         "Byte budget (MB) for parked device latents; past it the stash "
         "evicts oldest-first and the evicted tile resumes from its "
         "host spill."),
    Knob("CDT_BF16_LANES", "empty", "scheduler",
         "Comma-separated scheduler lane names whose jobs carry latents "
         "in bfloat16 between steps (`*` = every lane): halves "
         "checkpoint/transfer bytes; step math stays in the model's "
         "param dtype. Precision joins the batch signature, so bf16 "
         "and f32 tiles never share a device batch."),
    # --- tile pipeline ---------------------------------------------------
    Knob("CDT_PIPELINE", "1", "pipeline",
         "`0` replaces the staged tile pipeline with the serial per-tile loop."),
    Knob("CDT_PIPELINE_DEPTH", "1", "pipeline",
         "In-flight device batches the sampler may run ahead of the I/O stage."),
    Knob("CDT_PIPELINE_PREFETCH", "1", "pipeline",
         "`0` disables claiming the next grant while the device samples the "
         "current one."),
    Knob("CDT_WARM_COMPILE", "1", "pipeline",
         "`0` skips AOT-compiling the steady-state tile bucket during the "
         "worker's ready-poll window."),
    Knob("CDT_COMPILE_CACHE_DIR", "./.cdt/compile_cache", "pipeline",
         "Persistent XLA compilation cache directory; `0`/`off`/`none` disables."),
    # --- durability ------------------------------------------------------
    Knob("CDT_JOURNAL_DIR", "unset", "durability",
         "Directory for the control-plane write-ahead journal + snapshots; "
         "unset disables the durable control plane entirely (master-only)."),
    Knob("CDT_JOURNAL_FSYNC", "1", "durability",
         "Journal fsync policy: 1 syncs every append before acknowledging "
         "(power-cut safe), N>1 syncs every N appends, 0 is write-behind "
         "via a dedicated writer thread (the <5% overhead mode; a SIGKILL "
         "may lose the last in-flight records, which recovery then "
         "recomputes bit-identically)."),
    Knob("CDT_JOURNAL_SEGMENT_BYTES", "4194304", "durability",
         "Journal segment size before fsync'd rotation (4 MiB default)."),
    Knob("CDT_SNAPSHOT_EVERY", "256", "durability",
         "Journal appends between control-plane snapshots; each snapshot "
         "prunes the segments it supersedes."),
    # --- high availability (failover / push grants) ----------------------
    Knob("CDT_FAILOVER_AFTER", "2", "ha",
         "Consecutive transport/5xx failures against one master address before "
         "the worker client rotates to the next address in its list."),
    Knob("CDT_LEASE_TTL", "10.0", "ha",
         "Master lease TTL in seconds (durability/lease.py): the standby "
         "promotes itself once the lease has been expired this long; also "
         "bounds the zombie window before epoch fencing bites."),
    Knob("CDT_PUSH_GRANTS", "1", "ha",
         "`0` disables push-mode grants: workers then pull-poll instead of "
         "waking on pushed grant_available events over /distributed/events."),
    Knob("CDT_PUSH_WAIT", "1.0", "ha",
         "Seconds a push-mode worker parks on the grant signal after an empty "
         "pull before concluding the queue is drained."),
    Knob("CDT_STANDBY_BUFFER", "4096", "ha",
         "Per-standby replication buffer in records; overflow marks the "
         "stream lost and the standby re-syncs from a fresh snapshot frame."),
    Knob("CDT_STANDBY_OF", "unset", "ha",
         "Comma-separated active-master URL list; set (or pass --standby) to "
         "run this master as a warm standby tailing the journal stream."),
    Knob("CDT_STANDBY_POLL", "1.0", "ha",
         "Standby reconnect/lease-poll cadence in seconds."),
    # --- region control plane (quorum lease / shards / autoscaler) -------
    Knob("CDT_AUTOSCALE", "0", "region",
         "`1` starts the usage-driven autoscaler loop on masters "
         "(scheduler/autoscale.py): SLO burn-rate alerts and measured "
         "chip-second demand drive launch/drain of managed local workers, "
         "each decision journaled with its chip-second cost/benefit."),
    Knob("CDT_AUTOSCALE_DOWN_HOLD", "120.0", "region",
         "Seconds utilization must stay below half the target before a "
         "scale-down drains a worker; scale-up is immediate, scale-down "
         "is patient (thrash guard)."),
    Knob("CDT_AUTOSCALE_INTERVAL", "15.0", "region",
         "Seconds between autoscaler evaluations; each evaluation emits "
         "one decision record and settles the previous decision's "
         "measured capacity/demand deltas."),
    Knob("CDT_AUTOSCALE_MAX", "8", "region",
         "Upper bound on managed worker count; pressure at the bound "
         "holds with `reason=pressure at max_workers` instead of "
         "launching."),
    Knob("CDT_AUTOSCALE_MIN", "1", "region",
         "Lower bound on managed worker count; scale-down never drains "
         "below it."),
    Knob("CDT_AUTOSCALE_TARGET_UTIL", "0.70", "region",
         "Demand/capacity chip-second ratio the controller steers "
         "toward: above it scale up, below half of it (sustained for "
         "the hold window) scale down."),
    Knob("CDT_LEASE_PEERS", "empty", "region",
         "Comma-separated lease-peer register directories; non-empty "
         "switches the master lease from the shared-filesystem flock "
         "sidecar to majority agreement across these registers "
         "(durability/quorum.py) — epoch fencing and FencedOut "
         "semantics carry over unchanged."),
    Knob("CDT_ROUTER_BACKOFF_BASE", "0.5", "region",
         "Base of the per-URL exponential backoff window "
         "(base*2^bursts seconds) a master address sits out after a "
         "failure burst trips the rotation threshold."),
    Knob("CDT_ROUTER_BACKOFF_CAP", "30.0", "region",
         "Ceiling in seconds on the per-URL backoff window so a "
         "long-dead address is still re-probed at a bounded cadence."),
    Knob("CDT_SHARDS", "empty", "region",
         "Region shard map: shards separated by `;`, each a "
         "comma-separated master address list (active first, standbys "
         "after). Non-empty enables consistent-hash job routing "
         "(scheduler/router.py); empty keeps the single-master "
         "topology."),
    Knob("CDT_SHARD_VNODES", "64", "region",
         "Virtual nodes per shard on the consistent-hash ring: more "
         "vnodes = smoother job spread and smaller reshuffle when a "
         "shard joins or leaves."),
    # --- telemetry -------------------------------------------------------
    Knob("CDT_METRIC_MAX_SERIES", "128", "telemetry",
         "Per-metric label-series cap; excess series collapse into `_overflow`."),
    Knob("CDT_EVENT_QUEUE_SIZE", "512", "telemetry",
         "Bounded per-subscriber queue for /distributed/events (drop-oldest)."),
    Knob("CDT_TRACE_EXPORT_DIR", "unset", "telemetry",
         "When set, each execution's span tree is exported as JSONL here."),
    Knob("CDT_RUNTIME_DEVICE_STATS", "1", "telemetry",
         "`0` disables the HBM/host-RSS scrape gauges."),
    Knob("CDT_FLEET", "1", "telemetry",
         "`0` disables the fleet observability plane (monitor thread, "
         "master-side sampling, SLO evaluation; routes answer enabled=false)."),
    Knob("CDT_FLEET_INTERVAL", "10.0", "telemetry",
         "Seconds between master-side fleet sampling passes "
         "(sweep + rollup + SLO burn-rate evaluation)."),
    Knob("CDT_FLEET_SNAPSHOT_SECONDS", "10.0", "telemetry",
         "Minimum seconds between a worker's piggybacked telemetry "
         "snapshots on heartbeat/request_image; <=0 disables the piggyback."),
    Knob("CDT_FLEET_TTL", "120.0", "telemetry",
         "Seconds without a snapshot before a worker is evicted from the "
         "fleet view (all its retained series drop)."),
    Knob("CDT_PROBE_REPORT", "./.cdt/bench_probe.json", "telemetry",
         "Path bench.py persists its backend probe report (backend, stage, "
         "library versions) to; `GET /distributed/system_info` serves it "
         "under `probe`. `0`/`off`/`none` disables persistence."),
    Knob("CDT_PROFILE_AUTO", "0", "telemetry",
         "`1` makes every incident bundle capture a short device trace "
         "(requires CDT_PROFILE_DIR; the bundle records the capture ids)."),
    Knob("CDT_PROFILE_AUTO_SECONDS", "2.0", "telemetry",
         "Duration in seconds of the automatic incident-triggered trace."),
    Knob("CDT_PROFILE_DIR", "unset", "telemetry",
         "Directory retained jax.profiler traces are captured into; unset "
         "disables the /distributed/profile capture routes (the "
         "CDT_JOURNAL_DIR idiom). The transfer ledger works without it."),
    Knob("CDT_PROFILE_MAX", "8", "telemetry",
         "Retained trace capture count; oldest captures pruned beyond it."),
    Knob("CDT_PROFILE_MAX_MB", "512.0", "telemetry",
         "Total on-disk trace budget in MB; oldest captures pruned beyond it."),
    Knob("CDT_PROFILE_MAX_SECONDS", "30.0", "telemetry",
         "Ceiling clamped onto any requested capture duration; every "
         "capture auto-stops at this bound even if /profile/stop never "
         "arrives."),
    Knob("CDT_PROFILING", "1", "telemetry",
         "`0` disables the transfer ledger (device/host time split, "
         "host-tax ratio, h2d/d2h byte accounting) on both execution "
         "tiers and its fleet-snapshot piggyback."),
    Knob("CDT_SLO_TILE_P95", "5.0", "telemetry",
         "Tile pull-to-submit latency target the tile_latency SLO "
         "classifies samples against (seconds)."),
    Knob("CDT_SLO_JOURNAL_P95", "0.25", "telemetry",
         "Journal-append latency target the journal_latency SLO "
         "classifies samples against (seconds)."),
    Knob("CDT_USAGE", "1", "telemetry",
         "`0` disables chip-time attribution records on both execution "
         "tiers and the master-side usage aggregation "
         "(GET /distributed/usage answers enabled=false)."),
    Knob("CDT_USAGE_COST", "0", "telemetry",
         "`1` multiplies DRR admission cost by the tenant's measured "
         "chip-seconds-per-tile ratio vs the fleet mean (clamped to "
         "[0.1, 10]), replacing the static estimated_tiles-only cost."),
    Knob("CDT_USAGE_TTL", "3600.0", "telemetry",
         "Seconds of inactivity before a job/tenant usage entry folds "
         "into retired aggregates and its retained series evict."),
    # --- tile result cache -----------------------------------------------
    Knob("CDT_CACHE", "0", "cache",
         "`1` enables the master-side content-addressed tile result "
         "cache: hits settle into the job at grant time (journaled, "
         "never dispatched) and blend from cached pixels."),
    Knob("CDT_CACHE_DIR", "unset", "cache",
         "Directory for the CRC-checked disk tier; unset/`0`/`off`/"
         "`none` keeps the cache RAM-only (the CDT_JOURNAL_DIR idiom)."),
    Knob("CDT_CACHE_DISK_MB", "1024.0", "cache",
         "Disk-tier byte budget in MB (oldest entries pruned beyond it; "
         "0 = unbounded)."),
    Knob("CDT_CACHE_RAM_MB", "256.0", "cache",
         "Host-RAM LRU byte budget in MB; an entry larger than the "
         "whole budget is stored disk-only."),
    Knob("CDT_CACHE_COST", "0", "cache",
         "`1` discounts DRR admission cost by the tenant's measured "
         "cache-hit share (tiles that settle from cache never burn "
         "chip time); bounded below by CDT_CACHE_COST_FLOOR."),
    Knob("CDT_CACHE_COST_FLOOR", "0.25", "cache",
         "Lower bound on the cache-hit admission discount multiplier: "
         "even an all-hits tenant pays this fraction of full cost."),
    # --- adapter plane ---------------------------------------------------
    Knob("CDT_ADAPTER_CACHE_MB", "256.0", "adapters",
         "Host-RAM LRU byte budget in MB for decoded adapter operands "
         "(per-adapter rank-bucketed down/up pairs)."),
    Knob("CDT_ADAPTER_COLD_COST", "1.0", "adapters",
         "DRR admission cost multiplier for requests whose adapter plan "
         "is not resident in the operand cache; 1.0 disables the cold "
         "surcharge."),
    Knob("CDT_ADAPTER_RANK_BUCKETS", "4,8,16,32,64", "adapters",
         "Comma-separated rank-bucket set adapters zero-pad to; one "
         "compiled program exists per (batch signature, bucket), so the "
         "set bounds adapter-induced compile count."),
    Knob("CDT_BUDGET_TENANTS", "empty", "adapters",
         "Comma-separated tenant ids routed to the cheap lane at the "
         "queue route when their request names no explicit lane."),
    Knob("CDT_CHEAP_LANE", "background", "adapters",
         "The lane CDT_BUDGET_TENANTS route to (the lane GGUF-quantized "
         "checkpoints are registered to serve)."),
    # --- incident plane --------------------------------------------------
    Knob("CDT_FLIGHT", "1", "incidents",
         "`0` disables the always-on flight recorder (the bus tap that "
         "retains recent events + span closes for incident bundles)."),
    Knob("CDT_FLIGHT_EVENTS", "2048", "incidents",
         "Flight-recorder event ring capacity (drop-oldest; drops counted "
         "in cdt_flight_dropped_total)."),
    Knob("CDT_FLIGHT_SPANS", "2048", "incidents",
         "Flight-recorder span-close ring capacity (drop-oldest)."),
    Knob("CDT_INCIDENT_DIR", "unset", "incidents",
         "Directory incident debug bundles are captured into; unset "
         "disables the incident manager (the CDT_JOURNAL_DIR idiom)."),
    Knob("CDT_INCIDENT_DEBOUNCE", "300.0", "incidents",
         "Seconds a trigger key (e.g. one SLO's alert) is debounced after "
         "a capture — a re-firing alert inside the window captures nothing."),
    Knob("CDT_INCIDENT_MIN_INTERVAL", "10.0", "incidents",
         "Global floor in seconds between ANY two automatic captures — an "
         "alert storm across many keys still cannot melt the disk."),
    Knob("CDT_INCIDENT_MAX", "32", "incidents",
         "Retained bundle count; the oldest bundles are pruned beyond it."),
    Knob("CDT_INCIDENT_MAX_MB", "64.0", "incidents",
         "Total on-disk bundle budget in MB; oldest pruned beyond it."),
    Knob("CDT_INCIDENT_WINDOW", "600.0", "incidents",
         "Seconds of retained fleet history pulled into a bundle around "
         "the trigger (the /distributed/fleet ?since= window)."),
    # --- jobs ------------------------------------------------------------
    Knob("CDT_JOB_INIT_GRACE", "10.0", "jobs",
         "Seconds result submission waits for the master-side queue to appear."),
    Knob("CDT_JOB_READY_POLLS", "20", "jobs",
         "Worker-side job-ready poll attempts before giving up."),
    Knob("CDT_JOB_READY_POLL_INTERVAL", "1.0", "jobs",
         "Seconds between worker-side job-ready polls."),
    Knob("CDT_QUEUE_POLL_INTERVAL", "0.1", "jobs",
         "Master collection-loop poll interval in seconds."),
    # --- workers ---------------------------------------------------------
    Knob("CDT_AUTO_LAUNCH_DELAY", "2.0", "workers",
         "Delay before auto-launching configured local workers at startup."),
    Knob("CDT_MONITOR_POLL_INTERVAL", "2.0", "workers",
         "Master-liveness poll interval inside worker processes."),
    Knob("CDT_LAUNCH_GRACE", "90.0", "workers",
         "Seconds a launched worker gets to answer probes before being declared dead."),
    Knob("CDT_LOG_DIR", "./logs/workers", "workers",
         "Directory for per-worker stdout/stderr log files."),
    # --- network ---------------------------------------------------------
    Knob("CDT_MASTER_PORT", "8188", "network",
         "Default master HTTP port."),
    Knob("CDT_FIRST_WORKER_PORT", "8189", "network",
         "First port assigned to auto-launched local workers."),
    Knob("CDT_CONN_POOL_LIMIT", "100", "network",
         "aiohttp connection pool total limit."),
    Knob("CDT_CONN_POOL_PER_HOST", "30", "network",
         "aiohttp connection pool per-host limit."),
    Knob("CDT_CONFIG_PATH", "<package>/tpu_config.json", "network",
         "Overrides the JSON config file location."),
    # --- tunnel ----------------------------------------------------------
    Knob("CDT_CLOUDFLARED_PATH", "unset", "tunnel",
         "Path to the cloudflared binary for master tunnels."),
    Knob("CDT_TUNNEL_AUTODOWNLOAD", "unset", "tunnel",
         "`1` permits downloading cloudflared when no binary is found."),
    Knob("CDT_TUNNEL_START_TIMEOUT", "30.0", "tunnel",
         "Seconds to wait for the tunnel URL before giving up."),
    # --- models ----------------------------------------------------------
    Knob("CDT_CHECKPOINT_DIR", "unset", "models",
         "Root directory (or direct file path) for model checkpoints "
         "(`<name>.{safetensors,ckpt,gguf}`)."),
    Knob("CDT_CLIP_VOCAB", "bundled asset dir", "models",
         "Directory holding OpenAI CLIP vocab.json/merges.txt."),
    Knob("CDT_T5_SPM", "unset", "models",
         "Path to a sentencepiece model for real T5 tokenization; unset uses "
         "the committed fallback vocab."),
    Knob("CDT_LORA_DIR", "empty", "models",
         "Root directory for LoRA adapter files."),
    Knob("CDT_PARAMS_DTYPE", "empty", "models",
         "`bfloat16` stores floating-point weights in bf16 (half HBM footprint)."),
    # --- ops -------------------------------------------------------------
    Knob("CDT_FLASH", "unset", "ops",
         "`0` force-disables the Pallas flash-attention kernel."),
    Knob("CDT_FLASH_BQ", "128", "ops",
         "Flash-attention query block size (MXU-aligned)."),
    Knob("CDT_FLASH_BK", "128", "ops",
         "Flash-attention key block size (MXU-aligned)."),
    Knob("CDT_BLEND", "unset", "ops",
         "`segment` selects segment-sum canvas blending for large grids."),
    Knob("CDT_DEVICE_CANVAS", "0", "ops",
         "`1` composites master-local tiles on-device (ops/tiles."
         "DeviceCanvas): one composited d2h per flush instead of a "
         "readback per tile; bit-identical to the deterministic host "
         "canvas. Engages only while the tile cache is off; remote "
         "worker tiles keep the PNG path."),
    # --- parallel --------------------------------------------------------
    Knob("CDT_MESH_SHAPE", "unset", "parallel",
         "Local device mesh axis sizes as `data,model` (e.g. `4,1`, `-1,2`; "
         "-1 infers the remainder). Unset auto-builds a pure data mesh over "
         "all local chips on accelerator platforms; on CPU the mesh is "
         "opt-in via this knob (forced host devices are a test construction)."),
    Knob("CDT_MESH_HBM_GB", "0", "parallel",
         "Per-chip HBM budget in GiB for the auto-tensor-parallel rule: a "
         "checkpoint whose parameters exceed it shards along the model axis "
         "(smallest power-of-two TP that fits) instead of failing to load; "
         "0 disables."),
    Knob("CDT_TP_SIZE", "unset", "parallel",
         "Tensor-parallel (model-axis) mesh size; overrides the model entry "
         "of CDT_MESH_SHAPE. Parameters shard along this axis via "
         "parallel/sharding.shard_params (TP outputs are allclose, not "
         "bit-identical)."),
    Knob("CDT_MULTIHOST", "unset", "parallel",
         "`1` requires multihost initialization to succeed (hard error otherwise)."),
    Knob("CDT_COORDINATOR", "unset", "parallel",
         "host:port of process 0 for multihost JAX initialization."),
    Knob("CDT_NUM_PROCESSES", "unset", "parallel",
         "Total process count for multihost initialization."),
    Knob("CDT_PROCESS_ID", "unset", "parallel",
         "This process's index for multihost initialization."),
    # --- graph I/O -------------------------------------------------------
    Knob("CDT_DATA_DIR", "./data", "graph-io",
         "Root data directory (inputs/outputs default beneath it)."),
    Knob("CDT_INPUT_DIR", "<data>/input", "graph-io",
         "Input image directory."),
    Knob("CDT_OUTPUT_DIR", "<data>/output", "graph-io",
         "Output image directory."),
    Knob("CDT_WORKFLOW_DIR", "empty", "graph-io",
         "Extra directory searched for workflow JSON files."),
    # --- native ----------------------------------------------------------
    Knob("CDT_NATIVE_BUILD_DIR", "<package>/native/build", "native",
         "Build directory for the optional native extension."),
    # --- tools -----------------------------------------------------------
    Knob("CDT_DRYRUN_PLATFORM", "cpu", "tools",
         "JAX platform forced by the graft-entry dry run."),
    Knob("CDT_GOLDEN_ATOL", "0.001", "tools",
         "Absolute tolerance for golden regeneration comparisons."),
)


def knob_names() -> set[str]:
    return {knob.name for knob in KNOBS}


def by_subsystem() -> dict[str, list[Knob]]:
    grouped: dict[str, list[Knob]] = {}
    for knob in KNOBS:
        grouped.setdefault(knob.subsystem, []).append(knob)
    return {sub: sorted(entries) for sub, entries in sorted(grouped.items())}
