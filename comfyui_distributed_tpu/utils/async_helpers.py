"""Sync ↔ async bridging.

The graph executor runs nodes on a plain worker thread (compute must
not block the control-plane event loop, and jitted JAX dispatch is
synchronous), while all distributed state (job queues, HTTP) lives on
one asyncio loop. `run_async_in_server_loop` is the keystone bridging
the two — behavior parity with reference utils/async_helpers.py:13-54
(run_coroutine_threadsafe + bounded wait + cancellation on timeout).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Awaitable, Optional

from .exceptions import DistributedError

_server_loop: Optional[asyncio.AbstractEventLoop] = None
_loop_lock = threading.Lock()


def set_server_loop(loop: Optional[asyncio.AbstractEventLoop]) -> None:
    """Register the control-plane event loop (called by the runtime at boot)."""
    global _server_loop
    with _loop_lock:
        _server_loop = loop


def get_server_loop() -> Optional[asyncio.AbstractEventLoop]:
    with _loop_lock:
        return _server_loop


async def run_blocking(fn, *args) -> Any:
    """Run a blocking callable on the default executor from a coroutine.

    The standard escape hatch for CDT001 (blocking-call-in-async): sync
    file I/O, digests, DNS, etc. move off the serving loop through here
    so route handlers never stall heartbeats and grants.
    """
    return await asyncio.get_running_loop().run_in_executor(None, fn, *args)


def run_async_in_server_loop(
    coroutine: Awaitable[Any], timeout: float | None = None
) -> Any:
    """Run `coroutine` on the registered server loop from a sync thread.

    Falls back to `asyncio.run` when no loop is registered (hermetic
    tests, standalone CLI use). Raises TimeoutError on expiry after
    cancelling the remote task so it doesn't leak.
    """
    loop = get_server_loop()
    if loop is None or not loop.is_running():
        return asyncio.run(_fallback_run(coroutine, timeout))
    if _running_on(loop):
        raise DistributedError(
            "run_async_in_server_loop called from the server loop itself; "
            "this would deadlock — await the coroutine directly instead"
        )
    future = asyncio.run_coroutine_threadsafe(coroutine, loop)
    try:
        return future.result(timeout=timeout)
    except concurrent.futures.TimeoutError:
        future.cancel()
        raise TimeoutError(f"async operation timed out after {timeout}s") from None


async def _with_timeout(coroutine: Awaitable[Any], timeout: float | None) -> Any:
    if timeout is None:
        return await coroutine
    return await asyncio.wait_for(coroutine, timeout)


async def _fallback_run(coroutine: Awaitable[Any], timeout: float | None) -> Any:
    """asyncio.run wrapper for the no-server-loop case: any pooled HTTP
    session created on this transient loop is closed before the loop
    dies, so fallback calls don't leak connectors. Timeouts surface as
    builtin TimeoutError matching the server-loop path's contract."""
    try:
        return await _with_timeout(coroutine, timeout)
    except asyncio.TimeoutError:
        raise TimeoutError(f"async operation timed out after {timeout}s") from None
    finally:
        from .network import close_client_session

        await close_client_session()


def _running_on(loop: asyncio.AbstractEventLoop) -> bool:
    try:
        return asyncio.get_running_loop() is loop
    except RuntimeError:
        return False


class ServerLoopThread:
    """Own an asyncio loop on a daemon thread (the control-plane loop).

    The reference piggybacks on ComfyUI's PromptServer loop; our runtime
    owns its own. `start()` registers the loop globally so
    run_async_in_server_loop works from any compute thread.
    """

    def __init__(self, name: str = "cdt-server-loop"):
        self._name = name
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        if self._loop is None:
            raise DistributedError("server loop not started")
        return self._loop

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, name=self._name, daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)
        set_server_loop(self._loop)

    def _run(self) -> None:
        # Work on a local reference: stop() may null self._loop after a
        # bounded join while this thread is still draining.
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        self._started.set()
        loop.run_forever()
        # Drain pending tasks on shutdown.
        pending = asyncio.all_tasks(loop)
        for task in pending:
            task.cancel()
        if pending:
            loop.run_until_complete(asyncio.gather(*pending, return_exceptions=True))
        loop.close()

    def stop(self) -> None:
        loop, thread = self._loop, self._thread
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(loop.stop)
        if thread is not None:
            thread.join(timeout=10)
            if thread.is_alive():
                # Drain is still running; leave state so a later stop()
                # can retry instead of starting a second loop over it.
                return
        if get_server_loop() is loop:
            set_server_loop(None)
        self._thread = None
        self._loop = None
