"""Foundation utilities (L1). Everything above depends on this layer."""
