"""Audio payload envelope for the HTTP tier.

Waveforms travel as base64 raw float32 bytes with an explicit
shape/dtype/sample_rate envelope and a hard size cap — parity with
reference utils/audio_payload.py:16-103. Canonical audio layout is
[B, C, S] float32 with samples last (concat axis = -1, matching the
collector's audio combine).
"""

from __future__ import annotations

import base64
from typing import Any

import numpy as np

from .constants import MAX_AUDIO_PAYLOAD_BYTES
from .exceptions import DistributedError


def encode_audio_payload(waveform, sample_rate: int) -> dict[str, Any]:
    arr = np.ascontiguousarray(np.asarray(waveform, dtype=np.float32))
    raw = arr.tobytes()
    if len(raw) > MAX_AUDIO_PAYLOAD_BYTES:
        raise DistributedError(
            f"audio payload {len(raw)} bytes exceeds cap {MAX_AUDIO_PAYLOAD_BYTES}"
        )
    return {
        "data": base64.b64encode(raw).decode("ascii"),
        "shape": list(arr.shape),
        "dtype": "float32",
        "sample_rate": int(sample_rate),
    }


def decode_audio_payload(payload: dict[str, Any]) -> tuple[np.ndarray, int]:
    if not isinstance(payload, dict):
        raise DistributedError("audio payload must be a dict")
    for key in ("data", "shape", "dtype", "sample_rate"):
        if key not in payload:
            raise DistributedError(f"audio payload missing '{key}'")
    if payload["dtype"] != "float32":
        raise DistributedError(f"unsupported audio dtype {payload['dtype']!r}")
    raw = base64.b64decode(payload["data"])
    if len(raw) > MAX_AUDIO_PAYLOAD_BYTES:
        raise DistributedError("audio payload exceeds size cap")
    shape = tuple(int(d) for d in payload["shape"])
    expected = int(np.prod(shape)) * 4 if shape else 0
    if expected != len(raw):
        raise DistributedError(
            f"audio payload size mismatch: shape {shape} wants {expected} bytes, got {len(raw)}"
        )
    arr = np.frombuffer(raw, dtype=np.float32).reshape(shape).copy()
    return arr, int(payload["sample_rate"])


def combine_audio(payloads: list[tuple[np.ndarray, int]]) -> tuple[np.ndarray, int]:
    """Concatenate waveforms along the samples axis (dim=-1)."""
    if not payloads:
        raise DistributedError("no audio to combine")
    rates = {rate for _, rate in payloads}
    if len(rates) != 1:
        raise DistributedError(f"mismatched sample rates: {sorted(rates)}")
    arrays = [arr for arr, _ in payloads]
    return np.concatenate(arrays, axis=-1), rates.pop()
