"""Cloudflare quick-tunnel management.

Parity with reference utils/cloudflare/ (tunnel/state/binary/
process_reader): an async-locked start/stop/status manager that spawns
`cloudflared tunnel --url http://127.0.0.1:<port>`, a reader thread
that regexes the public trycloudflare URL from stderr/stdout, state
persisted in config (restored across restarts, stale PIDs cleared),
and the master.host swap to the tunnel URL + restore on stop.

Binary resolution: CDT_CLOUDFLARED_PATH env or config tunnel.binary,
else PATH lookup. Auto-download from GitHub releases (the reference's
behavior) is gated behind CDT_TUNNEL_AUTODOWNLOAD=1 since production
images are often egress-free.
"""

from __future__ import annotations

import asyncio
import os
import re
import shutil
import subprocess
import threading
from typing import Any, Optional

from . import config as config_mod
from .constants import TUNNEL_START_TIMEOUT
from .exceptions import TunnelError
from .logging import debug_log, log

TUNNEL_URL_RE = re.compile(r"https://[a-z0-9-]+\.trycloudflare\.com")
DOWNLOAD_URL = (
    "https://github.com/cloudflare/cloudflared/releases/latest/download/"
    "cloudflared-linux-amd64"
)


def resolve_binary(config: dict[str, Any]) -> Optional[str]:
    candidates = [
        os.environ.get("CDT_CLOUDFLARED_PATH"),
        config.get("tunnel", {}).get("binary"),
        shutil.which("cloudflared"),
    ]
    for path in candidates:
        if path and os.path.isfile(path) and os.access(path, os.X_OK):
            return path
    return None


class TunnelManager:
    def __init__(self, config_path: str | None = None):
        self.config_path = config_path
        self._lock = asyncio.Lock()
        self._proc: Optional[subprocess.Popen] = None
        self._url: Optional[str] = None
        self._url_event = threading.Event()
        self._saved_master_host: Optional[str] = None

    # --- state ------------------------------------------------------------

    def status(self) -> dict[str, Any]:
        running = self._proc is not None and self._proc.poll() is None
        return {
            "running": running,
            "url": self._url if running else None,
            "pid": self._proc.pid if running else None,
        }

    async def restore_from_config(self) -> None:
        """Clear stale persisted tunnel state on boot (a previous
        master's tunnel process does not survive it)."""
        async with config_mod.config_transaction(self.config_path) as cfg:
            state = cfg.get("tunnel", {})
            pid = state.get("pid")
            if pid is not None:
                from ..workers.process_manager import is_process_alive

                if not is_process_alive(int(pid)):
                    state.pop("pid", None)
                    state.pop("url", None)
                    debug_log("cleared stale tunnel state")

    # --- lifecycle ----------------------------------------------------------

    async def start(self, port: int) -> str:
        async with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return self._url or ""
            config = config_mod.load_config(self.config_path)
            binary = resolve_binary(config)
            if binary is None:
                binary = self._maybe_download()
            if binary is None:
                raise TunnelError(
                    "cloudflared binary not found; set CDT_CLOUDFLARED_PATH "
                    "or install cloudflared (auto-download requires "
                    "CDT_TUNNEL_AUTODOWNLOAD=1 and network egress)"
                )
            self._url = None
            self._url_event.clear()
            self._proc = subprocess.Popen(
                [binary, "tunnel", "--url", f"http://127.0.0.1:{port}"],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            threading.Thread(
                target=self._read_output, name="cdt-tunnel-reader", daemon=True
            ).start()

            found = await asyncio.get_running_loop().run_in_executor(
                None, self._url_event.wait, TUNNEL_START_TIMEOUT
            )
            if not found or not self._url:
                await self._terminate()
                raise TunnelError(
                    f"tunnel URL not seen within {TUNNEL_START_TIMEOUT}s"
                )

            async with config_mod.config_transaction(self.config_path) as cfg:
                self._saved_master_host = cfg.get("master", {}).get("host", "")
                cfg.setdefault("tunnel", {}).update(
                    {"url": self._url, "pid": self._proc.pid}
                )
                cfg.setdefault("master", {})["host"] = self._url
            log(f"tunnel up: {self._url}")
            return self._url

    async def stop(self) -> bool:
        async with self._lock:
            stopped = await self._terminate()
            async with config_mod.config_transaction(self.config_path) as cfg:
                cfg.get("tunnel", {}).pop("url", None)
                cfg.get("tunnel", {}).pop("pid", None)
                if self._saved_master_host is not None:
                    cfg.setdefault("master", {})["host"] = self._saved_master_host
            self._saved_master_host = None
            self._url = None
            return stopped

    async def _terminate(self) -> bool:
        if self._proc is None:
            return False
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, self._proc.wait, 10
                )
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._proc = None
        return True

    # --- internals -----------------------------------------------------------

    def _read_output(self) -> None:
        proc = self._proc
        if proc is None or proc.stdout is None:
            return
        for raw in iter(proc.stdout.readline, b""):
            line = raw.decode("utf-8", errors="replace")
            match = TUNNEL_URL_RE.search(line)
            if match and not self._url_event.is_set():
                self._url = match.group(0)
                self._url_event.set()

    def _maybe_download(self) -> Optional[str]:
        if os.environ.get("CDT_TUNNEL_AUTODOWNLOAD") != "1":
            return None
        target = os.path.join(os.path.expanduser("~"), ".cdt", "cloudflared")
        os.makedirs(os.path.dirname(target), exist_ok=True)
        try:
            import urllib.request

            log(f"downloading cloudflared from {DOWNLOAD_URL}")
            urllib.request.urlretrieve(DOWNLOAD_URL, target)  # noqa: S310
            os.chmod(target, 0o755)
            return target
        except Exception as exc:  # noqa: BLE001 - env without egress
            log(f"cloudflared download failed: {exc}")
            return None
