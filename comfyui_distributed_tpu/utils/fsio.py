"""Crash-safe filesystem primitives.

The durable-control-plane invariant (docs/durability.md): a reader
must only ever observe either the OLD complete file or the NEW complete
file — never a truncated or interleaved state — no matter where the
writing process is killed. The recipe is the classic one:

    write tmp (same directory) -> flush -> fsync(tmp) -> os.replace
    -> fsync(directory)

The final directory fsync is the step ad-hoc writers usually skip: on
a power cut the rename itself can be lost without it, silently rolling
the file back to its previous version. All JSON state files in this
repo (config, lint baseline, snapshots, soak reports) go through
``atomic_write_json`` so the recipe lives in exactly one place.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
from typing import Any


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename/create inside it is durable.

    Best effort on platforms whose filesystems don't support opening
    directories (the write itself already succeeded; losing only the
    rename needs a power cut at the wrong instant).
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    """Atomically replace ``path`` with ``data`` (tmp + fsync + rename
    + directory fsync). The tmp file is unlinked on any failure so a
    crashed writer never litters half-written state next to the real
    file."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp_path = tempfile.mkstemp(
        dir=directory, prefix=f".{os.path.basename(path)}.", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            if fsync:
                os.fsync(fh.fileno())
        os.replace(tmp_path, path)
    except Exception:
        with contextlib.suppress(OSError):
            os.unlink(tmp_path)
        raise
    if fsync:
        fsync_dir(directory)


def atomic_write_json(
    path: str,
    data: Any,
    fsync: bool = True,
    indent: int | None = 2,
    sort_keys: bool = False,
) -> None:
    """Atomically replace ``path`` with ``data`` serialized as JSON.

    Serialization happens BEFORE the tmp file is created: a
    non-serializable payload raises without touching the filesystem at
    all (no empty tmp, no clobbered target).
    """
    payload = json.dumps(data, indent=indent, sort_keys=sort_keys) + "\n"
    atomic_write_bytes(path, payload.encode("utf-8"), fsync=fsync)
