"""HTTP transport utilities for the elastic (cross-host / DCN) tier.

Inside a pod slice, participants communicate via ICI collectives (see
parallel/); this module is the control plane and the transport for
remote participants. Behavior parity with reference utils/network.py:
one shared pooled ClientSession, host normalization, scheme-aware
worker/master URL builders (cloud hosts get https), and a `/prompt`
probe whose `queue_remaining` doubles as the busy-ness metric.
"""

from __future__ import annotations

import asyncio
import ipaddress
import threading
from typing import Any

import aiohttp

from .constants import (
    CONNECTION_POOL_LIMIT,
    CONNECTION_POOL_PER_HOST,
    DEFAULT_MASTER_PORT,
    PROBE_TIMEOUT_SECONDS,
)
from .logging import debug_log

def parse_master_urls(raw) -> list[str]:
    """One URL or a comma-separated failover list ('active,standby').
    Shared by the worker client (rotates on consecutive failures,
    CDT_FAILOVER_AFTER) and the standby controller (rotates its
    replication stream) so both sides agree on list semantics."""
    if isinstance(raw, str):
        urls = [u.strip().rstrip("/") for u in raw.split(",")]
    else:
        urls = [str(u).strip().rstrip("/") for u in raw]
    return [u for u in urls if u]


# One pooled session per event loop (the server loop keeps one long-lived
# session; transient asyncio.run loops get their own and must close it
# via close_client_session before the loop dies).
_sessions: dict[asyncio.AbstractEventLoop, aiohttp.ClientSession] = {}
_sessions_lock = threading.Lock()


async def get_client_session() -> aiohttp.ClientSession:
    """Shared pooled session for the current event loop.

    Under an active fault plan (CDT_FAULT_PLAN / an installed
    injector) the session is wrapped so chaos tests can inject
    connection errors, 5xx responses, and latency spikes at the
    transport without touching call sites."""
    loop = asyncio.get_running_loop()
    with _sessions_lock:
        session = _sessions.get(loop)
        if session is None or session.closed:
            connector = aiohttp.TCPConnector(
                limit=CONNECTION_POOL_LIMIT, limit_per_host=CONNECTION_POOL_PER_HOST
            )
            session = aiohttp.ClientSession(connector=connector)
            _sessions[loop] = session
            # Drop map entries for loops that are gone so the dict stays
            # bounded; run_async_in_server_loop's fallback closes transient
            # loops' sessions before their loop exits.
            for stale in [l for l in _sessions if l.is_closed()]:
                _sessions.pop(stale)
    from ..resilience.faults import get_fault_injector

    injector = get_fault_injector()
    if injector is not None:
        return FaultingClientSession(session, injector)
    return session


# --- fault-injecting transport wrapper ------------------------------------

class _InjectedResponse:
    """Minimal stand-in for an aiohttp response (injected http500/drop)."""

    def __init__(self, status: int, url: str):
        self.status = status
        self.url = url

    async def json(self) -> dict:
        return {}

    async def text(self) -> str:
        return f"injected fault response ({self.status}) for {self.url}"

    def release(self) -> None:
        pass


class _FaultingRequestContext:
    """Async context manager around one request; consults the injector
    with op `http:<METHOD>:<path>` before touching the network."""

    def __init__(self, session, injector, method: str, url: str, kwargs: dict):
        self._session = session
        self._injector = injector
        self._method = method
        self._url = url
        self._kwargs = kwargs
        self._ctx = None

    async def __aenter__(self):
        from urllib.parse import urlsplit

        path = urlsplit(str(self._url)).path or "/"
        action = self._injector.hit(f"http:{self._method}:{path}")
        if action is not None:
            if action.kind == "latency":
                await asyncio.sleep(action.arg or 0.0)
            elif action.kind in ("connect_error", "crash"):
                raise aiohttp.ClientConnectionError(
                    f"injected {action.kind} at {path}"
                )
            elif action.kind == "http500":
                return _InjectedResponse(500, str(self._url))
            elif action.kind == "drop":
                # Swallowed server-side: caller sees a generic OK with
                # an empty body; the operation never happens.
                return _InjectedResponse(200, str(self._url))
        self._ctx = getattr(self._session, self._method.lower())(
            self._url, **self._kwargs
        )
        return await self._ctx.__aenter__()

    async def __aexit__(self, *exc_info):
        if self._ctx is not None:
            return await self._ctx.__aexit__(*exc_info)
        return False


class FaultingClientSession:
    """Transparent proxy over the pooled ClientSession; GET/POST go
    through the fault injector, everything else delegates."""

    def __init__(self, session: aiohttp.ClientSession, injector):
        self._session = session
        self._injector = injector

    def get(self, url, **kwargs):
        return _FaultingRequestContext(
            self._session, self._injector, "GET", url, kwargs
        )

    def post(self, url, **kwargs):
        return _FaultingRequestContext(
            self._session, self._injector, "POST", url, kwargs
        )

    def __getattr__(self, name):
        return getattr(self._session, name)


async def close_client_session() -> None:
    """Close the current loop's session (call before a transient loop exits)."""
    loop = asyncio.get_running_loop()
    with _sessions_lock:
        session = _sessions.pop(loop, None)
    if session is not None and not session.closed:
        await session.close()


def handle_api_error(context: str, exc: Exception) -> str:
    message = f"{context}: {type(exc).__name__}: {exc}"
    debug_log(message)
    return message


# --- host / URL handling -------------------------------------------------

def normalize_host(host: str) -> str:
    """Strip scheme/trailing slash; keep bare host[:port] or hostname."""
    host = (host or "").strip()
    for scheme in ("https://", "http://"):
        if host.startswith(scheme):
            host = host[len(scheme):]
    return host.rstrip("/")


def split_host_port(host: str, default_port: int | None = None) -> tuple[str, int | None]:
    host = normalize_host(host)
    if host.startswith("["):  # [ipv6]:port
        bracket_end = host.find("]")
        if bracket_end != -1:
            addr = host[1:bracket_end]
            rest = host[bracket_end + 1:]
            if rest.startswith(":"):
                try:
                    return addr, int(rest[1:])
                except ValueError:
                    return addr, default_port
            return addr, default_port
    if host.count(":") == 1:
        name, _, port_s = host.partition(":")
        try:
            return name, int(port_s)
        except ValueError:
            return name, default_port
    return host, default_port


def is_private_host(host: str) -> bool:
    name, _ = split_host_port(host)
    if name in ("localhost", ""):
        return True
    try:
        return ipaddress.ip_address(name).is_private
    except ValueError:
        return False


_LOOPBACK_HOSTS = {"", "localhost", "127.0.0.1", "::1", "0.0.0.0"}


def is_loopback_host(host: str) -> bool:
    """True only for this-machine addresses (NOT arbitrary private LAN
    IPs — a 192.168.x worker is a different box and must call back to
    the master's real address)."""
    name, _ = split_host_port(host)
    return name in _LOOPBACK_HOSTS


def _fmt_host(name: str) -> str:
    """Re-bracket bare IPv6 addresses for URL assembly."""
    return f"[{name}]" if ":" in name and not name.startswith("[") else name


def _wants_https(host: str, port: int | None, worker_type: str) -> bool:
    if worker_type in ("cloud", "remote_https"):
        return True
    if port == 443:
        return True
    name, _ = split_host_port(host)
    if name.endswith(".trycloudflare.com") or ".proxy.runpod.net" in name:
        return True
    return False


def build_worker_url(worker: dict[str, Any], path: str = "") -> str:
    """URL for reaching a worker described by a config entry.

    https for cloud/tunnel/port-443 hosts, http otherwise
    (reference utils/network.py:88-105).
    """
    host = normalize_host(str(worker.get("host") or "localhost"))
    worker_type = str(worker.get("type", "local"))
    name, embedded_port = split_host_port(host)
    explicit_port = embedded_port or worker.get("port") or 0
    https = _wants_https(host, explicit_port or None, worker_type)
    scheme = "https" if https else "http"
    if https and explicit_port in (443, 0):
        base = f"{scheme}://{_fmt_host(name)}"
    else:
        base = f"{scheme}://{_fmt_host(name)}:{explicit_port or DEFAULT_MASTER_PORT}"
    return f"{base}{path}" if path.startswith("/") or not path else f"{base}/{path}"


def build_master_url(master_host: str, master_port: int, path: str = "") -> str:
    host = normalize_host(master_host) or "127.0.0.1"
    name, embedded_port = split_host_port(host)
    port = embedded_port or master_port
    https = _wants_https(host, port, "remote")
    scheme = "https" if https else "http"
    if https and port in (443, 0):
        base = f"{scheme}://{_fmt_host(name)}"
    else:
        base = f"{scheme}://{_fmt_host(name)}:{port}"
    return f"{base}{path}"


def build_master_callback_url(
    worker: dict[str, Any], master_host: str, master_port: int, path: str = ""
) -> str:
    """URL a worker should use to call back to the master.

    Same-machine workers (type local/mesh, or loopback hosts) always
    call back over loopback regardless of the advertised master host
    (reference utils/network.py:139-201) — the advertised host may be
    a tunnel or external IP unreachable from the same box. Workers on
    other machines (including private LAN IPs) get the real master URL.
    """
    if worker.get("type") in ("local", "mesh") or is_loopback_host(
        str(worker.get("host", ""))
    ):
        return f"http://127.0.0.1:{master_port}{path}"
    return build_master_url(master_host, master_port, path)


# --- probing -------------------------------------------------------------

async def probe_worker(
    url_base: str, timeout: float = PROBE_TIMEOUT_SECONDS
) -> dict[str, Any]:
    """GET {worker}/prompt; returns {"online", "queue_remaining"}.

    `queue_remaining` doubles as the busy-ness metric for least-busy
    selection and busy-probe grace on timeouts.
    """
    session = await get_client_session()
    try:
        async with session.get(
            f"{url_base}/prompt", timeout=aiohttp.ClientTimeout(total=timeout)
        ) as resp:
            if resp.status != 200:
                return {"online": False, "queue_remaining": None}
            data = await resp.json()
            remaining = (
                data.get("exec_info", {}).get("queue_remaining")
                if isinstance(data, dict)
                else None
            )
            if remaining is None:
                return {"online": False, "queue_remaining": None}
            return {"online": True, "queue_remaining": int(remaining)}
    except (aiohttp.ClientError, asyncio.TimeoutError, OSError, ValueError) as exc:
        handle_api_error(f"probe {url_base}", exc)
        return {"online": False, "queue_remaining": None}
