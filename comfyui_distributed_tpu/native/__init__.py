"""Native data-plane bindings: compile-on-first-use C++ with numpy
fallback.

`get_lib()` returns the ctypes module or None (no toolchain); the
public wrappers (`u8_to_f32`, `f32_to_u8`, `feathered_blend_inplace`,
`content_hash`) always work — native when available, numpy otherwise —
and are drop-in equal (tests pin exact equality).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..utils.logging import debug_log

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _source_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "blendlib.cpp")


def _build_dir() -> str:
    return os.environ.get(
        "CDT_NATIVE_BUILD_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "build"),
    )


# -ffp-contract=off: GCC's default contraction may fuse the blend lerp
# (`region*inv + tile*m`) into an FMA, which rounds once instead of
# twice — ulp-different from the numpy fallback and from eager XLA CPU.
# The device-canvas bit-identity gate (DeviceCanvas ≡
# DeterministicHostCanvas) requires all three paths to round alike.
_CXX_FLAGS = ("-O3", "-march=native", "-ffp-contract=off", "-shared", "-fPIC")


def _compile() -> Optional[str]:
    src = _source_path()
    out_dir = _build_dir()
    os.makedirs(out_dir, exist_ok=True)
    # cache key: source + flags digest, so edits OR flag changes rebuild
    with open(src, "rb") as fh:
        hasher = hashlib.sha256(fh.read())
    hasher.update(" ".join(_CXX_FLAGS).encode())
    digest = hasher.hexdigest()[:16]
    so_path = os.path.join(out_dir, f"blendlib_{digest}.so")
    if os.path.isfile(so_path):
        return so_path
    cmd = ["g++", *_CXX_FLAGS, src, "-o", so_path]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return so_path
    except (OSError, subprocess.SubprocessError) as exc:
        debug_log(f"native build failed ({exc}); using numpy fallback")
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        so_path = _compile()
        if so_path is None:
            _lib_failed = True
            return None
        lib = ctypes.CDLL(so_path)
        lib.u8_to_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t
        ]
        lib.f32_to_u8.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t
        ]
        lib.feathered_blend.argtypes = [ctypes.c_void_p] * 3 + [ctypes.c_int64] * 8
        lib.weighted_accumulate.argtypes = (
            [ctypes.c_void_p] * 4 + [ctypes.c_int64] * 8
        )
        lib.fnv1a64.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        lib.fnv1a64.restype = ctypes.c_uint64
        _lib = lib
        return _lib


def u8_to_f32(src: np.ndarray) -> np.ndarray:
    src = np.ascontiguousarray(src, dtype=np.uint8)
    lib = get_lib()
    if lib is None:
        return src.astype(np.float32) / 255.0
    dst = np.empty(src.shape, dtype=np.float32)
    lib.u8_to_f32(src.ctypes.data, dst.ctypes.data, src.size)
    return dst


def f32_to_u8(src: np.ndarray) -> np.ndarray:
    src = np.ascontiguousarray(src, dtype=np.float32)
    lib = get_lib()
    if lib is None:
        return (np.clip(src, 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)
    dst = np.empty(src.shape, dtype=np.uint8)
    lib.f32_to_u8(src.ctypes.data, dst.ctypes.data, src.size)
    return dst


def feathered_blend_inplace(
    canvas: np.ndarray, tile: np.ndarray, mask: np.ndarray, y: int, x: int
) -> None:
    """canvas[:, y:y+th, x:x+tw, :] = lerp(canvas, tile, mask); all
    float32 contiguous, canvas modified in place."""
    assert canvas.flags["C_CONTIGUOUS"] and canvas.dtype == np.float32
    tile = np.ascontiguousarray(tile, dtype=np.float32)
    mask = np.ascontiguousarray(mask, dtype=np.float32)
    b, th, tw, c = tile.shape
    _, ch, cw, _ = canvas.shape
    lib = get_lib()
    if lib is None:
        region = canvas[:, y : y + th, x : x + tw, :]
        m = mask[None, :, :, None]
        region *= 1.0 - m
        region += tile * m
        return
    lib.feathered_blend(
        canvas.ctypes.data, tile.ctypes.data, mask.ctypes.data,
        b, th, tw, c, ch, cw, y, x,
    )


def weighted_accumulate_inplace(
    canvas: np.ndarray, weights: np.ndarray, tile: np.ndarray,
    mask: np.ndarray, y: int, x: int,
) -> None:
    """canvas[:, win] += tile*mask; weights[win] += mask (in place)."""
    assert canvas.flags["C_CONTIGUOUS"] and weights.flags["C_CONTIGUOUS"]
    tile = np.ascontiguousarray(tile, dtype=np.float32)
    mask = np.ascontiguousarray(mask, dtype=np.float32)
    b, th, tw, c = tile.shape
    _, ch, cw, _ = canvas.shape
    lib = get_lib()
    if lib is None:
        m = mask[None, :, :, None]
        canvas[:, y : y + th, x : x + tw, :] += tile * m
        weights[y : y + th, x : x + tw] += mask
        return
    lib.weighted_accumulate(
        canvas.ctypes.data, weights.ctypes.data, tile.ctypes.data,
        mask.ctypes.data, b, th, tw, c, ch, cw, y, x,
    )


def content_hash(data: bytes | np.ndarray) -> int:
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data).tobytes()
    lib = get_lib()
    if lib is None:
        h = 1469598103934665603
        for byte in data:
            h = ((h ^ byte) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
        return h
    buf = (ctypes.c_char * len(data)).from_buffer_copy(data)
    return int(lib.fnv1a64(ctypes.addressof(buf), len(data)))
