// Native host-side data plane for the HTTP (elastic) tier.
//
// The reference has no native code (SURVEY §2: GPU compute delegated to
// torch); this framework's device compute is XLA, but the HTTP tier
// moves every image/tile through host-side u8<->f32 conversion and
// feathered compositing — pure-Python/numpy hot paths worth native
// treatment. Compiled on demand by native/__init__.py (g++ -O3) with a
// numpy fallback when no toolchain is present.
//
// ABI: plain C functions over contiguous row-major buffers.

#include <cstdint>
#include <cstddef>

extern "C" {

// u8 [n] -> f32 [n] scaled to [0, 1]
void u8_to_f32(const uint8_t* src, float* dst, size_t n) {
    // true division, not reciprocal-multiply: bit-exact with numpy's
    // `arr / 255.0` (a 1-ULP difference here would break image-hash
    // dedup between native and fallback hosts)
    for (size_t i = 0; i < n; ++i) {
        dst[i] = static_cast<float>(src[i]) / 255.0f;
    }
}

// f32 [n] in [0, 1] -> u8 [n] with round-half-up and clamping
void f32_to_u8(const float* src, uint8_t* dst, size_t n) {
    for (size_t i = 0; i < n; ++i) {
        float v = src[i];
        v = v < 0.0f ? 0.0f : (v > 1.0f ? 1.0f : v);
        dst[i] = static_cast<uint8_t>(v * 255.0f + 0.5f);
    }
}

// Alpha-composite one padded tile into a padded canvas, in place.
//   canvas: [B, CH, CW, C]   tile: [B, TH, TW, C]   mask: [TH, TW]
//   origin (y, x) in canvas coords; caller guarantees bounds.
void feathered_blend(
    float* canvas, const float* tile, const float* mask,
    int64_t b, int64_t th, int64_t tw, int64_t c,
    int64_t ch, int64_t cw, int64_t y, int64_t x) {
    for (int64_t bi = 0; bi < b; ++bi) {
        float* cbase = canvas + bi * ch * cw * c;
        const float* tbase = tile + bi * th * tw * c;
        for (int64_t row = 0; row < th; ++row) {
            float* crow = cbase + ((y + row) * cw + x) * c;
            const float* trow = tbase + row * tw * c;
            const float* mrow = mask + row * tw;
            for (int64_t col = 0; col < tw; ++col) {
                const float m = mrow[col];
                const float inv = 1.0f - m;
                for (int64_t ci = 0; ci < c; ++ci) {
                    const int64_t idx = col * c + ci;
                    crow[idx] = crow[idx] * inv + trow[idx] * m;
                }
            }
        }
    }
}

// Weighted accumulation variant (order-independent blending):
// canvas += tile * mask; weights += mask. Shapes as above, weights [CH, CW].
void weighted_accumulate(
    float* canvas, float* weights, const float* tile, const float* mask,
    int64_t b, int64_t th, int64_t tw, int64_t c,
    int64_t ch, int64_t cw, int64_t y, int64_t x) {
    for (int64_t bi = 0; bi < b; ++bi) {
        float* cbase = canvas + bi * ch * cw * c;
        const float* tbase = tile + bi * th * tw * c;
        for (int64_t row = 0; row < th; ++row) {
            float* crow = cbase + ((y + row) * cw + x) * c;
            const float* trow = tbase + row * tw * c;
            const float* mrow = mask + row * tw;
            for (int64_t col = 0; col < tw; ++col) {
                const float m = mrow[col];
                for (int64_t ci = 0; ci < c; ++ci) {
                    const int64_t idx = col * c + ci;
                    crow[idx] += trow[idx] * m;
                }
            }
        }
    }
    for (int64_t row = 0; row < th; ++row) {
        float* wrow = weights + (y + row) * cw + x;
        const float* mrow = mask + row * tw;
        for (int64_t col = 0; col < tw; ++col) {
            wrow[col] += mrow[col];
        }
    }
}

// FNV-1a 64-bit content hash (fast change detection for media sync).
uint64_t fnv1a64(const uint8_t* data, size_t n) {
    uint64_t h = 1469598103934665603ull;
    for (size_t i = 0; i < n; ++i) {
        h ^= data[i];
        h *= 1099511628211ull;
    }
    return h;
}

}  // extern "C"
