/* Control panel for the distributed TPU runtime.
 *
 * Standalone build of the reference's sidebar extension (reference
 * web/main.js + workerLifecycle.js + workerSettings.js + apiClient.js):
 * adaptive status polling (1s while anything is busy/launching, 5s
 * idle), worker CRUD against the config API, launch/stop with a
 * launching grace window, log modal with auto-refresh, tunnel
 * controls, and workflow submission to /distributed/queue.
 */

"use strict";

const POLL_ACTIVE_MS = 1000;
const POLL_IDLE_MS = 5000;
const LAUNCH_GRACE_MS = 90000;

const state = {
  config: null,
  workerStatus: new Map(), // id -> {online, queueRemaining, launchingSince}
  pollTimer: null,
  logTimer: null,
  anythingBusy: false,
};

// ---------- API client with retry/backoff ----------

async function api(path, options = {}, retries = 2) {
  for (let attempt = 0; ; attempt++) {
    try {
      const resp = await fetch(path, {
        headers: { "Content-Type": "application/json" },
        ...options,
      });
      const body = await resp.json().catch(() => ({}));
      if (!resp.ok) throw new Error(body.error || `HTTP ${resp.status}`);
      return body;
    } catch (err) {
      if (attempt >= retries) throw err;
      await new Promise((r) => setTimeout(r, 300 * 2 ** attempt));
    }
  }
}

function workerUrl(worker, path) {
  const scheme =
    worker.type === "cloud" || Number(worker.port) === 443 ? "https" : "http";
  const host = worker.host || "127.0.0.1";
  const port = worker.port ? `:${worker.port}` : "";
  return `${scheme}://${host}${port}${path}`;
}

async function probeWorker(worker) {
  try {
    const resp = await fetch(workerUrl(worker, "/prompt"), {
      signal: AbortSignal.timeout(4000),
    });
    if (!resp.ok) return { online: false };
    const body = await resp.json();
    const remaining = body?.exec_info?.queue_remaining;
    if (remaining === undefined) return { online: false };
    return { online: true, queueRemaining: remaining };
  } catch {
    return { online: false };
  }
}

// ---------- status polling ----------

async function refreshStatus() {
  try {
    const master = await api("/prompt");
    setDot("master-dot", master.exec_info.queue_remaining > 0 ? "busy" : "online");
    document.getElementById("master-summary").textContent =
      `queue: ${master.exec_info.queue_remaining}`;
    state.anythingBusy = master.exec_info.queue_remaining > 0;
  } catch {
    setDot("master-dot", "offline");
    document.getElementById("master-summary").textContent = "unreachable";
  }

  const workers = state.config?.workers || [];
  await Promise.all(
    workers.map(async (w) => {
      const prev = state.workerStatus.get(w.id) || {};
      const probe = await probeWorker(w);
      const launching =
        prev.launchingSince && Date.now() - prev.launchingSince < LAUNCH_GRACE_MS;
      if (probe.online && prev.launchingSince) {
        prev.launchingSince = null;
        // tell the server the launch completed so the persisted
        // 'launching' marker can't wedge a later grace window
        api("/distributed/worker/clear_launching", {
          method: "POST",
          body: JSON.stringify({ worker_id: w.id }),
        }).catch(() => {});
      }
      state.workerStatus.set(w.id, { ...prev, ...probe, launching: launching && !probe.online });
      if (probe.online && probe.queueRemaining > 0) state.anythingBusy = true;
    })
  );
  renderWorkers();
  schedulePoll();
}

function schedulePoll() {
  clearTimeout(state.pollTimer);
  state.pollTimer = setTimeout(
    refreshStatus,
    state.anythingBusy ? POLL_ACTIVE_MS : POLL_IDLE_MS
  );
}

function setDot(id, cls) {
  const el = document.getElementById(id);
  el.className = `dot ${cls}`;
}

// ---------- rendering ----------

function renderWorkers() {
  const container = document.getElementById("workers");
  container.innerHTML = "";
  for (const worker of state.config?.workers || []) {
    const status = state.workerStatus.get(worker.id) || {};
    const card = document.createElement("div");
    card.className = "worker-card";
    const dotCls = status.online
      ? status.queueRemaining > 0 ? "busy" : "online"
      : status.launching ? "busy" : "offline";
    const statusText = status.online
      ? `online · queue ${status.queueRemaining}`
      : status.launching ? "launching…" : "offline";
    card.innerHTML = `
      <div>
        <span class="dot ${dotCls}"></span>
        <strong>${escapeHtml(worker.name || worker.id)}</strong>
        <span class="meta">${escapeHtml(worker.type)} · ${escapeHtml(worker.host || "local")}:${worker.port}
          ${worker.tpu_chips?.length ? "· chips " + worker.tpu_chips.join(",") : ""}
          · ${statusText}</span>
      </div>
      <div class="controls">
        <label class="small toggle"><input type="checkbox" data-enable="${worker.id}"
          ${worker.enabled ? "checked" : ""}> on</label>
        ${worker.type === "local"
          ? `<button class="small" data-launch="${worker.id}">launch</button>
             <button class="small" data-stop="${worker.id}">stop</button>`
          : ""}
        <button class="small" data-log="${worker.id}">log</button>
        <button class="small" data-edit="${worker.id}">edit</button>
        <button class="small" data-delete="${worker.id}">✕</button>
      </div>`;
    container.appendChild(card);
  }
}

function escapeHtml(value) {
  return String(value ?? "").replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  })[c]);
}

function renderSettings() {
  const grid = document.createElement("div");
  grid.className = "settings-grid";
  const settings = state.config?.settings || {};
  const editable = [
    ["debug", "checkbox"],
    ["auto_launch_workers", "checkbox"],
    ["stop_workers_on_master_exit", "checkbox"],
    ["master_delegate_only", "checkbox"],
    ["websocket_orchestration", "checkbox"],
    ["worker_timeout_seconds", "number"],
  ];
  for (const [name, kind] of editable) {
    const label = document.createElement("label");
    label.textContent = name.replaceAll("_", " ");
    const input = document.createElement("input");
    input.type = kind;
    if (kind === "checkbox") input.checked = !!settings[name];
    else input.value = settings[name] ?? "";
    input.addEventListener("change", async () => {
      const value = kind === "checkbox" ? input.checked : Number(input.value);
      try {
        await api("/distributed/config/setting", {
          method: "POST",
          body: JSON.stringify({ name, value }),
        });
        state.config.settings[name] = value;
      } catch (err) {
        alert(`save failed: ${err.message}`);
      }
    });
    grid.append(label, input);
  }
  const container = document.getElementById("settings");
  container.innerHTML = "";
  container.appendChild(grid);
}

async function renderTopology() {
  try {
    const info = await api("/distributed/system_info");
    const topo = info.topology || {};
    const container = document.getElementById("topology");
    const chips = (topo.devices || [])
      .map((d) => `<span class="chip">${escapeHtml(d.platform)}:${d.id}</span>`)
      .join("");
    container.innerHTML =
      `platform <b>${escapeHtml(topo.platform)}</b> · ` +
      `${topo.local_device_count}/${topo.device_count} local chips · ` +
      `host ${escapeHtml(info.machine_id)}<br>${chips}`;
  } catch {
    document.getElementById("topology").textContent = "unavailable";
  }
}

// ---------- worker CRUD ----------

function nextWorkerDefaults() {
  const workers = state.config?.workers || [];
  const ports = workers.map((w) => Number(w.port)).filter(Boolean);
  const port = Math.max(8188, ...ports) + 1;
  const usedChips = new Set(workers.flatMap((w) => w.tpu_chips || []));
  const chips = (state.topoChips || []).filter((c) => !usedChips.has(c));
  return { port, chip: chips.length ? [chips[0]] : [] };
}

function workerForm(existing) {
  const worker = existing || {
    id: `w${Date.now() % 100000}`,
    name: "",
    type: "local",
    host: "127.0.0.1",
    ...(() => { const d = nextWorkerDefaults(); return { port: d.port, tpu_chips: d.chip }; })(),
    enabled: true,
    extra_args: "",
  };
  const fields = ["id", "name", "type", "host", "port", "extra_args"];
  const html = fields
    .map(
      (f) => `<div class="row"><label style="width:90px">${f}</label>
        <input type="text" id="wf-${f}" value="${escapeHtml(worker[f] ?? "")}"></div>`
    )
    .join("") +
    `<div class="row"><label style="width:90px">tpu_chips</label>
      <input type="text" id="wf-tpu_chips" value="${(worker.tpu_chips || []).join(",")}"></div>
     <div class="row"><button class="primary" id="wf-save">Save</button></div>`;
  showModal(existing ? `Edit ${worker.id}` : "Add worker", html);
  document.getElementById("wf-save").addEventListener("click", async () => {
    const body = { enabled: worker.enabled };
    for (const f of fields) {
      let value = document.getElementById(`wf-${f}`).value;
      if (f === "port") value = Number(value) || 0;
      body[f] = value;
    }
    body.tpu_chips = document
      .getElementById("wf-tpu_chips")
      .value.split(",").map((s) => Number(s.trim())).filter((n) => !isNaN(n));
    try {
      await api("/distributed/config/worker", {
        method: "POST",
        body: JSON.stringify(body),
      });
      hideModal();
      await loadConfig();
    } catch (err) {
      alert(`save failed: ${err.message}`);
    }
  });
}

// ---------- modal ----------

function showModal(title, html) {
  document.getElementById("modal-title").textContent = title;
  document.getElementById("modal-content").innerHTML = html;
  document.getElementById("modal").classList.remove("hidden");
}

function hideModal() {
  document.getElementById("modal").classList.add("hidden");
  clearInterval(state.logTimer);
}

async function showWorkerLog(workerId) {
  const worker = state.config.workers.find((w) => w.id === workerId);
  const refresh = async () => {
    try {
      const body = await api(
        `/distributed/worker_log/${encodeURIComponent(worker.name || worker.id)}?tail=200`
      );
      document.getElementById("modal-content").innerHTML =
        `<pre class="log">${escapeHtml(body.lines.join("\n"))}</pre>`;
    } catch (err) {
      document.getElementById("modal-content").innerHTML =
        `<pre class="log">no log: ${escapeHtml(err.message)}</pre>`;
    }
  };
  showModal(`Log — ${worker.name || worker.id}`, "<pre class='log'>loading…</pre>");
  await refresh();
  state.logTimer = setInterval(refresh, 2000);
}

// ---------- actions ----------

async function loadConfig() {
  state.config = await api("/distributed/config");
  renderWorkers();
  renderSettings();
}

async function queueWorkflow() {
  const resultEl = document.getElementById("queue-result");
  let prompt;
  try {
    prompt = JSON.parse(document.getElementById("workflow-json").value);
  } catch {
    resultEl.textContent = "invalid JSON";
    return;
  }
  const enabledWorkers = (state.config?.workers || [])
    .filter((w) => w.enabled)
    .map((w) => w.id);
  try {
    const body = await api("/distributed/queue", {
      method: "POST",
      body: JSON.stringify({
        prompt: prompt.prompt || prompt,
        client_id: "panel",
        workers: enabledWorkers,
        load_balance: document.getElementById("load-balance").checked,
      }),
    });
    resultEl.textContent = `queued ${body.prompt_id} → workers [${body.workers}]`;
    state.anythingBusy = true;
    schedulePoll();
  } catch (err) {
    resultEl.textContent = `queue failed: ${err.message}`;
  }
}

async function refreshMasterLog() {
  try {
    const body = await api("/distributed/master_log?tail=100");
    document.getElementById("master-log").textContent = body.lines.join("\n");
  } catch { /* panel works without logs */ }
}

async function loadExamples() {
  try {
    const body = await api("/distributed/workflows");
    const select = document.getElementById("example-select");
    for (const name of body.workflows || []) {
      const opt = document.createElement("option");
      opt.value = name;
      opt.textContent = name;
      select.appendChild(opt);
    }
    select.addEventListener("change", async () => {
      if (!select.value) return;
      const wf = await api(`/distributed/workflows/${encodeURIComponent(select.value)}`);
      document.getElementById("workflow-json").value = JSON.stringify(wf, null, 2);
      renderWorkflowNodes();
    });
  } catch { /* optional */ }
}

// ---------- workflow node widgets ----------
// Parity with the reference's graph-embedded widget UIs
// (web/distributedValue.js, web/image_batch_divider.js): the panel
// reads the pasted workflow, renders per-worker value inputs for every
// DistributedValue node and an output-count control for every batch
// divider, and writes changes back into the workflow JSON.

const VALUE_TYPES = ["STRING", "INT", "FLOAT", "BOOLEAN"];
const MAX_DIVIDER_OUTPUTS = 10;

function currentWorkflow() {
  try {
    const parsed = JSON.parse(document.getElementById("workflow-json").value);
    return parsed.prompt || parsed;
  } catch {
    return null;
  }
}

function patchWorkflowNode(nodeId, patch) {
  const textarea = document.getElementById("workflow-json");
  let parsed;
  try {
    parsed = JSON.parse(textarea.value);
  } catch {
    return;
  }
  const prompt = parsed.prompt || parsed;
  if (!prompt[nodeId]) return;
  prompt[nodeId].inputs = { ...prompt[nodeId].inputs, ...patch };
  textarea.value = JSON.stringify(parsed, null, 2);
}

function enabledWorkers() {
  return (state.config?.workers || []).filter((w) => w.enabled);
}

function renderWorkflowNodes() {
  const container = document.getElementById("workflow-nodes");
  const prompt = currentWorkflow();
  if (!prompt) {
    container.textContent =
      "paste a workflow to configure per-worker values and batch dividers";
    return;
  }
  container.innerHTML = "";
  container.classList.remove("mono");
  let any = false;

  for (const [nodeId, node] of Object.entries(prompt)) {
    if (node.class_type === "DistributedValue") {
      any = true;
      const overrides = node.inputs?.overrides || {};
      const block = document.createElement("div");
      block.className = "node-widget";
      const typeOptions = VALUE_TYPES.map(
        (t) =>
          `<option ${t === (overrides._type || "STRING") ? "selected" : ""}>${t}</option>`
      ).join("");
      const workerRows = enabledWorkers()
        .map(
          (w, idx) => `<div class="row">
            <label style="width:140px">${escapeHtml(w.name || w.id)} (#${idx + 1})</label>
            <input type="text" data-dv-node="${escapeHtml(nodeId)}" data-dv-slot="${idx + 1}"
              value="${escapeHtml(overrides[String(idx + 1)] ?? "")}"
              placeholder="master value"></div>`
        )
        .join("");
      block.innerHTML = `
        <div class="row"><strong>DistributedValue #${escapeHtml(nodeId)}</strong>
          <span class="meta">master value: ${escapeHtml(node.inputs?.value ?? "")}</span>
          <select data-dv-type="${escapeHtml(nodeId)}">${typeOptions}</select></div>
        ${workerRows ||
          '<div class="meta">no enabled workers — values apply per enabled worker</div>'}`;
      container.appendChild(block);
    }
    if (
      node.class_type === "ImageBatchDivider" ||
      node.class_type === "AudioBatchDivider"
    ) {
      any = true;
      const divideBy = Number(node.inputs?.divide_by ?? 2);
      const block = document.createElement("div");
      block.className = "node-widget";
      block.innerHTML = `
        <div class="row"><strong>${escapeHtml(node.class_type)} #${escapeHtml(nodeId)}</strong>
          <label>outputs <input type="number" min="1" max="${MAX_DIVIDER_OUTPUTS}"
            value="${divideBy}" data-divider-node="${escapeHtml(nodeId)}"
            style="width:60px"></label>
          <span class="meta" id="divider-used-${escapeHtml(nodeId)}">
            ${divideBy} of ${MAX_DIVIDER_OUTPUTS} outputs carry data</span></div>`;
      container.appendChild(block);
    }
  }
  if (!any) {
    container.classList.add("mono");
    container.textContent =
      "no DistributedValue / batch-divider nodes in this workflow";
  }
}

function collectDistributedValueOverrides(nodeId) {
  const overrides = {};
  const typeSel = document.querySelector(`select[data-dv-type="${nodeId}"]`);
  overrides._type = typeSel ? typeSel.value : "STRING";
  for (const input of document.querySelectorAll(
    `input[data-dv-node="${nodeId}"]`
  )) {
    if (input.value !== "") overrides[input.dataset.dvSlot] = input.value;
  }
  return overrides;
}

// ---------- master detection (reference web/masterDetection.js) ----------

async function renderNetworkInfo() {
  const container = document.getElementById("network-info");
  try {
    const info = await api("/distributed/network_info");
    const master = state.config?.master || {};
    const autoCount = (state.config?.workers || []).filter(
      (w) => w.auto_populated
    ).length;
    container.innerHTML =
      `recommended master IP: <b>${escapeHtml(info.recommended)}</b> ` +
      `<button class="small" id="use-recommended-ip">use as master host</button>` +
      `<br>current master host: ${escapeHtml(master.host || "(unset)")}` +
      `<br>candidates: ${(info.candidates || []).map(escapeHtml).join(", ")}` +
      (autoCount
        ? `<br>${autoCount} worker(s) auto-populated for spare chips`
        : "");
    const btn = document.getElementById("use-recommended-ip");
    if (btn)
      btn.addEventListener("click", async () => {
        try {
          await api("/distributed/config/master", {
            method: "POST",
            body: JSON.stringify({ host: info.recommended }),
          });
          await loadConfig();
          renderNetworkInfo();
        } catch (err) {
          alert(`save failed: ${err.message}`);
        }
      });
  } catch {
    container.textContent = "network info unavailable";
  }
}

// ---------- wiring ----------

document.addEventListener("click", async (event) => {
  const t = event.target;
  if (t.dataset.launch) {
    const status = state.workerStatus.get(t.dataset.launch) || {};
    status.launchingSince = Date.now();
    state.workerStatus.set(t.dataset.launch, status);
    try {
      await api("/distributed/launch_worker", {
        method: "POST",
        body: JSON.stringify({ worker_id: t.dataset.launch }),
      });
    } catch (err) { alert(`launch failed: ${err.message}`); }
    refreshStatus();
  } else if (t.dataset.stop) {
    await api("/distributed/stop_worker", {
      method: "POST",
      body: JSON.stringify({ worker_id: t.dataset.stop }),
    }).catch((err) => alert(err.message));
    refreshStatus();
  } else if (t.dataset.log) {
    showWorkerLog(t.dataset.log);
  } else if (t.dataset.edit) {
    workerForm(state.config.workers.find((w) => w.id === t.dataset.edit));
  } else if (t.dataset.delete) {
    if (confirm(`Delete worker ${t.dataset.delete}?`)) {
      await api(`/distributed/config/worker/${t.dataset.delete}`, { method: "DELETE" });
      await loadConfig();
    }
  }
});

document.addEventListener("change", async (event) => {
  const t = event.target;
  if (t.dataset.enable) {
    await api("/distributed/config/worker", {
      method: "POST",
      body: JSON.stringify({ id: t.dataset.enable, enabled: t.checked }),
    }).catch((err) => alert(err.message));
    await loadConfig();
    renderWorkflowNodes(); // per-worker widget rows follow enablement
  } else if (t.dataset.dvNode || t.dataset.dvType) {
    const nodeId = t.dataset.dvNode || t.dataset.dvType;
    patchWorkflowNode(nodeId, {
      overrides: collectDistributedValueOverrides(nodeId),
    });
  } else if (t.dataset.dividerNode) {
    const nodeId = t.dataset.dividerNode;
    const parts = Math.max(
      1, Math.min(Number(t.value) || 1, MAX_DIVIDER_OUTPUTS)
    );
    patchWorkflowNode(nodeId, { divide_by: parts });
    const used = document.getElementById(`divider-used-${nodeId}`);
    if (used)
      used.textContent = `${parts} of ${MAX_DIVIDER_OUTPUTS} outputs carry data`;
  }
});

document
  .getElementById("workflow-json")
  .addEventListener("input", () => {
    clearTimeout(state.nodesTimer);
    state.nodesTimer = setTimeout(renderWorkflowNodes, 400);
  });

document.getElementById("add-worker").addEventListener("click", () => workerForm(null));
document.getElementById("modal-close").addEventListener("click", hideModal);
document.getElementById("queue-btn").addEventListener("click", queueWorkflow);
document.getElementById("interrupt-all").addEventListener("click", async () => {
  await api("/interrupt", { method: "POST" }).catch(() => {});
  for (const w of state.config?.workers || []) {
    fetch(workerUrl(w, "/interrupt"), { method: "POST" }).catch(() => {});
  }
});
document.getElementById("clear-memory").addEventListener("click", async () => {
  await api("/distributed/clear_memory", { method: "POST" }).catch(() => {});
  for (const w of state.config?.workers || []) {
    fetch(workerUrl(w, "/distributed/clear_memory"), { method: "POST" }).catch(() => {});
  }
});
document.getElementById("tunnel-toggle").addEventListener("click", async () => {
  const btn = document.getElementById("tunnel-toggle");
  const urlEl = document.getElementById("tunnel-url");
  const status = await api("/distributed/tunnel/status");
  try {
    if (status.running) {
      await api("/distributed/tunnel/stop", { method: "POST" });
      btn.textContent = "Start tunnel";
      urlEl.textContent = "";
    } else {
      btn.textContent = "starting…";
      const body = await api("/distributed/tunnel/start", { method: "POST" });
      btn.textContent = "Stop tunnel";
      urlEl.textContent = body.url;
    }
  } catch (err) {
    btn.textContent = "Start tunnel";
    alert(`tunnel: ${err.message}`);
  }
});

(async function init() {
  await loadConfig().catch(() => {});
  await renderTopology();
  try {
    const info = await api("/distributed/system_info");
    state.topoChips = (info.topology?.devices || []).map((d) => d.id);
  } catch { state.topoChips = []; }
  await loadExamples();
  refreshStatus();
  renderNetworkInfo();
  setInterval(refreshMasterLog, 3000);
  refreshMasterLog();
})();
