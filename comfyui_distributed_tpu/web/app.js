/* Control panel for the distributed TPU runtime — entry module.
 *
 * Standalone build of the reference's sidebar extension (reference
 * web/main.js): adaptive status polling (1s while anything is
 * busy/launching, 5s idle), worker CRUD against the config API,
 * launch/stop with a launching grace window, log modal with
 * auto-refresh, tunnel controls, workflow submission to
 * /distributed/queue, and the tokenizer-fidelity banner.
 *
 * Pure logic lives in modules/ (urlUtils, apiClient, state, widgets,
 * render) — tested by web/tests/ without a browser. This file is only
 * wiring: event listeners, timers, and DOM lookups.
 */

"use strict";

import { api, probeWorker } from "./modules/apiClient.js";
import {
  computeAnythingBusy,
  enabledWorkers,
  pollDelay,
  pruneWorkerStatus,
  reduceWorkerStatus,
  state,
} from "./modules/state.js";
import {
  connectEvents,
  EVENT_TYPES,
  reduceLiveStatus,
} from "./modules/events.js";
import {
  clampDividerParts,
  collectOverrides,
  MAX_DIVIDER_OUTPUTS,
  newWorkerTemplate,
  parseChipList,
  parseWorkflowText,
  patchWorkflowText,
} from "./modules/widgets.js";
import {
  cacheHtml,
  durabilityHtml,
  fleetHtml,
  incidentsHtml,
  networkInfoHtml,
  parsePipelineMetrics,
  pipelineHtml,
  profilingHtml,
  regionHtml,
  renderVocabBanner,
  renderWorkers,
  renderWorkflowNodes,
  schedulerHtml,
  topologyHtml,
  usageHtml,
  WORKER_FORM_FIELDS,
  workerFormHtml,
} from "./modules/render.js";
import { escapeHtml, workerUrl } from "./modules/urlUtils.js";

// ---------- status polling ----------

async function refreshStatus() {
  let masterQueue = 0;
  try {
    const master = await api("/prompt");
    masterQueue = master.exec_info.queue_remaining;
    setDot("master-dot", masterQueue > 0 ? "busy" : "online");
    document.getElementById("master-summary").textContent =
      `queue: ${masterQueue}`;
  } catch {
    setDot("master-dot", "offline");
    document.getElementById("master-summary").textContent = "unreachable";
  }

  const workers = state.config?.workers || [];
  pruneWorkerStatus(state.workerStatus, workers);
  await Promise.all(
    workers.map(async (w) => {
      const prev = state.workerStatus.get(w.id);
      const probe = await probeWorker(w);
      const { status, clearLaunching } = reduceWorkerStatus(
        prev, probe, Date.now()
      );
      if (clearLaunching) {
        // tell the server the launch completed so the persisted
        // 'launching' marker can't wedge a later grace window
        api("/distributed/worker/clear_launching", {
          method: "POST",
          body: JSON.stringify({ worker_id: w.id }),
        }).catch(() => {});
      }
      state.workerStatus.set(w.id, status);
    })
  );
  state.anythingBusy = computeAnythingBusy(
    masterQueue, state.workerStatus.values()
  );
  renderWorkers(
    document.getElementById("workers"), state.config, state.workerStatus
  );
  refreshScheduler();
  refreshPipeline();
  refreshDurability();
  refreshRegion();
  refreshFleet();
  refreshUsage();
  refreshCache();
  refreshIncidents();
  refreshProfiling();
  schedulePoll();
}

// ---------- scheduler lane view ----------

async function refreshScheduler() {
  const container = document.getElementById("scheduler-lanes");
  try {
    container.innerHTML = schedulerHtml(
      await api("/distributed/scheduler/status")
    );
  } catch {
    container.textContent = "scheduler unreachable";
  }
}

// ---------- tile pipeline stage view ----------

async function refreshPipeline() {
  const container = document.getElementById("tile-pipeline");
  try {
    // the metrics route serves Prometheus text, not JSON — fetch raw
    const resp = await fetch("/distributed/metrics");
    if (!resp.ok) throw new Error(`HTTP ${resp.status}`);
    container.innerHTML = pipelineHtml(parsePipelineMetrics(await resp.text()));
  } catch {
    container.textContent = "pipeline metrics unreachable";
  }
}

// ---------- durable control plane card ----------

async function refreshDurability() {
  const container = document.getElementById("durability");
  try {
    container.innerHTML = durabilityHtml(await api("/distributed/durability"));
  } catch {
    container.textContent = "durability status unreachable";
  }
}

// ---------- region control-plane card ----------

async function refreshRegion() {
  const container = document.getElementById("region");
  try {
    const [region, autoscale] = await Promise.all([
      api("/distributed/region"),
      api("/distributed/autoscale").catch(() => null),
    ]);
    container.innerHTML = regionHtml(region, autoscale);
  } catch {
    container.textContent = "region status unreachable";
  }
}

// ---------- fleet observability card ----------

async function refreshFleet() {
  const container = document.getElementById("fleet");
  try {
    const [fleet, alerts] = await Promise.all([
      api("/distributed/fleet"),
      api("/distributed/alerts").catch(() => null),
    ]);
    container.innerHTML = fleetHtml(fleet, alerts);
  } catch {
    container.textContent = "fleet status unreachable";
  }
}

// ---------- usage / chip-time attribution card ----------

async function refreshUsage() {
  const container = document.getElementById("usage");
  try {
    container.innerHTML = usageHtml(await api("/distributed/usage"));
  } catch {
    container.textContent = "usage status unreachable";
  }
}

// ---------- tile result cache card ----------

async function refreshCache() {
  const container = document.getElementById("cache");
  try {
    container.innerHTML = cacheHtml(await api("/distributed/cache"));
  } catch {
    container.textContent = "cache status unreachable";
  }
}

// ---------- profiling card ----------

async function refreshProfiling() {
  const container = document.getElementById("profiling");
  try {
    container.innerHTML = profilingHtml(await api("/distributed/profile"));
  } catch {
    container.textContent = "profiling status unreachable";
  }
}

async function profileAction(path) {
  try {
    await api(path, { method: "POST" });
  } catch (err) {
    alert(`profiler: ${err.message}`);
  }
  refreshProfiling();
}

// ---------- incidents card ----------

async function refreshIncidents() {
  const container = document.getElementById("incidents");
  try {
    container.innerHTML = incidentsHtml(await api("/distributed/incidents"));
  } catch {
    container.textContent = "incident status unreachable";
  }
}

async function schedulerAction(path) {
  try {
    await api(path, { method: "POST" });
  } catch (err) {
    alert(`scheduler: ${err.message}`);
  }
  refreshScheduler();
}

function schedulePoll() {
  clearTimeout(state.pollTimer);
  state.pollTimer = setTimeout(
    refreshStatus,
    pollDelay(state.anythingBusy, state.eventsConnected)
  );
}

function setDot(id, cls) {
  const el = document.getElementById(id);
  el.className = `dot ${cls}`;
}

// ---------- live event stream (replaces the fast poll while open) ----------

function renderLiveEvents() {
  const { connected, events } = state.liveStatus;
  setDot("events-dot", connected ? "online" : "offline");
  document.getElementById("events-summary").textContent = connected
    ? "streaming"
    : "polling fallback";
  const container = document.getElementById("live-events");
  if (!events.length) {
    container.textContent = "waiting for events…";
    return;
  }
  container.innerHTML = events
    .map((e) => `<div>${escapeHtml(e.label)}</div>`)
    .join("");
}

function startEventStream() {
  const proto = location.protocol === "https:" ? "wss" : "ws";
  const types = EVENT_TYPES.join(",");
  connectEvents({
    url: `${proto}://${location.host}/distributed/events?types=${types}`,
    onEvent: (event) => {
      state.liveStatus = reduceLiveStatus(state.liveStatus, event);
      renderLiveEvents();
      if (event.type === "health_transition") {
        // a breaker just moved; reflect it in the worker list now
        // instead of waiting for the idle poll tick
        refreshStatus();
      } else if (
        event.type === "fleet_rollup" ||
        event.type === "alert_fired" ||
        event.type === "alert_resolved"
      ) {
        // the fleet card is stream-fed: each pushed rollup / alert
        // transition refreshes it without waiting for the slow poll
        refreshFleet();
      } else if (event.type === "usage_rollup") {
        // the attribution card is stream-fed: render the pushed rollup
        // directly (no extra fetch — the event IS the payload)
        const container = document.getElementById("usage");
        if (container) container.innerHTML = usageHtml(event.data);
      } else if (event.type === "cache_stats") {
        // the cache card is stream-fed: the pushed stats snapshot IS
        // the GET /distributed/cache payload minus the enabled flag
        const container = document.getElementById("cache");
        if (container) container.innerHTML = cacheHtml(event.data);
      } else if (event.type === "incident_captured") {
        // a bundle just landed; show it without waiting for the poll
        refreshIncidents();
      }
    },
    onStatus: (connected) => {
      state.eventsConnected = connected;
      state.liveStatus = { ...state.liveStatus, connected };
      renderLiveEvents();
      schedulePoll(); // cadence follows the stream state
    },
  });
}

// ---------- settings / topology ----------

async function saveSetting(name, value) {
  await api("/distributed/config/setting", {
    method: "POST",
    body: JSON.stringify({ name, value }),
  });
  if (state.config?.settings) state.config.settings[name] = value;
}

/** Header toggle mirrors the inverse of delegate-only mode (reference
 * web/main.js master-participation toggle). */
function syncMasterToggle() {
  document.getElementById("master-participates").checked =
    !state.config?.settings?.master_delegate_only;
}

function renderSettings() {
  const grid = document.createElement("div");
  grid.className = "settings-grid";
  const settings = state.config?.settings || {};
  const editable = [
    ["debug", "checkbox"],
    ["auto_launch_workers", "checkbox"],
    ["stop_workers_on_master_exit", "checkbox"],
    ["master_delegate_only", "checkbox"],
    ["websocket_orchestration", "checkbox"],
    ["worker_timeout_seconds", "number"],
  ];
  for (const [name, kind] of editable) {
    const label = document.createElement("label");
    label.textContent = name.replaceAll("_", " ");
    const input = document.createElement("input");
    input.type = kind;
    if (kind === "checkbox") input.checked = !!settings[name];
    else input.value = settings[name] ?? "";
    input.addEventListener("change", async () => {
      const value = kind === "checkbox" ? input.checked : Number(input.value);
      try {
        await saveSetting(name, value);
        if (name === "master_delegate_only") syncMasterToggle();
      } catch (err) {
        alert(`save failed: ${err.message}`);
      }
    });
    grid.append(label, input);
  }
  const container = document.getElementById("settings");
  container.innerHTML = "";
  container.appendChild(grid);
}

async function renderTopology() {
  try {
    const info = await api("/distributed/system_info");
    state.topoChips = (info.topology?.devices || []).map((d) => d.id);
    document.getElementById("topology").innerHTML = topologyHtml(info);
    renderVocabBanner(
      document.getElementById("vocab-banner"),
      info,
      state.vocabBannerDismissed,
      () => {
        state.vocabBannerDismissed = true;
        renderVocabBanner(
          document.getElementById("vocab-banner"), info, true, () => {}
        );
      }
    );
  } catch {
    document.getElementById("topology").textContent = "unavailable";
  }
}

// ---------- worker CRUD ----------

function workerForm(existing) {
  const worker = existing || newWorkerTemplate(
    state.config?.workers, state.topoChips, Date.now() % 100000
  );
  showModal(
    existing ? `Edit ${worker.id}` : "Add worker", workerFormHtml(worker)
  );
  document.getElementById("wf-save").addEventListener("click", async () => {
    const body = { enabled: worker.enabled };
    for (const f of WORKER_FORM_FIELDS) {
      let value = document.getElementById(`wf-${f}`).value;
      if (f === "port") value = Number(value) || 0;
      body[f] = value;
    }
    body.tpu_chips = parseChipList(
      document.getElementById("wf-tpu_chips").value
    );
    try {
      await api("/distributed/config/worker", {
        method: "POST",
        body: JSON.stringify(body),
      });
      hideModal();
      await loadConfig();
    } catch (err) {
      alert(`save failed: ${err.message}`);
    }
  });
}

// ---------- modal ----------

function showModal(title, html) {
  document.getElementById("modal-title").textContent = title;
  document.getElementById("modal-content").innerHTML = html;
  document.getElementById("modal").classList.remove("hidden");
}

function hideModal() {
  document.getElementById("modal").classList.add("hidden");
  clearInterval(state.logTimer);
}

async function showWorkerLog(workerId) {
  const worker = state.config.workers.find((w) => w.id === workerId);
  const refresh = async () => {
    try {
      const body = await api(
        `/distributed/worker_log/${encodeURIComponent(worker.name || worker.id)}?tail=200`
      );
      document.getElementById("modal-content").innerHTML =
        `<pre class="log">${escapeHtml(body.lines.join("\n"))}</pre>`;
    } catch (err) {
      document.getElementById("modal-content").innerHTML =
        `<pre class="log">no log: ${escapeHtml(err.message)}</pre>`;
    }
  };
  showModal(`Log — ${worker.name || worker.id}`, "<pre class='log'>loading…</pre>");
  await refresh();
  state.logTimer = setInterval(refresh, 2000);
}

// ---------- actions ----------

async function loadConfig() {
  state.config = await api("/distributed/config");
  renderWorkers(
    document.getElementById("workers"), state.config, state.workerStatus
  );
  renderSettings();
  syncMasterToggle();
}

function refreshWorkflowNodes() {
  renderWorkflowNodes(
    document.getElementById("workflow-nodes"),
    parseWorkflowText(document.getElementById("workflow-json").value),
    enabledWorkers(state.config)
  );
}

async function queueWorkflow() {
  const resultEl = document.getElementById("queue-result");
  const prompt = parseWorkflowText(
    document.getElementById("workflow-json").value
  );
  if (!prompt) {
    resultEl.textContent = "invalid JSON";
    return;
  }
  try {
    const body = await api("/distributed/queue", {
      method: "POST",
      body: JSON.stringify({
        prompt,
        client_id: "panel",
        workers: enabledWorkers(state.config).map((w) => w.id),
        load_balance: document.getElementById("load-balance").checked,
      }),
    });
    resultEl.textContent = `queued ${body.prompt_id} → workers [${body.workers}]`;
    state.anythingBusy = true;
    schedulePoll();
  } catch (err) {
    resultEl.textContent = `queue failed: ${err.message}`;
  }
}

async function refreshMasterLog() {
  try {
    const body = await api("/distributed/master_log?tail=100");
    document.getElementById("master-log").textContent = body.lines.join("\n");
  } catch { /* panel works without logs */ }
}

async function loadExamples() {
  try {
    const body = await api("/distributed/workflows");
    const select = document.getElementById("example-select");
    for (const name of body.workflows || []) {
      const opt = document.createElement("option");
      opt.value = name;
      opt.textContent = name;
      select.appendChild(opt);
    }
    select.addEventListener("change", async () => {
      if (!select.value) return;
      const wf = await api(`/distributed/workflows/${encodeURIComponent(select.value)}`);
      document.getElementById("workflow-json").value = JSON.stringify(wf, null, 2);
      refreshWorkflowNodes();
    });
  } catch { /* optional */ }
}

// ---------- master detection (reference web/masterDetection.js) ----------

async function renderNetworkInfo() {
  const container = document.getElementById("network-info");
  try {
    const info = await api("/distributed/network_info");
    const autoCount = (state.config?.workers || []).filter(
      (w) => w.auto_populated
    ).length;
    container.innerHTML = networkInfoHtml(
      info, state.config?.master?.host, autoCount
    );
    const btn = document.getElementById("use-recommended-ip");
    if (btn)
      btn.addEventListener("click", async () => {
        try {
          await api("/distributed/config/master", {
            method: "POST",
            body: JSON.stringify({ host: info.recommended }),
          });
          await loadConfig();
          renderNetworkInfo();
        } catch (err) {
          alert(`save failed: ${err.message}`);
        }
      });
  } catch {
    container.textContent = "network info unavailable";
  }
}

// ---------- wiring ----------

document.addEventListener("click", async (event) => {
  const t = event.target;
  if (t.dataset.launch) {
    const status = state.workerStatus.get(t.dataset.launch) || {};
    status.launchingSince = Date.now();
    state.workerStatus.set(t.dataset.launch, status);
    try {
      await api("/distributed/launch_worker", {
        method: "POST",
        body: JSON.stringify({ worker_id: t.dataset.launch }),
      });
    } catch (err) { alert(`launch failed: ${err.message}`); }
    refreshStatus();
  } else if (t.dataset.stop) {
    await api("/distributed/stop_worker", {
      method: "POST",
      body: JSON.stringify({ worker_id: t.dataset.stop }),
    }).catch((err) => alert(err.message));
    refreshStatus();
  } else if (t.dataset.log) {
    showWorkerLog(t.dataset.log);
  } else if (t.dataset.edit) {
    workerForm(state.config.workers.find((w) => w.id === t.dataset.edit));
  } else if (t.dataset.delete) {
    if (confirm(`Delete worker ${t.dataset.delete}?`)) {
      await api(`/distributed/config/worker/${t.dataset.delete}`, { method: "DELETE" });
      await loadConfig();
    }
  }
});

document.addEventListener("change", async (event) => {
  const t = event.target;
  if (t.dataset.enable) {
    await api("/distributed/config/worker", {
      method: "POST",
      body: JSON.stringify({ id: t.dataset.enable, enabled: t.checked }),
    }).catch((err) => alert(err.message));
    await loadConfig();
    refreshWorkflowNodes(); // per-worker widget rows follow enablement
  } else if (t.dataset.dvNode || t.dataset.dvType) {
    const nodeId = t.dataset.dvNode || t.dataset.dvType;
    const typeSel = document.querySelector(`select[data-dv-type="${nodeId}"]`);
    const rows = [...document.querySelectorAll(
      `input[data-dv-node="${nodeId}"]`
    )].map((input) => ({ slot: input.dataset.dvSlot, value: input.value }));
    const textarea = document.getElementById("workflow-json");
    const patched = patchWorkflowText(textarea.value, nodeId, {
      overrides: collectOverrides(typeSel ? typeSel.value : "STRING", rows),
    });
    if (patched !== null) textarea.value = patched;
  } else if (t.dataset.dividerNode) {
    const nodeId = t.dataset.dividerNode;
    const parts = clampDividerParts(t.value);
    const textarea = document.getElementById("workflow-json");
    const patched = patchWorkflowText(textarea.value, nodeId, { divide_by: parts });
    if (patched !== null) textarea.value = patched;
    const used = document.getElementById(`divider-used-${nodeId}`);
    if (used)
      used.textContent = `${parts} of ${MAX_DIVIDER_OUTPUTS} outputs carry data`;
  }
});

document
  .getElementById("workflow-json")
  .addEventListener("input", () => {
    clearTimeout(state.nodesTimer);
    state.nodesTimer = setTimeout(refreshWorkflowNodes, 400);
  });

document
  .getElementById("master-participates")
  .addEventListener("change", async (event) => {
    try {
      await saveSetting("master_delegate_only", !event.target.checked);
      renderSettings(); // keep the settings-grid checkbox in sync
    } catch (err) {
      event.target.checked = !event.target.checked; // revert on failure
      alert(`save failed: ${err.message}`);
    }
  });
document.getElementById("sched-pause").addEventListener("click", () =>
  schedulerAction("/distributed/scheduler/pause"));
document.getElementById("sched-resume").addEventListener("click", () =>
  schedulerAction("/distributed/scheduler/resume"));
document.getElementById("sched-drain").addEventListener("click", () =>
  schedulerAction("/distributed/scheduler/drain"));
document.getElementById("profile-start").addEventListener("click", () =>
  profileAction("/distributed/profile/start"));
document.getElementById("profile-stop").addEventListener("click", () =>
  profileAction("/distributed/profile/stop"));
document.getElementById("add-worker").addEventListener("click", () => workerForm(null));
document.getElementById("modal-close").addEventListener("click", hideModal);
document.getElementById("queue-btn").addEventListener("click", queueWorkflow);
document.getElementById("interrupt-all").addEventListener("click", async () => {
  await api("/interrupt", { method: "POST" }).catch(() => {});
  for (const w of state.config?.workers || []) {
    fetch(workerUrl(w, "/interrupt"), { method: "POST" }).catch(() => {});
  }
});
document.getElementById("clear-memory").addEventListener("click", async () => {
  await api("/distributed/clear_memory", { method: "POST" }).catch(() => {});
  for (const w of state.config?.workers || []) {
    fetch(workerUrl(w, "/distributed/clear_memory"), { method: "POST" }).catch(() => {});
  }
});
document.getElementById("tunnel-toggle").addEventListener("click", async () => {
  const btn = document.getElementById("tunnel-toggle");
  const urlEl = document.getElementById("tunnel-url");
  const status = await api("/distributed/tunnel/status");
  try {
    if (status.running) {
      await api("/distributed/tunnel/stop", { method: "POST" });
      btn.textContent = "Start tunnel";
      urlEl.textContent = "";
    } else {
      btn.textContent = "starting…";
      const body = await api("/distributed/tunnel/start", { method: "POST" });
      btn.textContent = "Stop tunnel";
      urlEl.textContent = body.url;
    }
  } catch (err) {
    btn.textContent = "Start tunnel";
    alert(`tunnel: ${err.message}`);
  }
});

(async function init() {
  await loadConfig().catch(() => {});
  await renderTopology();
  await loadExamples();
  refreshStatus();
  startEventStream();
  renderNetworkInfo();
  setInterval(refreshMasterLog, 3000);
  refreshMasterLog();
})();
