/* Worker-status reduction: the launch-grace / clear-launching flow
 * (reference web/workerLifecycle.js 90s launching grace). */

"use strict";

import { assert, assertEqual, test } from "./harness.js";
import {
  computeAnythingBusy,
  enabledWorkers,
  pruneWorkerStatus,
  reduceWorkerStatus,
} from "../modules/state.js";

const T0 = 1_000_000;

test("reduce: offline probe inside the grace window shows launching", () => {
  const { status, clearLaunching } = reduceWorkerStatus(
    { launchingSince: T0 }, { online: false }, T0 + 30_000, 90_000
  );
  assert(status.launching, "still inside 90s grace");
  assert(!clearLaunching);
  assertEqual(status.launchingSince, T0, "grace window keeps its anchor");
});

test("reduce: grace expiry falls back to plain offline", () => {
  const { status, clearLaunching } = reduceWorkerStatus(
    { launchingSince: T0 }, { online: false }, T0 + 90_001, 90_000
  );
  assert(!status.launching, "grace expired");
  assert(!clearLaunching);
});

test("reduce: worker coming up inside grace clears the server marker", () => {
  const { status, clearLaunching } = reduceWorkerStatus(
    { launchingSince: T0 }, { online: true, queueRemaining: 0 }, T0 + 5_000
  );
  assert(clearLaunching, "must POST clear_launching exactly once");
  assertEqual(status.launchingSince, null, "anchor dropped after arrival");
  assert(status.online && !status.launching);
});

test("reduce: online worker without a pending launch stays quiet", () => {
  const { status, clearLaunching } = reduceWorkerStatus(
    { online: true, queueRemaining: 1 }, { online: true, queueRemaining: 0 }, T0
  );
  assert(!clearLaunching, "no marker to clear");
  assertEqual(status.queueRemaining, 0, "probe result wins");
});

test("reduce: first probe with no prior state", () => {
  const { status, clearLaunching } = reduceWorkerStatus(
    undefined, { online: false }, T0
  );
  assert(!status.launching && !clearLaunching);
});

test("computeAnythingBusy: master queue or any busy worker", () => {
  assert(computeAnythingBusy(1, []));
  assert(!computeAnythingBusy(0, []));
  assert(
    computeAnythingBusy(0, [
      { online: false },
      { online: true, queueRemaining: 2 },
    ])
  );
  assert(
    !computeAnythingBusy(0, [
      { online: true, queueRemaining: 0 },
      { online: false, queueRemaining: 9 }, // offline queue doesn't count
    ])
  );
});

test("pruneWorkerStatus drops deleted workers' stale entries", () => {
  const statuses = new Map([
    ["a", { online: true, queueRemaining: 3 }],
    ["gone", { online: true, queueRemaining: 9 }],
  ]);
  pruneWorkerStatus(statuses, [{ id: "a" }]);
  assertEqual([...statuses.keys()], ["a"]);
  // a deleted busy worker must not pin the fast poll cadence
  assert(!computeAnythingBusy(0, [...statuses.values()].filter((s) => s.queueRemaining === 9)));
  pruneWorkerStatus(statuses, undefined);
  assertEqual(statuses.size, 0);
});

test("enabledWorkers filters and tolerates missing config", () => {
  assertEqual(enabledWorkers(null), []);
  assertEqual(
    enabledWorkers({
      workers: [{ id: "a", enabled: true }, { id: "b", enabled: false }],
    }).map((w) => w.id),
    ["a"]
  );
});
