/* API client retry loop + probe validation (reference
 * web/tests/apiClient.test.js mocks fetch the same way). */

"use strict";

import { assert, assertEqual, assertThrows, test } from "./harness.js";
import {
  api,
  parseProbeBody,
  probeWorker,
  setApiDeps,
} from "../modules/apiClient.js";

function jsonResponse(body, ok = true, status = 200) {
  return { ok, status, json: async () => body };
}

async function withDeps(overrides, fn) {
  const prev = setApiDeps({ delay: async () => {}, ...overrides });
  try {
    return await fn();
  } finally {
    setApiDeps(prev);
  }
}

test("api: retries transient failures with backoff then succeeds", async () => {
  let calls = 0;
  const result = await withDeps(
    {
      fetch: async () => {
        calls++;
        if (calls < 3) throw new Error("ECONNREFUSED");
        return jsonResponse({ fine: true });
      },
    },
    () => api("/distributed/config")
  );
  assertEqual(result, { fine: true });
  assertEqual(calls, 3, "two retries then success");
});

test("api: gives up after the retry budget", async () => {
  let calls = 0;
  await withDeps(
    {
      fetch: async () => {
        calls++;
        throw new Error("down");
      },
    },
    () => assertThrows(() => api("/x", {}, 2))
  );
  assertEqual(calls, 3, "initial attempt + 2 retries");
});

test("api: non-ok response surfaces the server's error field", async () => {
  await withDeps(
    { fetch: async () => jsonResponse({ error: "bad worker" }, false, 400) },
    () =>
      assertThrows(async () => {
        try {
          await api("/x", {}, 0);
        } catch (err) {
          assertEqual(err.message, "bad worker");
          throw err;
        }
      })
  );
});

test("api: non-ok without a body falls back to HTTP status", async () => {
  await withDeps(
    {
      fetch: async () => ({
        ok: false,
        status: 503,
        json: async () => { throw new Error("not json"); },
      }),
    },
    () =>
      assertThrows(async () => {
        try {
          await api("/x", {}, 0);
        } catch (err) {
          assertEqual(err.message, "HTTP 503");
          throw err;
        }
      })
  );
});

test("parseProbeBody: requires the exec_info.queue_remaining contract", () => {
  assertEqual(parseProbeBody({ exec_info: { queue_remaining: 0 } }), {
    online: true,
    queueRemaining: 0,
  });
  assertEqual(parseProbeBody({ exec_info: { queue_remaining: 3 } }), {
    online: true,
    queueRemaining: 3,
  });
  assertEqual(parseProbeBody({}), { online: false });
  assertEqual(parseProbeBody(null), { online: false });
  assertEqual(parseProbeBody({ exec_info: {} }), { online: false });
});

test("probeWorker: offline on fetch failure, online on contract", async () => {
  const offline = await withDeps(
    { fetch: async () => { throw new Error("refused"); } },
    () => probeWorker({ type: "local", host: "h", port: 1 })
  );
  assertEqual(offline, { online: false });

  let requested = null;
  const online = await withDeps(
    {
      fetch: async (url) => {
        requested = url;
        return jsonResponse({ exec_info: { queue_remaining: 2 } });
      },
    },
    () => probeWorker({ type: "local", host: "h", port: 8189 })
  );
  assertEqual(online, { online: true, queueRemaining: 2 });
  assertEqual(requested, "http://h:8189/prompt", "probes the /prompt surface");
});

test("probeWorker: non-ok probe response is offline", async () => {
  const result = await withDeps(
    { fetch: async () => jsonResponse({}, false, 500) },
    () => probeWorker({ type: "local", host: "h", port: 1 })
  );
  assert(!result.online);
});
