/* Template builders: worker cards, widget blocks, and the
 * tokenizer-fidelity banner (pure string functions, no DOM). */

"use strict";

import { assert, assertEqual, assertIncludes, test } from "./harness.js";
import {
  dividerNodeHtml,
  valueNodeHtml,
  vocabBannerHtml,
  workerCardHtml,
  workerStatusParts,
} from "../modules/render.js";

test("workerStatusParts: online / busy / launching / offline", () => {
  assertEqual(workerStatusParts({ online: true, queueRemaining: 0 }), {
    dotCls: "online",
    statusText: "online · queue 0",
  });
  assertEqual(workerStatusParts({ online: true, queueRemaining: 2 }).dotCls, "busy");
  assertEqual(workerStatusParts({ launching: true }), {
    dotCls: "busy",
    statusText: "launching…",
  });
  assertEqual(workerStatusParts({}), { dotCls: "offline", statusText: "offline" });
});

test("workerCardHtml: local workers get launch/stop, remotes don't", () => {
  const local = workerCardHtml(
    { id: "w1", name: "alpha", type: "local", host: "127.0.0.1", port: 8189 },
    {}
  );
  assertIncludes(local, 'data-launch="w1"');
  assertIncludes(local, 'data-stop="w1"');
  const remote = workerCardHtml(
    { id: "w2", name: "beta", type: "remote", host: "10.0.0.9", port: 8188 },
    {}
  );
  assert(!remote.includes("data-launch"), "remote card has no launch button");
  assertIncludes(remote, 'data-log="w2"');
});

test("workerCardHtml escapes hostile names", () => {
  const html = workerCardHtml(
    { id: "w1", name: "<img src=x>", type: "local", port: 1 }, {}
  );
  assert(!html.includes("<img"), "name must be escaped");
  assertIncludes(html, "&lt;img");
});

test("valueNodeHtml: one row per enabled worker, selected type, slots 1-indexed", () => {
  const html = valueNodeHtml(
    "12",
    { inputs: { value: "seed", overrides: { _type: "INT", "2": "99" } } },
    [{ id: "a", name: "A" }, { id: "b", name: "B" }]
  );
  assertIncludes(html, '<option selected>INT</option>');
  assertIncludes(html, 'data-dv-slot="1"');
  assertIncludes(html, 'data-dv-slot="2"');
  assertIncludes(html, 'value="99"', "existing override round-trips");
});

test("valueNodeHtml: no enabled workers shows the hint row", () => {
  assertIncludes(
    valueNodeHtml("1", { inputs: {} }, []),
    "no enabled workers"
  );
});

test("dividerNodeHtml shows the current divide_by and bounds", () => {
  const html = dividerNodeHtml("3", {
    class_type: "ImageBatchDivider",
    inputs: { divide_by: 4 },
  });
  assertIncludes(html, 'value="4"');
  assertIncludes(html, 'max="10"');
  assertIncludes(html, "4 of 10 outputs carry data");
});

test("vocabBannerHtml: only a non-canonical vocab raises the banner", () => {
  assertEqual(vocabBannerHtml({ clip_vocab_canonical: true }), "");
  assertEqual(vocabBannerHtml({}), "", "unknown state stays quiet");
  assertEqual(vocabBannerHtml(null), "");
  const html = vocabBannerHtml({ clip_vocab_canonical: false });
  assertIncludes(html, "fetch_clip_vocab.py");
  assertIncludes(html, 'id="vocab-banner-dismiss"');
});
