/* Template builders: worker cards, widget blocks, and the
 * tokenizer-fidelity banner (pure string functions, no DOM). */

"use strict";

import { assert, assertEqual, assertIncludes, test } from "./harness.js";
import {
  cacheHtml,
  dividerNodeHtml,
  fleetHtml,
  incidentsHtml,
  networkInfoHtml,
  parsePipelineMetrics,
  pipelineHtml,
  profilingHtml,
  regionHtml,
  schedulerHtml,
  topologyHtml,
  usageHtml,
  valueNodeHtml,
  vocabBannerHtml,
  workerCardHtml,
  workerFormHtml,
  workerStatusParts,
  WORKER_FORM_FIELDS,
} from "../modules/render.js";

test("workerStatusParts: online / busy / launching / offline", () => {
  assertEqual(workerStatusParts({ online: true, queueRemaining: 0 }), {
    dotCls: "online",
    statusText: "online · queue 0",
  });
  assertEqual(workerStatusParts({ online: true, queueRemaining: 2 }).dotCls, "busy");
  assertEqual(workerStatusParts({ launching: true }), {
    dotCls: "busy",
    statusText: "launching…",
  });
  assertEqual(workerStatusParts({}), { dotCls: "offline", statusText: "offline" });
});

test("workerCardHtml: local workers get launch/stop, remotes don't", () => {
  const local = workerCardHtml(
    { id: "w1", name: "alpha", type: "local", host: "127.0.0.1", port: 8189 },
    {}
  );
  assertIncludes(local, 'data-launch="w1"');
  assertIncludes(local, 'data-stop="w1"');
  const remote = workerCardHtml(
    { id: "w2", name: "beta", type: "remote", host: "10.0.0.9", port: 8188 },
    {}
  );
  assert(!remote.includes("data-launch"), "remote card has no launch button");
  assertIncludes(remote, 'data-log="w2"');
});

test("workerCardHtml escapes hostile names", () => {
  const html = workerCardHtml(
    { id: "w1", name: "<img src=x>", type: "local", port: 1 }, {}
  );
  assert(!html.includes("<img"), "name must be escaped");
  assertIncludes(html, "&lt;img");
});

test("valueNodeHtml: one row per enabled worker, selected type, slots 1-indexed", () => {
  const html = valueNodeHtml(
    "12",
    { inputs: { value: "seed", overrides: { _type: "INT", "2": "99" } } },
    [{ id: "a", name: "A" }, { id: "b", name: "B" }]
  );
  assertIncludes(html, '<option selected>INT</option>');
  assertIncludes(html, 'data-dv-slot="1"');
  assertIncludes(html, 'data-dv-slot="2"');
  assertIncludes(html, 'value="99"', "existing override round-trips");
});

test("valueNodeHtml: no enabled workers shows the hint row", () => {
  assertIncludes(
    valueNodeHtml("1", { inputs: {} }, []),
    "no enabled workers"
  );
});

test("dividerNodeHtml shows the current divide_by and bounds", () => {
  const html = dividerNodeHtml("3", {
    class_type: "ImageBatchDivider",
    inputs: { divide_by: 4 },
  });
  assertIncludes(html, 'value="4"');
  assertIncludes(html, 'max="10"');
  assertIncludes(html, "4 of 10 outputs carry data");
});

test("vocabBannerHtml: only a non-canonical vocab raises the banner", () => {
  assertEqual(vocabBannerHtml({ clip_vocab_canonical: true }), "");
  assertEqual(vocabBannerHtml({}), "", "unknown state stays quiet");
  assertEqual(vocabBannerHtml(null), "");
  const html = vocabBannerHtml({ clip_vocab_canonical: false });
  assertIncludes(html, "fetch_clip_vocab.py");
  assertIncludes(html, 'id="vocab-banner-dismiss"');
});

test("vocabBannerHtml: T5 fallback raises its own banner line", () => {
  assertEqual(
    vocabBannerHtml({ clip_vocab_canonical: true, t5_vocab_canonical: true }),
    ""
  );
  const t5Only = vocabBannerHtml({
    clip_vocab_canonical: true, t5_vocab_canonical: false,
  });
  assertIncludes(t5Only, "CDT_T5_SPM");
  assert(!t5Only.includes("fetch_clip_vocab"), "clip line absent");
  const both = vocabBannerHtml({
    clip_vocab_canonical: false, t5_vocab_canonical: false,
  });
  assertIncludes(both, "CDT_T5_SPM");
  assertIncludes(both, "fetch_clip_vocab.py");
});

test("topologyHtml summarizes platform, counts, host and chips", () => {
  const html = topologyHtml({
    machine_id: "host-1",
    topology: {
      platform: "tpu", device_count: 8, local_device_count: 4,
      devices: [{ platform: "tpu", id: 0 }, { platform: "tpu", id: 1 }],
    },
  });
  assertIncludes(html, "platform <b>tpu</b>");
  assertIncludes(html, "4/8 local chips");
  assertIncludes(html, "host host-1");
  assertIncludes(html, '<span class="chip">tpu:0</span>');
});

test("networkInfoHtml: recommended IP, master host fallback, auto count", () => {
  const html = networkInfoHtml(
    { recommended: "10.0.0.5", candidates: ["10.0.0.5", "192.168.1.2"] },
    undefined, 2
  );
  assertIncludes(html, "<b>10.0.0.5</b>");
  assertIncludes(html, 'id="use-recommended-ip"');
  assertIncludes(html, "current master host: (unset)");
  assertIncludes(html, "2 worker(s) auto-populated");
  const none = networkInfoHtml({ recommended: "h", candidates: [] }, "m", 0);
  assert(!none.includes("auto-populated"), "no auto row when count is 0");
});

test("workerFormHtml: one input per field + chips + save button", () => {
  const html = workerFormHtml({
    id: "w9", name: "n", type: "local", host: "127.0.0.1", port: 8191,
    tpu_chips: [0, 2], extra_args: "",
  });
  for (const f of WORKER_FORM_FIELDS) assertIncludes(html, `id="wf-${f}"`);
  assertIncludes(html, 'id="wf-tpu_chips"');
  assertIncludes(html, 'value="0,2"');
  assertIncludes(html, 'id="wf-save"');
});

test("schedulerHtml: lanes, deficits, weights, and the unavailable fallback", () => {
  assertIncludes(schedulerHtml(null), "unavailable");
  assertIncludes(schedulerHtml({}), "unavailable");
  const html = schedulerHtml({
    admission: {
      state: "running",
      active: 1,
      max_active: 4,
      queued: 3,
      lanes: [
        {
          name: "interactive",
          depth: 3,
          max_depth: 64,
          tenants: { "tenant-a": { queued: 2, deficit: 1.5 } },
        },
        { name: "batch", depth: 0, max_depth: 256, tenants: {} },
      ],
      tenant_weights: { "tenant-a": 3 },
    },
    worker_weights: { w1: 0.2, w2: 1.8 },
  });
  assertIncludes(html, "running");
  assertIncludes(html, "interactive");
  assertIncludes(html, "depth 3/64");
  assertIncludes(html, "tenant-a: 2 queued (deficit 1.5)");
  assertIncludes(html, "w2=1.8x");
  assertIncludes(html, "tenant-a=3");
});

test("schedulerHtml escapes hostile tenant and worker names", () => {
  const html = schedulerHtml({
    admission: {
      state: "running", active: 0, max_active: 1, queued: 1,
      lanes: [
        {
          name: "interactive", depth: 1, max_depth: 8,
          tenants: { "<img src=x>": { queued: 1, deficit: 0 } },
        },
      ],
      tenant_weights: {},
    },
    worker_weights: { "<b>w</b>": 1.0 },
  });
  assert(!html.includes("<img"), "tenant name escaped");
  assert(!html.includes("<b>w</b>"), "worker name escaped");
});

test("parsePipelineMetrics pulls pipeline + cache series from text", () => {
  const text = [
    "# TYPE cdt_pipeline_batches_total counter",
    'cdt_pipeline_batches_total{role="worker",bucket="8"} 12',
    'cdt_pipeline_batches_total{role="master",bucket="2"} 3',
    'cdt_pipeline_inflight{role="worker"} 1',
    'cdt_pipeline_padded_tiles_total{role="worker"} 4',
    "cdt_jax_cache_hits 7",
    "cdt_jax_cache_misses 2",
    "unrelated_metric 99",
  ].join("\n");
  const stats = parsePipelineMetrics(text);
  assertEqual(stats.batches, { worker: { "8": 12 }, master: { "2": 3 } });
  assertEqual(stats.inflight, { worker: 1 });
  assertEqual(stats.padded, { worker: 4 });
  assertEqual(stats.cache, { hits: 7, misses: 2 });
});

test("pipelineHtml renders per-role buckets and the cache line", () => {
  const html = pipelineHtml({
    batches: { worker: { 8: 12, 4: 1 } },
    inflight: { worker: 1 },
    padded: { worker: 4 },
    cache: { hits: 7, misses: 2 },
  });
  assertIncludes(html, "worker");
  assertIncludes(html, "K=4: 1");
  assertIncludes(html, "K=8: 12");
  assertIncludes(html, "in-flight 1");
  assertIncludes(html, "padded 4");
  assertIncludes(html, "compile cache: 7 hits / 2 misses");
  assertIncludes(
    pipelineHtml({ batches: {}, inflight: {}, padded: {}, cache: {} }),
    "no pipeline activity"
  );
});

test("fleetHtml: disabled / rollup + workers / alert strip", () => {
  assertIncludes(fleetHtml(null), "unavailable");
  assertIncludes(fleetHtml({ enabled: false }), "CDT_FLEET=1");
  const fleet = {
    enabled: true,
    rollup: {
      workers: 2, devices: 6, tiles_per_s: 3.21, tiles_per_chip_s: 0.535,
      inflight: 1, alerts_active: [],
    },
    workers: {
      w1: {
        tiles_per_s: 2.5, seen_ago_s: 4.2,
        snapshot: {
          devices: 4,
          stages: { sample: { p50: 0.1, p95: 0.42, count: 12 } },
        },
      },
    },
    series: { count: 9, overflows: 0 },
  };
  const html = fleetHtml(fleet, { active: [] });
  assertIncludes(html, "workers <b>2</b>");
  assertIncludes(html, "3.21 tiles/s");
  assertIncludes(html, "w1");
  assertIncludes(html, "4 chip(s)");
  assertIncludes(html, "sample p95 0.42s");
  assertIncludes(html, "no alerts firing");
  assertIncludes(html, "retained series: 9");
  const burning = fleetHtml(fleet, { active: ["tile_latency"] });
  assertIncludes(burning, "ALERT");
  assertIncludes(burning, "tile_latency");
});

test("usageHtml: disabled / tenant rows / waste breakdown", () => {
  assertIncludes(usageHtml(null), "unavailable");
  assertIncludes(usageHtml({ enabled: false }), "CDT_USAGE=1");
  const usage = {
    enabled: true,
    rollup: {
      tenants: {
        "tenant-a": { chip_s: 3.5, chip_share: 0.7, tiles: 12, waste_s: 0.2 },
        "tenant-b": { chip_s: 1.5, chip_share: 0.3, tiles: 4, waste_s: 0 },
      },
      totals: {
        chip_s: 5.0, attributed_s: 4.4, dispatches: 20, waste_share: 0.12,
        waste_s: { padding: 0.4, preempt_recompute: 0.2 },
      },
    },
  };
  const html = usageHtml(usage);
  assertIncludes(html, "chips burned <b>5.00s</b>");
  assertIncludes(html, "tenant-a");
  assertIncludes(html, "3.50 chip-s");
  assertIncludes(html, "(70.0%)");
  assertIncludes(html, "12 tile(s)");
  assertIncludes(html, "padding 0.40s");
  assertIncludes(html, "preempt_recompute 0.20s");
  // a pushed usage_rollup event IS the rollup (no wrapper): same card
  const pushed = usageHtml(usage.rollup);
  assertIncludes(pushed, "tenant-b");
});

test("cacheHtml: disabled / tiers / corrupt emphasis", () => {
  assertIncludes(cacheHtml(null), "unavailable");
  assertIncludes(cacheHtml({ enabled: false }), "CDT_CACHE=1");
  const stats = {
    enabled: true,
    hits: 9,
    hits_ram: 7,
    hits_disk: 2,
    misses: 3,
    hit_rate: 0.75,
    puts: 3,
    evictions: 1,
    corrupt: 0,
    settled: 9,
    ram_entries: 4,
    ram_bytes: 4 * 1024 * 1024,
    disk_bytes: 12 * 1024 * 1024,
    disk_tier: true,
  };
  const html = cacheHtml(stats);
  assertIncludes(html, "hit rate <b>75.0%</b>");
  assertIncludes(html, "9 hit(s) / 3 miss(es)");
  assertIncludes(html, "9 tile(s) settled from cache");
  assertIncludes(html, "ram 4 entries / 4.0 MiB");
  assertIncludes(html, "disk 12.0 MiB (2 hit(s))");
  assertIncludes(html, "3 put(s)");
  // corrupt entries are loud; a clean cache never mentions them
  if (html.includes("corrupt")) {
    throw new Error("clean cache must not render a corrupt line");
  }
  const corrupt = cacheHtml({ ...stats, corrupt: 2 });
  assertIncludes(corrupt, "<b>2 corrupt entr(ies) dropped</b>");
  // RAM-only cache labels the disk tier off
  const ramOnly = cacheHtml({ ...stats, disk_tier: false });
  assertIncludes(ramOnly, "disk tier off");
  // a pushed cache_stats event IS the stats payload (no wrapper)
  assertIncludes(cacheHtml({ hits: 0, misses: 0, hit_rate: 0 }), "hit rate");
});

test("profilingHtml: ledger / capture states / trace index", () => {
  assertIncludes(profilingHtml(null), "unavailable");
  // ledger off (CDT_PROFILING=0) but capture enabled
  assertIncludes(profilingHtml({ enabled: true, ledger: null }), "CDT_PROFILING=0");
  const ledger = {
    host_tax: 0.25,
    device_ns: 3e9,
    eager_ns: 0,
    host_ns: { gather: 5e8, encode: 3e8, ship: 2e8 },
    tiles: 4,
    transfer: {
      h2d: { bytes: 2 * 1024 * 1024, count: 3 },
      d2h: { bytes: 1024 * 1024, count: 4 },
    },
  };
  // capture disabled: ledger still renders, with the enable hint
  const disabled = profilingHtml({ enabled: false, ledger });
  assertIncludes(disabled, "25.0%");
  assertIncludes(disabled, "device 3.000s");
  assertIncludes(disabled, "host 1.000s");
  assertIncludes(disabled, "CDT_PROFILE_DIR");
  // idle capture + retained trace index
  const idle = profilingHtml({
    enabled: true,
    ledger,
    capture: { active: null },
    captures: [{ id: "trace-0002-drill", bytes: 3 * 1024 * 1024 }],
  });
  assertIncludes(idle, "no capture in flight");
  assertIncludes(idle, "trace-0002-drill");
  assertIncludes(idle, "3.0 MiB");
  // in-flight capture: the route serves active as {id, ...}
  const busy = profilingHtml({
    enabled: true,
    ledger,
    capture: { active: { id: "trace-0003-smoke", elapsed_s: 1.2 } },
    captures: [],
  });
  assertIncludes(busy, "capturing");
  assertIncludes(busy, "trace-0003-smoke");
  assertIncludes(busy, "no retained traces");
  // eager-only ledger surfaces the eager bucket
  const eager = profilingHtml({
    enabled: false,
    ledger: { ...ledger, device_ns: 0, eager_ns: 5e8, host_tax: 1.0 },
  });
  assertIncludes(eager, "eager 0.500s");
  assertIncludes(eager, "100.0%");
});

test("incidentsHtml: disabled / flight accounting / bundle rows", () => {
  assertIncludes(incidentsHtml(null), "unavailable");
  assertIncludes(incidentsHtml({ enabled: false }), "CDT_INCIDENT_DIR");
  const info = {
    enabled: true,
    flight: {
      retained: { events: 120, spans: 40 },
      dropped: { events: 3, spans: 0 },
    },
    manager: { counters: { captured: 2, debounced: 1, rate_limited: 0 } },
    incidents: [
      {
        id: "incident-0000000001000-0001-alert_fired",
        trigger: "alert_fired",
        ts: 1.0,
        bytes: 2048,
      },
    ],
  };
  const html = incidentsHtml(info);
  assertIncludes(html, "120 event(s)");
  assertIncludes(html, "3 dropped");
  assertIncludes(html, "captured 2");
  assertIncludes(html, "debounced 1");
  assertIncludes(html, "alert_fired");
  assertIncludes(html, "incident-0000000001000-0001-alert_fired");
  assertIncludes(html, "2.0 KiB");
  assertIncludes(
    incidentsHtml({ enabled: true, incidents: [] }),
    "no incident bundles"
  );
});

test("regionHtml: unsharded / shard map / quorum lease / autoscale", () => {
  assertIncludes(regionHtml(null), "unavailable");
  const off = regionHtml({ enabled: false, shards: { shards: {} } }, null);
  assertIncludes(off, "CDT_SHARDS");
  assertIncludes(off, "CDT_AUTOSCALE=1");
  const region = {
    enabled: true,
    deposed: false,
    shards: {
      shards: {
        shard0: {
          epoch: 4,
          urls: ["http://a:8188", "http://a2:8188"],
          endpoints: [
            { url: "http://a:8188", current: true, backoff_remaining_s: 0 },
            { url: "http://a2:8188", current: false, backoff_remaining_s: 2.5 },
          ],
        },
      },
    },
    lease: {
      backend: "quorum",
      epoch: 4,
      quorum: 2,
      peers: [
        { name: "peer0", state: { epoch: 4 } },
        { name: "peer1", error: "EIO" },
      ],
    },
  };
  const autoscale = {
    enabled: true,
    workers: 3,
    chips: 3,
    bounds: { min: 1, max: 8 },
    target_utilization: 0.7,
    decisions: [
      {
        action: "scale_up",
        reason: "burn:tile_latency",
        utilization: 0.91,
        demand_chip_s: 18.2,
        capacity_chip_s: 20.0,
      },
    ],
  };
  const html = regionHtml(region, autoscale);
  assertIncludes(html, "shard0");
  assertIncludes(html, "epoch 4");
  assertIncludes(html, "backoff 2.5s");
  assertIncludes(html, "quorum 2");
  assertIncludes(html, "peer0:e4");
  assertIncludes(html, "peer1:ERR");
  assertIncludes(html, "scale_up");
  assertIncludes(html, "burn:tile_latency");
  assertIncludes(html, "18.2/20.0 chip-s");
  assertIncludes(html, "bounds 1–8");
  // a deposed master is loudly flagged
  assertIncludes(
    regionHtml({ ...region, deposed: true }, null),
    "DEPOSED"
  );
});
