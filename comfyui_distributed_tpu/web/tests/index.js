/* Imports every test module (registration side effects) and re-exports
 * the runner. Entry points: run-node.mjs (node) and runner.html
 * (any browser). */

"use strict";

import "./urlUtils.test.js";
import "./apiClient.test.js";
import "./state.test.js";
import "./events.test.js";
import "./widgets.test.js";
import "./render.test.js";
import "./vectors.test.js";

export { registry, runAll } from "./harness.js";
