/* Live event-stream consumer: status reduction, labels, reconnect
 * backoff, and the injectable-WebSocket wiring. */

"use strict";

import { assert, assertEqual, test } from "./harness.js";
import {
  connectEvents,
  eventLabel,
  MAX_LIVE_EVENTS,
  nextRetryDelay,
  reduceLiveStatus,
} from "../modules/events.js";
import {
  pollDelay,
  POLL_ACTIVE_MS,
  POLL_IDLE_MS,
  POLL_STREAM_IDLE_MS,
} from "../modules/state.js";

test("reduce: hello snapshot seeds breaker states", () => {
  const next = reduceLiveStatus(null, {
    type: "hello",
    data: { health: { w1: { state: "suspect" }, w2: { state: "healthy" } } },
  });
  assertEqual(next.breakers, { w1: "suspect", w2: "healthy" });
  assertEqual(next.events, [], "hello is not a display event");
});

test("reduce: health transition updates breakers and prepends an event", () => {
  const prev = { connected: true, breakers: { w1: "healthy" }, events: [] };
  const next = reduceLiveStatus(prev, {
    type: "health_transition",
    ts: 1,
    data: { worker_id: "w1", from_state: "healthy", to_state: "suspect" },
  });
  assertEqual(next.breakers.w1, "suspect");
  assertEqual(next.events.length, 1);
  assert(next.events[0].label.includes("w1"), next.events[0].label);
});

test("reduce: the event ring is capped newest-first", () => {
  let status = null;
  for (let i = 0; i < MAX_LIVE_EVENTS + 5; i++) {
    status = reduceLiveStatus(status, {
      type: "stall_detected",
      ts: i,
      data: { job_id: `j${i}`, quiet_seconds: 1, in_flight: 2 },
    });
  }
  assertEqual(status.events.length, MAX_LIVE_EVENTS);
  assert(status.events[0].label.includes(`j${MAX_LIVE_EVENTS + 4}`), "newest first");
});

test("labels: watchdog verdicts render, metric deltas stay silent", () => {
  assert(
    eventLabel({
      type: "straggler_detected",
      data: { worker_id: "w1", median_seconds: 0.5, global_median_seconds: 0.01 },
    }).includes("straggler")
  );
  assert(
    eventLabel({
      type: "speculative_requeue",
      data: { job_id: "j", task_ids: [3, 4] },
    }).includes("[3, 4]")
  );
  assertEqual(eventLabel({ type: "metric_delta", data: {} }), null);
  assertEqual(eventLabel({ type: "span_close", data: {} }), null);
});

test("labels: lifecycle events (cancel / poison / brownout) render", () => {
  assert(
    eventLabel({
      type: "job_cancelled",
      data: { job_id: "j", reason: "client", pending_refunded: 3, in_flight_refunded: 2 },
    }).includes("refunded 5 tile(s)")
  );
  assert(
    eventLabel({
      type: "tile_quarantined",
      data: { job_id: "j", task_ids: [7] },
    }).includes("poison")
  );
  assert(
    eventLabel({ type: "shed", data: { lane: "background", level: 1 } }).includes(
      "background"
    )
  );
  assert(
    eventLabel({
      type: "brownout_level",
      data: { level: 2, direction: "up" },
    }).includes("2")
  );
});

test("backoff: exponential and capped", () => {
  assertEqual(nextRetryDelay(0, 1000, 8000), 1000);
  assertEqual(nextRetryDelay(1, 1000, 8000), 2000);
  assertEqual(nextRetryDelay(10, 1000, 8000), 8000);
});

test("poll cadence: the stream stretches the idle poll, never the busy one", () => {
  assertEqual(pollDelay(true, false), POLL_ACTIVE_MS);
  assertEqual(pollDelay(true, true), POLL_ACTIVE_MS, "progress is poll-only");
  assertEqual(pollDelay(false, false), POLL_IDLE_MS);
  assertEqual(
    pollDelay(false, true),
    POLL_STREAM_IDLE_MS,
    "pushed health events replace the idle heartbeat"
  );
});

test("connectEvents: decodes frames, reports status, reconnects", () => {
  const sockets = [];
  class FakeWS {
    constructor(url) {
      this.url = url;
      sockets.push(this);
    }
    close() {
      if (this.onclose) this.onclose();
    }
  }
  const seen = [];
  const statuses = [];
  const timers = [];
  const stop = connectEvents({
    url: "ws://x/distributed/events",
    WebSocketImpl: FakeWS,
    setTimeoutImpl: (fn, ms) => timers.push({ fn, ms }),
    onEvent: (e) => seen.push(e),
    onStatus: (s) => statuses.push(s),
  });
  assertEqual(sockets.length, 1);
  sockets[0].onopen();
  sockets[0].onmessage({ data: '{"type":"hello","data":{}}' });
  sockets[0].onmessage({ data: "not json" }); // tolerated
  sockets[0].onmessage({
    data: '{"type":"health_transition","data":{"worker_id":"w1"}}',
  });
  assertEqual(seen.length, 2);
  assertEqual(statuses, [true]);
  // server drop → disconnected status + a scheduled reconnect
  sockets[0].onclose();
  assertEqual(statuses, [true, false]);
  assertEqual(timers.length, 1);
  timers[0].fn();
  assertEqual(sockets.length, 2, "reconnect opened a new socket");
  stop(); // closing the handle closes the socket without reconnecting
  sockets[1].onclose();
  assertEqual(timers.length, 1, "no reconnect after explicit stop");
});

test("reduceLiveStatus: fleet rollups and alert transitions tracked", () => {
  let status = reduceLiveStatus(undefined, {
    type: "fleet_rollup",
    data: { workers: 2, tiles_per_s: 3.0 },
  });
  assertEqual(status.fleet.workers, 2);
  status = reduceLiveStatus(status, {
    type: "alert_fired",
    ts: 1,
    data: { slo: "tile_latency" },
  });
  assert(status.alerts.has("tile_latency"), "alert tracked as active");
  status = reduceLiveStatus(status, {
    type: "alert_resolved",
    ts: 2,
    data: { slo: "tile_latency", active_seconds: 12 },
  });
  assert(!status.alerts.has("tile_latency"), "alert cleared on resolve");
});

test("eventLabel: alert transitions readable, fleet_rollup silent", () => {
  assertIncludes(
    eventLabel({ type: "alert_fired", data: { slo: "availability" } }),
    "availability"
  );
  assertIncludes(
    eventLabel({
      type: "alert_resolved",
      data: { slo: "availability", active_seconds: 30 },
    }),
    "resolved"
  );
  assertEqual(eventLabel({ type: "fleet_rollup", data: {} }), null);
  assertEqual(eventLabel({ type: "usage_rollup", data: {} }), null);
  assertEqual(eventLabel({ type: "cache_stats", data: {} }), null);
});

test("reduceLiveStatus: cache stats tracked for the cache card", () => {
  const status = reduceLiveStatus(undefined, {
    type: "cache_stats",
    data: { hits: 4, misses: 1, hit_rate: 0.8 },
  });
  assertEqual(status.cache.hit_rate, 0.8);
  const next = reduceLiveStatus(status, { type: "hello", data: {} });
  assertEqual(next.cache.hits, 4, "snapshot survives a hello frame");
});

test("reduceLiveStatus: usage rollups tracked for the usage card", () => {
  const status = reduceLiveStatus(undefined, {
    type: "usage_rollup",
    data: { tenants: { "tenant-a": { chip_s: 1.5 } }, totals: { chip_s: 2 } },
  });
  assertEqual(status.usage.totals.chip_s, 2);
  const next = reduceLiveStatus(status, { type: "hello", data: {} });
  assertEqual(next.usage.totals.chip_s, 2, "rollup survives a hello frame");
});

test("eventLabel: incident captures render with trigger and key", () => {
  assertIncludes(
    eventLabel({
      type: "incident_captured",
      data: {
        id: "incident-0000000001000-0001-alert_fired",
        trigger: "alert_fired",
        key: "tile_latency",
      },
    }),
    "alert_fired:tile_latency"
  );
});
