/* Shared-vector execution: every case in web/tests/vectors/*.json is
 * run against the real module functions. The same JSON files are
 * mirror-executed in Python by tests/test_web_js.py, so the expected
 * outputs here are independently validated even on CI images with no
 * JS runtime (reference parallel: web/tests under vitest in the
 * reference's CI). */

"use strict";

import { loadVectors, test } from "./harness.js";
import * as state from "../modules/state.js";
import * as urlUtils from "../modules/urlUtils.js";
import * as widgets from "../modules/widgets.js";

const MODULES = { state, urlUtils, widgets };
export const VECTOR_FILES = ["state", "urlUtils", "widgets"];

/** Key-sorted stringify: object comparison must not depend on key
 * insertion order (the JSON file's order vs the function's spread
 * order are both implementation details). Dropping undefined-valued
 * keys matches the JSON.stringify semantics the harness's assertEqual
 * always had — this comparator only adds order-insensitivity. */
function stable(value) {
  if (Array.isArray(value)) return `[${value.map(stable).join(",")}]`;
  if (value && typeof value === "object") {
    const keys = Object.keys(value)
      .filter((k) => value[k] !== undefined)
      .sort();
    return `{${keys.map((k) => `${JSON.stringify(k)}:${stable(value[k])}`).join(",")}}`;
  }
  return JSON.stringify(value) ?? "undefined";
}

for (const name of VECTOR_FILES) {
  test(`vectors: ${name}`, async () => {
    const spec = await loadVectors(name);
    const mod = MODULES[spec.module];
    if (!mod) throw new Error(`unknown vector module ${spec.module}`);
    if (!spec.cases.length) throw new Error(`${name}: empty vector file`);
    for (const [i, c] of spec.cases.entries()) {
      let got = mod[c.fn](...c.args);
      if (c.parseResult && got !== null) got = JSON.parse(got);
      const a = stable(got);
      const b = stable(c.want);
      if (a !== b) {
        throw new Error(`${name}[${i}] ${c.fn}: ${a} !== ${b}`);
      }
    }
  });
}
