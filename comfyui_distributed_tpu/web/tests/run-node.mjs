/* Node entry point: `node comfyui_distributed_tpu/web/tests/run-node.mjs`
 * (or `bash scripts/test-web.sh`, which skips gracefully when the
 * image has no node). Exits non-zero on any failure. */

import { runAll } from "./index.js";

const failed = await runAll();
process.exit(failed ? 1 : 0);
