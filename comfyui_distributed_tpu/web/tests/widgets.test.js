/* Workflow widget math: override collection, divider clamping, JSON
 * patching, worker defaults (reference web/tests: distributedValue +
 * image_batch_divider + workerSettings coverage). */

"use strict";

import { assertEqual, test } from "./harness.js";
import {
  clampDividerParts,
  collectOverrides,
  findWidgetNodes,
  newWorkerTemplate,
  nextWorkerDefaults,
  parseChipList,
  parseWorkflowText,
  patchWorkflowText,
} from "../modules/widgets.js";

test("parseWorkflowText: bare and {prompt:...}-wrapped graphs", () => {
  const graph = { "1": { class_type: "KSampler", inputs: {} } };
  assertEqual(parseWorkflowText(JSON.stringify(graph)), graph);
  assertEqual(parseWorkflowText(JSON.stringify({ prompt: graph })), graph);
  assertEqual(parseWorkflowText("not json"), null);
});

test("patchWorkflowText merges inputs and preserves the wrapper", () => {
  const text = JSON.stringify({
    prompt: { "7": { class_type: "DistributedValue", inputs: { value: "x" } } },
  });
  const patched = patchWorkflowText(text, "7", { overrides: { _type: "INT" } });
  const parsed = JSON.parse(patched);
  assertEqual(parsed.prompt["7"].inputs, {
    value: "x",
    overrides: { _type: "INT" },
  });
});

test("patchWorkflowText: unknown node or bad JSON returns null", () => {
  assertEqual(patchWorkflowText("{}", "9", { a: 1 }), null);
  assertEqual(patchWorkflowText("garbage", "9", { a: 1 }), null);
});

test("collectOverrides: 1-indexed slots, empties omitted, type guarded", () => {
  assertEqual(
    collectOverrides("INT", [
      { slot: 1, value: "5" },
      { slot: 2, value: "" },
      { slot: 3, value: "7" },
    ]),
    { _type: "INT", "1": "5", "3": "7" }
  );
  assertEqual(collectOverrides("BOGUS", []), { _type: "STRING" });
});

test("clampDividerParts: [1, 10] with junk tolerated", () => {
  assertEqual(clampDividerParts(0), 1);
  assertEqual(clampDividerParts(4), 4);
  assertEqual(clampDividerParts(99), 10);
  assertEqual(clampDividerParts("abc"), 1);
  assertEqual(clampDividerParts(""), 1);
});

test("nextWorkerDefaults: next port above max, first unclaimed chip", () => {
  const workers = [
    { port: 8189, tpu_chips: [0] },
    { port: 8191, tpu_chips: [1] },
  ];
  assertEqual(nextWorkerDefaults(workers, [0, 1, 2, 3]), {
    port: 8192,
    chip: [2],
  });
});

test("nextWorkerDefaults: empty config starts at 8189, no chips known", () => {
  assertEqual(nextWorkerDefaults([], []), { port: 8189, chip: [] });
  assertEqual(nextWorkerDefaults(undefined, undefined), { port: 8189, chip: [] });
});

test("newWorkerTemplate: deterministic defaults from config + topology", () => {
  assertEqual(
    newWorkerTemplate([{ port: 8189, tpu_chips: [0] }], [0, 1], 42),
    {
      id: "w42", name: "", type: "local", host: "127.0.0.1",
      port: 8190, tpu_chips: [1], enabled: true, extra_args: "",
    }
  );
});

test("parseChipList tolerates spaces, junk, and empties", () => {
  assertEqual(parseChipList("0,1, 2"), [0, 1, 2]);
  assertEqual(parseChipList(""), []);
  assertEqual(parseChipList("a,1,"), [1]);
});

test("findWidgetNodes picks value + divider nodes only", () => {
  const prompt = {
    "1": { class_type: "KSampler" },
    "2": { class_type: "DistributedValue", inputs: {} },
    "3": { class_type: "ImageBatchDivider", inputs: { divide_by: 3 } },
    "4": { class_type: "AudioBatchDivider", inputs: {} },
  };
  assertEqual(
    findWidgetNodes(prompt).map(({ nodeId, kind }) => [nodeId, kind]),
    [["2", "value"], ["3", "divider"], ["4", "divider"]]
  );
  assertEqual(findWidgetNodes(null), []);
});
