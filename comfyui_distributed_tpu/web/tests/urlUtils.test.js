/* URL building + escaping (reference web/tests/urlUtils.test.js). */

"use strict";

import { assertEqual, test } from "./harness.js";
import { escapeHtml, workerUrl } from "../modules/urlUtils.js";

test("workerUrl: local http with port", () => {
  assertEqual(
    workerUrl({ type: "local", host: "127.0.0.1", port: 8189 }, "/prompt"),
    "http://127.0.0.1:8189/prompt"
  );
});

test("workerUrl: remote host defaults to http", () => {
  assertEqual(
    workerUrl({ type: "remote", host: "10.0.0.7", port: 8188 }, "/x"),
    "http://10.0.0.7:8188/x"
  );
});

test("workerUrl: cloud worker uses https", () => {
  assertEqual(
    workerUrl({ type: "cloud", host: "pod.example.com", port: 8443 }, "/p"),
    "https://pod.example.com:8443/p"
  );
});

test("workerUrl: port 443 implies https", () => {
  assertEqual(
    workerUrl({ type: "remote", host: "h", port: 443 }, "/p"),
    "https://h:443/p"
  );
});

test("workerUrl: missing host falls back to loopback, no port omits colon", () => {
  assertEqual(workerUrl({ type: "local" }, "/p"), "http://127.0.0.1/p");
});

test("escapeHtml escapes the five specials and stringifies", () => {
  assertEqual(
    escapeHtml(`<b a="1" b='2'>&`),
    "&lt;b a=&quot;1&quot; b=&#39;2&#39;&gt;&amp;"
  );
  assertEqual(escapeHtml(null), "");
  assertEqual(escapeHtml(42), "42");
});
