/* Minimal dual-environment test harness.
 *
 * The image this framework ships in has no node/npm (verified: no JS
 * runtime at all), so the suite can't depend on vitest like the
 * reference's web/tests do. This harness is plain ES modules: run it
 * with `node web/tests/run-node.mjs` wherever node exists, or open
 * web/tests/runner.html in any browser.
 */

"use strict";

export const registry = [];

export function test(name, fn) {
  registry.push({ name, fn });
}

export function assert(cond, msg) {
  if (!cond) throw new Error(msg || "assertion failed");
}

export function assertEqual(actual, expected, msg) {
  const a = JSON.stringify(actual);
  const b = JSON.stringify(expected);
  if (a !== b) {
    throw new Error(`${msg || "not equal"}: ${a} !== ${b}`);
  }
}

export function assertIncludes(haystack, needle, msg) {
  if (!String(haystack).includes(needle)) {
    throw new Error(`${msg || "missing substring"}: ${needle}`);
  }
}

export async function assertThrows(fn, msg) {
  try {
    await fn();
  } catch {
    return;
  }
  throw new Error(msg || "expected an exception");
}

/** Load a shared JSON test-vector file (web/tests/vectors/<name>.json)
 * under either runtime: node reads from disk, the browser runner
 * fetches relative to this module (runner.html is served over http —
 * ES modules don't load from file:// anyway). The SAME files are
 * structurally validated and mirror-executed by the Python CI net
 * (tests/test_web_js.py), so a node-less CI and an operator box with
 * node check identical behavior. */
export async function loadVectors(name) {
  const url = new URL(`./vectors/${name}.json`, import.meta.url);
  if (typeof window === "undefined") {
    const { readFile } = await import("node:fs/promises");
    return JSON.parse(await readFile(url, "utf-8"));
  }
  const resp = await fetch(url);
  return resp.json();
}

export async function runAll(log = console.log) {
  let failed = 0;
  for (const { name, fn } of registry) {
    try {
      await fn();
      log(`ok - ${name}`);
    } catch (err) {
      failed++;
      log(`FAIL - ${name}: ${err.message}`);
    }
  }
  log(`# ${registry.length - failed}/${registry.length} passed`);
  return failed;
}
