/* API client with retry/backoff + worker probing.
 *
 * Counterpart of the reference's web/apiClient.js. The fetch function
 * is injectable so the retry loop and probe validation are testable
 * without a browser (reference web/tests/apiClient.test.js mocks
 * global fetch the same way).
 */

"use strict";

import { workerUrl } from "./urlUtils.js";

const deps = {
  fetch: (...args) => fetch(...args),
  delay: (ms) => new Promise((r) => setTimeout(r, ms)),
};

/** Test hook: override fetch/delay; returns the previous values. */
export function setApiDeps(overrides) {
  const prev = { ...deps };
  Object.assign(deps, overrides);
  return prev;
}

export async function api(path, options = {}, retries = 2) {
  for (let attempt = 0; ; attempt++) {
    try {
      const resp = await deps.fetch(path, {
        headers: { "Content-Type": "application/json" },
        ...options,
      });
      const body = await resp.json().catch(() => ({}));
      if (!resp.ok) throw new Error(body.error || `HTTP ${resp.status}`);
      return body;
    } catch (err) {
      if (attempt >= retries) throw err;
      await deps.delay(300 * 2 ** attempt);
    }
  }
}

/** Pure validation of a /prompt probe body: a worker is only "online"
 * when the response carries the exec_info.queue_remaining contract
 * (reference web/apiClient.js probeWorker validation). */
export function parseProbeBody(body) {
  const remaining = body?.exec_info?.queue_remaining;
  if (remaining === undefined || remaining === null) return { online: false };
  return { online: true, queueRemaining: Number(remaining) };
}

export async function probeWorker(worker, timeoutMs = 4000) {
  try {
    const resp = await deps.fetch(workerUrl(worker, "/prompt"), {
      signal: AbortSignal.timeout(timeoutMs),
    });
    if (!resp.ok) return { online: false };
    return parseProbeBody(await resp.json());
  } catch {
    return { online: false };
  }
}
