/* HTML template builders (pure string functions) + DOM appliers.
 *
 * Counterpart of the reference's web/ui.js + sidebarRenderer.js. The
 * template builders are pure (worker card, widget blocks, banner) so
 * they are testable without a DOM; the thin `render*` appliers at the
 * bottom do the only innerHTML writes.
 */

"use strict";

import { escapeHtml } from "./urlUtils.js";
import { MAX_DIVIDER_OUTPUTS, VALUE_TYPES, findWidgetNodes } from "./widgets.js";

export function workerStatusParts(status) {
  const dotCls = status.online
    ? status.queueRemaining > 0 ? "busy" : "online"
    : status.launching ? "busy" : "offline";
  const statusText = status.online
    ? `online · queue ${status.queueRemaining}`
    : status.launching ? "launching…" : "offline";
  return { dotCls, statusText };
}

export function workerCardHtml(worker, status) {
  const { dotCls, statusText } = workerStatusParts(status || {});
  return `
      <div>
        <span class="dot ${dotCls}"></span>
        <strong>${escapeHtml(worker.name || worker.id)}</strong>
        <span class="meta">${escapeHtml(worker.type)} · ${escapeHtml(worker.host || "local")}:${worker.port}
          ${worker.tpu_chips?.length ? "· chips " + worker.tpu_chips.join(",") : ""}
          · ${statusText}</span>
      </div>
      <div class="controls">
        <label class="small toggle"><input type="checkbox" data-enable="${escapeHtml(worker.id)}"
          ${worker.enabled ? "checked" : ""}> on</label>
        ${worker.type === "local"
          ? `<button class="small" data-launch="${escapeHtml(worker.id)}">launch</button>
             <button class="small" data-stop="${escapeHtml(worker.id)}">stop</button>`
          : ""}
        <button class="small" data-log="${escapeHtml(worker.id)}">log</button>
        <button class="small" data-edit="${escapeHtml(worker.id)}">edit</button>
        <button class="small" data-delete="${escapeHtml(worker.id)}">✕</button>
      </div>`;
}

export function valueNodeHtml(nodeId, node, workers) {
  const overrides = node.inputs?.overrides || {};
  const typeOptions = VALUE_TYPES.map(
    (t) =>
      `<option ${t === (overrides._type || "STRING") ? "selected" : ""}>${t}</option>`
  ).join("");
  const workerRows = workers
    .map(
      (w, idx) => `<div class="row">
            <label style="width:140px">${escapeHtml(w.name || w.id)} (#${idx + 1})</label>
            <input type="text" data-dv-node="${escapeHtml(nodeId)}" data-dv-slot="${idx + 1}"
              value="${escapeHtml(overrides[String(idx + 1)] ?? "")}"
              placeholder="master value"></div>`
    )
    .join("");
  return `
        <div class="row"><strong>DistributedValue #${escapeHtml(nodeId)}</strong>
          <span class="meta">master value: ${escapeHtml(node.inputs?.value ?? "")}</span>
          <select data-dv-type="${escapeHtml(nodeId)}">${typeOptions}</select></div>
        ${workerRows ||
          '<div class="meta">no enabled workers — values apply per enabled worker</div>'}`;
}

export function dividerNodeHtml(nodeId, node) {
  const divideBy = Number(node.inputs?.divide_by ?? 2);
  return `
        <div class="row"><strong>${escapeHtml(node.class_type)} #${escapeHtml(nodeId)}</strong>
          <label>outputs <input type="number" min="1" max="${MAX_DIVIDER_OUTPUTS}"
            value="${divideBy}" data-divider-node="${escapeHtml(nodeId)}"
            style="width:60px"></label>
          <span class="meta" id="divider-used-${escapeHtml(nodeId)}">
            ${divideBy} of ${MAX_DIVIDER_OUTPUTS} outputs carry data</span></div>`;
}

/** Tokenizer-fidelity warning (round-3 verdict item 5; T5 added round
 * 5): shown when /distributed/system_info reports
 * clip_vocab_canonical=false and/or t5_vocab_canonical=false — the
 * committed stand-in vocab (CLIP) / fallback ids (T5) produce wrong
 * conditioning for real checkpoints until the exact assets are
 * installed. Returns "" when both are canonical or state unknown. */
export function vocabBannerHtml(info) {
  if (!info) return "";
  const clipBad = info.clip_vocab_canonical === false;
  const t5Bad = info.t5_vocab_canonical === false;
  if (!clipBad && !t5Bad) return "";
  const parts = [];
  if (clipBad) {
    parts.push(`<b>CLIP vocab is a stand-in:</b> real SD/SDXL checkpoints will
    produce wrong images. Run <code>python scripts/fetch_clip_vocab.py</code>
    on this host (or set <code>CDT_CLIP_VOCAB</code>) to install OpenAI's
    published table.`);
  }
  if (t5Bad) {
    parts.push(`<b>T5 vocab not configured:</b> Flux/SD3/WAN conditioning
    falls back to placeholder ids. Point <code>CDT_T5_SPM</code> at the
    model's sentencepiece vocab for real-checkpoint fidelity.`);
  }
  return `
    <span>${parts.join("<br>")}</span>
    <button class="small" id="vocab-banner-dismiss">dismiss</button>`;
}

/** Scheduler lane view (pure; app.js refreshScheduler applies it):
 * admission state, per-lane depth with per-tenant queue/deficit
 * breakdown, and the placement policy's current worker speed weights
 * (GET /distributed/scheduler/status shape). */
export function schedulerHtml(status) {
  if (!status || !status.admission) {
    return '<span class="meta">scheduler status unavailable</span>';
  }
  const adm = status.admission;
  const header =
    `state <b>${escapeHtml(adm.state)}</b> · ` +
    `active ${adm.active}/${adm.max_active} · queued ${adm.queued}`;
  const lanes = (adm.lanes || [])
    .map((lane) => {
      const tenants = Object.entries(lane.tenants || {})
        .map(
          ([tenant, info]) =>
            `${escapeHtml(tenant)}: ${info.queued} queued` +
            ` (deficit ${info.deficit})`
        )
        .join(" · ");
      return (
        `<div class="row"><strong>${escapeHtml(lane.name)}</strong>` +
        `<span class="meta">depth ${lane.depth}/${lane.max_depth}` +
        `${tenants ? " · " + tenants : ""}</span></div>`
      );
    })
    .join("");
  const weightEntries = Object.entries(status.worker_weights || {});
  const weights = weightEntries.length
    ? weightEntries
        .map(([worker, ratio]) => `${escapeHtml(worker)}=${ratio}x`)
        .join(", ")
    : "no samples yet";
  const tenantWeights = Object.entries(adm.tenant_weights || {})
    .map(([tenant, w]) => `${escapeHtml(tenant)}=${w}`)
    .join(", ");
  return (
    `<div class="row">${header}</div>${lanes}` +
    `<div class="row"><span class="meta">worker speed weights: ${weights}</span></div>` +
    (tenantWeights
      ? `<div class="row"><span class="meta">tenant weights: ${tenantWeights}</span></div>`
      : "")
  );
}

/** Parse the tile-pipeline + compile-cache series out of the
 * /distributed/metrics Prometheus text (pure; no DOM). Returns
 * { batches: {role: {bucket: n}}, inflight: {role: n},
 *   padded: {role: n}, cache: {hits, misses} }. */
export function parsePipelineMetrics(text) {
  const out = { batches: {}, inflight: {}, padded: {}, cache: {} };
  const line_re = /^(\w+)(?:\{([^}]*)\})?\s+(-?[\d.eE+]+)$/;
  const labels = (raw) => {
    const map = {};
    for (const part of (raw || "").split(",")) {
      const m = part.match(/^(\w+)="([^"]*)"$/);
      if (m) map[m[1]] = m[2];
    }
    return map;
  };
  for (const line of (text || "").split("\n")) {
    const m = line.trim().match(line_re);
    if (!m) continue;
    const [, name, rawLabels, value] = m;
    const lbl = labels(rawLabels);
    const num = Number(value);
    if (name === "cdt_pipeline_batches_total") {
      const role = lbl.role || "?";
      out.batches[role] = out.batches[role] || {};
      out.batches[role][lbl.bucket || "?"] = num;
    } else if (name === "cdt_pipeline_inflight") {
      out.inflight[lbl.role || "?"] = num;
    } else if (name === "cdt_pipeline_padded_tiles_total") {
      out.padded[lbl.role || "?"] = num;
    } else if (name === "cdt_jax_cache_hits") {
      out.cache.hits = num;
    } else if (name === "cdt_jax_cache_misses") {
      out.cache.misses = num;
    }
  }
  return out;
}

/** Tile-pipeline stage view (pure; app.js refreshPipeline applies it):
 * batched device dispatches per role/bucket, in-flight batches, pad
 * waste, and the persistent compile-cache hit/miss counters. */
export function pipelineHtml(stats) {
  if (!stats) return '<span class="meta">pipeline status unavailable</span>';
  const roles = Object.keys(stats.batches || {}).sort();
  if (!roles.length && stats.cache.hits === undefined) {
    return '<span class="meta">no pipeline activity yet</span>';
  }
  const rows = roles.map((role) => {
    const buckets = stats.batches[role] || {};
    const parts = Object.keys(buckets)
      .sort((a, b) => Number(a) - Number(b))
      .map((b) => `K=${escapeHtml(b)}: ${buckets[b]}`)
      .join(" · ");
    const inflight = stats.inflight?.[role] ?? 0;
    const padded = stats.padded?.[role] ?? 0;
    return (
      `<div class="row"><strong>${escapeHtml(role)}</strong>` +
      `<span class="meta">${parts || "no batches"} · in-flight ${inflight}` +
      `${padded ? ` · padded ${padded}` : ""}</span></div>`
    );
  });
  const cache = stats.cache || {};
  const cacheLine =
    cache.hits !== undefined || cache.misses !== undefined
      ? `<div class="row"><span class="meta">compile cache: ` +
        `${cache.hits ?? 0} hits / ${cache.misses ?? 0} misses</span></div>`
      : "";
  return rows.join("") + cacheLine;
}

/** Fleet observability card (pure; app.js refreshFleet applies it):
 * rollup line (workers / devices / tiles-per-second / inflight), the
 * per-worker drill-down from GET /distributed/fleet, and the SLO
 * alert strip from GET /distributed/alerts. Pushed `fleet_rollup` /
 * `alert_*` events refresh the same card between polls. */
export function fleetHtml(fleet, alerts) {
  if (!fleet) return '<span class="meta">fleet status unavailable</span>';
  if (fleet.enabled === false) {
    return '<span class="meta">fleet plane off — masters with CDT_FLEET=1 serve it</span>';
  }
  const roll = fleet.rollup || {};
  const header =
    `workers <b>${roll.workers ?? 0}</b> · devices ${roll.devices ?? 0}` +
    ` · ${Number(roll.tiles_per_s ?? 0).toFixed(2)} tiles/s` +
    ` (${Number(roll.tiles_per_chip_s ?? 0).toFixed(2)}/chip)` +
    ` · in-flight ${roll.inflight ?? 0}`;
  const active = new Set(
    (alerts && alerts.active) || roll.alerts_active || []
  );
  const alertLine = active.size
    ? `<div class="row"><strong class="alert">ALERT</strong>` +
      `<span class="meta">${[...active].map(escapeHtml).join(", ")} burning</span></div>`
    : '<div class="row"><span class="meta">SLOs: no alerts firing</span></div>';
  const workers = Object.entries(fleet.workers || {})
    .sort(([a], [b]) => a.localeCompare(b))
    .map(([id, w]) => {
      const snap = w.snapshot || {};
      const sample = (snap.stages || {}).sample || {};
      const p95 =
        sample.p95 == null ? "" : ` · sample p95 ${Number(sample.p95).toFixed(2)}s`;
      return (
        `<div class="row"><strong>${escapeHtml(id)}</strong>` +
        `<span class="meta">${Number(w.tiles_per_s ?? 0).toFixed(2)} tiles/s` +
        // snapshot fields are worker-supplied (unauthenticated RPC):
        // numeric coercion, never raw interpolation
        ` · ${Number(snap.devices) || 1} chip(s)${p95}` +
        ` · seen ${Number(w.seen_ago_s ?? 0).toFixed(0)}s ago</span></div>`
      );
    })
    .join("");
  const series = fleet.series || {};
  const seriesLine =
    series.count === undefined
      ? ""
      : `<div class="row"><span class="meta">retained series: ${series.count}` +
        `${series.overflows ? ` (${series.overflows} capped)` : ""}</span></div>`;
  return (
    `<div class="row">${header}</div>` + alertLine +
    (workers || '<div class="row"><span class="meta">no worker snapshots yet</span></div>') +
    seriesLine
  );
}

/** Usage card (pure; app.js refreshUsage applies it): per-tenant
 * chip-second attribution + the waste breakdown from
 * GET /distributed/usage; pushed `usage_rollup` events refresh the
 * same card between polls. */
export function usageHtml(usage) {
  if (!usage) return '<span class="meta">usage status unavailable</span>';
  if (usage.enabled === false) {
    return '<span class="meta">usage metering off — masters with CDT_USAGE=1 serve it</span>';
  }
  const roll = usage.rollup || usage; // route payload vs pushed event
  const totals = roll.totals || {};
  const waste = totals.waste_s || {};
  const wasteTotal = Object.values(waste).reduce(
    (a, v) => a + Number(v || 0), 0
  );
  const header =
    `<div class="row">chips burned <b>${Number(totals.chip_s ?? 0).toFixed(2)}s</b>` +
    ` · attributed ${Number(totals.attributed_s ?? 0).toFixed(2)}s` +
    ` · waste ${wasteTotal.toFixed(2)}s` +
    ` (${(Number(totals.waste_share ?? 0) * 100).toFixed(1)}% dispatch)` +
    ` · ${totals.dispatches ?? 0} dispatch(es)</div>`;
  const tenants = Object.entries(roll.tenants || {})
    .sort(([, a], [, b]) => Number(b.chip_s || 0) - Number(a.chip_s || 0))
    .slice(0, 8)
    .map(
      ([tenant, t]) =>
        `<div class="row"><strong>${escapeHtml(tenant)}</strong>` +
        `<span class="meta">${Number(t.chip_s ?? 0).toFixed(2)} chip-s` +
        ` (${(Number(t.chip_share ?? 0) * 100).toFixed(1)}%)` +
        ` · ${t.tiles ?? 0} tile(s)` +
        `${Number(t.waste_s ?? 0) ? ` · waste ${Number(t.waste_s).toFixed(2)}s` : ""}` +
        `</span></div>`
    )
    .join("");
  const wasteLine = Object.keys(waste).length
    ? `<div class="row"><span class="meta">waste: ` +
      Object.keys(waste)
        .sort()
        .map((r) => `${escapeHtml(r)} ${Number(waste[r]).toFixed(2)}s`)
        .join(" · ") +
      `</span></div>`
    : "";
  return (
    header +
    (tenants ||
      '<div class="row"><span class="meta">no attributed chip time yet</span></div>') +
    wasteLine
  );
}

/** Cache card (pure; app.js refreshCache applies it): tile result
 * cache tiers + hit rate from GET /distributed/cache; pushed
 * `cache_stats` events refresh the same card between polls. */
export function cacheHtml(stats) {
  if (!stats) return '<span class="meta">cache status unavailable</span>';
  if (stats.enabled === false) {
    return '<span class="meta">tile cache off — masters with CDT_CACHE=1 serve it</span>';
  }
  const mib = (n) => (Number(n ?? 0) / (1024 * 1024)).toFixed(1);
  const hits = Number(stats.hits ?? 0);
  const misses = Number(stats.misses ?? 0);
  const header =
    `<div class="row">hit rate <b>${(Number(stats.hit_rate ?? 0) * 100).toFixed(1)}%</b>` +
    ` · ${hits} hit(s) / ${misses} miss(es)` +
    ` · ${Number(stats.settled ?? 0)} tile(s) settled from cache</div>`;
  const tiers =
    `<div class="row"><span class="meta">ram ${Number(stats.ram_entries ?? 0)} entries` +
    ` / ${mib(stats.ram_bytes)} MiB` +
    (stats.disk_tier
      ? ` · disk ${mib(stats.disk_bytes)} MiB (${Number(stats.hits_disk ?? 0)} hit(s))`
      : " · disk tier off") +
    `</span></div>`;
  const churn =
    `<div class="row"><span class="meta">${Number(stats.puts ?? 0)} put(s)` +
    ` · ${Number(stats.evictions ?? 0)} eviction(s)` +
    `${Number(stats.corrupt ?? 0) ? ` · <b>${Number(stats.corrupt)} corrupt entr(ies) dropped</b>` : ""}` +
    `</span></div>`;
  return header + tiers + churn;
}

/** Profiling card (pure; app.js refreshProfiling applies it): the
 * transfer ledger's device/host split + host-tax ratio, plus the
 * jax.profiler capture state and retained trace index from
 * GET /distributed/profile. */
export function profilingHtml(info) {
  if (!info) return '<span class="meta">profiling status unavailable</span>';
  const secs = (ns) => (Number(ns ?? 0) / 1e9).toFixed(3);
  const mib = (n) => (Number(n ?? 0) / (1024 * 1024)).toFixed(1);
  const ledger = info.ledger;
  let ledgerLines;
  if (!ledger) {
    ledgerLines =
      '<div class="row"><span class="meta">transfer ledger off — CDT_PROFILING=0</span></div>';
  } else {
    const hostNs = Object.values(ledger.host_ns || {}).reduce(
      (a, v) => a + Number(v || 0), 0
    );
    const transfer = ledger.transfer || {};
    const h2d = transfer.h2d || {};
    const d2h = transfer.d2h || {};
    ledgerLines =
      `<div class="row">host tax <b>${(Number(ledger.host_tax ?? 0) * 100).toFixed(1)}%</b>` +
      ` · device ${secs(ledger.device_ns)}s` +
      ` · host ${secs(hostNs)}s` +
      ` · ${Number(ledger.tiles ?? 0)} tile(s)</div>` +
      `<div class="row"><span class="meta">h2d ${mib(h2d.bytes)} MiB (${Number(h2d.count ?? 0)})` +
      ` · d2h ${mib(d2h.bytes)} MiB (${Number(d2h.count ?? 0)})` +
      `${Number(ledger.eager_ns ?? 0) ? ` · eager ${secs(ledger.eager_ns)}s` : ""}` +
      `</span></div>`;
  }
  if (info.enabled === false) {
    return (
      ledgerLines +
      '<div class="row"><span class="meta">trace capture off — set CDT_PROFILE_DIR to enable</span></div>'
    );
  }
  const capture = info.capture || {};
  // the route serves active as {id, elapsed_s, ...}; older shapes a bare id
  const activeId = capture.active && (capture.active.id || capture.active);
  const captureLine = activeId
    ? `<div class="row"><strong>capturing</strong><span class="meta mono">${escapeHtml(activeId)}</span></div>`
    : '<div class="row"><span class="meta">no capture in flight</span></div>';
  const traces = (info.captures || [])
    .slice(0, 8)
    .map(
      (c) =>
        `<div class="row"><span class="meta mono">${escapeHtml(c.id || "")}` +
        ` · ${mib(c.bytes)} MiB</span></div>`
    )
    .join("");
  return (
    ledgerLines +
    captureLine +
    (traces ||
      '<div class="row"><span class="meta">no retained traces</span></div>')
  );
}

/** Incidents card (pure; app.js refreshIncidents applies it): the
 * newest-first bundle listing from GET /distributed/incidents plus
 * flight-recorder accounting; pushed `incident_captured` events
 * refresh the same card between polls. */
export function incidentsHtml(info) {
  if (!info) return '<span class="meta">incident status unavailable</span>';
  if (info.enabled === false) {
    return '<span class="meta">incident capture off — set CDT_INCIDENT_DIR to enable</span>';
  }
  const flight = info.flight || {};
  const dropped = flight.dropped || {};
  const retained = flight.retained || {};
  const flightLine =
    `<div class="row"><strong>flight</strong><span class="meta">` +
    `${Number(retained.events ?? 0)} event(s) + ` +
    `${Number(retained.spans ?? 0)} span(s) retained` +
    `${
      Number(dropped.events ?? 0) + Number(dropped.spans ?? 0)
        ? ` · ${Number(dropped.events ?? 0) + Number(dropped.spans ?? 0)} dropped`
        : ""
    }</span></div>`;
  const counters = (info.manager || {}).counters || {};
  const counterLine =
    `<div class="row"><span class="meta">captured ${counters.captured ?? 0}` +
    ` · debounced ${counters.debounced ?? 0}` +
    ` · rate-limited ${counters.rate_limited ?? 0}</span></div>`;
  const bundles = (info.incidents || [])
    .slice(0, 8)
    .map(
      (b) =>
        `<div class="row"><strong>${escapeHtml(b.trigger || "?")}</strong>` +
        `<span class="meta mono">${escapeHtml(b.id || "")}` +
        ` · ${(Number(b.bytes ?? 0) / 1024).toFixed(1)} KiB</span></div>`
    )
    .join("");
  return (
    flightLine +
    counterLine +
    (bundles ||
      '<div class="row"><span class="meta">no incident bundles captured</span></div>')
  );
}

/** Durable-control-plane card (pure; app.js refreshDurability applies
 * it): journal head + segment count, last snapshot lsn/age, the
 * post-recovery admission hold, and the last recovery's report — the
 * JSON served by GET /distributed/durability. */
export function durabilityHtml(info) {
  if (!info) return '<span class="meta">durability status unavailable</span>';
  if (!info.enabled) {
    return '<span class="meta">journaling off — set CDT_JOURNAL_DIR to enable</span>';
  }
  const journal = info.journal || {};
  const age =
    info.snapshot_age_seconds == null
      ? "never"
      : `${Number(info.snapshot_age_seconds).toFixed(1)}s ago`;
  const repl = info.replication || {};
  const role = info.role || "active";
  const roleMeta =
    role === "standby"
      ? `epoch ${info.epoch ?? 0} · lag ${repl.lag_records ?? "?"} record(s)` +
        (repl.lag_seconds == null
          ? ""
          : ` / ${Number(repl.lag_seconds).toFixed(1)}s`) +
        ` · ${repl.synced ? "synced" : "SYNCING"}`
      : `epoch ${info.epoch ?? 0} · ${repl.standbys ?? 0} standby(s)` +
        (repl.lost ? ` (${repl.lost} lost)` : "") +
        (info.failovers ? ` · ${info.failovers} failover(s)` : "");
  const rows = [
    `<div class="row"><strong>role</strong><span class="meta">` +
      `${escapeHtml(String(role))}${
        role === "deposed" ? " — a standby took the lease" : ""
      } · ${roleMeta}</span></div>`,
    `<div class="row"><strong>journal</strong><span class="meta">` +
      `lsn ${journal.next_lsn ?? "?"} · ${info.appends ?? 0} appends · ` +
      `${journal.closed_segments ?? 0} closed segment(s)` +
      `${journal.write_behind ? " · write-behind" : " · write-ahead"}</span></div>`,
    `<div class="row"><strong>snapshot</strong><span class="meta">` +
      `lsn ${info.last_snapshot_lsn ?? 0} · ${age} · ` +
      `every ${info.snapshot_every ?? "?"} appends</span></div>`,
  ];
  const rec = info.recovery || {};
  if (rec.performed) {
    rows.push(
      `<div class="row"><strong>last recovery</strong><span class="meta">` +
        `${rec.jobs_recovered ?? 0} job(s) · ` +
        `${rec.replayed_records ?? 0} record(s) replayed · ` +
        `${rec.tasks_requeued ?? 0} requeued · ` +
        `${rec.tasks_restored ?? 0} restored</span></div>`
    );
  }
  if (info.admission_held) {
    rows.push(
      `<div class="row"><span class="busy">admission PAUSED — waiting for a ` +
        `worker heartbeat after recovery</span></div>`
    );
  }
  return rows.join("");
}

/** Region card (pure; app.js refreshRegion applies it): the shard
 * map + per-endpoint health from GET /distributed/region, the lease
 * view (file or quorum with every peer's register), and the
 * autoscaler's latest decisions with their chip-second cost lines
 * from GET /distributed/autoscale. */
export function regionHtml(region, autoscale) {
  if (!region) return '<span class="meta">region status unavailable</span>';
  const rows = [];
  if (!region.enabled) {
    rows.push(
      '<div class="row"><span class="meta">unsharded — set CDT_SHARDS ' +
        "for a multi-master region</span></div>"
    );
  } else {
    const shards = (region.shards || {}).shards || {};
    for (const name of Object.keys(shards).sort()) {
      const shard = shards[name];
      const endpoints = (shard.endpoints || [])
        .map((e) => {
          const backoff = Number(e.backoff_remaining_s || 0);
          return (
            `${e.current ? "<b>" : ""}${escapeHtml(e.url)}` +
            `${e.current ? "</b>" : ""}` +
            (backoff > 0 ? ` (backoff ${backoff.toFixed(1)}s)` : "")
          );
        })
        .join(" · ");
      rows.push(
        `<div class="row"><strong>${escapeHtml(name)}</strong>` +
          `<span class="meta">epoch ${shard.epoch ?? "?"} · ` +
          `${endpoints}</span></div>`
      );
    }
  }
  const lease = region.lease;
  if (lease) {
    const peers = (lease.peers || [])
      .map((p) => {
        if (p.error) return `${escapeHtml(p.name)}:ERR`;
        const peerEpoch = (p.state || {}).epoch ?? "-";
        return `${escapeHtml(p.name)}:e${peerEpoch}`;
      })
      .join(" ");
    rows.push(
      `<div class="row"><strong>lease</strong><span class="meta">` +
        `${escapeHtml(lease.backend || "file")} · epoch ${lease.epoch ?? 0}` +
        (lease.quorum ? ` · quorum ${lease.quorum}` : "") +
        (peers ? ` · ${peers}` : "") +
        (region.deposed ? ' · <span class="busy">DEPOSED</span>' : "") +
        `</span></div>`
    );
  }
  if (autoscale && autoscale.enabled) {
    const bounds = autoscale.bounds || {};
    const last = (autoscale.decisions || []).slice(-3).reverse();
    const lines = last
      .map(
        (d) =>
          `<div class="row"><strong>${escapeHtml(d.action)}</strong>` +
          `<span class="meta">${escapeHtml(d.reason || "")} · ` +
          `util ${(Number(d.utilization ?? 0) * 100).toFixed(0)}% · ` +
          `${Number(d.demand_chip_s ?? 0).toFixed(1)}/` +
          `${Number(d.capacity_chip_s ?? 0).toFixed(1)} chip-s</span></div>`
      )
      .join("");
    rows.push(
      `<div class="row"><strong>autoscale</strong><span class="meta">` +
        `${autoscale.workers ?? 0} worker(s) / ${autoscale.chips ?? 0} ` +
        `chip(s) · bounds ${bounds.min ?? "?"}–${bounds.max ?? "?"} · target ` +
        `${(Number(autoscale.target_utilization ?? 0) * 100).toFixed(0)}%` +
        `</span></div>` + lines
    );
  } else {
    rows.push(
      '<div class="row"><span class="meta">autoscaler off — set ' +
        "CDT_AUTOSCALE=1 to enable</span></div>"
    );
  }
  return rows.join("");
}

/** Topology summary line (pure; app.js renderTopology applies it). */
export function topologyHtml(info) {
  const topo = info.topology || {};
  const chips = (topo.devices || [])
    .map((d) => `<span class="chip">${escapeHtml(d.platform)}:${d.id}</span>`)
    .join("");
  return (
    `platform <b>${escapeHtml(topo.platform)}</b> · ` +
    `${topo.local_device_count}/${topo.device_count} local chips · ` +
    `host ${escapeHtml(info.machine_id)}<br>${chips}`
  );
}

/** Master-detection block (reference web/masterDetection.js). */
export function networkInfoHtml(info, masterHost, autoCount) {
  return (
    `recommended master IP: <b>${escapeHtml(info.recommended)}</b> ` +
    `<button class="small" id="use-recommended-ip">use as master host</button>` +
    `<br>current master host: ${escapeHtml(masterHost || "(unset)")}` +
    `<br>candidates: ${(info.candidates || []).map(escapeHtml).join(", ")}` +
    (autoCount
      ? `<br>${autoCount} worker(s) auto-populated for spare chips`
      : "")
  );
}

/** Add/edit worker modal body (pure; app.js workerForm applies it and
 * attaches the save handler). */
export const WORKER_FORM_FIELDS = ["id", "name", "type", "host", "port", "extra_args"];

export function workerFormHtml(worker) {
  return (
    WORKER_FORM_FIELDS.map(
      (f) => `<div class="row"><label style="width:90px">${f}</label>
        <input type="text" id="wf-${f}" value="${escapeHtml(worker[f] ?? "")}"></div>`
    ).join("") +
    `<div class="row"><label style="width:90px">tpu_chips</label>
      <input type="text" id="wf-tpu_chips" value="${(worker.tpu_chips || []).join(",")}"></div>
     <div class="row"><button class="primary" id="wf-save">Save</button></div>`
  );
}

// ---------- DOM appliers (the only innerHTML writes) ----------

export function renderWorkers(container, config, workerStatus) {
  container.innerHTML = "";
  for (const worker of config?.workers || []) {
    const card = document.createElement("div");
    card.className = "worker-card";
    card.innerHTML = workerCardHtml(worker, workerStatus.get(worker.id) || {});
    container.appendChild(card);
  }
}

export function renderWorkflowNodes(container, prompt, workers) {
  if (!prompt) {
    container.classList.add("mono");
    container.textContent =
      "paste a workflow to configure per-worker values and batch dividers";
    return;
  }
  container.innerHTML = "";
  container.classList.remove("mono");
  const nodes = findWidgetNodes(prompt);
  for (const { nodeId, kind, node } of nodes) {
    const block = document.createElement("div");
    block.className = "node-widget";
    block.innerHTML =
      kind === "value"
        ? valueNodeHtml(nodeId, node, workers)
        : dividerNodeHtml(nodeId, node);
    container.appendChild(block);
  }
  if (!nodes.length) {
    container.classList.add("mono");
    container.textContent =
      "no DistributedValue / batch-divider nodes in this workflow";
  }
}

export function renderVocabBanner(container, info, dismissed, onDismiss) {
  const html = dismissed ? "" : vocabBannerHtml(info);
  container.innerHTML = html;
  container.classList.toggle("hidden", !html);
  const btn = container.querySelector("#vocab-banner-dismiss");
  if (btn) btn.addEventListener("click", onDismiss);
}
