/* Live event-stream consumer for GET /distributed/events.
 *
 * The push counterpart of the panel's adaptive status polling: while a
 * WebSocket to /distributed/events is open, the panel stops fast-poll
 * spinning and reacts to pushed health/watchdog/metric events instead;
 * on disconnect it falls back to the poll loop and retries with
 * backoff.
 *
 * Pure logic (reduceLiveStatus, eventLabel, nextRetryDelay) is
 * separated from the socket wiring (connectEvents) so the reduction is
 * testable without a browser, matching the modules/ convention.
 */

"use strict";

export const EVENT_TYPES = [
  "health_transition",
  "straggler_detected",
  "stall_detected",
  "speculative_requeue",
  "job_cancelled",
  "tile_quarantined",
  "shed",
  "brownout_level",
  "fleet_rollup",
  "usage_rollup",
  "cache_stats",
  "alert_fired",
  "alert_resolved",
  "incident_captured",
];

export const MAX_LIVE_EVENTS = 20;
export const RETRY_BASE_MS = 2000;
export const RETRY_MAX_MS = 30000;

/** One step of the live-status reduction: fold a decoded event into
 * {connected, breakers, events} (events = newest-first ring of
 * display-ready entries, capped at MAX_LIVE_EVENTS). */
export function reduceLiveStatus(prev, event) {
  const next = {
    connected: true,
    breakers: { ...(prev?.breakers || {}) },
    events: [...(prev?.events || [])],
    fleet: prev?.fleet || null,
    usage: prev?.usage || null,
    cache: prev?.cache || null,
    alerts: new Set(prev?.alerts || []),
  };
  if (event.type === "hello") {
    for (const [id, h] of Object.entries(event.data?.health || {})) {
      next.breakers[id] = h.state;
    }
    return next;
  }
  if (event.type === "health_transition") {
    next.breakers[event.data.worker_id] = event.data.to_state;
  }
  if (event.type === "fleet_rollup") {
    next.fleet = event.data; // latest rollup wins; the card re-renders
  }
  if (event.type === "usage_rollup") {
    next.usage = event.data; // latest attribution rollup wins
  }
  if (event.type === "cache_stats") {
    next.cache = event.data; // latest tile-cache snapshot wins
  }
  if (event.type === "alert_fired") next.alerts.add(event.data.slo);
  if (event.type === "alert_resolved") next.alerts.delete(event.data.slo);
  const label = eventLabel(event);
  if (label) {
    next.events.unshift({ ts: event.ts, label });
    next.events.length = Math.min(next.events.length, MAX_LIVE_EVENTS);
  }
  return next;
}

/** Human line for one stream event; null = not display-worthy
 * (metric deltas and span noise stay off the panel). */
export function eventLabel(event) {
  const d = event.data || {};
  switch (event.type) {
    case "health_transition":
      return `worker ${d.worker_id}: ${d.from_state} → ${d.to_state}`;
    case "straggler_detected":
      return `straggler: ${d.worker_id} (median ${Number(
        d.median_seconds
      ).toFixed(2)}s vs ${Number(d.global_median_seconds).toFixed(2)}s)`;
    case "stall_detected":
      return `stall: job ${d.job_id} quiet ${Number(d.quiet_seconds).toFixed(
        1
      )}s (${d.in_flight} in flight)`;
    case "speculative_requeue":
      return `speculative re-dispatch: job ${d.job_id} tiles [${(
        d.task_ids || []
      ).join(", ")}]`;
    case "job_cancelled":
      return `cancelled: job ${d.job_id} (${d.reason}) — refunded ${
        (d.pending_refunded || 0) + (d.in_flight_refunded || 0)
      } tile(s)`;
    case "tile_quarantined":
      return `poison: job ${d.job_id} tile(s) [${(d.task_ids || []).join(
        ", "
      )}] quarantined`;
    case "shed":
      return `brownout: lane ${d.lane} shed (level ${d.level})`;
    case "brownout_level":
      return `brownout level ${d.direction === "up" ? "↑" : "↓"} ${d.level}`;
    case "alert_fired":
      return `SLO alert: ${d.slo} burning error budget`;
    case "alert_resolved":
      return `SLO alert resolved: ${d.slo}${
        d.active_seconds == null
          ? ""
          : ` (open ${Number(d.active_seconds).toFixed(0)}s)`
      }`;
    case "incident_captured":
      return `incident bundle captured: ${d.id} (${d.trigger}${
        d.key ? `:${d.key}` : ""
      })`;
    case "fleet_rollup":
      return null; // rendered as the fleet card, not an event line
    case "usage_rollup":
      return null; // rendered as the usage card, not an event line
    case "cache_stats":
      return null; // rendered as the cache card, not an event line
    case "events_dropped":
      return `stream dropped ${d.count} event(s) (slow consumer)`;
    default:
      return null;
  }
}

/** Exponential reconnect backoff, capped. */
export function nextRetryDelay(attempt, base = RETRY_BASE_MS, max = RETRY_MAX_MS) {
  return Math.min(max, base * 2 ** Math.max(0, attempt));
}

/** Open (and keep reopening) the event stream. `handlers`:
 *   onEvent(event)  — each decoded event (including hello)
 *   onStatus(bool)  — connected / disconnected transitions
 * `WebSocketImpl` is injectable for tests. Returns a close function. */
export function connectEvents(
  { url, onEvent, onStatus, WebSocketImpl, setTimeoutImpl } = {}
) {
  const WS = WebSocketImpl || globalThis.WebSocket;
  const later = setTimeoutImpl || ((fn, ms) => setTimeout(fn, ms));
  let closed = false;
  let attempt = 0;
  let socket = null;

  function open() {
    if (closed || !WS) return;
    socket = new WS(url);
    socket.onopen = () => {
      attempt = 0;
      if (onStatus) onStatus(true);
    };
    socket.onmessage = (msg) => {
      let event;
      try {
        event = JSON.parse(msg.data);
      } catch {
        return; // tolerate a malformed frame; the stream continues
      }
      if (onEvent) onEvent(event);
    };
    socket.onclose = () => {
      if (onStatus) onStatus(false);
      if (!closed) later(open, nextRetryDelay(attempt++));
    };
    socket.onerror = () => {};
  }

  open();
  return () => {
    closed = true;
    if (socket) socket.close();
  };
}
