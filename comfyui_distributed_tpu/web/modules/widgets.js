/* Workflow-node widget logic (pure functions).
 *
 * Counterpart of the reference's web/distributedValue.js +
 * web/image_batch_divider.js widget math: per-worker override
 * collection, divider output clamping, workflow JSON patching, and
 * new-worker defaults (port/chip auto-pick, reference
 * web/workerSettings.js).
 */

"use strict";

export const VALUE_TYPES = ["STRING", "INT", "FLOAT", "BOOLEAN"];
export const MAX_DIVIDER_OUTPUTS = 10;

/** Parse a pasted workflow (optionally wrapped in {prompt: ...});
 * null when the JSON is invalid. */
export function parseWorkflowText(text) {
  try {
    const parsed = JSON.parse(text);
    return parsed.prompt || parsed;
  } catch {
    return null;
  }
}

/** Merge an inputs patch into one node of the workflow text, returning
 * the re-serialized text (null when the text/nodeId is invalid). */
export function patchWorkflowText(text, nodeId, patch) {
  let parsed;
  try {
    parsed = JSON.parse(text);
  } catch {
    return null;
  }
  const prompt = parsed.prompt || parsed;
  if (!prompt[nodeId]) return null;
  prompt[nodeId].inputs = { ...prompt[nodeId].inputs, ...patch };
  return JSON.stringify(parsed, null, 2);
}

/** Assemble a DistributedValue overrides map from widget rows:
 * [{slot, value}] -> {"_type": t, "1": v, ...}, empty values omitted
 * (reference web/distributedValue.js collection; slots are 1-indexed
 * by enabled-worker position). */
export function collectOverrides(type, rows) {
  const overrides = { _type: VALUE_TYPES.includes(type) ? type : "STRING" };
  for (const { slot, value } of rows) {
    if (value !== "" && value !== undefined && value !== null) {
      overrides[String(slot)] = value;
    }
  }
  return overrides;
}

/** Clamp a divider output count to [1, MAX_DIVIDER_OUTPUTS]
 * (reference web/image_batch_divider.js divide_by widget). */
export function clampDividerParts(value) {
  return Math.max(1, Math.min(Number(value) || 1, MAX_DIVIDER_OUTPUTS));
}

/** Defaults for a new worker: next free port above the current
 * maximum (>= 8189) and the first unclaimed TPU chip (reference
 * web/workerSettings.js CUDA/port auto-pick). */
export function nextWorkerDefaults(workers, topoChips) {
  workers = workers || [];
  const ports = workers.map((w) => Number(w.port)).filter(Boolean);
  const port = Math.max(8188, ...ports) + 1;
  const usedChips = new Set(workers.flatMap((w) => w.tpu_chips || []));
  const chips = (topoChips || []).filter((c) => !usedChips.has(c));
  return { port, chip: chips.length ? [chips[0]] : [] };
}

/** Default object for a brand-new worker (pure; the caller supplies
 * the id suffix so tests stay deterministic). */
export function newWorkerTemplate(workers, topoChips, idSuffix) {
  const d = nextWorkerDefaults(workers, topoChips);
  return {
    id: `w${idSuffix}`,
    name: "",
    type: "local",
    host: "127.0.0.1",
    port: d.port,
    tpu_chips: d.chip,
    enabled: true,
    extra_args: "",
  };
}

/** Parse a comma-separated chip list from the worker form. */
export function parseChipList(text) {
  return String(text || "")
    .split(",")
    .filter((s) => s.trim() !== "")
    .map((s) => Number(s.trim()))
    .filter((n) => Number.isFinite(n));
}

/** Scan a workflow for panel-configurable nodes. Returns
 * [{nodeId, kind: "value"|"divider", node}] in stable key order. */
export function findWidgetNodes(prompt) {
  const found = [];
  for (const [nodeId, node] of Object.entries(prompt || {})) {
    if (node.class_type === "DistributedValue") {
      found.push({ nodeId, kind: "value", node });
    } else if (
      node.class_type === "ImageBatchDivider" ||
      node.class_type === "AudioBatchDivider"
    ) {
      found.push({ nodeId, kind: "divider", node });
    }
  }
  return found;
}
