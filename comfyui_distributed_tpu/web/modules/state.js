/* Panel state + the worker-status reduction.
 *
 * Counterpart of the reference's web/stateManager.js +
 * workerLifecycle.js state machine. The status transition on each
 * probe result is a pure function (`reduceWorkerStatus`) so the
 * launch-grace / clear-launching flow is testable without timers or a
 * DOM.
 */

"use strict";

export const POLL_ACTIVE_MS = 1000;
export const POLL_IDLE_MS = 5000;
export const POLL_STREAM_IDLE_MS = 15000;
export const LAUNCH_GRACE_MS = 90000;

export const state = {
  config: null,
  workerStatus: new Map(), // id -> {online, queueRemaining, launching, launchingSince}
  pollTimer: null,
  logTimer: null,
  nodesTimer: null,
  anythingBusy: false,
  topoChips: [],
  vocabBannerDismissed: false,
  // live /distributed/events stream: while connected, pushed events
  // replace the fast poll cadence (pollDelay below)
  eventsConnected: false,
  liveStatus: { connected: false, breakers: {}, events: [] },
};

/** Poll cadence selection. Busy keeps the 1 s fast poll either way —
 * queue depth / progress are poll-only signals the stream does not
 * carry. What the stream replaces is the IDLE heartbeat: health
 * transitions and watchdog verdicts are pushed (and trigger an
 * immediate refresh), so an idle panel with a live stream polls at a
 * much slower keepalive cadence. */
export function pollDelay(anythingBusy, eventsConnected) {
  if (anythingBusy) return POLL_ACTIVE_MS;
  return eventsConnected ? POLL_STREAM_IDLE_MS : POLL_IDLE_MS;
}

/** One step of the per-worker status machine.
 *
 * Returns { status, clearLaunching }: the next status record, and
 * whether the server's persisted 'launching' marker should be cleared
 * (the worker came up inside its grace window — reference
 * web/workerLifecycle.js launch grace + clear_launching call).
 */
export function reduceWorkerStatus(prev, probe, now, graceMs = LAUNCH_GRACE_MS) {
  prev = prev || {};
  const inGrace =
    !!prev.launchingSince && now - prev.launchingSince < graceMs;
  const clearLaunching = !!(probe.online && prev.launchingSince);
  const status = {
    ...prev,
    ...probe,
    launchingSince: clearLaunching ? null : prev.launchingSince,
    launching: inGrace && !probe.online,
  };
  return { status, clearLaunching };
}

/** Whether any participant has work queued (drives the 1s/5s adaptive
 * poll cadence, reference web/main.js status-poll lifecycle). */
export function computeAnythingBusy(masterQueueRemaining, statuses) {
  if (masterQueueRemaining > 0) return true;
  for (const s of statuses) {
    if (s && s.online && s.queueRemaining > 0) return true;
  }
  return false;
}

export function enabledWorkers(config) {
  return ((config || {}).workers || []).filter((w) => w.enabled);
}

/** Drop status entries for workers no longer in the config — a
 * deleted worker's stale {online, queueRemaining} record is never
 * re-probed and would otherwise pin the adaptive poll at its fast
 * cadence forever. */
export function pruneWorkerStatus(statusMap, workers) {
  const known = new Set((workers || []).map((w) => w.id));
  for (const id of [...statusMap.keys()]) {
    if (!known.has(id)) statusMap.delete(id);
  }
}
