/* URL + HTML-escaping helpers (pure functions).
 *
 * Counterpart of the reference's web/urlUtils.js: scheme heuristics
 * (https for cloud workers and port 443), host/port assembly, and the
 * escaping used by every innerHTML template in the panel.
 */

"use strict";

export function workerUrl(worker, path) {
  const scheme =
    worker.type === "cloud" || Number(worker.port) === 443 ? "https" : "http";
  const host = worker.host || "127.0.0.1";
  const port = worker.port ? `:${worker.port}` : "";
  return `${scheme}://${host}${port}${path}`;
}

export function escapeHtml(value) {
  return String(value ?? "").replace(/[&<>"']/g, (c) => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  })[c]);
}
