"""Snapshot compaction for the control-plane journal.

A snapshot is the shadow state (``durability/state.py``) serialized as
one atomically-renamed JSON file, ``snapshot-<last_lsn>.json``. It is
written through ``utils.fsio.atomic_write_json`` (tmp + fsync + rename
+ directory fsync), so a crash mid-snapshot leaves the previous
snapshot intact and at worst a stray tmp file.

Compaction policy: after a snapshot at lsn L lands, every CLOSED
journal segment whose records are all ≤ L is superseded and pruned,
and older snapshots are deleted. Recovery therefore reads exactly one
snapshot plus the WAL tail (records with lsn > L).

The snapshot also carries the scheduler's exported aggregates (tenant
DRR deficits, tenant weights, placement speed EWMAs) sampled at write
time — those mutate outside the job-store journal seam, so their
durability granularity is the snapshot cadence, not per-mutation
(documented trade-off: losing sub-cadence EWMA updates re-learns worker
speeds in seconds and cannot affect output correctness).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional

from ..utils.fsio import atomic_write_json, fsync_dir
from ..utils.logging import log
from .state import SNAPSHOT_VERSION, SnapshotVersionMismatch

SNAPSHOT_PREFIX = "snapshot-"
SNAPSHOT_SUFFIX = ".json"
_SNAPSHOT_RE = re.compile(
    re.escape(SNAPSHOT_PREFIX) + r"(\d+)" + re.escape(SNAPSHOT_SUFFIX) + r"$"
)


def snapshot_path(directory: str, last_lsn: int) -> str:
    return os.path.join(
        directory, f"{SNAPSHOT_PREFIX}{last_lsn:012d}{SNAPSHOT_SUFFIX}"
    )


def list_snapshots(directory: str) -> list[tuple[int, str]]:
    """(last_lsn, path) pairs, oldest first. Sorted numerically —
    never readdir order."""
    out: list[tuple[int, str]] = []
    try:
        names = sorted(os.listdir(directory))
    except FileNotFoundError:
        return out
    for name in names:
        match = _SNAPSHOT_RE.match(name)
        if match:
            out.append((int(match.group(1)), os.path.join(directory, name)))
    return sorted(out)


def prune_snapshots(directory: str, keep_path: str, upto_lsn: int) -> None:
    """Delete snapshots superseded by the one at ``keep_path``."""
    for lsn, old_path in list_snapshots(directory):
        if old_path != keep_path and lsn <= upto_lsn:
            try:
                os.remove(old_path)
            except OSError as exc:
                log(f"snapshot: prune of {old_path} failed: {exc}")
    fsync_dir(directory)


def write_snapshot(directory: str, state: dict[str, Any]) -> str:
    """Serialize ``state`` atomically; prunes superseded snapshots.
    Returns the written path."""
    last_lsn = int(state.get("last_lsn", 0))
    path = snapshot_path(directory, last_lsn)
    atomic_write_json(path, state, indent=None, sort_keys=True)
    prune_snapshots(directory, path, last_lsn)
    return path


def load_latest_snapshot(directory: str) -> Optional[dict[str, Any]]:
    """The newest snapshot's state, or None when the directory holds
    none (first boot / journal-only recovery). A version mismatch
    raises ``SnapshotVersionMismatch`` loudly — recovery must never
    guess at an incompatible schema."""
    snapshots = list_snapshots(directory)
    if not snapshots:
        return None
    _lsn, path = snapshots[-1]
    with open(path, "r", encoding="utf-8") as fh:
        state = json.load(fh)
    version = state.get("version")
    if version != SNAPSHOT_VERSION:
        raise SnapshotVersionMismatch(
            f"{path}: snapshot version {version!r} != supported "
            f"{SNAPSHOT_VERSION}; refusing to reinterpret acknowledged state"
        )
    return state
