"""Epoch-numbered master lease: who is allowed to write the journal.

The warm-standby failover protocol (docs/durability.md §failover)
needs two things a plain "is the master up?" probe can't give:

- **arbitration** — exactly one process may append to the journal at a
  time, decided by a medium both contenders share (the journal
  directory itself: ``lease.json``, written atomically via
  utils/fsio);
- **fencing** — a deposed master must be *unable* to keep mutating
  acknowledged state, even if its process is still alive and its
  clock is wrong. The lease carries a monotonically increasing
  **epoch**; every takeover bumps it, and the write-ahead seam
  (``DurabilityManager.record``) checks ``Lease.held()`` before every
  append — a holder whose epoch no longer matches the file raises
  ``FencedOut`` instead of journaling (the fencing-token pattern).

Acquisition policy:

- ``acquire()`` — takes a free or *expired* lease (epoch+1); raises
  ``LeaseHeld`` while another owner's lease is live. This is the
  standby's promotion path: it can only take over once the active
  master has missed renewals for a full TTL.
- ``acquire(force=True)`` — takes the lease unconditionally (epoch+1).
  This is the *restarting master's* path: a process that owns the
  journal directory and is booting on it is the newest claimant by
  construction; waiting out the dead incarnation's TTL would just add
  downtime. The deposed holder (if somehow still alive) is fenced by
  the epoch bump on its next ``held()`` re-read.

``held()`` is the hot-path check: it trusts the local clock for
``ttl/4`` after the last successful file verification, then re-reads
the file — so a zombie keeps serving for at most ``ttl/4`` beyond the
takeover before its journal appends start raising, and the steady
state costs one small file read every ``ttl/4`` seconds.

Split-brain analysis lives in docs/durability.md: the lease file is
the arbitration medium, so fencing is exactly as strong as the
filesystem's rename atomicity plus ``flock(2)`` (local fs / NFSv4 both
qualify) — every acquire/renew/release read-modify-write cycle
serializes under a flocked sidecar file (``lease.lock``) so two
claimants racing an expired lease can never both take the same epoch,
and a transient read error (EIO/ESTALE) is classified as
*indeterminate*, never as a takeover — one NFS blip cannot depose a
healthy active. Two
masters pointed at *different* directories are two clusters, not a
split brain — the replication stream carries the active epoch so a
remote standby can at least detect the misconfiguration.
"""

from __future__ import annotations

import contextlib
import dataclasses
import fcntl
import json
import os
import time
from typing import Any, Callable, Iterator, Optional

from ..utils.constants import LEASE_TTL_SECONDS
from ..utils.fsio import atomic_write_json
from ..utils.logging import log

LEASE_FILENAME = "lease.json"
CLAIM_LOCK_FILENAME = "lease.lock"


class LeaseHeld(Exception):
    """Another owner's lease is still live; the caller may not take it."""


class LeaseLost(Exception):
    """We no longer own the lease (a newer epoch exists): the caller
    has been deposed and must stop acting as the active master."""


class FencedOut(Exception):
    """A journal append was attempted after losing the lease. The
    mutation was NOT journaled and must not be acknowledged."""


@dataclasses.dataclass
class LeaseState:
    epoch: int
    owner: str
    expires_at: float
    renewed_at: float

    def as_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, data: dict[str, Any]) -> "LeaseState":
        return cls(
            epoch=int(data["epoch"]),
            owner=str(data["owner"]),
            expires_at=float(data["expires_at"]),
            renewed_at=float(data.get("renewed_at", 0.0)),
        )


def lease_path(directory: str) -> str:
    return os.path.join(directory, LEASE_FILENAME)


@contextlib.contextmanager
def _claim_mutex(directory: str, owner: str, ttl: float) -> Iterator[None]:
    """Serialize lease.json read-modify-write cycles across processes.

    ``atomic_write_json`` makes each *write* atomic, but acquire/renew/
    release are read-THEN-write: without mutual exclusion two claimants
    racing an expired lease can both read epoch N and both write N+1 —
    the same-epoch split brain the lease exists to prevent. The mutex
    is ``flock(2)`` on a persistent sidecar file: kernel-arbitrated
    (per open-file-description, so it excludes threads and processes
    alike), and a holder that dies releases the lock with its fd —
    there is no stale-lock breaking, and therefore no break/recreate
    race two contenders could use to both enter the cycle. NFSv4 maps
    flock onto leased byte-range locks; the cycle it guards lasts
    milliseconds, so the 10ms contention poll (bounded by one TTL)
    resolves immediately in practice."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, CLAIM_LOCK_FILENAME)
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        deadline = time.monotonic() + max(1.0, float(ttl))
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise LeaseHeld(
                        f"lease claim lock busy for over {ttl:.1f}s: {path}"
                    )
                time.sleep(0.01)
        with contextlib.suppress(OSError):
            os.ftruncate(fd, 0)
            os.write(fd, owner.encode("utf-8", "replace"))
        yield
    finally:
        with contextlib.suppress(OSError):
            fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def read_lease(
    directory: str, strict: bool = False
) -> Optional[LeaseState]:
    """Parse the directory's lease file; None when absent or corrupt
    (a corrupt lease reads as free — arbitration falls back to the
    epoch bump, which stays monotonic because a fresh acquire still
    reads whatever epoch digits survive). With ``strict=True`` a
    *transient I/O error* (EIO, ESTALE, ...) raises instead of reading
    as free: holders use this so one NFS blip is never mistaken for a
    takeover — absent and unreadable are different verdicts."""
    try:
        with open(lease_path(directory), encoding="utf-8") as fh:
            return LeaseState.from_json(json.load(fh))
    except (FileNotFoundError, ValueError, KeyError, TypeError):
        return None
    except OSError:
        if strict:
            raise
        return None


class Lease:
    """One contender's handle on the directory's lease file.

    Not thread-safe by design: acquire/renew run on one owner thread
    (the server's renewal task or the standby's promotion path);
    ``held()`` is safe to call from the journal seam because it only
    reads."""

    def __init__(
        self,
        directory: str,
        owner: str,
        ttl: Optional[float] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        self.directory = directory
        self.owner = owner
        self.ttl = float(ttl) if ttl is not None else LEASE_TTL_SECONDS
        self.clock = clock
        self._epoch = 0  # epoch we hold; 0 = not holding
        self._lost = False
        self._last_verified = 0.0

    # --- state ------------------------------------------------------------

    @property
    def epoch(self) -> int:
        """The epoch this handle holds (0 when not holding)."""
        return 0 if self._lost else self._epoch

    def read(self, strict: bool = False) -> Optional[LeaseState]:
        return read_lease(self.directory, strict=strict)

    # --- acquisition ------------------------------------------------------

    def acquire(self, force: bool = False) -> int:
        """Take the lease (epoch+1) and return the new epoch. Without
        ``force``, a live lease owned by someone else raises
        ``LeaseHeld`` — the standby promotion gate. With ``force`` the
        newest claimant always wins (restarting-master policy); the
        previous holder is fenced by the epoch bump. The whole
        read-check-write cycle runs under the directory's claim mutex
        so racing claimants serialize: exactly one takes epoch N+1,
        the rest re-read its fresh lease and raise ``LeaseHeld``."""
        with _claim_mutex(self.directory, self.owner, self.ttl):
            now = self.clock()
            current = self.read(strict=True)
            if (
                not force
                and current is not None
                and current.owner != self.owner
                and current.expires_at > now
            ):
                raise LeaseHeld(
                    f"lease held by {current.owner!r} "
                    f"(epoch {current.epoch}) for another "
                    f"{current.expires_at - now:.1f}s"
                )
            epoch = (current.epoch if current is not None else 0) + 1
            self._write(LeaseState(epoch, self.owner, now + self.ttl, now))
            self._epoch = epoch
            self._lost = False
            self._last_verified = now
        if current is not None and current.owner != self.owner:
            log(
                f"lease: {self.owner} took over from {current.owner} "
                f"(epoch {current.epoch} -> {epoch}"
                f"{', forced' if force and current.expires_at > now else ''})"
            )
        return epoch

    def renew(self) -> None:
        """Extend the expiry. Raises ``LeaseLost`` when the file no
        longer carries our (epoch, owner) — someone took over; the
        caller must demote immediately. A *transient* read error
        (strict read) propagates as OSError instead: the renewal loop
        retries on those — one NFS blip must never read as a takeover
        and permanently depose a healthy active."""
        if self._epoch <= 0 or self._lost:
            raise LeaseLost("lease was never acquired (or already lost)")
        with _claim_mutex(self.directory, self.owner, self.ttl):
            current = self.read(strict=True)
            now = self.clock()
            if (
                current is None
                or current.epoch != self._epoch
                or current.owner != self.owner
            ):
                self._lost = True
                raise LeaseLost(
                    f"lease superseded: file carries "
                    f"{(current.owner, current.epoch) if current else None}, "
                    f"we held epoch {self._epoch}"
                )
            self._write(LeaseState(self._epoch, self.owner, now + self.ttl, now))
            self._last_verified = now

    def release(self) -> None:
        """Clean shutdown: expire our lease NOW (same epoch) so a
        standby or restart can take over without waiting out the TTL.
        A no-op if we don't hold it anymore."""
        if self._epoch <= 0 or self._lost:
            return
        with _claim_mutex(self.directory, self.owner, self.ttl):
            current = self.read()
            if (
                current is None
                or current.epoch != self._epoch
                or current.owner != self.owner
            ):
                return
            now = self.clock()
            self._write(LeaseState(self._epoch, self.owner, now, now))
            self._epoch = 0

    # --- the fencing check (journal seam) ---------------------------------

    def held(self, verify: bool = False) -> bool:
        """Do we still own the lease? Trusts the local clock within
        ``ttl/4`` of the last successful file verification; beyond that
        (or with ``verify=True``) re-reads the file and compares epochs
        — the bounded-staleness fencing check ``DurabilityManager``
        runs before every journal append."""
        if self._lost or self._epoch <= 0:
            return False
        now = self.clock()
        if not verify and now - self._last_verified <= self.ttl / 4:
            return True
        try:
            current = self.read(strict=True)
        except OSError:
            # Transient I/O blip: neither confirms nor denies a
            # takeover, so keep the cached verdict WITHOUT advancing
            # the trust window — a real takeover is caught on the next
            # successful re-read, and a genuinely dead disk fails the
            # journal append itself (nothing gets acknowledged).
            return True
        if (
            current is None
            or current.epoch != self._epoch
            or current.owner != self.owner
        ):
            self._lost = True
            return False
        self._last_verified = now
        return True

    # --- internals --------------------------------------------------------

    def _write(self, state: LeaseState) -> None:
        atomic_write_json(lease_path(self.directory), state.as_json())

    def status(self) -> dict[str, Any]:
        current = self.read()
        return {
            "owner": self.owner,
            "epoch": self.epoch,
            "ttl_seconds": self.ttl,
            "file": (current.as_json() if current is not None else None),
        }
