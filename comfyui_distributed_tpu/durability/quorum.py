"""Quorum lease: master arbitration without a shared filesystem.

The flock-sidecar lease (lease.py) arbitrates through the journal
directory itself, which works exactly as far as the filesystem is
shared and its rename/flock semantics hold. Region mode removes that
dependency: ``QuorumLease`` presents the *same* interface (acquire /
renew / release / held / epoch) but decides ownership by majority
agreement across N independent **lease peers** — single-register
stores that accept or reject ``(holder, epoch, ttl)`` proposals under
a compare-and-swap rule. Epoch fencing, the indeterminate-read
semantics, and the ``FencedOut`` append gate all carry over unchanged:
``DurabilityManager`` only ever calls ``lease.held()`` and reads
``lease.epoch``, so the two backends are drop-in interchangeable.

Protocol (a classical majority-register lease, not full Paxos — the
register per peer is the stable storage, the epoch is the ballot):

- **peer accept rule** (evaluated atomically per peer): a proposal
  ``(epoch, owner)`` is accepted iff the peer's stored epoch is lower,
  OR equal with the same owner (renew/release). Same epoch + different
  owner is rejected — two claimants racing the same epoch can never
  both assemble a majority, because any two majorities intersect.
- **acquire**: read all peers; a majority of *determinate* responses
  is required (fewer raises ``OSError`` — indeterminate, mirroring the
  file lease's strict read). The max-epoch view decides liveness
  (``LeaseHeld`` when a foreign lease is live and ``force`` is off);
  then ``epoch = view.epoch + 1`` is proposed everywhere and the
  acquire succeeds only on a majority of accepts. A partial write
  (proposer or peer crash mid-acquire) burns the epoch but corrupts
  nothing: the next claimant reads the burned epoch from the surviving
  peers and goes higher — epochs stay monotonic.
- **renew**: re-propose our own epoch with a fresh expiry. A lagging
  peer (missed the acquire, or restarted empty) catches up here — its
  stored epoch is lower, so it accepts. A rejection revealing a higher
  epoch is ``LeaseLost``; anything short of a majority with no higher
  epoch seen is ``OSError`` (transient — the renewal loop retries; a
  blip must never read as a takeover).
- **held()**: trusts the local clock for ``ttl/4`` after the last
  verified read, then re-reads the cluster. Fewer than a majority of
  determinate responses keeps the cached verdict WITHOUT advancing the
  trust window (one unreachable peer set cannot depose a healthy
  active); a majority view showing a higher epoch is a takeover —
  fenced. Majority intersection makes this sound: any majority of
  reads overlaps the usurper's write majority in at least one peer.

Peers are duck-typed (``read()`` + ``propose(state)``), which is the
external-KV shim seam: anything that can CAS a small JSON record — an
etcd key, a cloud KV entry, a tiny HTTP register service — can serve.
In-repo peers: ``FileLeasePeer`` (one register directory per peer,
flock-serialized, modelling one node-local disk each) and
``MemoryLeasePeer`` (in-process, with fault hooks for chaos).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, NamedTuple, Optional

from ..utils.constants import LEASE_TTL_SECONDS
from ..utils.fsio import atomic_write_json
from ..utils.logging import debug_log, log
from .lease import LeaseHeld, LeaseLost, LeaseState

PEER_REGISTER_FILENAME = "peer_register.json"
PEER_LOCK_FILENAME = "peer_register.lock"


class LeasePeerError(Exception):
    """One peer neither confirmed nor denied (I/O trouble, crash
    injection): an *indeterminate* response. Counted toward neither
    accepts nor rejects."""


class PeerDecision(NamedTuple):
    accepted: bool
    state: Optional[LeaseState]  # the peer's post-decision register


class MemoryLeasePeer:
    """In-process register peer: the unit-test and chaos-suite medium.

    Fault hooks (all one-shot counters or latches, set by the chaos
    scenarios):

    - ``fail_reads`` / ``fail_writes`` — the next N calls raise
      ``LeasePeerError`` (indeterminate);
    - ``crashed`` — every call raises until cleared (a dead peer);
    - ``crash_next_propose`` — ``"before"`` loses the proposal then
      raises (write never applied), ``"after"`` applies it then raises
      (ack lost): the two halves of a mid-acquire peer crash.
    """

    def __init__(self, name: str = "peer") -> None:
        self.name = name
        self._lock = threading.Lock()
        self._state: Optional[LeaseState] = None
        self.fail_reads = 0
        self.fail_writes = 0
        self.crashed = False
        self.crash_next_propose: Optional[str] = None

    def read(self) -> Optional[LeaseState]:
        with self._lock:
            if self.crashed:
                raise LeasePeerError(f"peer {self.name} is down")
            if self.fail_reads > 0:
                self.fail_reads -= 1
                raise LeasePeerError(f"peer {self.name} read blip")
            return self._state

    def propose(self, state: LeaseState) -> PeerDecision:
        with self._lock:
            if self.crashed:
                raise LeasePeerError(f"peer {self.name} is down")
            if self.fail_writes > 0:
                self.fail_writes -= 1
                raise LeasePeerError(f"peer {self.name} write blip")
            if self.crash_next_propose == "before":
                self.crash_next_propose = None
                raise LeasePeerError(
                    f"peer {self.name} crashed before applying"
                )
            decision = self._decide(state)
            if self.crash_next_propose == "after":
                self.crash_next_propose = None
                raise LeasePeerError(
                    f"peer {self.name} crashed after applying (ack lost)"
                )
            return decision

    def _decide(self, state: LeaseState) -> PeerDecision:
        cur = self._state
        if (
            cur is None
            or state.epoch > cur.epoch
            or (state.epoch == cur.epoch and state.owner == cur.owner)
        ):
            self._state = state
            return PeerDecision(True, state)
        return PeerDecision(False, cur)


class FileLeasePeer:
    """One register directory per peer — each directory models one
    lease-holder node's local disk (no directory is shared between
    peers, so no single filesystem is a correctness dependency). The
    per-peer flock sidecar serializes this peer's read-modify-write;
    cross-peer agreement comes from the quorum, not from locking."""

    def __init__(self, directory: str, name: Optional[str] = None) -> None:
        self.directory = directory
        self.name = name or os.path.basename(os.path.normpath(directory))

    def _path(self) -> str:
        return os.path.join(self.directory, PEER_REGISTER_FILENAME)

    def read(self) -> Optional[LeaseState]:
        import json

        try:
            with open(self._path(), encoding="utf-8") as fh:
                return LeaseState.from_json(json.load(fh))
        except FileNotFoundError:
            return None
        except (ValueError, KeyError, TypeError):
            # corrupt register reads as empty: the epoch CAS still
            # holds cluster-wide because the other peers carry it
            return None
        except OSError as exc:
            raise LeasePeerError(f"peer {self.name}: {exc}") from exc

    def propose(self, state: LeaseState) -> PeerDecision:
        import fcntl

        try:
            os.makedirs(self.directory, exist_ok=True)
            lock_path = os.path.join(self.directory, PEER_LOCK_FILENAME)
            fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        except OSError as exc:
            raise LeasePeerError(f"peer {self.name}: {exc}") from exc
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            cur = self.read()
            if (
                cur is None
                or state.epoch > cur.epoch
                or (state.epoch == cur.epoch and state.owner == cur.owner)
            ):
                atomic_write_json(self._path(), state.as_json())
                return PeerDecision(True, state)
            return PeerDecision(False, cur)
        except LeasePeerError:
            raise
        except OSError as exc:
            raise LeasePeerError(f"peer {self.name}: {exc}") from exc
        finally:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            except OSError:
                pass
            os.close(fd)


class QuorumLease:
    """Majority-register lease with the file lease's exact interface.

    Not thread-safe by design (same contract as ``Lease``): acquire /
    renew run on one owner thread; ``held()`` only reads."""

    def __init__(
        self,
        peers: list,
        owner: str,
        ttl: Optional[float] = None,
        clock: Callable[[], float] = time.time,
    ) -> None:
        if not peers:
            raise ValueError("QuorumLease needs at least one peer")
        self.peers = list(peers)
        self.owner = owner
        self.ttl = float(ttl) if ttl is not None else LEASE_TTL_SECONDS
        self.clock = clock
        self.quorum = len(self.peers) // 2 + 1
        self._epoch = 0
        self._lost = False
        self._last_verified = 0.0

    # --- state ------------------------------------------------------------

    @property
    def epoch(self) -> int:
        return 0 if self._lost else self._epoch

    def _read_cluster(self) -> tuple[list[Optional[LeaseState]], int]:
        """Every peer's register (None = empty), plus the count of
        indeterminate (errored) peers."""
        states: list[Optional[LeaseState]] = []
        errors = 0
        for peer in self.peers:
            try:
                states.append(peer.read())
            except LeasePeerError as exc:
                debug_log(f"quorum lease read: {exc}")
                errors += 1
        return states, errors

    @staticmethod
    def _view(states: list[Optional[LeaseState]]) -> Optional[LeaseState]:
        """The max-epoch register among determinate responses."""
        best: Optional[LeaseState] = None
        for state in states:
            if state is not None and (best is None or state.epoch > best.epoch):
                best = state
        return best

    def read(self, strict: bool = False) -> Optional[LeaseState]:
        states, errors = self._read_cluster()
        if len(states) < self.quorum:
            if strict:
                raise OSError(
                    f"lease quorum indeterminate: only {len(states)}/"
                    f"{len(self.peers)} peers answered"
                )
            return None
        return self._view(states)

    # --- acquisition ------------------------------------------------------

    def acquire(self, force: bool = False) -> int:
        """Take the lease (majority epoch+1) and return the new epoch.
        Raises ``LeaseHeld`` on a live foreign lease (or a racing
        claimant that out-voted us), ``OSError`` when the cluster is
        too indeterminate to decide either way."""
        states, _ = self._read_cluster()
        if len(states) < self.quorum:
            raise OSError(
                f"lease quorum indeterminate: only {len(states)}/"
                f"{len(self.peers)} peers answered the acquire read"
            )
        now = self.clock()
        view = self._view(states)
        if (
            not force
            and view is not None
            and view.owner != self.owner
            and view.expires_at > now
        ):
            raise LeaseHeld(
                f"lease held by {view.owner!r} (epoch {view.epoch}) for "
                f"another {view.expires_at - now:.1f}s"
            )
        epoch = (view.epoch if view is not None else 0) + 1
        proposal = LeaseState(epoch, self.owner, now + self.ttl, now)
        accepts, best_reject = self._propose_all(proposal)
        if accepts >= self.quorum:
            self._epoch = epoch
            self._lost = False
            self._last_verified = now
            if view is not None and view.owner != self.owner:
                log(
                    f"quorum lease: {self.owner} took over from "
                    f"{view.owner} (epoch {view.epoch} -> {epoch}"
                    f"{', forced' if force and view.expires_at > now else ''})"
                )
            return epoch
        if best_reject is not None and best_reject.epoch >= epoch:
            # a racing claimant assembled the majority for this (or a
            # higher) epoch — we lost the election cleanly
            raise LeaseHeld(
                f"lease race lost to {best_reject.owner!r} "
                f"(epoch {best_reject.epoch})"
            )
        raise OSError(
            f"lease acquire indeterminate: {accepts}/{len(self.peers)} "
            f"accepts (quorum {self.quorum}); epoch {epoch} burned"
        )

    def renew(self) -> None:
        if self._epoch <= 0 or self._lost:
            raise LeaseLost("lease was never acquired (or already lost)")
        now = self.clock()
        proposal = LeaseState(self._epoch, self.owner, now + self.ttl, now)
        accepts, best_reject = self._propose_all(proposal)
        if accepts >= self.quorum:
            self._last_verified = now
            return
        if best_reject is not None and best_reject.epoch > self._epoch:
            self._lost = True
            raise LeaseLost(
                f"lease superseded: quorum carries "
                f"({best_reject.owner!r}, epoch {best_reject.epoch}), "
                f"we held epoch {self._epoch}"
            )
        raise OSError(
            f"lease renew indeterminate: {accepts}/{len(self.peers)} "
            f"accepts (quorum {self.quorum}); will retry"
        )

    def release(self) -> None:
        """Clean shutdown: expire our lease NOW (same epoch) on every
        reachable peer. Best effort — an unreachable minority just sees
        the TTL run out."""
        if self._epoch <= 0 or self._lost:
            return
        now = self.clock()
        self._propose_all(LeaseState(self._epoch, self.owner, now, now))
        self._epoch = 0

    def _propose_all(
        self, proposal: LeaseState
    ) -> tuple[int, Optional[LeaseState]]:
        accepts = 0
        best_reject: Optional[LeaseState] = None
        for peer in self.peers:
            try:
                decision = peer.propose(proposal)
            except LeasePeerError as exc:
                debug_log(f"quorum lease propose: {exc}")
                continue
            if decision.accepted:
                accepts += 1
            elif decision.state is not None and (
                best_reject is None or decision.state.epoch > best_reject.epoch
            ):
                best_reject = decision.state
        return accepts, best_reject

    # --- the fencing check (journal seam) ---------------------------------

    def held(self, verify: bool = False) -> bool:
        if self._lost or self._epoch <= 0:
            return False
        now = self.clock()
        if not verify and now - self._last_verified <= self.ttl / 4:
            return True
        states, _ = self._read_cluster()
        if len(states) < self.quorum:
            # Indeterminate cluster: neither confirms nor denies a
            # takeover — keep the cached verdict WITHOUT advancing the
            # trust window (same contract as the file lease's OSError
            # path; a real takeover is caught on the next majority
            # read, which must intersect the usurper's write set).
            return True
        view = self._view(states)
        if view is not None and view.epoch > self._epoch:
            self._lost = True
            return False
        if any(
            s is not None
            and s.epoch == self._epoch
            and s.owner == self.owner
            for s in states
        ):
            self._last_verified = now
            return True
        # A majority answered but none carries our register and none
        # supersedes it (peers restarted empty): indeterminate — keep
        # the cached verdict, don't advance the window.
        return True

    # --- introspection ----------------------------------------------------

    def status(self) -> dict[str, Any]:
        peers = []
        for peer in self.peers:
            entry: dict[str, Any] = {"name": getattr(peer, "name", "?")}
            try:
                state = peer.read()
                entry["state"] = state.as_json() if state is not None else None
            except LeasePeerError as exc:
                entry["error"] = str(exc)
            peers.append(entry)
        return {
            "backend": "quorum",
            "owner": self.owner,
            "epoch": self.epoch,
            "ttl_seconds": self.ttl,
            "quorum": self.quorum,
            "peers": peers,
        }


def quorum_lease_from_env(
    owner: str, ttl: Optional[float] = None
) -> Optional[QuorumLease]:
    """Build the region-mode lease from CDT_LEASE_PEERS (a comma list
    of peer register directories); None when the knob is unset — the
    caller falls back to the shared-filesystem file lease."""
    from ..utils import constants

    peer_dirs = constants.LEASE_PEERS
    if not peer_dirs:
        return None
    peers = [
        FileLeasePeer(directory, name=f"peer{i}")
        for i, directory in enumerate(peer_dirs)
    ]
    return QuorumLease(peers, owner=owner, ttl=ttl)
