"""The journaled control-plane state machine.

One pure ``apply_record`` shared by two consumers keeps them
definitionally consistent:

- the **snapshot shadow** — the DurabilityManager applies every record
  it journals to an in-memory copy of this state, so a snapshot is a
  serialization of exactly what the journal would replay to;
- **recovery replay** — restart applies the WAL tail to the state
  loaded from the newest snapshot.

The state is plain JSON-able data (dicts/lists/strings/ints) so a
snapshot round-trips losslessly; task ids inside ``completed`` are
string-keyed for the same reason and normalized at materialize time.

Record vocabulary (emitted by ``JobStore`` — docs/durability.md):

    job_init        {job, kind, batched, tasks, deadline_s?}
    pull            {job, worker, tasks}
    submit          {job, worker, task, payload}   payload null = volatile
    requeue         {job, worker, tasks, reason}   failure-class reasons
                    (timeout|quarantine) charge each task's attempt
                    counter — the poison budget replays exactly
    tile_quarantine {job, tasks}                   tasks leave the pull
                    set for good (settled degraded)
    cache_settle    {job, tasks}                   tasks completed from
                    the content-addressed tile cache (payload volatile:
                    the canvas pixels live in the master's cache, so a
                    restarted master recomputes OR re-settles from the
                    cache — both bit-identical by the key contract)
    cancel          {job, reason}                  terminal: pending
                    drained, assignments revoked, later records no-op
    speculate       {job, tasks}
    worker_done     {job, worker}
    cleanup         {job}

``prepare_for_restart`` is the recovery-time transform: in-flight
assignments are revoked back to pending (the workers holding them died
with — or were orphaned by — the old master), and completions whose
payload was volatile (master-local blends that lived only in the dead
process's canvas) are demoted to pending for recompute. Per-tile
determinism (noise keys folding the global tile index) makes both
recompute paths bit-identical to the uninterrupted run.
"""

from __future__ import annotations

import copy
from typing import Any

SNAPSHOT_VERSION = 1


class SnapshotVersionMismatch(Exception):
    """A snapshot written by an incompatible schema version: recovery
    must stop loudly rather than misinterpret acknowledged state."""


def new_state() -> dict[str, Any]:
    return {"version": SNAPSHOT_VERSION, "last_lsn": 0, "jobs": {}, "scheduler": {}}


def _new_job(
    kind: str, batched: bool, tasks: list[int],
    deadline_s: Any = None, lane: Any = "", tenant: Any = "default",
    adapters: Any = None,
) -> dict[str, Any]:
    try:
        deadline_s = float(deadline_s) if deadline_s else None
    except (TypeError, ValueError):
        deadline_s = None
    return {
        "kind": kind,
        "batched": bool(batched),
        "tasks": [int(t) for t in tasks],
        "pending": [int(t) for t in tasks],
        "assigned": {},  # worker -> [task ids] in claim order
        "completed": {},  # str(task id) -> payload | None
        "speculated": [],
        "finished_workers": [],
        # --- lifecycle armor ---
        "deadline_s": deadline_s,
        "cancelled": False,
        "cancel_reason": "",
        "attempts": {},     # str(task id) -> failed delivery attempts
        "quarantined": [],  # task ids settled degraded (poison)
        "cached": [],       # task ids settled from the tile cache
        # --- xjob tier: admission lane/tenant ride job_init so a
        # recovered master can rank recovered jobs for preemption
        # (checkpoints do NOT — they are volatile; recompute covers)
        "lane": str(lane or ""),
        "tenant": str(tenant or "default"),
        # --- adapter plane: the resolved wire plan rides job_init so a
        # recovered master re-serves the exact personalization from
        # job_status (content hashes included — workers re-verify)
        "adapters": list(adapters or []),
    }


def apply_record(state: dict[str, Any], record: dict[str, Any]) -> None:
    """Apply one journal record. Unknown job references are ignored
    (a record after its job's ``cleanup`` — e.g. a late release racing
    teardown — is a no-op exactly as it is in the live store)."""
    rtype = record.get("type")
    jobs = state["jobs"]
    lsn = int(record.get("lsn", 0))
    if lsn:
        state["last_lsn"] = max(int(state.get("last_lsn", 0)), lsn)
    if rtype == "job_init":
        job_id = str(record["job"])
        if job_id not in jobs:
            jobs[job_id] = _new_job(
                str(record.get("kind", "tile")),
                bool(record.get("batched", True)),
                list(record.get("tasks", [])),
                deadline_s=record.get("deadline_s"),
                lane=record.get("lane", ""),
                tenant=record.get("tenant", "default"),
                adapters=record.get("adapters", []),
            )
        return
    job = jobs.get(str(record.get("job", "")))
    if rtype == "cleanup":
        jobs.pop(str(record.get("job", "")), None)
        return
    if job is None:
        return
    if job.get("cancelled") and rtype != "cancel":
        # terminal: the live store refuses every mutation after the
        # cancel record, so replay must too (defense in depth against
        # a record that raced past the terminal state)
        return
    if rtype == "pull":
        worker = str(record["worker"])
        claimed = job["assigned"].setdefault(worker, [])
        for tid in record.get("tasks", []):
            tid = int(tid)
            if tid in job["pending"]:
                job["pending"].remove(tid)
            if tid not in claimed:
                claimed.append(tid)
    elif rtype == "submit":
        worker = str(record.get("worker", ""))
        tid = int(record["task"])
        claimed = job["assigned"].get(worker)
        if claimed and tid in claimed:
            claimed.remove(tid)
            if not claimed:
                del job["assigned"][worker]
        key = str(tid)
        if key not in job["completed"]:  # first result wins, as in the store
            job["completed"][key] = record.get("payload")
            # a speculated copy settling a poison-quarantined tile
            # drops the quarantine, exactly as the live store does —
            # the tile must count exactly once toward completion
            quarantined = job.get("quarantined")
            if quarantined and tid in quarantined:
                quarantined.remove(tid)
    elif rtype == "requeue":
        worker = str(record.get("worker", ""))
        claimed = job["assigned"].get(worker, [])
        charge = str(record.get("reason", "")) in ("timeout", "quarantine")
        attempts = job.setdefault("attempts", {})
        quarantined = job.setdefault("quarantined", [])
        for tid in record.get("tasks", []):
            tid = int(tid)
            if tid in claimed:
                claimed.remove(tid)
            if charge:
                attempts[str(tid)] = int(attempts.get(str(tid), 0)) + 1
            if (
                str(tid) not in job["completed"]
                and tid not in job["pending"]
                and tid not in quarantined
            ):
                job["pending"].append(tid)
        if worker in job["assigned"] and not job["assigned"][worker]:
            del job["assigned"][worker]
    elif rtype == "tile_quarantine":
        quarantined = job.setdefault("quarantined", [])
        for tid in record.get("tasks", []):
            tid = int(tid)
            if tid in job["pending"]:
                job["pending"] = [t for t in job["pending"] if t != tid]
            if str(tid) not in job["completed"] and tid not in quarantined:
                quarantined.append(tid)
    elif rtype == "cache_settle":
        # tiles settled straight from the tile cache: completed with a
        # VOLATILE payload (the pixels live in the master's cache, not
        # the journal) and removed from the pull set — the shadow must
        # track the live store's shrunken queue exactly
        cached = job.setdefault("cached", [])
        quarantined = job.get("quarantined") or []
        for tid in record.get("tasks", []):
            tid = int(tid)
            key = str(tid)
            if key in job["completed"] or tid in quarantined:
                continue
            job["completed"][key] = None
            if tid in job["pending"]:
                job["pending"] = [t for t in job["pending"] if t != tid]
            if tid not in cached:
                cached.append(tid)
    elif rtype == "cancel":
        # terminal: the whole refund happens here, so crash-after-cancel
        # replay reaches the same drained state the live store had
        job["cancelled"] = True
        job["cancel_reason"] = str(record.get("reason", ""))
        job["pending"] = []
        job["assigned"] = {}
    elif rtype == "speculate":
        for tid in record.get("tasks", []):
            tid = int(tid)
            if tid not in job["speculated"]:
                job["speculated"].append(tid)
            job["pending"].append(tid)  # a COPY rides next to the original
    elif rtype == "worker_done":
        worker = str(record["worker"])
        if worker not in job["finished_workers"]:
            job["finished_workers"].append(worker)
    # unknown record types are ignored: a newer master may journal
    # types an older reader doesn't know; they must not abort replay


def replay_into(state: dict[str, Any], records: list[dict[str, Any]]) -> int:
    """Apply records in order; returns how many were applied. Pure with
    respect to the inputs (records are not mutated), so applying the
    same (snapshot, records) twice yields identical states — the
    idempotence property tests/test_durability.py enforces."""
    for record in records:
        apply_record(state, record)
    return len(records)


def prepare_for_restart(state: dict[str, Any]) -> dict[str, int]:
    """Mutate a recovered state for a fresh master process; returns
    counters for the recovery report.

    - every in-flight assignment is revoked to pending (its worker's
      connection to the dead master is gone; workers re-register via
      heartbeat against the restarted process);
    - completions with a durable payload are kept (the payload will be
      re-enqueued for the new master's blender);
    - volatile completions (payload null — master-local blends) are
      demoted to pending for bit-identical recompute;
    - speculation marks are cleared so the watchdog may speculate
      afresh in the new process.

    Requeue order is sorted for determinism (recovery must not depend
    on the journal's interleaving of the dead process's races).
    """
    requeued = 0
    restored = 0
    cancelled = 0
    for job_id in sorted(state["jobs"]):
        job = state["jobs"][job_id]
        if job.get("cancelled"):
            # terminal: a restarted master has nothing to resume here —
            # the cancel already refunded everything; drop the record
            # (the dead process would have cleaned it up next).
            del state["jobs"][job_id]
            cancelled += 1
            continue
        quarantined = {int(t) for t in job.get("quarantined", [])}
        back: set[int] = set()
        for worker in sorted(job["assigned"]):
            back.update(int(t) for t in job["assigned"][worker])
        job["assigned"] = {}
        durable: dict[str, Any] = {}
        for key in sorted(job["completed"], key=int):
            payload = job["completed"][key]
            if payload is None:
                back.add(int(key))
            else:
                durable[key] = payload
                restored += 1
        job["completed"] = durable
        # quarantined tiles stay settled (degraded) across the restart:
        # re-running known poison would just crash the new fleet too
        back -= quarantined
        pending = [
            int(t)
            for t in job["pending"]
            if int(t) not in back and int(t) not in quarantined
        ]
        already = set(pending)
        additions = [
            t for t in sorted(back) if t not in already and str(t) not in durable
        ]
        job["pending"] = pending + additions
        job["speculated"] = []
        # cache-settlement marks reset with the demotion: the restarted
        # master re-consults the cache at grant time and re-settles (or
        # recomputes on a cold cache) — bit-identical either way
        job["cached"] = []
        requeued += len(additions)
    return {
        "tasks_requeued": requeued,
        "tasks_restored": restored,
        "jobs_cancelled": cancelled,
    }


def materialize(state: dict[str, Any]):
    """Build live ``TileJob``/``ImageJob`` objects from a prepared
    state: ``{job_id: job}`` ready to install into a ``JobStore``.
    Durable completed payloads are re-enqueued on ``job.results`` so
    the new master's drain loop blends them without recompute."""
    from ..jobs.models import ImageJob, TileJob

    out = {}
    for job_id in sorted(state["jobs"]):
        spec = state["jobs"][job_id]
        cls = TileJob if spec.get("kind", "tile") == "tile" else ImageJob
        job = cls(
            job_id=job_id,
            total_tasks=len(spec["tasks"]),
            batched=bool(spec.get("batched", True)),
        )
        for tid in spec["pending"]:
            job.pending.put_nowait(int(tid))
        for key in sorted(spec["completed"], key=int):
            payload = spec["completed"][key]
            job.completed[int(key)] = payload
            job.results.put_nowait((int(key), payload))
        job.finished_workers = set(spec.get("finished_workers", []))
        # lifecycle armor: poison budgets and quarantines survive the
        # restart; a journaled deadline re-arms its FULL window (the
        # dead process's monotonic cutoff is meaningless here — the
        # recovered job gets a fresh clock, documented in
        # docs/resilience.md)
        job.attempts = {
            int(t): int(n)
            for t, n in (spec.get("attempts") or {}).items()
        }
        job.quarantined_tiles = {
            int(t) for t in spec.get("quarantined", [])
        }
        job.cached_tiles = {int(t) for t in spec.get("cached", [])}
        job.lane = str(spec.get("lane", "") or "")
        job.tenant = str(spec.get("tenant", "default") or "default")
        job.adapters = list(spec.get("adapters", []) or [])
        deadline_s = spec.get("deadline_s")
        if deadline_s:
            import time as _time

            job.deadline_s = float(deadline_s)
            job.deadline_at = _time.monotonic() + float(deadline_s)
        out[job_id] = job
    return out


def clone(state: dict[str, Any]) -> dict[str, Any]:
    return copy.deepcopy(state)
