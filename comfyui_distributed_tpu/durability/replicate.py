"""Journal replication: the active master's WAL as a live stream, and
the standby replica that tails it.

The durable control plane made a dead master *recoverable* (snapshot +
WAL replay); this module makes it *replaceable without restart*: a
warm standby holds an up-to-date copy of the journaled state at all
times, so takeover is a promotion (prepare_for_restart + materialize —
the SAME transform disk recovery applies, minus the disk), not a boot.

Two halves, both transport-neutral (api/replication_routes.py and
api/standby.py put them on a WebSocket; the chaos harness wires them
directly):

- **source side** — ``ReplicationSubscription``: a bounded record
  buffer the ``DurabilityManager`` tees every journaled record into,
  created *under the manager lock* together with a serialization of
  the current shadow state, so the (snapshot, tail) pair a subscriber
  receives is exactly consistent (no record is ever missed or applied
  twice — frames at or below the snapshot's lsn are deduplicated by
  the replica). Overflow marks the subscription **lost** instead of
  dropping interior records: a hole would silently desync the replica,
  so the standby re-syncs from a fresh snapshot frame instead;
- **standby side** — ``StandbyReplica``: applies frames through the
  same pure ``state.apply_record`` machine the snapshot shadow and
  disk replay use (three consumers, one state machine — consistency by
  construction), tracks replication lag in records (source head lsn −
  applied lsn) and seconds (staleness of the newest applied frame),
  and performs the promotion transform into a live JobStore.

Determinism: this module is inside the CDT004 determinism lint scope —
replication/promotion must be a pure function of the frame sequence.
The only clock here is injected and used for *lag observability*,
never for state.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Optional

from ..utils.constants import STANDBY_BUFFER_RECORDS
from ..utils.logging import log
from . import state as state_mod
from .recovery import RecoveryReport


class ReplicationSubscription:
    """One standby connection's view of the active master's journal.

    Created by ``DurabilityManager.subscribe_replica`` under the
    manager lock: ``snapshot_state`` is the shadow state at attach time
    and every record journaled after that instant is offered, in lsn
    order. Thread-safe: the source offers from the journal seam, the
    consumer drains from its own thread/loop."""

    def __init__(
        self,
        snapshot_state: dict[str, Any],
        head_lsn: int,
        epoch: int = 0,
        maxlen: Optional[int] = None,
    ) -> None:
        self.snapshot_state = snapshot_state
        self.head_lsn = int(head_lsn)
        self.epoch = int(epoch)
        self._maxlen = maxlen if maxlen is not None else STANDBY_BUFFER_RECORDS
        self._records: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._event = threading.Event()
        self.lost = False
        self.closed = False

    def offer(self, record: dict[str, Any]) -> None:
        """Source side: enqueue one journaled record (already carrying
        its lsn). On overflow the subscription is marked LOST and the
        buffer cleared — suffix integrity over completeness, exactly
        the journal's own write-behind rule."""
        with self._lock:
            if self.closed or self.lost:
                return
            if len(self._records) >= self._maxlen:
                self.lost = True
                self._records.clear()
            else:
                self._records.append(record)
        self._event.set()

    def pop(self, max_items: int = 256) -> list[dict[str, Any]]:
        """Consumer side: drain up to ``max_items`` buffered records in
        lsn order; clears the wakeup flag when the buffer empties."""
        out: list[dict[str, Any]] = []
        with self._lock:
            while self._records and len(out) < max_items:
                out.append(self._records.popleft())
            if not self._records:
                self._event.clear()
        return out

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until records are buffered (or lost/closed); False on
        timeout. Safe to call off-loop (the WS route wraps it in
        ``run_blocking``)."""
        return self._event.wait(timeout)

    def close(self) -> None:
        with self._lock:
            self.closed = True
            self._records.clear()
        self._event.set()


class StandbyReplica:
    """The standby's in-memory copy of the active master's journaled
    state, plus lag accounting and the promotion transform."""

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self.clock = clock
        self._lock = threading.Lock()
        self._state = state_mod.new_state()
        self._synced = False
        self.source_epoch = 0
        self._source_head_lsn = 0
        self._last_frame_at: Optional[float] = None
        self.applied_records = 0
        self.resyncs = 0

    # --- stream consumption ----------------------------------------------

    def reset(
        self, snapshot_state: dict[str, Any], head_lsn: int, epoch: int = 0
    ) -> None:
        """Adopt a full snapshot frame (initial sync, or re-sync after
        a lost stream). The state is cloned so the caller's buffer is
        never shared."""
        with self._lock:
            if self._synced:
                self.resyncs += 1
            self._state = state_mod.clone(snapshot_state)
            self._synced = True
            self.source_epoch = max(self.source_epoch, int(epoch))
            self._source_head_lsn = max(self._source_head_lsn, int(head_lsn))
            self._last_frame_at = self.clock()

    def apply(self, record: dict[str, Any]) -> bool:
        """Apply one replicated record; returns False when the frame is
        at or below the replica's lsn (the snapshot already covers it —
        the attach-time dedup rule)."""
        with self._lock:
            lsn = int(record.get("lsn", 0))
            if lsn and lsn <= int(self._state.get("last_lsn", 0)):
                return False
            state_mod.apply_record(self._state, record)
            self.applied_records += 1
            self._source_head_lsn = max(self._source_head_lsn, lsn)
            self._last_frame_at = self.clock()
            return True

    def note_head(self, head_lsn: int, epoch: int = 0) -> None:
        """Source heartbeat frame: advances the head the lag is
        measured against even when no records flow."""
        with self._lock:
            self._source_head_lsn = max(self._source_head_lsn, int(head_lsn))
            if epoch:
                self.source_epoch = max(self.source_epoch, int(epoch))

    # --- lag --------------------------------------------------------------

    @property
    def synced(self) -> bool:
        return self._synced

    def last_lsn(self) -> int:
        with self._lock:
            return int(self._state.get("last_lsn", 0))

    def lag_records(self) -> int:
        with self._lock:
            return max(
                0, self._source_head_lsn - int(self._state.get("last_lsn", 0))
            )

    def lag_seconds(self) -> Optional[float]:
        """Staleness of the newest applied frame (None before the first
        sync). Zero-lag streams still age between appends — consumers
        should read this together with ``lag_records``."""
        with self._lock:
            if self._last_frame_at is None:
                return None
            return max(0.0, self.clock() - self._last_frame_at)

    def status(self) -> dict[str, Any]:
        with self._lock:
            last_lsn = int(self._state.get("last_lsn", 0))
            lag_rec = max(0, self._source_head_lsn - last_lsn)
            lag_sec = (
                max(0.0, self.clock() - self._last_frame_at)
                if self._last_frame_at is not None
                else None
            )
            return {
                "synced": self._synced,
                "source_epoch": self.source_epoch,
                "source_head_lsn": self._source_head_lsn,
                "applied_lsn": last_lsn,
                "applied_records": self.applied_records,
                "lag_records": lag_rec,
                "lag_seconds": lag_sec,
                "resyncs": self.resyncs,
                "jobs_tracked": len(self._state.get("jobs", {})),
            }

    # --- promotion --------------------------------------------------------

    def promoted_state(self) -> tuple[dict[str, Any], RecoveryReport]:
        """The promotion transform, pure: clone the replicated state,
        run ``prepare_for_restart`` (in-flight grants revoked to
        pending for bit-identical recompute, durable worker payloads
        kept), and return (prepared state, report). The caller
        materializes it into a store and hands the state to its
        ``DurabilityManager.adopt``."""
        with self._lock:
            prepared = state_mod.clone(self._state)
        report = RecoveryReport()
        report.performed = True
        report.snapshot_lsn = 0
        report.replayed_records = self.applied_records
        report.last_lsn = int(prepared.get("last_lsn", 0))
        stats = state_mod.prepare_for_restart(prepared)
        report.tasks_requeued = stats["tasks_requeued"]
        report.tasks_restored = stats["tasks_restored"]
        report.jobs_cancelled = stats.get("jobs_cancelled", 0)
        return prepared, report

    def promote(self, store: Any, scheduler: Any = None) -> tuple[
        dict[str, Any], RecoveryReport
    ]:
        """Materialize the prepared state into a live JobStore (and
        restore scheduler aggregates) — disk recovery's sequence with
        the replica standing in for (snapshot + WAL tail). The caller
        (``DurabilityManager.adopt``) pauses admission when jobs were
        recovered, exactly like a restart."""
        prepared, report = self.promoted_state()
        jobs = state_mod.materialize(prepared)
        report.jobs_recovered = len(jobs)
        for job_id in sorted(jobs):
            store.tile_jobs[job_id] = jobs[job_id]
        scheduler_state = prepared.get("scheduler") or {}
        if scheduler is not None and scheduler_state:
            try:
                scheduler.restore_state(scheduler_state)
                report.scheduler_restored = True
            except Exception as exc:  # noqa: BLE001 - aggregates advisory
                log(f"promotion: scheduler state restore failed: {exc}")
        log(
            f"promotion: standby took over {report.jobs_recovered} job(s) "
            f"at lsn {report.last_lsn}; {report.tasks_requeued} tile(s) "
            f"requeued, {report.tasks_restored} durable result(s) restored"
        )
        return prepared, report
