"""DurabilityManager: the one object a DistributedServer owns.

Couples the WAL (journal.py), the snapshot shadow (state.py +
snapshot.py), and restart recovery (recovery.py), and is the
``journal_sink`` the JobStore emits typed mutation records into:

    JobStore transition
        → manager.record(rec)          (BEFORE the store acknowledges)
            → journal.append           (framed, CRC'd, fsync policy)
            → apply_record(shadow)     (snapshot stays definitionally
                                        consistent with replay)
            → every CDT_SNAPSHOT_EVERY appends: snapshot + prune

Enabled by setting ``CDT_JOURNAL_DIR``; without it the server runs
exactly as before (no sink, no files, no overhead). Scheduler
aggregates (tenant deficits/weights, placement EWMAs) are sampled into
each snapshot via the scheduler's export hook rather than journaled
per-mutation — see durability/snapshot.py for the trade-off.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Optional

from ..telemetry import instruments
from ..utils.constants import _env_int
from ..utils.logging import log
from . import recovery as recovery_mod
from . import snapshot as snapshot_mod
from . import state as state_mod
from .journal import Journal
from .lease import FencedOut, Lease
from .recovery import RecoveryReport
from .replicate import ReplicationSubscription

DEFAULT_SNAPSHOT_EVERY = 256


def journal_dir_from_env() -> Optional[str]:
    """CDT_JOURNAL_DIR resolution; empty/unset = durability off."""
    raw = os.environ.get("CDT_JOURNAL_DIR", "").strip()
    return raw or None


class DurabilityManager:
    def __init__(
        self,
        directory: str,
        snapshot_every: Optional[int] = None,
        segment_bytes: Optional[int] = None,
        fsync_every: Optional[int] = None,
        scheduler: Any = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.directory = directory
        self.snapshot_every = (
            snapshot_every
            if snapshot_every is not None
            else _env_int("CDT_SNAPSHOT_EVERY", DEFAULT_SNAPSHOT_EVERY)
        )
        self._segment_bytes = segment_bytes
        self._fsync_every = fsync_every
        self.scheduler = scheduler
        self.clock = clock
        self._lock = threading.Lock()
        self._state = state_mod.new_state()
        self._journal: Optional[Journal] = None
        self._appends = 0
        self._appends_since_snapshot = 0
        self._last_snapshot_at: Optional[float] = None
        self._last_snapshot_lsn = 0
        self.report = RecoveryReport()
        self._paused_for_recovery = False
        # Single-flight background snapshot writer: periodic snapshots
        # triggered from the journal seam (which runs on the serving
        # loop) must not pay the write+fsync+prune there.
        self._snapshot_thread: Optional[threading.Thread] = None
        # High-availability layer (optional): the epoch lease fencing
        # this process's right to append, and the live replication
        # subscriptions every journaled record is teed into (see
        # durability/lease.py and durability/replicate.py).
        self.lease: Optional[Lease] = None
        self._replicas: list[ReplicationSubscription] = []
        self.failovers = 0  # promotions performed by THIS process
        # Optional per-append latency feed (seconds) — the brownout
        # controller's journal-saturation signal. Called OUTSIDE the
        # manager lock; must never raise into the journal seam.
        self.append_latency_sink: Optional[Callable[[float], None]] = None

    # --- lifecycle --------------------------------------------------------

    def recover(self, store: Any, scheduler: Any = None) -> RecoveryReport:
        """Run crash recovery into ``store`` (must not be serving yet),
        adopt the recovered state as the snapshot shadow, open the
        journal for appends, and checkpoint immediately so the WAL tail
        the dead process left behind is compacted away."""
        if scheduler is not None:
            self.scheduler = scheduler
        state, report = recovery_mod.recover(
            self.directory, store, scheduler=self.scheduler
        )
        with self._lock:
            self._state = state
            self.report = report
            self._journal = self._open_journal(int(state["last_lsn"]) + 1)
            if report.jobs_recovered:
                self._paused_for_recovery = recovery_mod.pause_after_recovery(
                    self.scheduler
                )
            self._snapshot_locked()
        instruments.recovery_replayed_records().set(report.replayed_records)
        instruments.recovery_requeued_tasks().set(report.tasks_requeued)
        return report

    def _open_journal(self, next_lsn: int) -> Journal:
        return Journal(
            self.directory,
            next_lsn=next_lsn,
            segment_bytes=self._segment_bytes,
            fsync_every=self._fsync_every,
        )

    def close(self) -> None:
        snapshot_thread = self._snapshot_thread
        if snapshot_thread is not None and snapshot_thread.is_alive():
            snapshot_thread.join(timeout=60)
        with self._lock:
            replicas, self._replicas = self._replicas, []
            if self._journal is not None:
                self._journal.close()
                self._journal = None
        for sub in replicas:
            sub.close()

    # --- the journal seam (JobStore.journal_sink) -------------------------

    def record(self, rec: dict) -> None:
        """Append one typed mutation record; called by the JobStore
        BEFORE it acknowledges the transition. A journal failure
        propagates — WAL semantics forbid acknowledging state that was
        not made durable.

        Fencing: when a lease is attached, every append first checks
        ``Lease.held()`` (local-clock cheap within ttl/4, a file
        re-read beyond). A deposed master — its lease taken by a
        promoted standby — raises ``FencedOut`` here, BEFORE any bytes
        land, so a zombie process cannot journal (and therefore cannot
        acknowledge) state after takeover."""
        lease = self.lease
        if lease is not None and not lease.held():
            raise FencedOut(
                f"journal append refused: this process no longer holds "
                f"the master lease for {self.directory} (a standby "
                "promoted itself); the mutation was NOT journaled"
            )
        append_started = time.monotonic()
        with self._lock:
            if self._journal is None:
                self._journal = self._open_journal(int(self._state["last_lsn"]) + 1)
            try:
                lsn = self._journal.append(rec)
            except (TypeError, ValueError):
                if rec.get("payload") is None:
                    raise
                # non-JSON payload (in-memory tensors): journal the
                # transition as volatile; recovery recomputes the tile.
                # (The failed attempt wrote nothing — serialization
                # happens before any bytes land — and lsn gaps are
                # legal in replay.)
                rec = {**rec, "payload": None}
                lsn = self._journal.append(rec)
            sequenced = {**rec, "lsn": lsn}
            state_mod.apply_record(self._state, sequenced)
            self._tee_replicas_locked(sequenced)
            self._appends += 1
            self._appends_since_snapshot += 1
            if self._appends_since_snapshot >= self.snapshot_every:
                self._snapshot_locked(asynchronous=True)
        sink = self.append_latency_sink
        if sink is not None:
            try:
                sink(time.monotonic() - append_started)
            except Exception:  # noqa: BLE001 - observability only
                pass

    # --- replication (durability/replicate.py) ----------------------------

    def subscribe_replica(self) -> ReplicationSubscription:
        """Attach one standby: under the manager lock, serialize the
        current shadow state and register the record tee — the
        (snapshot, tail) pair the subscriber sees is exactly
        consistent by construction (no record between the snapshot
        serialization and the first teed frame)."""
        with self._lock:
            sub = ReplicationSubscription(
                snapshot_state=state_mod.clone(self._state),
                head_lsn=int(self._state["last_lsn"]),
                epoch=self.epoch,
            )
            self._replicas.append(sub)
        return sub

    def unsubscribe_replica(self, sub: ReplicationSubscription) -> None:
        sub.close()
        with self._lock:
            if sub in self._replicas:
                self._replicas.remove(sub)

    def _tee_replicas_locked(self, record: dict) -> None:
        """Caller holds self._lock. Offers never block or raise; a lost
        subscription stays registered (its consumer notices and
        re-syncs or disconnects)."""
        for sub in self._replicas:
            sub.offer(record)

    @property
    def epoch(self) -> int:
        return self.lease.epoch if self.lease is not None else 0

    @property
    def role(self) -> str:
        return "active"

    def head_lsn(self) -> int:
        with self._lock:
            return int(self._state["last_lsn"])

    # --- promotion (standby → active) -------------------------------------

    def adopt(
        self,
        store: Any,
        replica: Any,
        scheduler: Any = None,
        lease: Optional[Lease] = None,
    ) -> RecoveryReport:
        """Standby promotion: the replica's replicated state becomes
        this manager's shadow (the mirror of ``recover``, with the
        replication stream standing in for snapshot + WAL tail).
        Materializes live jobs into ``store``, opens the journal for
        appends at the replicated head, snapshots immediately, and
        holds admission paused until a worker re-registers — the
        ``prepare_for_restart`` semantics reused end to end, so the
        promoted standby requeues in-flight tiles and completes the
        job bit-identically."""
        if scheduler is not None:
            self.scheduler = scheduler
        if lease is not None:
            self.lease = lease
        state, report = replica.promote(store, scheduler=self.scheduler)
        with self._lock:
            self._state = state
            self.report = report
            self.failovers += 1
            if self._journal is not None:
                self._journal.close()
            self._journal = self._open_journal(int(state["last_lsn"]) + 1)
            if report.jobs_recovered:
                self._paused_for_recovery = recovery_mod.pause_after_recovery(
                    self.scheduler
                )
            self._snapshot_locked()
        instruments.recovery_replayed_records().set(report.replayed_records)
        instruments.recovery_requeued_tasks().set(report.tasks_requeued)
        instruments.failover_total().inc(role="standby")
        return report

    # --- snapshots --------------------------------------------------------

    def _snapshot_locked(self, asynchronous: bool = False) -> None:
        """Caller holds self._lock. Synchronous for recovery/close
        (ordering matters there); the periodic path hands the
        write+fsync+prune to a single-flight daemon thread — only the
        state serialization (a json.dumps) stays on the caller, so the
        serving loop never waits on a slow filesystem."""
        if self.scheduler is not None:
            try:
                self._state["scheduler"] = self.scheduler.export_state()
            except Exception as exc:  # noqa: BLE001 - aggregates advisory
                log(f"durability: scheduler export failed: {exc}")
        self._appends_since_snapshot = 0
        if not asynchronous:
            snapshot_mod.write_snapshot(self.directory, self._state)
            self._note_snapshot_locked(int(self._state["last_lsn"]))
            return
        if self._snapshot_thread is not None and self._snapshot_thread.is_alive():
            return  # single flight; the next interval retries
        import json as _json

        blob = (
            _json.dumps(self._state, separators=(",", ":"), sort_keys=True)
            + "\n"
        ).encode("utf-8")
        lsn = int(self._state["last_lsn"])
        self._snapshot_thread = threading.Thread(
            target=self._snapshot_body,
            args=(blob, lsn),
            name="cdt-snapshot-writer",
            daemon=True,
        )
        self._snapshot_thread.start()

    def _note_snapshot_locked(self, lsn: int) -> None:
        self._last_snapshot_at = self.clock()
        self._last_snapshot_lsn = lsn
        if self._journal is not None:
            self._journal.prune(lsn)
        instruments.snapshots_total().inc()

    def _snapshot_body(self, blob: bytes, lsn: int) -> None:
        from ..utils.fsio import atomic_write_bytes

        try:
            path = snapshot_mod.snapshot_path(self.directory, lsn)
            atomic_write_bytes(path, blob)
            snapshot_mod.prune_snapshots(self.directory, path, lsn)
            with self._lock:
                self._note_snapshot_locked(lsn)
        except Exception as exc:  # noqa: BLE001 - surfaced, next interval retries
            log(f"durability: background snapshot at lsn {lsn} failed: {exc}")

    def snapshot_now(self) -> None:
        with self._lock:
            self._snapshot_locked()

    def flush_snapshots(self) -> None:
        """Block until any in-flight background snapshot has landed
        (tests and pre-shutdown hooks)."""
        snapshot_thread = self._snapshot_thread
        if snapshot_thread is not None and snapshot_thread.is_alive():
            snapshot_thread.join(timeout=60)

    # --- post-recovery admission hold -------------------------------------

    def note_worker_activity(self, worker_id: str) -> None:
        """First worker heartbeat after a recovery that restored jobs:
        the fleet is alive again, release the admission lanes."""
        if worker_id == "master" or not self._admission_held():
            return
        with self._lock:
            if not self._paused_for_recovery:
                return
            self._paused_for_recovery = False
        scheduler = self.scheduler
        if scheduler is not None:
            try:
                scheduler.resume()
                log(
                    f"durability: worker {worker_id} re-registered; "
                    "admission lanes resumed"
                )
            except Exception as exc:  # noqa: BLE001 - advisory
                log(f"durability: post-recovery resume failed: {exc}")

    # --- observability ----------------------------------------------------

    def collect_metrics(self) -> None:
        """Scrape-time hook (instruments.bind_server_collectors)."""
        with self._lock:
            last = self._last_snapshot_at
        if last is not None:
            instruments.snapshot_age_seconds().set(max(self.clock() - last, 0.0))

    def _admission_held(self) -> bool:
        """The post-recovery hold, reconciled against reality: an
        operator who resumed the scheduler by hand (runbook §4f) must
        not keep seeing a stale PAUSED banner — and the later worker
        heartbeat must not re-resume over their head."""
        if not self._paused_for_recovery:
            return False
        scheduler = self.scheduler
        if scheduler is not None:
            try:
                if scheduler.queue.state != "paused":
                    self._paused_for_recovery = False
            except Exception:  # noqa: BLE001 - reporting only
                pass
        return self._paused_for_recovery

    def status(self) -> dict[str, Any]:
        with self._lock:
            journal_status = (
                self._journal.status() if self._journal is not None else None
            )
            snapshot_age = (
                max(self.clock() - self._last_snapshot_at, 0.0)
                if self._last_snapshot_at is not None
                else None
            )
            return {
                "enabled": True,
                "role": self.role,
                "epoch": self.epoch,
                "journal_dir": self.directory,
                "journal": journal_status,
                "appends": self._appends,
                "snapshot_every": self.snapshot_every,
                "last_snapshot_lsn": self._last_snapshot_lsn,
                "snapshot_age_seconds": snapshot_age,
                "admission_held": self._admission_held(),
                "recovery": self.report.as_json(),
                "jobs_tracked": len(self._state["jobs"]),
                "replication": {
                    "standbys": len(self._replicas),
                    "lost": sum(1 for s in self._replicas if s.lost),
                },
                "failovers": self.failovers,
            }
