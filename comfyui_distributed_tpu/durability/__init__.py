"""Durable control plane (L2, zero-dependency).

Write-ahead journal + snapshot + crash recovery under the JobStore and
the scheduler, removing the master as the one component whose crash
loses work. See docs/durability.md for the record schema, the
rotation/compaction policy, and the recovery sequence.

    journal.py   — append-only CRC32 WAL, segment rotation, torn-tail
                   truncation on replay
    state.py     — the journaled state machine (one apply_record
                   shared by snapshot shadow and recovery replay)
    snapshot.py  — atomic snapshot write + segment/snapshot pruning
    recovery.py  — snapshot + WAL tail → live JobStore/scheduler
    manager.py   — DurabilityManager: the JobStore's journal_sink
"""

from .journal import Journal, JournalCorruption, replay_journal
from .manager import DurabilityManager, journal_dir_from_env
from .recovery import RecoveryReport, recover, recover_state
from .state import SnapshotVersionMismatch

__all__ = [
    "DurabilityManager",
    "Journal",
    "JournalCorruption",
    "RecoveryReport",
    "SnapshotVersionMismatch",
    "journal_dir_from_env",
    "recover",
    "recover_state",
    "replay_journal",
]
