"""Durable control plane (L2, zero-dependency).

Write-ahead journal + snapshot + crash recovery under the JobStore and
the scheduler, removing the master as the one component whose crash
loses work — and, with the high-availability layer, whose crash causes
downtime at all. See docs/durability.md for the record schema, the
rotation/compaction policy, the recovery sequence, and the failover
protocol (lease, epoch fencing, replication lag).

    journal.py   — append-only CRC32 WAL, segment rotation, torn-tail
                   truncation on replay
    state.py     — the journaled state machine (one apply_record
                   shared by snapshot shadow, recovery replay, and the
                   standby replica)
    snapshot.py  — atomic snapshot write + segment/snapshot pruning
    recovery.py  — snapshot + WAL tail → live JobStore/scheduler
    manager.py   — DurabilityManager: the JobStore's journal_sink,
                   replication tee, and promotion adopter
    lease.py     — epoch-numbered master lease + FencedOut fencing
    quorum.py    — quorum lease backend (region mode: no shared fs)
    replicate.py — replication subscriptions + the standby replica
"""

from .journal import Journal, JournalCorruption, replay_journal
from .lease import FencedOut, Lease, LeaseHeld, LeaseLost, read_lease
from .manager import DurabilityManager, journal_dir_from_env
from .quorum import (
    FileLeasePeer,
    LeasePeerError,
    MemoryLeasePeer,
    QuorumLease,
    quorum_lease_from_env,
)
from .recovery import RecoveryReport, recover, recover_state
from .replicate import ReplicationSubscription, StandbyReplica
from .state import SnapshotVersionMismatch

__all__ = [
    "DurabilityManager",
    "FencedOut",
    "FileLeasePeer",
    "Journal",
    "JournalCorruption",
    "Lease",
    "LeaseHeld",
    "LeaseLost",
    "LeasePeerError",
    "MemoryLeasePeer",
    "QuorumLease",
    "RecoveryReport",
    "ReplicationSubscription",
    "SnapshotVersionMismatch",
    "StandbyReplica",
    "journal_dir_from_env",
    "quorum_lease_from_env",
    "read_lease",
    "recover",
    "recover_state",
    "replay_journal",
]
