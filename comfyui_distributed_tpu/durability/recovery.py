"""Crash recovery: snapshot + WAL tail → a live, resumable master.

The restart sequence (docs/durability.md has the diagram):

1. **load** the newest snapshot (None on first boot); a version
   mismatch aborts loudly (``SnapshotVersionMismatch``);
2. **replay** the journal tail — every record with lsn beyond the
   snapshot — through the same ``apply_record`` the snapshot shadow
   used, truncating a torn final frame and refusing CRC corruption
   anywhere else (``JournalCorruption``);
3. **prepare** the state for a new process: in-flight tiles revoked to
   pending (the old master's workers re-register via heartbeat),
   volatile completions demoted for bit-identical recompute, durable
   worker payloads kept for re-blend;
4. **materialize** live job objects into the JobStore and hand the
   scheduler its exported aggregates back, with admission lanes held
   PAUSED until a worker shows life (the manager resumes on the first
   post-recovery heartbeat).

Replay is a pure function of the on-disk bytes: running it twice
yields identical states (test-enforced), so a recovery interrupted by
a second crash simply runs again.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from ..utils.logging import log
from . import journal as journal_mod
from . import snapshot as snapshot_mod
from . import state as state_mod


@dataclasses.dataclass
class RecoveryReport:
    """What recovery found and did; served by /distributed/durability
    and written by scripts/durability_soak.py."""

    performed: bool = False
    snapshot_lsn: int = 0
    replayed_records: int = 0
    last_lsn: int = 0
    truncated_bytes: int = 0
    jobs_recovered: int = 0
    jobs_cancelled: int = 0
    tasks_requeued: int = 0
    tasks_restored: int = 0
    scheduler_restored: bool = False

    def as_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def recover_state(directory: str) -> tuple[dict[str, Any], RecoveryReport]:
    """Pure read side: (recovered-but-unprepared state, report).
    Torn-tail truncation is the only write this performs."""
    report = RecoveryReport()
    state = snapshot_mod.load_latest_snapshot(directory)
    if state is None:
        state = state_mod.new_state()
    else:
        report.snapshot_lsn = int(state.get("last_lsn", 0))
    replay = journal_mod.replay_journal(directory, after_lsn=report.snapshot_lsn)
    report.replayed_records = state_mod.replay_into(state, replay.records)
    report.truncated_bytes = replay.truncated_bytes
    report.last_lsn = max(int(state.get("last_lsn", 0)), replay.last_lsn)
    state["last_lsn"] = report.last_lsn
    report.performed = bool(
        report.snapshot_lsn or report.replayed_records or report.last_lsn
    )
    return state, report


def recover(
    directory: str,
    store: Any,
    scheduler: Any = None,
) -> tuple[dict[str, Any], RecoveryReport]:
    """Full recovery into a live JobStore (and scheduler): returns the
    PREPARED state (the manager adopts it as its snapshot shadow) and
    the report. The caller must not be serving traffic yet."""
    state, report = recover_state(directory)
    stats = state_mod.prepare_for_restart(state)
    report.tasks_requeued = stats["tasks_requeued"]
    report.tasks_restored = stats["tasks_restored"]
    report.jobs_cancelled = stats.get("jobs_cancelled", 0)
    jobs = state_mod.materialize(state)
    report.jobs_recovered = len(jobs)
    for job_id in sorted(jobs):
        store.tile_jobs[job_id] = jobs[job_id]
    scheduler_state = state.get("scheduler") or {}
    if scheduler is not None and scheduler_state:
        try:
            scheduler.restore_state(scheduler_state)
            report.scheduler_restored = True
        except Exception as exc:  # noqa: BLE001 - aggregates are advisory
            log(f"recovery: scheduler state restore failed: {exc}")
    if report.performed:
        log(
            f"recovery: {report.jobs_recovered} job(s) restored from "
            f"snapshot lsn {report.snapshot_lsn} + "
            f"{report.replayed_records} journal record(s); "
            f"{report.tasks_requeued} tile(s) requeued, "
            f"{report.tasks_restored} durable result(s) restored"
        )
    return state, report


def verify_idempotent_replay(directory: str) -> bool:
    """Replay the same on-disk state twice and compare: the invariant
    tier-1 enforces and operators can check from a REPL."""
    import json as _json

    first, _ = recover_state(directory)
    second, _ = recover_state(directory)
    return _json.dumps(first, sort_keys=True) == _json.dumps(second, sort_keys=True)


def pause_after_recovery(scheduler: Optional[Any]) -> bool:
    """Hold admission lanes until a worker re-registers (the manager
    resumes on the first post-recovery heartbeat). Returns whether a
    pause actually happened."""
    if scheduler is None:
        return False
    try:
        scheduler.pause()
        return True
    except Exception as exc:  # noqa: BLE001 - advisory
        log(f"recovery: scheduler pause failed: {exc}")
        return False
