"""Append-only write-ahead journal for the control plane.

ARIES discipline, scoped to the job store's state machine: every
mutation is appended (and optionally fsync'd) BEFORE the caller
acknowledges it, so a master killed at any instant can reconstruct
the exact set of acknowledged transitions on restart.

On-disk format — a directory of numbered segment files
(``segment-<n>.wal``), each a sequence of length-prefixed frames::

    [4B payload length, big-endian][4B CRC32 of payload][payload]

where payload is one UTF-8 JSON record carrying its log sequence
number (``lsn``) plus the typed fields the job store emitted
(docs/durability.md lists the record schema). Properties:

- **rotation** — when a segment crosses ``CDT_JOURNAL_SEGMENT_BYTES``
  it is fsync'd, closed, and a new segment is created with a directory
  fsync, so segment boundaries are themselves durable;
- **torn-tail truncation** — a crash mid-append leaves a final frame
  that is short or CRC-broken; replay truncates the LAST segment back
  to its last complete frame (the record was never acknowledged, so
  dropping it is correct). A broken frame anywhere else — mid-segment,
  or in a non-final segment — is real corruption and raises
  ``JournalCorruption`` loudly instead of skipping records;
- **fsync policy** — ``CDT_JOURNAL_FSYNC``: ``1`` (default) syncs
  every append (a power cut loses nothing acknowledged) and ``N>1``
  syncs every N appends — both write SYNCHRONOUSLY on the caller
  before the mutation is acknowledged (strict write-ahead). ``0`` is
  the page-cache **write-behind** mode: frames are serialized and
  sequenced on the caller (so ordering is exact) but written by a
  dedicated journal-writer thread, keeping filesystem latency spikes
  off the serving loop — the <5% overhead mode. Its loss window is
  the writer's in-flight queue: a SIGKILL can drop a SUFFIX of
  acknowledged records, and replay then recovers a consistent earlier
  prefix whose missing tiles recompute bit-identically (recovery
  correctness never depends on journal completeness, only on prefix
  consistency — docs/durability.md).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

import json

from ..telemetry import instruments
from ..utils.constants import _env_int
from ..utils.fsio import fsync_dir
from ..utils.logging import log

_HEADER = struct.Struct(">II")  # payload length, CRC32(payload)
SEGMENT_PREFIX = "segment-"
SEGMENT_SUFFIX = ".wal"
DEFAULT_SEGMENT_BYTES = 4 * 1024 * 1024


class JournalCorruption(Exception):
    """A CRC-broken or structurally impossible record that is NOT the
    journal's torn tail: state has been damaged after it was
    acknowledged, and recovery must stop rather than silently skip."""


def segment_path(directory: str, index: int) -> str:
    return os.path.join(directory, f"{SEGMENT_PREFIX}{index:08d}{SEGMENT_SUFFIX}")


def list_segments(directory: str) -> list[tuple[int, str]]:
    """(index, path) pairs in index order. Sorted numerically — replay
    order must never depend on readdir order."""
    out: list[tuple[int, str]] = []
    try:
        names = sorted(os.listdir(directory))
    except FileNotFoundError:
        return out
    for name in names:
        if not (name.startswith(SEGMENT_PREFIX) and name.endswith(SEGMENT_SUFFIX)):
            continue
        stem = name[len(SEGMENT_PREFIX) : -len(SEGMENT_SUFFIX)]
        try:
            out.append((int(stem), os.path.join(directory, name)))
        except ValueError:
            continue
    return sorted(out)


@dataclass
class ReplayResult:
    """What ``replay_journal`` saw on disk."""

    records: list[dict] = field(default_factory=list)
    last_lsn: int = 0
    segments: int = 0
    truncated_bytes: int = 0  # torn tail dropped from the final segment


def _iter_frames(path: str) -> Iterator[tuple[int, bool, bytes]]:
    """Yield (frame_offset, crc_ok, payload) for every structurally
    complete frame; a final short frame is signalled by a terminal
    (offset, False, b"") sentinel (payload empty = short, not CRC)."""
    with open(path, "rb") as fh:
        data = fh.read()
    offset = 0
    total = len(data)
    while offset < total:
        if offset + _HEADER.size > total:
            yield offset, False, b""
            return
        length, crc = _HEADER.unpack_from(data, offset)
        start = offset + _HEADER.size
        end = start + length
        if end > total:
            yield offset, False, b""
            return
        payload = data[start:end]
        yield offset, zlib.crc32(payload) == crc, payload
        offset = end


def replay_journal(
    directory: str, after_lsn: int = 0, truncate_torn_tail: bool = True
) -> ReplayResult:
    """Read every record with lsn > ``after_lsn`` across all segments.

    The final segment's torn tail (short or CRC-broken LAST frame) is
    truncated away when ``truncate_torn_tail`` — that frame was never
    acknowledged. Any other broken frame raises ``JournalCorruption``.
    Pure function of the directory contents otherwise: replaying twice
    yields identical results (test-enforced).
    """
    result = ReplayResult()
    segments = list_segments(directory)
    result.segments = len(segments)
    for seg_pos, (_idx, path) in enumerate(segments):
        is_last_segment = seg_pos == len(segments) - 1
        frames = list(_iter_frames(path))
        for frame_pos, (offset, ok, payload) in enumerate(frames):
            is_last_frame = frame_pos == len(frames) - 1
            if not ok:
                if is_last_segment and is_last_frame:
                    if truncate_torn_tail:
                        size = os.path.getsize(path)
                        with open(path, "r+b") as fh:
                            fh.truncate(offset)
                            fh.flush()
                            os.fsync(fh.fileno())
                        result.truncated_bytes = size - offset
                        log(
                            f"journal: truncated torn tail of {path} "
                            f"({result.truncated_bytes} bytes)"
                        )
                    else:
                        result.truncated_bytes = os.path.getsize(path) - offset
                    break
                raise JournalCorruption(
                    f"{path}: broken record at byte {offset} is not the "
                    "journal tail; refusing to skip acknowledged state"
                )
            try:
                record = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as exc:
                raise JournalCorruption(
                    f"{path}: CRC-valid frame at byte {offset} is not "
                    f"JSON: {exc}"
                ) from exc
            lsn = int(record.get("lsn", 0))
            if lsn <= 0:
                raise JournalCorruption(
                    f"{path}: record at byte {offset} carries no lsn"
                )
            if lsn <= result.last_lsn and lsn > after_lsn:
                raise JournalCorruption(
                    f"{path}: lsn {lsn} at byte {offset} is not "
                    f"monotonic (last {result.last_lsn})"
                )
            result.last_lsn = max(result.last_lsn, lsn)
            if lsn > after_lsn:
                result.records.append(record)
    return result


class Journal:
    """The append side. Thread-safe: appends may arrive from any loop
    or thread (the job store's asyncio methods and test fallbacks).

    Two write modes by fsync policy:

    - ``fsync_every >= 1`` — strict write-ahead: frame, write, flush
      (and fsync per policy) happen synchronously on the caller before
      ``append`` returns;
    - ``fsync_every == 0`` — write-behind group commit: the frame is
      serialized and sequenced on the caller (ordering is exact) and
      handed to a dedicated writer thread, so a filesystem latency
      spike never stalls the serving loop mid-pipeline. A writer-side
      failure is surfaced on the NEXT append/close — the journal never
      silently drops acknowledged state.
    """

    _CLOSE = object()

    def __init__(
        self,
        directory: str,
        next_lsn: int = 1,
        segment_bytes: Optional[int] = None,
        fsync_every: Optional[int] = None,
    ) -> None:
        self.directory = directory
        self.segment_bytes = (
            segment_bytes
            if segment_bytes is not None
            else _env_int("CDT_JOURNAL_SEGMENT_BYTES", DEFAULT_SEGMENT_BYTES)
        )
        self.fsync_every = (
            fsync_every if fsync_every is not None else _env_int("CDT_JOURNAL_FSYNC", 1)
        )
        # Reentrant: the sync write path appends (and may rotate) while
        # holding the lock; the writer thread takes it briefly for the
        # shared rotation bookkeeping.
        self._lock = threading.RLock()
        self._next_lsn = max(1, int(next_lsn))
        self._fh = None
        self._segment_index = 0
        self._appends_since_sync = 0
        # (path, last_lsn) of segments closed by rotation, for pruning.
        self._closed: list[tuple[str, int]] = []
        self._writer: Optional[threading.Thread] = None
        self._queue = None
        # Sticky: once a write-behind frame fails, the journal is dead
        # — later frames are DISCARDED (suffix loss, the documented
        # contract) and every subsequent append raises. Writing past a
        # failed frame would punch an undetectable mid-stream hole in
        # acknowledged state instead.
        self._writer_error: Optional[BaseException] = None
        os.makedirs(directory, exist_ok=True)
        existing = list_segments(directory)
        self._segment_index = (existing[-1][0] + 1) if existing else 1
        # Segments already on disk are never appended to again (their
        # tails may have been truncated by replay); note them as closed
        # with "everything before next_lsn" so pruning can retire them.
        for _idx, path in existing:
            self._closed.append((path, self._next_lsn - 1))
        self._open_segment()
        if self.fsync_every == 0:
            import queue as _queue

            self._queue = _queue.SimpleQueue()
            self._writer = threading.Thread(
                target=self._writer_body, name="cdt-journal-writer", daemon=True
            )
            self._writer.start()

    # --- segment lifecycle ------------------------------------------------

    @property
    def _syncing(self) -> bool:
        """False in the page-cache mode (CDT_JOURNAL_FSYNC=0): fsync
        only buys power-cut durability there, and on slow filesystems
        costs tens of ms per call — the documented overhead trade."""
        return self.fsync_every > 0

    def _open_segment(self) -> None:
        path = segment_path(self.directory, self._segment_index)
        self._fh = open(path, "ab")
        if self._syncing:
            fsync_dir(self.directory)

    def _rotate(self, last_lsn: int) -> None:
        """Close the current segment and open the next. Called by
        whichever thread owns the file (caller in sync mode, the writer
        thread in write-behind mode)."""
        fh = self._fh
        path = segment_path(self.directory, self._segment_index)
        fh.flush()
        if self._syncing:
            os.fsync(fh.fileno())
        fh.close()
        with self._lock:
            self._closed.append((path, last_lsn))
            self._segment_index += 1
        self._open_segment()

    # --- appends ----------------------------------------------------------

    def append(self, record: dict) -> int:
        """Frame one record and make it durable per the fsync policy;
        returns its assigned lsn. The record dict is not mutated.
        Thread-safe: lsn assignment and the write/enqueue happen under
        one lock, so concurrent appenders can never land frames out of
        lsn order (replay treats non-monotonic lsns as corruption)."""
        with self._lock:
            if self._writer_error is not None:
                raise self._writer_error  # sticky: the journal is dead
            lsn = self._next_lsn
            payload = json.dumps(
                {"lsn": lsn, **record}, separators=(",", ":"), sort_keys=True
            ).encode("utf-8")
            self._next_lsn += 1
            frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
            if self._queue is not None:
                self._queue.put((frame, lsn))
            else:
                self._write_frame(frame, lsn)
        instruments.journal_appends_total().inc(
            record=str(record.get("type", "unknown"))
        )
        return lsn

    def _write_frame(self, frame: bytes, lsn: int) -> None:
        fh = self._fh
        fh.write(frame)
        fh.flush()
        if self._syncing:
            self._appends_since_sync += 1
            if self._appends_since_sync >= self.fsync_every:
                started = time.monotonic()
                os.fsync(fh.fileno())
                instruments.journal_fsync_seconds().observe(
                    time.monotonic() - started
                )
                self._appends_since_sync = 0
        if fh.tell() >= self.segment_bytes:
            self._rotate(lsn)

    def _writer_body(self) -> None:
        """Write-behind drain loop: frames arrive in lsn order and are
        written in lsn order, so a SIGKILL mid-queue loses only a
        SUFFIX — replay still reconstructs a consistent prefix. The
        same prefix rule governs failures: after the FIRST failed
        frame, every later frame is discarded (never written past the
        hole) and the sticky error fails all subsequent appends."""
        failed = False
        while True:
            item = self._queue.get()
            if item is self._CLOSE:
                return
            if isinstance(item, threading.Event):  # sync barrier
                try:
                    if not failed:
                        self._fh.flush()
                        os.fsync(self._fh.fileno())
                except OSError as exc:
                    failed = True
                    with self._lock:
                        if self._writer_error is None:
                            self._writer_error = exc
                finally:
                    item.set()
                continue
            frame, lsn = item
            if failed:
                continue  # discard: suffix loss, never a mid-stream hole
            try:
                self._write_frame(frame, lsn)
            except Exception as exc:  # noqa: BLE001 - surfaced on next append
                failed = True
                with self._lock:
                    if self._writer_error is None:
                        self._writer_error = exc
                log(
                    f"journal: write-behind append of lsn {lsn} failed; "
                    f"journal halted, later frames discarded: {exc}"
                )

    # --- maintenance ------------------------------------------------------

    @property
    def next_lsn(self) -> int:
        with self._lock:
            return self._next_lsn

    def prune(self, upto_lsn: int) -> list[str]:
        """Delete closed segments whose every record is covered by a
        snapshot at ``upto_lsn``; returns the removed paths."""
        removed: list[str] = []
        with self._lock:
            keep: list[tuple[str, int]] = []
            for path, last_lsn in self._closed:
                if last_lsn <= upto_lsn:
                    try:
                        os.remove(path)
                        removed.append(path)
                    except OSError as exc:
                        log(f"journal: prune of {path} failed: {exc}")
                        keep.append((path, last_lsn))
                else:
                    keep.append((path, last_lsn))
            self._closed = keep
        if removed:
            fsync_dir(self.directory)
        return removed

    def sync(self) -> None:
        """Block until everything appended so far is fsync'd (barrier
        through the writer thread in write-behind mode)."""
        if self._queue is not None:
            barrier = threading.Event()
            self._queue.put(barrier)
            barrier.wait(timeout=60)
            return
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._appends_since_sync = 0

    def close(self) -> None:
        if self._writer is not None:
            self._queue.put(self._CLOSE)
            self._writer.join(timeout=60)
            self._writer = None
        with self._lock:
            error, self._writer_error = self._writer_error, None
            if self._fh is not None:
                self._fh.flush()
                if self._syncing:
                    os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None
        if error is not None:
            raise error

    def status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "directory": self.directory,
                "next_lsn": self._next_lsn,
                "segment_index": self._segment_index,
                "segment_bytes": self.segment_bytes,
                "fsync_every": self.fsync_every,
                "write_behind": self._queue is not None,
                "closed_segments": len(self._closed),
            }
