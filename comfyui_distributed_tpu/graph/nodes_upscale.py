"""UltimateSDUpscaleDistributed node.

Facade over ops/upscale.py mirroring the reference's node surface
(reference nodes/distributed_upscale.py): image + model/conditioning/
vae + sampling knobs + tile geometry in, upscaled image out. Mode
routing (reference _determine_processing_mode):

- mesh participants available → static tile sharding over ICI
  (ops/upscale.upscale_mesh) — one SPMD program;
- no participants → local scan over tiles;
- elastic HTTP workers → master/worker tile-queue loops
  (graph/usdu_elastic.py) with heartbeats and requeue.

The 4n+1 video-batch constraint of WAN-style models is validated here
like the reference does (reference nodes/distributed_upscale.py:131-142).
"""

from __future__ import annotations

from typing import Any

import jax

from ..models import pipeline as pl
from ..ops import upscale as upscale_ops
from ..utils.logging import log
from .registry import register_node


@register_node
class UltimateSDUpscaleDistributed:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "model": ("MODEL",),
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "vae": ("VAE",),
                "seed": ("INT", {"default": 0}),
                "steps": ("INT", {"default": 20}),
                "cfg": ("FLOAT", {"default": 7.0}),
                "sampler_name": ("STRING", {"default": "euler"}),
                "scheduler": ("STRING", {"default": "karras"}),
                "denoise": ("FLOAT", {"default": 0.35}),
                "upscale_by": ("FLOAT", {"default": 2.0}),
                "tile_width": ("INT", {"default": 512}),
                "tile_height": ("INT", {"default": 512}),
                "tile_padding": ("INT", {"default": 32}),
            },
            "optional": {
                "upscale_method": ("STRING", {"default": "bicubic"}),
                "mask_blur": ("INT", {"default": 8}),
                "tiled_decode": ("BOOLEAN", {"default": False}),
                "force_uniform_tiles": ("BOOLEAN", {"default": True}),
                "dynamic_threshold": ("INT", {"default": 8}),
                "upscale_model": ("UPSCALE_MODEL", {"default": None}),
            },
            "hidden": {
                "is_worker": ("BOOLEAN", {"default": False}),
                "worker_id": ("STRING", {"default": ""}),
                "master_url": ("STRING", {"default": ""}),
                "job_id": ("STRING", {"default": ""}),
            },
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "run"
    # IS_CHANGED = nan parity: the reference forces re-execution every
    # queue; NEVER_CACHE opts out of the executor's cross-run cache.
    NEVER_CACHE = True

    def run(
        self,
        image,
        model: pl.PipelineBundle,
        positive,
        negative,
        vae,
        seed=0,
        steps=20,
        cfg=7.0,
        sampler_name="euler",
        scheduler="karras",
        denoise=0.35,
        upscale_by=2.0,
        tile_width=512,
        tile_height=512,
        tile_padding=32,
        upscale_method="bicubic",
        mask_blur=8,
        tiled_decode=False,
        force_uniform_tiles=True,
        dynamic_threshold=8,
        upscale_model=None,
        is_worker=False,
        worker_id="",
        master_url="",
        job_id="",
        enabled_worker_ids=None,
        context=None,
        **_extra: Any,
    ):
        from ..ops.samplers import SAMPLER_NAMES

        seed = getattr(seed, "base_seed", seed)  # accept SeedSpec links
        if sampler_name not in SAMPLER_NAMES:
            raise ValueError(f"unknown sampler {sampler_name!r}")
        if vae is not None and vae.vae is not model.vae:
            # a standalone VAE (VAELoader) replaces the checkpoint's
            # bundled one for the tile encode/decode — ops/upscale
            # reads the VAE off the model bundle, so graft it on
            import dataclasses

            model = dataclasses.replace(
                model,
                vae=vae.vae,
                params={**model.params, "vae": vae.params["vae"]},
                latent_channels=vae.latent_channels,
                latent_scale=vae.latent_scale,
            )
        # force_uniform_tiles=False keeps the reference's non-uniform
        # seam positions (reference upscale/tile_ops.py:73-78) but with
        # static tile shapes: edge tiles overhang into an edge-extended
        # canvas strip that blending crops (ops/tiles.py module doc).
        batch = int(image.shape[0])
        if batch > 1 and (batch - 1) % 4 != 0:
            # WAN-family video models require 4n+1 frame batches
            log(f"USDU: batch {batch} is not 4n+1; video models may reject it")

        tile = int(tile_width)
        tile_h = int(tile_height)
        mesh = getattr(context, "mesh", None) if context is not None else None
        enabled = enabled_worker_ids or []

        if upscale_model is not None:
            # model-based pre-upscale to the exact target, then tiles
            # refine at 1x (reference USDU upscale_model semantics).
            # Deterministic per model name, so every participant
            # reproduces the identical pre-upscaled image.
            b, h, w, c = image.shape
            target_h = int(round(h * float(upscale_by) / 8)) * 8
            target_w = int(round(w * float(upscale_by) / 8)) * 8
            image = upscale_model.upscale(image)
            if image.shape[1] != target_h or image.shape[2] != target_w:
                image = jax.image.resize(
                    image, (b, target_h, target_w, c), method="cubic"
                )
            upscale_by = 1.0

        # Mode selection, decided identically on master and workers from
        # shared inputs (reference _determine_processing_mode): dynamic
        # (whole-image queue) for large video batches, static (tile
        # queue) otherwise.
        dynamic = batch > 1 and batch >= int(dynamic_threshold)
        common = dict(
            bundle=model, image=image, pos=positive, neg=negative,
            upscale_by=float(upscale_by), tile=tile, tile_h=tile_h,
            padding=int(tile_padding), steps=int(steps),
            sampler=sampler_name, scheduler=scheduler, cfg=float(cfg),
            denoise=float(denoise), seed=int(seed),
            upscale_method=upscale_method, context=context,
            mask_blur=int(mask_blur), tiled_decode=bool(tiled_decode),
            uniform=bool(force_uniform_tiles),
        )

        if is_worker:
            from .usdu_elastic import run_worker_dynamic, run_worker_loop

            worker_fn = run_worker_dynamic if dynamic else run_worker_loop
            worker_fn(
                job_id=job_id, worker_id=worker_id, master_url=master_url,
                **common,
            )
            return (image,)

        if enabled and getattr(context, "server", None) is not None:
            from .usdu_elastic import run_master_dynamic, run_master_elastic

            if dynamic:
                return (
                    run_master_dynamic(
                        job_id=job_id, enabled_worker_ids=list(enabled), **common
                    ),
                )
            return (
                run_master_elastic(
                    job_id=job_id, enabled_worker_ids=list(enabled),
                    mesh=mesh, **common,
                ),
            )

        out = upscale_ops.run_upscale(
            bundle=model, image=image, pos=positive, neg=negative, mesh=mesh,
            upscale_by=float(upscale_by), tile=tile, tile_h=tile_h,
            padding=int(tile_padding),
            steps=int(steps), sampler=sampler_name, scheduler=scheduler,
            cfg=float(cfg), denoise=float(denoise), seed=int(seed),
            upscale_method=upscale_method,
            mask_blur=int(mask_blur), tiled_decode=bool(tiled_decode),
            uniform=bool(force_uniform_tiles),
        )
        return (out,)
