"""Input/output directory resolution for media nodes.

The reference delegates to ComfyUI's folder_paths; here directories
come from config (settings.output_dir / settings.input_dir) with
sane defaults under the repo/package root, overridable by env.
"""

from __future__ import annotations

import os

from ..utils.exceptions import DistributedError


def _base_dir() -> str:
    return os.environ.get("CDT_DATA_DIR", os.path.join(os.getcwd(), "data"))


def get_output_dir(context=None) -> str:
    cfg = getattr(context, "config", None) or {}
    return (
        os.environ.get("CDT_OUTPUT_DIR")
        or cfg.get("settings", {}).get("output_dir")
        or os.path.join(_base_dir(), "output")
    )


def get_input_dir(context=None) -> str:
    cfg = getattr(context, "config", None) or {}
    return (
        os.environ.get("CDT_INPUT_DIR")
        or cfg.get("settings", {}).get("input_dir")
        or os.path.join(_base_dir(), "input")
    )


def resolve_input_path(name: str, context=None) -> str:
    """Find a media file by name: absolute paths pass through; bare
    names resolve against the input dir. Rejects path escapes."""
    if os.path.isabs(name):
        return name
    base = get_input_dir(context)
    path = os.path.normpath(os.path.join(base, name))
    if not path.startswith(os.path.normpath(base) + os.sep) and path != os.path.normpath(base):
        raise DistributedError(f"input path {name!r} escapes input dir")
    return path


def next_counter(out_dir: str, prefix: str, ext: str) -> int:
    """First free <prefix>_NNNNN.<ext> counter: max existing + 1 (the
    ComfyUI counter-scan convention — never clobbers on gaps, unlike a
    len() count). Shared by SaveImage and the animated savers."""
    suffix = f".{ext}"
    start = 0
    for f in os.listdir(out_dir):
        if not (f.startswith(f"{prefix}_") and f.endswith(suffix)):
            continue
        stem = f[len(prefix) + 1 : -len(suffix)]
        if stem.isdigit():
            start = max(start, int(stem) + 1)
    return start
