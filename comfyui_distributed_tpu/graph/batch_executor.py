"""Cross-job continuous batching with step-level preemption.

PR 8 made the K x D-chip device batch the unit of work, but batching
stayed per-grant within ONE job: at many-small-concurrent-jobs traffic
the steady state is ragged grants that under-fill the device batch —
chips run wraparound padding while other jobs' tiles wait in other
queues. This module is the vLLM/Orca-style answer (iteration-level
scheduling) transplanted to tile diffusion:

- **one ready-queue, many jobs** — registered jobs feed
  ``(job, tile)`` work items into a shape-bucketed ready queue keyed
  by the job's ``StepwiseProcessor.signature`` (same geometry + model
  + sampler config = same compiled programs = batchable together).
  Each scheduling round composes ONE device batch from the
  most-urgent signature group's items — across jobs and tenants —
  padded to the bounded ``ops/upscale.grant_buckets`` set exactly like
  the per-job tier, so compile counts stay bounded and the padding is
  wraparound duplicates of real items.

- **iteration-level scheduling** — work advances ONE denoise step per
  dispatch (ops/stepwise.py): items at different step indices share a
  batch (the step index is a traced per-item input), finished items
  decode + leave, new items join at the next boundary. That is what
  lets a premium tile start next-step instead of next-grant.

- **step-level preemption** — when a job's client reports a
  preemption request (the master's scheduler/preempt.py coordinator
  raised it for a premium-lane arrival, or brownout eviction), the
  executor checkpoints that job's in-flight latents at the NEXT step
  boundary (``encode_checkpoint``: latents + step index; the fold key
  is recomputed from job key + tile index) and hands every claimed
  tile back through the job's ``release`` callback — the existing
  ``release_tasks``/``return_tiles`` requeue path, now carrying
  checkpoints. On re-grant the tile resumes from its checkpoint; a
  lost checkpoint (worker crash, master restart — checkpoints are
  volatile by design) falls back to recompute-from-step-0, which is
  the bit-identity reference.

Determinism contract (tests/graph/test_batch_executor.py +
tests/test_chaos_xjob.py): a tile's output is bit-identical whether it
is sampled alone, batched with its own job, or batched with another
tenant's tiles — per-item inputs are pure functions of (job key, tile
index, step index), vmap batching never mixes lanes of the batch, and
the per-job fold key gains the job id (parallel/seeds.fold_job_key) so
two jobs sharing a user seed still draw independent streams.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Optional, Sequence

from ..ops.stepwise import (
    CheckpointError,
    decode_checkpoint,
    encode_checkpoint,
    validate_checkpoint_meta,
)
from ..telemetry.instruments import (
    batch_fill_ratio,
    pipeline_batches_total,
    pipeline_padded_tiles_total,
    preempt_resume_total,
    tiles_processed_total,
)
from ..telemetry.profiling import (
    D2H,
    H2D,
    ledger_if_enabled,
    transfer_nbytes,
)
from ..telemetry.usage import (
    SLOT_PADDING,
    SLOT_REAL,
    SLOT_RECOMPUTE,
    get_usage_meter,
)
from ..utils.logging import debug_log
from .tile_pipeline import stage_span


class XJobHandle:
    """One registered job's data + client seam for the executor.

    ``proc`` carries (init, step, finish, n_steps, signature) — see
    ops/stepwise.StepwiseProcessor; chaos/test stubs pass plain
    callables with a hand-made signature. Jobs whose signatures are
    EQUAL may share device batches; the executor never mixes
    signatures in one dispatch.

    Client callbacks (all run on the executor thread):

      pull()            -> {"tile_idxs": [...], "checkpoints": {...}}
                           | None (nothing pullable now = drained)
      emit(idx, arr)    one finished tile (host [B, h, w, C])
      flush(final)      submit pending results (size thresholds inside)
      release(idxs, checkpoints)  hand claimed tiles back on preemption
      preempt_check()   -> bool: the master wants this job evicted
      heartbeat()       optional liveness ping
    """

    def __init__(
        self,
        *,
        job_id: str,
        proc: Any,
        params: Any,
        extracted: Any,
        positions: Any,
        pos: Any,
        neg: Any,
        base_key: Any,
        pull: Callable[[], Optional[dict]],
        emit: Callable[[int, Any], None],
        flush: Callable[[bool], None],
        release: Optional[Callable[[list[int], dict], None]] = None,
        preempt_check: Optional[Callable[[], bool]] = None,
        heartbeat: Optional[Callable[[], None]] = None,
        check_interrupted: Optional[Callable[[], None]] = None,
        tenant: str = "default",
        lane: str = "",
        priority: int = 0,
        adapter: Any = None,
        device_emit: bool = False,
    ) -> None:
        self.job_id = str(job_id)
        self.proc = proc
        # Adapter plane (adapters/segmented.SegmentOperands | None):
        # when set, this job's tiles sample with the per-slot low-rank
        # patch and batch under the EXTENDED signature — rank bucket +
        # target-path digest — so same-bucket jobs wearing *different*
        # adapters still share one compiled program, while adapter-less
        # jobs keep the unmodified signature (and bit-identity).
        self.adapter = adapter
        if adapter is not None:
            from ..adapters import adapter_signature

            self.sig = adapter_signature(proc.signature, adapter)
        else:
            self.sig = proc.signature
        self.params = params
        self.extracted = extracted
        self.positions = positions
        self.pos = pos
        self.neg = neg
        self.base_key = base_key
        self.pull = pull
        self.emit = emit
        self.flush = flush
        self.release = release
        self.preempt_check = preempt_check
        self.heartbeat = heartbeat
        self.check_interrupted = check_interrupted
        self.tenant = str(tenant)
        self.lane = str(lane)
        # device_emit: this job's emit() accepts DEVICE arrays — the
        # executor skips the per-tile host readback and the consumer
        # (a DeviceCanvas master) owns the single composited d2h
        self.device_emit = bool(device_emit)
        # lower = more urgent; ties broken by registration order so
        # scheduling is a pure function of the registered sequence
        self.priority = int(priority)
        self.seq = 0  # assigned at register()
        self.done = False
        self.error: Optional[BaseException] = None
        # set when the executor finishes (drain) or fails this job —
        # the blocking production entries park on it
        self.finished = threading.Event()
        self.preempted = False  # currently evicted by request
        self.tiles_done = 0
        # (executor-local) tiles this job has claimed from its master
        # and neither emitted nor released — the crash-release set
        self.claimed: set[int] = set()


class _Item:
    """One tile's position in the executor: job, index, step cursor,
    and (after init / checkpoint adoption) its latent state."""

    __slots__ = (
        "job", "tile_idx", "step", "x", "key", "seq", "resumed",
        "recompute_until",
    )

    def __init__(self, job: XJobHandle, tile_idx: int, seq: int):
        self.job = job
        self.tile_idx = int(tile_idx)
        self.step = 0
        self.x = None
        self.key = None
        self.seq = seq  # arrival order; ties in priority break on this
        self.resumed = False
        # steps below this index are RE-RUNS of work a preemption
        # eviction already paid for (checkpoint lost → recompute): the
        # usage meter charges them to waste{preempt_recompute}
        self.recompute_until = 0

    def order(self) -> tuple[int, int, int]:
        return (self.job.priority, self.job.seq, self.seq)


class CrossJobExecutor:
    """Drains registered jobs through shared, shape-bucketed,
    step-granular device batches. Single driver thread (``run``);
    ``register`` may be called from any thread — new jobs are picked
    up at the next scheduling round.

    ``k_max``: device batch width (callers pass
    ``tile_scan_batch() x D`` exactly like GrantSampler).
    ``bucket_multiple``: buckets round up to multiples of this (the
    mesh data-axis width D), so every participant holds an equal
    slice — same rule as the mesh-aware GrantSampler.
    ``cross_job=False`` restricts every batch to a single job's items
    (the per-job baseline the bench A/Bs against).
    """

    def __init__(
        self,
        *,
        k_max: int = 8,
        bucket_multiple: int = 1,
        mesh: Any = None,
        role: str = "worker",
        cross_job: bool = True,
        preempt_enabled: bool = True,
        idle_poll_seconds: float = 0.02,
        clock: Callable[[], float] = time.monotonic,
        usage_meter: Any = None,
    ) -> None:
        from ..ops.upscale import grant_buckets
        from ..utils.constants import USAGE_ENABLED

        self.k_max = max(1, int(k_max))
        self.mesh = mesh
        self.role = str(role)
        self.cross_job = bool(cross_job)
        self.preempt_enabled = bool(preempt_enabled)
        self.idle_poll_seconds = float(idle_poll_seconds)
        self.clock = clock
        dp = max(1, int(bucket_multiple))
        if mesh is not None:
            from ..parallel.mesh import data_axis_size

            dp = max(dp, data_axis_size(mesh))
        self.bucket_multiple = dp
        if dp > 1:
            self.k_max = max(self.k_max, dp)
            self.buckets = tuple(
                sorted({max(dp, -(-b // dp) * dp) for b in grant_buckets(self.k_max)})
            )
        else:
            self.buckets = grant_buckets(self.k_max)
        self._lock = threading.Lock()
        self._jobs: dict[str, XJobHandle] = {}
        self._job_seq = 0
        self._item_seq = 0
        # signature -> live items (ready or mid-trajectory). One flat
        # list per signature: scheduling sorts by (priority, seq) each
        # round, which is cheap at device-batch scale and keeps the
        # policy in one place.
        self._items: dict[tuple, list[_Item]] = {}
        self._sig_order: list[tuple] = []  # first-seen signature order
        self._vstep_cache: dict[tuple, Any] = {}
        self._shardings: dict[int, Any] = {}
        # (job_id, tile_idx) -> step reached when this executor evicted
        # the tile: a later arrival without a checkpoint is a
        # recompute-from-0 resume, and the usage meter charges its
        # re-run steps (below that mark) to waste{preempt_recompute}
        self._evicted: dict[tuple[str, int], int] = {}
        # Device-resident latent stash (CDT_XJOB_DEVICE_RESIDENT):
        # (job_id, tile_idx) -> (device latent, step) kept at eviction
        # so a re-grant on THIS executor resumes without re-uploading
        # the host checkpoint (the host copy becomes the lazy spill —
        # written at the preemption boundary, read only when the tile
        # lands elsewhere or the stash was evicted). Insertion-ordered
        # dict = deterministic FIFO eviction under the byte budget.
        self._device_stash: dict[tuple[str, int], tuple[Any, int]] = {}
        self._device_stash_bytes = 0
        # chip-time attribution (telemetry/usage.py); None = disabled
        self.usage = usage_meter if usage_meter is not None else (
            get_usage_meter() if USAGE_ENABLED else None
        )
        self._chips = 1
        if mesh is not None:
            from ..parallel.mesh import data_axis_size as _das

            self._chips = max(1, _das(mesh))
        self._stop = threading.Event()
        # --- accounting (read by bench + chaos assertions) ---------------
        self.dispatches = 0
        self.slots_real = 0
        self.slots_padded = 0
        self.steps_run = 0
        self.tiles_finished = 0
        self.preempt_evictions = 0
        self.resumes_checkpoint = 0
        self.resumes_recompute = 0
        self.resumes_device = 0
        # completion order for scheduling assertions: (job_id, tile_idx).
        # Bounded: the PROCESS-shared executor outlives jobs, so an
        # unbounded list would grow one entry per tile served forever.
        self.completion_order: list[tuple[str, int]] = []
        self._max_completion_order = 65536

    # --- registration -----------------------------------------------------

    def register(self, job: XJobHandle) -> XJobHandle:
        with self._lock:
            if job.job_id in self._jobs:
                raise ValueError(f"job {job.job_id!r} already registered")
            self._job_seq += 1
            job.seq = self._job_seq
            self._jobs[job.job_id] = job
            sig = job.sig
            if sig not in self._items:
                self._items[sig] = []
                self._sig_order.append(sig)
        if self.usage is not None:
            # advisory attrs (the store's init path lands the
            # authoritative tenant/lane on masters)
            self.usage.note_job_attrs(job.job_id, job.tenant, job.lane)
        return job

    def stop(self) -> None:
        self._stop.set()

    @property
    def active_jobs(self) -> int:
        with self._lock:
            return len(self._jobs)

    def fill_ratio(self) -> float:
        total = self.slots_real + self.slots_padded
        return (self.slots_real / total) if total else 1.0

    # --- device programs --------------------------------------------------

    def _vstep(
        self,
        sig: tuple,
        step_one: Callable,
        adapter_paths: Optional[tuple] = None,
    ) -> Callable:
        """The batched one-step program for a signature: vmapped over
        (x, key, pos, neg, yx, i) with params shared. Jitted only when
        the per-item step is itself compiled (production) — raw Python
        stubs stay eager so the chaos parity suite's bit-identity
        against the serial path survives XLA's batch-size-specific
        rewrites (the PR 5 jit-vs-eager ulp hazard).

        ``adapter_paths`` (adapter-extended signatures only) grows the
        arity by per-slot (downs, ups, scale) operands applied as a
        low-rank weight patch inside each lane: params broadcast, only
        the targeted leaves batch. The jit gate stays on the UNDERLYING
        step — the adapter wrapper is plain Python on top of it."""
        cached = self._vstep_cache.get(sig)
        if cached is not None:
            return cached
        import jax

        if adapter_paths is not None:
            from ..adapters import make_adapter_step

            wrapped = make_adapter_step(step_one, adapter_paths)
            vmapped = jax.vmap(
                wrapped, in_axes=(None, 0, 0, 0, 0, 0, 0, 0, 0, 0)
            )
        else:
            vmapped = jax.vmap(step_one, in_axes=(None, 0, 0, 0, 0, 0, 0))
        # donate the stacked latents (arg 1): XLA aliases the input
        # batch buffer into the output, so the per-step loop holds ONE
        # batch-of-latents allocation instead of two. Safe because
        # _step_batch stacks xs fresh per dispatch (the stack is a
        # copy; per-item latents are never themselves donated), and
        # nothing reads xs after the call — outputs scatter back to
        # item.x. Raw Python stubs stay eager AND undonated (donation
        # is a jit concept).
        fn = (
            jax.jit(vmapped, donate_argnums=(1,))
            if hasattr(step_one, "lower")
            else vmapped
        )
        self._vstep_cache[sig] = fn
        return fn

    def _place(self, batched: tuple) -> tuple:
        """Pin every batched input's leading axis across the mesh's
        data axis (NamedSharding), replicating trailing dims — the
        GrantSampler._place idiom generalized to pytrees. No-op
        without a data-parallel mesh."""
        if self.mesh is None:
            return batched
        from ..parallel.mesh import DATA_AXIS, data_axis_size

        if data_axis_size(self.mesh) <= 1:
            return batched
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def shard_leaf(leaf):
            ndim = getattr(leaf, "ndim", 0)
            if ndim < 1:
                return leaf
            sharding = self._shardings.get(ndim)
            if sharding is None:
                sharding = NamedSharding(
                    self.mesh, P(DATA_AXIS, *([None] * (ndim - 1)))
                )
                self._shardings[ndim] = sharding
            return jax.device_put(leaf, sharding)

        started = time.monotonic()
        placed = tuple(
            jax.tree_util.tree_map(shard_leaf, part) for part in batched
        )
        ledger = ledger_if_enabled()
        if ledger is not None:
            nbytes = sum(
                transfer_nbytes(leaf)
                for part in batched
                for leaf in jax.tree_util.tree_leaves(part)
            )
            ledger.note_transfer(H2D, nbytes, time.monotonic() - started)
        return placed

    # --- grant intake -----------------------------------------------------

    def _tile_key(self, job: XJobHandle, tile_idx: int):
        import jax

        return jax.random.fold_in(job.base_key, int(tile_idx))

    # --- device-resident latent stash --------------------------------------

    def _stash_put(self, job_id: str, tile_idx: int, x: Any, step: int) -> None:
        """Park an evicted tile's device latent for re-grant on this
        executor. Bounded by CDT_XJOB_DEVICE_RESIDENT_MB with FIFO
        eviction (insertion order — deterministic); a latent larger
        than the whole budget is never stashed."""
        from ..utils.constants import (
            xjob_device_resident_budget_bytes,
            xjob_device_resident_enabled,
        )

        if not xjob_device_resident_enabled():
            return
        nbytes = int(getattr(x, "nbytes", 0))
        budget = xjob_device_resident_budget_bytes()
        if nbytes <= 0 or nbytes > budget:
            return
        stale = self._device_stash.pop((job_id, tile_idx), None)
        if stale is not None:
            self._device_stash_bytes -= int(getattr(stale[0], "nbytes", 0))
        self._device_stash[(job_id, tile_idx)] = (x, int(step))
        self._device_stash_bytes += nbytes
        while self._device_stash_bytes > budget and len(self._device_stash) > 1:
            mark = next(iter(self._device_stash))
            old, _ = self._device_stash.pop(mark)
            self._device_stash_bytes -= int(getattr(old, "nbytes", 0))

    def _stash_take(self, job_id: str, tile_idx: int, step: int) -> Any:
        """Pop the stashed latent iff its step matches the checkpoint's
        resume step (the checkpoint payload stays the authoritative
        resume instruction; the stash only elides its decode + H2D).
        Returns None on miss or step mismatch."""
        entry = self._device_stash.pop((job_id, tile_idx), None)
        if entry is None:
            return None
        self._device_stash_bytes -= int(getattr(entry[0], "nbytes", 0))
        if entry[1] != int(step):
            return None
        return entry[0]

    def _drop_job_stash(self, job_id: str) -> None:
        dead = [mark for mark in self._device_stash if mark[0] == job_id]
        for mark in dead:
            x, _ = self._device_stash.pop(mark)
            self._device_stash_bytes -= int(getattr(x, "nbytes", 0))

    def _adopt_grant(self, job: XJobHandle, grant: dict) -> int:
        """Turn one pull answer into ready items; returns item count.
        Checkpoints that fail to decode are dropped (recompute)."""
        idxs = [int(t) for t in (grant.get("tile_idxs") or [])]
        checkpoints = grant.get("checkpoints") or {}
        added = 0
        sig = job.sig
        for tile_idx in idxs:
            self._item_seq += 1
            item = _Item(job, tile_idx, self._item_seq)
            item.key = self._tile_key(job, tile_idx)
            payload = checkpoints.get(tile_idx, checkpoints.get(str(tile_idx)))
            evicted_step = self._evicted.get((job.job_id, tile_idx))
            evicted_here = evicted_step is not None
            if payload is not None:
                try:
                    import jax.numpy as jnp

                    # Device-resident fast path: this executor evicted
                    # the tile and still holds its latent on device.
                    # The checkpoint stays the authority on WHICH step
                    # to resume at (validated structurally, cheap); the
                    # stash elides the b64 decode + H2D re-upload.
                    # Byte-exact equivalence with the host decode is
                    # pinned by tests — the checkpoint was encoded FROM
                    # this very latent at eviction.
                    step_hint = None
                    if isinstance(payload, dict):
                        try:
                            step_hint = int(payload.get("step"))
                        except (TypeError, ValueError):
                            step_hint = None
                    stashed = None
                    if (
                        step_hint is not None
                        and 0 < step_hint < job.proc.n_steps
                    ):
                        validate_checkpoint_meta(payload)
                        stashed = self._stash_take(
                            job.job_id, tile_idx, step_hint
                        )
                    if stashed is not None:
                        item.x = stashed
                        item.step = step_hint
                        item.resumed = True
                        self.resumes_device += 1
                        preempt_resume_total().inc(mode="device")
                    else:
                        state, step = decode_checkpoint(payload)
                        if 0 < step < job.proc.n_steps:
                            item.x = jnp.asarray(state)
                            item.step = step
                            item.resumed = True
                            self.resumes_checkpoint += 1
                            preempt_resume_total().inc(mode="checkpoint")
                except CheckpointError as exc:
                    debug_log(
                        f"xjob {job.job_id}:{tile_idx} checkpoint rejected "
                        f"({exc}); recomputing from step 0"
                    )
            if not item.resumed and evicted_here:
                self.resumes_recompute += 1
                preempt_resume_total().inc(mode="recompute")
                # the steps it re-runs up to the eviction mark were
                # already paid for once: waste, not tenant time
                item.recompute_until = int(evicted_step)
            self._evicted.pop((job.job_id, tile_idx), None)
            job.claimed.add(tile_idx)
            self._items.setdefault(sig, []).append(item)
            added += 1
        return added

    def _refill(self, jobs: list[XJobHandle]) -> bool:
        """Pull grants for jobs that have no live items (priority
        order). A pull answering None marks the job drained-pending-
        final-flush; preempt-flagged jobs don't pull (their released
        tiles must go to the premium work first)."""
        progressed = False
        live_jobs = {
            it.job.job_id
            for items in self._items.values()
            for it in items
        }
        for job in jobs:
            if job.done or job.error is not None:
                continue
            self._sync_preempt(job)
            if job.preempted:
                continue
            if job.job_id in live_jobs:
                continue
            try:
                grant = job.pull()
            except BaseException as exc:  # noqa: BLE001 - isolated per job
                self._fail_job(job, exc)
                continue
            if grant and grant.get("tile_idxs"):
                if self._adopt_grant(job, grant) > 0:
                    progressed = True
            else:
                # an empty pull may itself have carried the preempt
                # flag (HTTP clients learn it from the drained-reading
                # response): re-check before concluding the job is
                # done, or a preempted job would be finished — and the
                # worker lost to it — instead of parked until the
                # premium settles
                self._sync_preempt(job)
                if job.preempted:
                    continue
                self._finish_job(job)
                progressed = True
        return progressed

    # --- preemption -------------------------------------------------------

    def _sync_preempt(self, job: XJobHandle) -> None:
        if not self.preempt_enabled or job.preempt_check is None:
            return
        try:
            flagged = bool(job.preempt_check())
        except Exception as exc:  # noqa: BLE001 - advisory signal
            debug_log(f"preempt check for {job.job_id} failed: {exc}")
            return
        if flagged and not job.preempted:
            self._evict_job(job)
        job.preempted = flagged

    def _evict_job(self, job: XJobHandle) -> None:
        """Checkpoint + release every live item of `job` at this step
        boundary: mid-trajectory latents serialize into checkpoints,
        uninitialized items release bare. The release callback routes
        through the master's requeue path, so the tiles are pullable
        by (or after) the premium work immediately."""
        sig = job.sig
        items = [it for it in self._items.get(sig, []) if it.job is job]
        if not items:
            return
        self._items[sig] = [it for it in self._items[sig] if it.job is not job]
        idxs: list[int] = []
        checkpoints: dict[int, Any] = {}
        for item in sorted(items, key=lambda it: it.tile_idx):
            idxs.append(item.tile_idx)
            self._evicted[(job.job_id, item.tile_idx)] = int(item.step)
            if item.x is not None and 0 < item.step < job.proc.n_steps:
                try:
                    checkpoints[item.tile_idx] = encode_checkpoint(
                        item.x, item.step
                    )
                except CheckpointError as exc:
                    debug_log(
                        f"xjob {job.job_id}:{item.tile_idx} checkpoint "
                        f"encode failed ({exc}); releasing bare"
                    )
                else:
                    # the encoded host copy is the SPILL; the live
                    # device latent stays parked for re-grant here
                    self._stash_put(
                        job.job_id, item.tile_idx, item.x, item.step
                    )
            job.claimed.discard(item.tile_idx)
        self.preempt_evictions += len(idxs)
        debug_log(
            f"xjob executor: preempted {len(idxs)} tile(s) of job "
            f"{job.job_id} at step boundary ({len(checkpoints)} "
            "checkpointed)"
        )
        if job.release is not None:
            try:
                job.release(idxs, checkpoints)
            except Exception as exc:  # noqa: BLE001 - master requeue covers
                debug_log(f"xjob release for {job.job_id} failed: {exc}")

    # --- completion / failure ---------------------------------------------

    def _drop_job_eviction_marks(self, job_id: str) -> None:
        """A departing job's eviction marks are dead weight on the
        process-shared executor — drop them so the set stays bounded
        by live in-flight work."""
        self._evicted = {
            mark: step for mark, step in self._evicted.items()
            if mark[0] != job_id
        }

    def _prune_signature(self, sig: tuple) -> None:
        """Drop a signature's queue/order/compiled-program entries once
        its LAST registered job departs: the process-shared executor
        outlives jobs, and a cached vstep closure pins the job's step
        function — bundle, sigmas, grid and (when jitted) executables —
        for the process lifetime otherwise. While any same-signature
        job remains, the cache stays (that sharing is what keeps
        same-config jobs compile-free). Check-and-prune is ATOMIC
        under the registration lock: a same-signature register()
        racing this must either see the entries intact or re-create
        them — never lose its _items list to a prune that decided
        before it registered."""
        with self._lock:
            alive = any(j.sig == sig for j in self._jobs.values())
            if alive or self._items.get(sig):
                return
            self._items.pop(sig, None)
            if sig in self._sig_order:
                self._sig_order.remove(sig)
            self._vstep_cache.pop(sig, None)

    def _finish_job(self, job: XJobHandle) -> None:
        if job.done:
            return
        job.done = True
        with contextlib.suppress(Exception):
            job.flush(True)
        with self._lock:
            self._jobs.pop(job.job_id, None)
        self._drop_job_eviction_marks(job.job_id)
        self._drop_job_stash(job.job_id)
        self._prune_signature(job.sig)
        job.finished.set()

    def _fail_job(self, job: XJobHandle, exc: BaseException) -> None:
        """Isolate one job's callback failure: release what it still
        claims (bare — its master's requeue path recomputes) and drop
        it from the executor; other jobs keep batching."""
        job.error = exc
        debug_log(f"xjob job {job.job_id} failed: {exc!r}")
        sig = job.sig
        items = [it for it in self._items.get(sig, []) if it.job is job]
        self._items[sig] = [it for it in self._items.get(sig, []) if it.job is not job]
        orphaned = sorted({it.tile_idx for it in items} | set(job.claimed))
        if orphaned and job.release is not None:
            with contextlib.suppress(Exception):
                job.release(orphaned, {})
        job.claimed.clear()
        with self._lock:
            self._jobs.pop(job.job_id, None)
        self._drop_job_eviction_marks(job.job_id)
        self._drop_job_stash(job.job_id)
        self._prune_signature(job.sig)
        job.finished.set()

    # --- the scheduling round ---------------------------------------------

    def _select_batch(self) -> list[_Item]:
        """Compose the next device batch: the signature group holding
        the most-urgent item, items sorted by (priority, arrival), up
        to k_max. ``cross_job=False`` further restricts the batch to
        the first item's job — the per-job baseline."""
        best_sig = None
        best_order = None
        for sig in self._sig_order:
            items = self._items.get(sig)
            if not items:
                continue
            head = min(it.order() for it in items)
            if best_order is None or head < best_order:
                best_order = head
                best_sig = sig
        if best_sig is None:
            return []
        items = sorted(self._items[best_sig], key=_Item.order)
        if not self.cross_job:
            owner = items[0].job
            items = [it for it in items if it.job is owner]
        batch = items[: self.k_max]
        remaining = [it for it in self._items[best_sig] if it not in batch]
        self._items[best_sig] = remaining
        return batch

    def _bucket_for(self, n: int) -> int:
        from ..ops.upscale import bucket_for

        return bucket_for(n, self.k_max, self.buckets)

    def _init_items(self, batch: list[_Item]) -> None:
        """Encode + noise items entering at step 0. Per-item single-
        tile programs (one compiled shape per signature): init and
        finish are one model call each, dwarfed by the per-step loop,
        so batching them would buy little and cost extra compiles."""
        for item in batch:
            if item.x is None:
                job = item.job
                item.x = job.proc.init(
                    job.params, job.extracted[item.tile_idx], item.key
                )

    def _step_batch(self, batch: list[_Item]) -> None:
        """ONE denoise step for the whole batch: pad to the bucket
        with wraparound duplicates of real items (their updated lanes
        are sliced off — numerics never depend on padding), stack
        per-item inputs, run the shared vmapped program, scatter the
        advanced latents back."""
        import jax.numpy as jnp
        import jax.tree_util as jtu

        sig = batch[0].job.sig
        n = len(batch)
        bucket = self._bucket_for(n)
        padded = [batch[i % n] for i in range(bucket)]
        params = batch[0].job.params
        xs = jnp.stack([it.x for it in padded], axis=0)
        keys = jnp.stack([it.key for it in padded], axis=0)
        poss = jtu.tree_map(
            lambda *leaves: jnp.stack(leaves, axis=0),
            *[it.job.pos for it in padded],
        )
        negs = jtu.tree_map(
            lambda *leaves: jnp.stack(leaves, axis=0),
            *[it.job.neg for it in padded],
        )
        yxs = jnp.stack(
            [jnp.asarray(it.job.positions[it.tile_idx]) for it in padded],
            axis=0,
        )
        steps = jnp.asarray([it.step for it in padded], jnp.int32)
        # Adapter plane: every batch-mate shares the extended signature
        # (same rank bucket + target-path set), so per-slot operands
        # stack into [B, r_b, I] / [B, O, r_b] stacks per targeted leaf
        # — each lane samples under ITS OWN job's low-rank patch while
        # the base params stay a single broadcast copy. Adapter-less
        # batches never reach this branch (their signature is the
        # unmodified stepwise tuple).
        adapter = batch[0].job.adapter
        if adapter is not None:
            downs = tuple(
                jnp.stack(
                    [it.job.adapter.downs[k] for it in padded], axis=0
                )
                for k in range(len(adapter.paths))
            )
            ups = tuple(
                jnp.stack([it.job.adapter.ups[k] for it in padded], axis=0)
                for k in range(len(adapter.paths))
            )
            scales = jnp.asarray(
                [it.job.adapter.scale for it in padded], jnp.float32
            )
            xs, keys, poss, negs, yxs, steps, downs, ups, scales = (
                self._place(
                    (xs, keys, poss, negs, yxs, steps, downs, ups, scales)
                )
            )
        else:
            xs, keys, poss, negs, yxs, steps = self._place(
                (xs, keys, poss, negs, yxs, steps)
            )
        fn = self._vstep(
            sig,
            batch[0].job.proc.step,
            adapter.paths if adapter is not None else None,
        )
        # slot-exact attribution: one entry per device slot of the
        # padded bucket, classified BEFORE the step advances — a real
        # item re-running steps below its eviction mark is recompute
        # waste, a wraparound duplicate is padding
        slots = [
            {
                "job_id": it.job.job_id,
                "kind": (
                    SLOT_RECOMPUTE
                    if it.step < it.recompute_until
                    else SLOT_REAL
                ),
            }
            for it in batch
        ] + [{"job_id": "", "kind": SLOT_PADDING}] * (bucket - n)
        slot_tenants: dict[str, int] = {}
        slot_jobs: dict[str, int] = {}
        for it in batch:
            slot_tenants[it.job.tenant] = slot_tenants.get(it.job.tenant, 0) + 1
            slot_jobs[it.job.job_id] = slot_jobs.get(it.job.job_id, 0) + 1
        # one span per DEVICE DISPATCH with its fill accounting —
        # perf_report's batch-fill column reconstructs the ratio from
        # exactly these attrs (real tiles vs bucket slots), and the
        # --usage column splits the span's wall across slot_jobs /
        # slot_tenants / padding the same way the meter does
        # compiled-vs-eager split for the transfer ledger (same rule as
        # _vstep's jit gate): only compiled programs count device time
        device = hasattr(batch[0].job.proc.step, "lower")
        ledger = ledger_if_enabled()
        started = time.monotonic()
        with stage_span(
            "dispatch", self.role, batch[0].tile_idx,
            real=n, bucket=int(bucket),
            jobs=len({it.job.job_id for it in batch}),
            slot_jobs=slot_jobs, slot_tenants=slot_tenants,
            device=device,
            recompute=sum(
                1 for s in slots if s["kind"] == SLOT_RECOMPUTE
            ),
            adapter=adapter is not None,
        ):
            if adapter is not None:
                out = fn(
                    params, xs, keys, poss, negs, yxs, steps,
                    downs, ups, scales,
                )
            else:
                out = fn(params, xs, keys, poss, negs, yxs, steps)
            if device and ledger is not None:
                # profiling wants honest device-execute wall: JAX
                # dispatch is async, so block inside the bracket
                import jax

                out = jax.block_until_ready(out)  # cdt: noqa[CDT007]
        elapsed = time.monotonic() - started
        if self.usage is not None:
            self.usage.note_dispatch(
                tier="xjob",
                role=self.role,
                elapsed_s=elapsed,
                chips=self._chips,
                slots=slots,
            )
        if ledger is not None:
            ledger.note_dispatch(
                elapsed, tier="xjob", role=self.role, device=device
            )
        self.dispatches += 1
        self.steps_run += n
        self.slots_real += n
        self.slots_padded += bucket - n
        batch_fill_ratio().set(n / bucket, role=self.role)
        if adapter is not None:
            from ..telemetry.instruments import adapter_slots_total

            adapter_slots_total().inc(n, role=self.role)
        pipeline_batches_total().inc(role=self.role, bucket=str(bucket))
        if bucket > n:
            pipeline_padded_tiles_total().inc(bucket - n, role=self.role)
        for i, item in enumerate(batch):
            item.x = out[i]
            item.step += 1

    def _retire(self, batch: list[_Item]) -> None:
        """Finish items whose trajectory completed: decode, emit to
        their OWNING job (the fan-back seam), count, flush. Unfinished
        items return to their signature queue for the next round."""
        for item in batch:
            job = item.job
            if job.error is not None:
                continue  # failed mid-retire: its master requeues
            if item.step >= job.proc.n_steps:
                with stage_span(
                    "sample", self.role, item.tile_idx, job_id=job.job_id
                ):
                    out = job.proc.finish(job.params, item.x)
                ledger = ledger_if_enabled()
                if job.device_emit:
                    # device-canvas consumer: the tile stays on device;
                    # the canvas flush pays ONE composited d2h instead
                    # of one per tile
                    host = out
                    if ledger is not None:
                        ledger.note_tiles(1)
                else:
                    readback_started = time.monotonic()
                    host = self._to_host(out)
                    if ledger is not None:
                        ledger.note_transfer(
                            D2H,
                            int(getattr(host, "nbytes", 0)),
                            time.monotonic() - readback_started,
                        )
                        ledger.note_tiles(1)
                try:
                    with stage_span(
                        "encode", self.role, item.tile_idx, job_id=job.job_id
                    ):
                        job.emit(item.tile_idx, host)
                    job.claimed.discard(item.tile_idx)
                    job.tiles_done += 1
                    self.tiles_finished += 1
                    if self.usage is not None:
                        self.usage.note_tiles(self.role, job.job_id, 1)
                    self.completion_order.append((job.job_id, item.tile_idx))
                    if len(self.completion_order) > self._max_completion_order:
                        del self.completion_order[
                            : -self._max_completion_order // 2
                        ]
                    tiles_processed_total().inc(role=self.role)
                    job.flush(False)
                except BaseException as exc:  # noqa: BLE001 - per-job isolation
                    self._fail_job(job, exc)
            else:
                self._items.setdefault(job.sig, []).append(item)

    @staticmethod
    def _to_host(result):
        from ..utils import image as img_utils

        # the _retire readback: ledger-bracketed (D2H note) at the one
        # call site, skipped entirely for device_emit jobs
        return img_utils.ensure_numpy(result)  # cdt: noqa[CDT007]

    # --- driver -----------------------------------------------------------

    def run(self) -> dict[str, Any]:
        """Drive scheduling rounds until every registered job drains
        (or ``stop()``). Returns summary stats; per-job errors are
        recorded on their handles and raised (first one) unless every
        job completed — callers that need partial progress inspect
        handles directly."""
        last_beat = self.clock()
        errors: list[BaseException] = []
        while not self._stop.is_set():
            with self._lock:
                jobs = sorted(
                    self._jobs.values(), key=lambda j: (j.priority, j.seq)
                )
            if not jobs:
                break
            # interrupt seam (the dispatched prompt's interrupt, a
            # cooperative cancel): checked at every step boundary; a
            # raising job releases its claims and leaves, like the
            # TilePipeline interrupt path
            for job in jobs:
                if job.done or job.error is not None:
                    continue
                if job.check_interrupted is not None:
                    try:
                        job.check_interrupted()
                    except BaseException as exc:  # noqa: BLE001
                        self._fail_job(job, exc)
            progressed = self._refill(jobs)
            # preemption flags may have flipped between refills; evict
            # at this boundary before composing the batch
            for job in jobs:
                if not job.done and job.error is None:
                    self._sync_preempt(job)
            batch = self._select_batch()
            if batch:
                try:
                    self._init_items(batch)
                    self._step_batch(batch)
                except BaseException as exc:  # noqa: BLE001
                    # a device-program failure poisons the whole batch:
                    # fail every owning job (their masters requeue)
                    for job in sorted(
                        {it.job for it in batch}, key=lambda j: j.seq
                    ):
                        self._fail_job(job, exc)
                    errors.append(exc)
                    continue
                self._retire(batch)
                progressed = True
            now = self.clock()
            if now - last_beat >= 1.0:
                # paced: an idle (preempt-parked / drained-waiting)
                # executor must not turn every 20 ms poll round into a
                # heartbeat RPC per job against the master
                last_beat = now
                for job in jobs:
                    if job.heartbeat is not None and not job.done:
                        with contextlib.suppress(Exception):
                            job.heartbeat()
            if not progressed:
                # nothing ready anywhere (all jobs preempt-parked or
                # their queues momentarily empty): idle briefly
                time.sleep(self.idle_poll_seconds)
        with self._lock:
            leftover = sorted(self._jobs.values(), key=lambda j: j.seq)
        for job in leftover:
            if job.error is not None:
                errors.append(job.error)
        stats = {
            "dispatches": self.dispatches,
            "steps_run": self.steps_run,
            "tiles": self.tiles_finished,
            "slots_real": self.slots_real,
            "slots_padded": self.slots_padded,
            "fill_ratio": self.fill_ratio(),
            "preempt_evictions": self.preempt_evictions,
            "resumes_checkpoint": self.resumes_checkpoint,
            "resumes_recompute": self.resumes_recompute,
            "resumes_device": self.resumes_device,
        }
        if errors:
            raise errors[0]
        return stats


# --------------------------------------------------------------------------
# production entries (CDT_XJOB_BATCH=1): elastic master/worker loops
# routed through one process-shared executor
# --------------------------------------------------------------------------


class SharedExecutor:
    """Process-global CrossJobExecutor + lazily-(re)started driver
    thread. Every concurrently-running elastic job in this process —
    dispatched worker prompts, the master's own participation —
    registers here, which is exactly what makes their tiles share
    device batches."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._executor: Optional[CrossJobExecutor] = None
        self._thread: Optional[threading.Thread] = None

    def executor(self, *, k_max: int, mesh: Any, role: str) -> CrossJobExecutor:
        from ..utils.constants import PREEMPT_ENABLED

        with self._lock:
            if self._executor is None:
                self._executor = CrossJobExecutor(
                    k_max=k_max,
                    mesh=mesh,
                    role=role,
                    preempt_enabled=PREEMPT_ENABLED == 1,
                )
            return self._executor

    def ensure_running(self) -> None:
        with self._lock:
            if self._executor is None:
                return
            if self._thread is not None and self._thread.is_alive():
                return

            executor = self._executor

            def drive() -> None:
                try:
                    executor.run()
                except BaseException as exc:  # noqa: BLE001 - per-job errors
                    # already delivered on each handle; the shared
                    # driver itself must not die loudly between jobs
                    debug_log(f"shared xjob executor driver exit: {exc!r}")

            self._thread = threading.Thread(
                target=drive, name="cdt-xjob-executor", daemon=True
            )
            self._thread.start()


_SHARED = SharedExecutor()


def get_shared_executor() -> SharedExecutor:
    return _SHARED


def _reset_shared_executor_for_tests() -> None:
    global _SHARED
    _SHARED = SharedExecutor()


def _prep_xjob(
    bundle, image, pos, neg, upscale_by, tile, padding, upscale_method,
    tile_h, mask_blur, uniform, steps, sampler, scheduler, cfg, denoise,
    tiled_decode, seed, job_id, precision=None, lane="",
):
    """Shared prep for the xjob master/worker entries: tile extraction,
    per-tile conditioning, the step-resumable processor, and the
    job-folded base key (parallel/seeds.fold_job_key — the key gains
    the job id so cross-tenant batch-mates can never correlate).

    ``precision`` (None = resolve from the lane via CDT_BF16_LANES)
    picks the latent-carry lane; it joins the processor signature, so
    f32 and bf16 jobs never share a device batch."""
    import jax

    from ..ops import upscale as upscale_ops
    from ..ops.stepwise import make_stepwise_tile_processor
    from ..parallel.seeds import fold_job_key
    from ..utils.constants import precision_for_lane

    if precision is None:
        precision = precision_for_lane(lane)
    upscaled, grid, extracted = upscale_ops.prepare_upscaled_tiles(
        image, upscale_by, tile, padding, upscale_method, tile_h,
        mask_blur=mask_blur, uniform=uniform,
    )
    pos = upscale_ops.prep_cond_for_tiles(pos, grid)
    neg = upscale_ops.prep_cond_for_tiles(neg, grid)
    proc = make_stepwise_tile_processor(
        bundle, grid, steps, sampler, scheduler, cfg, denoise, tiled_decode,
        precision=precision,
    )
    base_key = fold_job_key(jax.random.key(seed), job_id)
    return upscaled, grid, extracted, pos, neg, proc, base_key


def run_worker_xjob(
    bundle,
    image,
    pos,
    neg,
    job_id: str,
    worker_id: str,
    master_url: str,
    upscale_by: float,
    tile: int,
    padding: int,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg: float,
    denoise: float,
    seed: int,
    upscale_method: str = "bicubic",
    mask_blur: int = 0,
    uniform: bool = True,
    tiled_decode: bool = False,
    tile_h: int | None = None,
    context=None,
    client: Any = None,
    mesh: Any = None,
    lane: str = "",
    precision: str | None = None,
) -> None:
    """CDT_XJOB_BATCH worker entry (same signature as
    ``run_worker_loop``): registers this job with the process-shared
    continuous-batching executor and parks until it drains. Raises
    ``ValueError`` from the stepwise factory for unsupported samplers —
    the delegating caller falls back to the scan tier."""
    from ..utils import image as img_utils
    from ..utils.constants import (
        MAX_TILE_BATCH,
        SCHED_MAX_PULL_BATCH,
        tile_scan_batch,
    )
    from ..utils.exceptions import WorkerError
    from ..utils.logging import log
    from ..parallel.mesh import (
        advertised_capacity,
        data_axis_size,
        note_serving_mesh,
        worker_mesh,
    )
    from ..parallel.sharding import maybe_shard_params, params_byte_size

    params = bundle.params
    if mesh is None:
        mesh = worker_mesh(params_bytes=params_byte_size(params))
    note_serving_mesh(mesh)
    capacity = advertised_capacity(mesh)
    _, grid, extracted, pos, neg, proc, base_key = _prep_xjob(
        bundle, image, pos, neg, upscale_by, tile, padding, upscale_method,
        tile_h, mask_blur, uniform, steps, sampler, scheduler, cfg, denoise,
        tiled_decode, seed, job_id, precision=precision, lane=lane,
    )
    from .usdu_elastic import HTTPWorkClient, _flush_threshold_bytes

    client = client or HTTPWorkClient(
        master_url, job_id, worker_id, devices=capacity
    )
    params = maybe_shard_params(params, mesh)
    if not client.poll_ready():
        raise WorkerError(f"job {job_id} never became ready", worker_id)

    # Adapter plane: the readiness poll carried the job's resolved wire
    # plan. Re-resolve against the LOCAL catalog — resolve() verifies
    # the master-stamped content hashes against local bytes, so a
    # divergent checkpoint fails loudly here instead of sampling wrong
    # pixels — then build the rank-bucketed per-slot operands (served
    # from the process adapter cache).
    adapter = None
    adapter_wire = getattr(client, "adapters", None) or []
    if adapter_wire:
        from ..adapters import (
            bundle_target_map,
            get_adapter_catalog,
            operands_for_plan,
            specs_from_wire,
        )
        from ..telemetry.instruments import adapter_jobs_total

        specs = get_adapter_catalog().resolve(specs_from_wire(adapter_wire))
        adapter = operands_for_plan(specs, bundle_target_map(bundle))
        adapter_jobs_total().inc(tier="xjob")

    pending: list[dict] = []
    pending_bytes = 0

    def emit(tile_idx: int, arr) -> None:
        nonlocal pending_bytes
        for batch_idx in range(arr.shape[0]):
            encoded = img_utils.encode_image_data_url(arr[batch_idx])
            y, x = grid.positions[tile_idx]
            pending.append(
                {
                    "tile_idx": tile_idx,
                    "batch_idx": batch_idx,
                    "global_idx": tile_idx * arr.shape[0] + batch_idx,
                    "x": int(x),
                    "y": int(y),
                    "extracted_w": grid.padded_w,
                    "extracted_h": grid.padded_h,
                    "image": encoded,
                }
            )
            pending_bytes += len(encoded)

    def flush(is_final: bool) -> None:
        nonlocal pending, pending_bytes
        if not is_final and (
            len(pending) < MAX_TILE_BATCH
            and pending_bytes < _flush_threshold_bytes()
        ):
            return
        if pending or is_final:
            with stage_span("submit", "worker", worker_id=worker_id):
                client.submit_tiles(pending, is_final)
        pending, pending_bytes = [], 0

    def pull() -> Optional[dict]:
        work = client.request_tile(batch_max=SCHED_MAX_PULL_BATCH * capacity)
        if work is None:
            return None
        idxs = work.get("tile_idxs") or (
            [work["tile_idx"]] if work.get("tile_idx") is not None else []
        )
        return {
            "tile_idxs": [int(t) for t in idxs],
            "checkpoints": work.get("checkpoints") or {},
        }

    def release(idxs: list[int], checkpoints: dict) -> None:
        client.return_tiles(idxs, checkpoints=checkpoints)

    def check_abort() -> None:
        if context is not None:
            context.check_interrupted()
        if getattr(client, "job_cancelled", False):
            raise InterruptedError(
                f"job {job_id} cancelled by master "
                f"({getattr(client, 'cancel_reason', '') or 'cancelled'})"
            )

    handle = XJobHandle(
        job_id=job_id,
        proc=proc,
        params=params,
        extracted=extracted,
        positions=grid.positions_array(),
        pos=pos,
        neg=neg,
        base_key=base_key,
        pull=pull,
        emit=emit,
        flush=flush,
        release=release,
        preempt_check=lambda: bool(getattr(client, "preempt_requested", False)),
        heartbeat=client.heartbeat,
        check_interrupted=check_abort,
        adapter=adapter,
    )
    shared = get_shared_executor()
    executor = shared.executor(
        k_max=tile_scan_batch() * max(1, data_axis_size(mesh) if mesh else 1),
        mesh=mesh,
        role="worker",
    )
    executor.register(handle)
    while True:
        shared.ensure_running()
        if handle.finished.wait(timeout=0.25):
            break
    if handle.error is not None:
        if isinstance(handle.error, InterruptedError) and getattr(
            client, "job_cancelled", False
        ):
            log(
                f"worker {worker_id}: job {job_id} cancelled; aborted cleanly"
            )
            return
        raise handle.error


def run_master_xjob(
    bundle,
    image,
    pos,
    neg,
    job_id: str,
    enabled_worker_ids: list,
    mesh=None,
    upscale_by: float = 2.0,
    tile: int = 512,
    padding: int = 32,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg: float = 7.0,
    denoise: float = 0.35,
    seed: int = 0,
    upscale_method: str = "bicubic",
    mask_blur: int = 0,
    uniform: bool = True,
    tiled_decode: bool = False,
    tile_h: int | None = None,
    context=None,
    lane: str = "",
    precision: str | None = None,
):
    """CDT_XJOB_BATCH master entry (same signature/contract as
    ``run_master_elastic``): the master participates through the shared
    continuous-batching executor — its own compute rides the same
    cross-job batches as any other registered job — while this thread
    runs the collection loop (worker-result drain, timeout requeue,
    deadline sweep, lifecycle settle)."""
    import time as _time

    import jax.numpy as jnp
    import numpy as np

    from ..ops import tiles as tile_ops
    from ..utils import image as img_utils
    from ..utils.async_helpers import run_async_in_server_loop
    from ..utils.config import get_worker_timeout_seconds
    from ..utils.constants import (
        QUEUE_POLL_INTERVAL_SECONDS,
        tile_scan_batch,
    )
    from ..utils.exceptions import JobCancelled, JobPoisoned
    from ..utils.logging import log
    from ..parallel.mesh import data_axis_size, note_serving_mesh

    import os as _os

    server = context.server
    store = server.job_store
    upscaled, grid, extracted, pos, neg, proc, base_key = _prep_xjob(
        bundle, image, pos, neg, upscale_by, tile, padding, upscale_method,
        tile_h, mask_blur, uniform, steps, sampler, scheduler, cfg, denoise,
        tiled_decode, seed, job_id, precision=precision, lane=lane,
    )
    note_serving_mesh(mesh)
    master_width = data_axis_size(mesh) if mesh is not None else 1

    async def _note_master_capacity() -> None:
        store.note_worker_capacity("master", master_width)

    run_async_in_server_loop(_note_master_capacity())
    done_tiles: set[int] = set()
    timeout = get_worker_timeout_seconds()

    # Adapter plane: the orchestration parked the resolved wire plan in
    # the store (note_job_adapters) — peek it (non-destructive; the
    # init below pops + journals it) and build this master's own
    # operands. The plan key joins the cache key: flipping ONLY the
    # adapter hash or strength must flip every tile key.
    adapter = None
    adapter_key = None
    adapter_wire = run_async_in_server_loop(
        store.peek_job_adapters(job_id), timeout=30
    )
    if adapter_wire:
        from ..adapters import (
            adapter_plan_key,
            bundle_target_map,
            get_adapter_catalog,
            operands_for_plan,
            specs_from_wire,
        )
        from ..telemetry.instruments import adapter_jobs_total

        adapter_specs = get_adapter_catalog().resolve(
            specs_from_wire(adapter_wire)
        )
        adapter_key = adapter_plan_key(adapter_specs)
        adapter = operands_for_plan(adapter_specs, bundle_target_map(bundle))
        adapter_jobs_total().inc(tier="xjob")

    # --- content-addressed tile cache (cache/), CDT_CACHE=1 ----------
    # The xjob tier keys on the JOB-FOLDED base key (_prep_xjob's
    # fold_job_key): its tile outputs depend on job_id, so entries can
    # only dedup a re-run of the SAME job (crash/requeue/retry) —
    # never across jobs. The per-tile key derivation is otherwise
    # identical to the elastic tier's. UNPATCHED params on purpose:
    # the adapter's identity enters through `adapter=` (the plan key),
    # so the params fingerprint stays one hash per checkpoint.
    from ..cache import bind_job_cache, job_key_context, tile_keys_for
    from ..utils.constants import USAGE_ENABLED

    cache_binding = bind_job_cache(
        lambda: tile_keys_for(
            job_key_context(
                bundle.params, pos, neg, base_key, grid,
                steps=steps, sampler=sampler, scheduler=scheduler,
                cfg=cfg, denoise=denoise, upscale_by=upscale_by,
                upscale_method=upscale_method, mask_blur=mask_blur,
                uniform=uniform, tiled_decode=tiled_decode,
                adapter=adapter_key,
            ),
            extracted, grid,
        )
    )

    # Canvas routing rule (see docs/performance.md): the on-device
    # canvas takes master-local tiles when CDT_DEVICE_CANVAS=1 AND the
    # tile result cache is off — cache population needs host tile
    # bytes, so with the cache on the per-tile materialization happens
    # regardless and the device canvas buys nothing. Remote workers
    # keep the PNG path either way (their tiles arrive host-side by
    # construction and are uploaded once into the device canvas).
    # Sorted compositing keeps the device canvas deterministic — and
    # bit-identical to DeterministicHostCanvas, a hard test gate.
    from ..utils.constants import device_canvas_enabled

    device_canvas = device_canvas_enabled() and cache_binding is None
    if device_canvas:
        canvas = tile_ops.DeviceCanvas(upscaled, grid)
    elif _os.environ.get("CDT_DETERMINISTIC_BLEND") == "1":
        canvas = tile_ops.DeterministicHostCanvas(upscaled, grid)
    else:
        canvas = tile_ops.HostIncrementalCanvas(upscaled, grid)

    def blend_local(tile_idx: int, result) -> None:
        with stage_span("blend", "master", tile_idx):
            y, x = grid.positions[tile_idx]
            if cache_binding is not None:
                # one host materialisation serves both the cache
                # write-back and the host canvas blend
                result = np.asarray(result)  # cdt: noqa[CDT007]
                cache_binding.populate(tile_idx, result)
            canvas.blend(result, y, x)
            done_tiles.add(tile_idx)

    # Probe BEFORE the job exists, settle ATOMICALLY with its creation
    # (init_tile_job's cache_settled): hits are journaled
    # (`cache_settle`) with the pending queue shrunken under the same
    # lock hold, so no puller or batch-mate ever burns a slot on them
    # and a warm run's settled count is deterministic. On a
    # pre-existing job (recovery re-entry) creation ignored the list —
    # fall back to the standalone op, which excludes tiles workers
    # already completed (those must not be re-blended).
    cached_hits: dict = {}
    if cache_binding is not None:
        with stage_span("cache.probe", "master") as probe_span:
            cached_hits = cache_binding.probe()
            probe_span.attrs["hits"] = len(cached_hits)
    job = run_async_in_server_loop(
        store.init_tile_job(
            job_id, list(range(grid.num_tiles)),
            cache_settled=sorted(cached_hits) if cached_hits else None,
        ),
        timeout=30,
    )
    if cached_hits:
        settled = [t for t in sorted(cached_hits) if t in job.cached_tiles]
        if not settled:
            settled = run_async_in_server_loop(
                store.settle_cached(job_id, sorted(cached_hits)), timeout=30
            )
        for tile_idx in settled:
            with stage_span("cache.hit", "master", tile_idx):
                y, x = grid.positions[tile_idx]
                canvas.blend(cached_hits[tile_idx], y, x)
                done_tiles.add(tile_idx)
        if settled:
            cache_binding.cache.note_settled(len(settled))
            if USAGE_ENABLED:
                get_usage_meter().note_cached(
                    "master", job_id, len(settled)
                )

    def drain_results() -> None:
        async def drain():
            job = await store.get_tile_job(job_id)
            items = []
            while job is not None and not job.results.empty():
                items.append(job.results.get_nowait())
            return items

        for tile_idx, payload in run_async_in_server_loop(drain(), timeout=30):
            if tile_idx in done_tiles or payload is None:
                continue
            with stage_span("decode", "master", tile_idx):
                batch = [
                    img_utils.decode_image_data_url(e["image"])
                    for e in sorted(payload, key=lambda e: e["batch_idx"])
                ]
            # remote PNG tiles are ALREADY host bytes — stacking them
            # pulls nothing off a device
            blend_local(tile_idx, jnp.asarray(np.stack(batch, axis=0)))  # cdt: noqa[CDT007]

    # --- master's own compute rides the shared executor ------------------
    def pull() -> Optional[dict]:
        async def pull_any():
            tasks = await store.pull_tasks(
                job_id, "master", timeout=QUEUE_POLL_INTERVAL_SECONDS
            )
            if not tasks:
                return None
            return {
                "tile_idxs": tasks,
                "checkpoints": await store.checkpoints_for(job_id, tasks),
            }

        return run_async_in_server_loop(pull_any(), timeout=30)

    def emit(tile_idx: int, arr) -> None:
        blend_local(int(tile_idx), jnp.asarray(arr))

    def flush(is_final: bool) -> None:
        pass  # blends are local; accounting rides emit->submit below

    def submit_done(tile_idx: int) -> None:
        run_async_in_server_loop(
            store.submit_flush(job_id, "master", {int(tile_idx): None}),
            timeout=30,
        )

    def emit_and_submit(tile_idx: int, arr) -> None:
        emit(tile_idx, arr)
        submit_done(tile_idx)

    def release(idxs: list[int], checkpoints: dict) -> None:
        run_async_in_server_loop(
            store.release_tasks(job_id, "master", idxs, checkpoints=checkpoints),
            timeout=30,
        )

    def preempt_check() -> bool:
        async def read():
            job = await store.get_tile_job(job_id)
            return bool(job is not None and job.preempt_requested)

        return run_async_in_server_loop(read(), timeout=30)

    def check_abort() -> None:
        if context is not None:
            context.check_interrupted()

    def make_master_handle() -> XJobHandle:
        return XJobHandle(
            job_id=job_id,
            proc=proc,
            params=bundle.params,
            extracted=extracted,
            positions=grid.positions_array(),
            pos=pos,
            neg=neg,
            base_key=base_key,
            pull=pull,
            emit=emit_and_submit,
            flush=flush,
            release=release,
            preempt_check=preempt_check,
            check_interrupted=check_abort,
            adapter=adapter,
            device_emit=device_canvas,
        )

    shared = get_shared_executor()
    executor = shared.executor(
        k_max=tile_scan_batch() * max(1, master_width), mesh=mesh,
        role="master",
    )
    handle = make_master_handle()
    executor.register(handle)

    def _lifecycle() -> dict:
        state = run_async_in_server_loop(store.job_lifecycle(job_id), timeout=30)
        return state or {
            "cancelled": False, "cancel_reason": "", "quarantined": [],
        }

    deadline = _time.monotonic() + timeout * max(1, len(enabled_worker_ids) + 1)
    while True:
        shared.ensure_running()
        lifecycle = _lifecycle()
        quarantined = set(lifecycle["quarantined"])
        if lifecycle["cancelled"] or (
            len(done_tiles | quarantined) >= grid.num_tiles
        ):
            break
        if context is not None:
            context.check_interrupted()
        run_async_in_server_loop(store.sweep_deadlines(), timeout=30)
        drain_results()
        run_async_in_server_loop(
            store.requeue_timed_out(job_id, timeout, None), timeout=60
        )
        if handle.error is not None:
            break
        if _time.monotonic() > deadline:
            log(f"USDU xjob: master deadline hit on job {job_id}")
            break
        if handle.finished.wait(timeout=QUEUE_POLL_INTERVAL_SECONDS):
            # the executor drained its view of the queue; keep draining
            # worker results until the job settles
            drain_results()
            if len(done_tiles | quarantined) >= grid.num_tiles:
                break
            pending_now = run_async_in_server_loop(
                store.remaining(job_id), timeout=30
            )
            if pending_now and not lifecycle["cancelled"]:
                # requeued tiles (a crashed/timed-out worker's claims,
                # watchdog speculation) landed AFTER the master's view
                # drained: re-enter the executor so the master can
                # re-run them locally — the run_master_elastic contract
                handle = make_master_handle()
                executor.register(handle)
                continue
            _time.sleep(QUEUE_POLL_INTERVAL_SECONDS)

    drain_results()
    lifecycle = _lifecycle()
    run_async_in_server_loop(store.cleanup_tile_job(job_id), timeout=30)
    if handle.error is not None and not isinstance(
        handle.error, InterruptedError
    ):
        raise handle.error
    if lifecycle["cancelled"]:
        raise JobCancelled(job_id, lifecycle["cancel_reason"] or "cancel")
    poisoned = sorted(set(lifecycle["quarantined"]) - done_tiles)
    if poisoned:
        policy = getattr(store, "poison_policy", "degrade")
        if policy == "fail":
            raise JobPoisoned(job_id, poisoned)
        log(
            f"USDU xjob: job {job_id} completes DEGRADED: tile(s) "
            f"{poisoned} quarantined"
        )
    if device_canvas:
        # ONE composited d2h per flush — the whole point. Ledger-noted
        # here so perf_report's d2h-bytes/tile column sees the canvas
        # transfer instead of per-tile readbacks.
        with stage_span("readback", "master", tiles=canvas.tile_count):
            started = _time.monotonic()
            composited = canvas.result()
            host = np.asarray(composited)  # cdt: noqa[CDT007]
            ledger = ledger_if_enabled()
            if ledger is not None:
                ledger.note_transfer(
                    D2H, int(host.nbytes), _time.monotonic() - started
                )
        return jnp.asarray(host)
    return canvas.result()
