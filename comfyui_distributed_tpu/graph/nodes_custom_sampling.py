"""Custom-sampling node cluster (ComfyUI custom_sampling parity).

The standard shape of published Flux/SD3 workflows: the monolithic
KSampler is decomposed into NOISE / GUIDER / SAMPLER / SIGMAS values
produced by small nodes and consumed by SamplerCustom(-Advanced).
The reference free-rides on ComfyUI for this entire surface
(reference upscale/tile_ops.py:168 imports ComfyUI's samplers);
here it is built on ops/samplers + models/pipeline.

TPU notes: the sigma grid is a compile-time constant of the sampling
program (static tuple through the jit boundary, see
pipeline._custom_sigmas_jit), so every sampler — including the
numpy-coefficient multistep ones — compiles to the same single-scan
XLA program the KSampler path uses. DistributedSeed flowing into
RandomNoise's noise_seed keeps the mesh fan-out path: one SPMD
program sampling per-participant folded seeds (nodes_core._sample_mesh).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..models import pipeline as pl
from ..ops import samplers as smp
from ..parallel.mesh import data_axis_size
from .nodes_core import SeedSpec, _prep_latents, _sample_mesh, resolve_seed
from .registry import register_node


@dataclasses.dataclass(frozen=True)
class SamplerSpec:
    """A SAMPLER value: which trajectory solver to run."""

    name: str


@dataclasses.dataclass(frozen=True)
class NoiseSpec:
    """A NOISE value: where the initial noise comes from.

    seed carries a SeedSpec so DistributedSeed flows through RandomNoise
    unchanged (per-participant folding happens at the sampler node).
    add_noise=False is DisableNoise: the trajectory starts from the
    latents as-is (refine passes over leftover-noise latents).
    """

    seed: SeedSpec
    add_noise: bool = True


@dataclasses.dataclass(frozen=True)
class GuiderSpec:
    """A GUIDER value: model + conditioning + guidance composition.

    negative=None is BasicGuider (single-cond, cfg 1.0: exactly one
    model eval per step); otherwise CFG over (positive, negative).
    """

    bundle: Any
    positive: Any
    negative: Any = None
    cfg: float = 1.0


def _terminal_zero(sigmas: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(
        np.concatenate([sigmas.astype(np.float32), np.zeros((1,), np.float32)])
    )


@register_node
class KSamplerSelect:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"sampler_name": ("STRING", {"default": "euler"})}}

    RETURN_TYPES = ("SAMPLER",)
    FUNCTION = "get_sampler"

    def get_sampler(self, sampler_name: str, context=None):
        name = str(sampler_name)
        if name not in smp.SAMPLER_NAMES:
            raise ValueError(
                f"unknown sampler {name!r}; use {smp.SAMPLER_NAMES}"
            )
        return (SamplerSpec(name),)


@register_node
class BasicScheduler:
    """Model-aware sigma schedule (ComfyUI BasicScheduler parity):
    family-correct grid (VP table or shifted rectified-flow), shaped by
    the scheduler knob; denoise < 1 truncates to the schedule tail
    (total steps scale up so the tail still has `steps` points);
    denoise == 0 yields an empty grid (the ComfyUI convention for
    "no sampling")."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "scheduler": ("STRING", {"default": "normal"}),
                "steps": ("INT", {"default": 20}),
                "denoise": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("SIGMAS",)
    FUNCTION = "get_sigmas"

    def get_sigmas(self, model, scheduler="normal", steps=20, denoise=1.0,
                   context=None):
        if float(denoise) <= 0.0:
            return (jnp.zeros((0,), jnp.float32),)
        param, shift = pl.model_schedule_info(model)
        return (
            smp.get_model_sigmas(
                param, str(scheduler), int(steps),
                denoise=float(denoise), flow_shift=shift,
            ),
        )


@register_node
class KarrasScheduler:
    """Model-free Karras rho-spaced grid (ComfyUI KarrasScheduler
    parity) with the terminal zero appended."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "steps": ("INT", {"default": 20}),
                "sigma_max": ("FLOAT", {"default": 14.614642}),
                "sigma_min": ("FLOAT", {"default": 0.0291675}),
                "rho": ("FLOAT", {"default": 7.0}),
            }
        }

    RETURN_TYPES = ("SIGMAS",)
    FUNCTION = "get_sigmas"

    def get_sigmas(self, steps=20, sigma_max=14.614642, sigma_min=0.0291675,
                   rho=7.0, context=None):
        return (
            _terminal_zero(
                smp.karras_sigmas(
                    float(sigma_min), float(sigma_max), int(steps),
                    rho=float(rho),
                )
            ),
        )


@register_node
class ExponentialScheduler:
    """Model-free log-uniform grid (ComfyUI ExponentialScheduler
    parity) with the terminal zero appended."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "steps": ("INT", {"default": 20}),
                "sigma_max": ("FLOAT", {"default": 14.614642}),
                "sigma_min": ("FLOAT", {"default": 0.0291675}),
            }
        }

    RETURN_TYPES = ("SIGMAS",)
    FUNCTION = "get_sigmas"

    def get_sigmas(self, steps=20, sigma_max=14.614642, sigma_min=0.0291675,
                   context=None):
        return (
            _terminal_zero(
                smp.exponential_sigmas(
                    float(sigma_min), float(sigma_max), int(steps)
                )
            ),
        )


@register_node
class PolyexponentialScheduler:
    """Model-free poly-exponential grid (ComfyUI
    PolyexponentialScheduler parity): a log-space ramp warped by rho
    (rho=1 is exactly ExponentialScheduler), with the terminal zero
    appended."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "steps": ("INT", {"default": 20}),
                "sigma_max": ("FLOAT", {"default": 14.614642}),
                "sigma_min": ("FLOAT", {"default": 0.0291675}),
                "rho": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("SIGMAS",)
    FUNCTION = "get_sigmas"

    def get_sigmas(self, steps=20, sigma_max=14.614642, sigma_min=0.0291675,
                   rho=1.0, context=None):
        return (
            _terminal_zero(
                smp.polyexponential_sigmas(
                    float(sigma_min), float(sigma_max), int(steps),
                    rho=float(rho),
                )
            ),
        )


@register_node
class BetaSamplingScheduler:
    """Beta-quantile spacing over the MODEL's sigma table (ComfyUI
    BetaSamplingScheduler parity): like scheduler='beta' but with
    alpha/beta exposed (0.6/0.6 is the scheduler default — dense at
    both schedule ends). Family-aware: flow models space over their
    shifted flow table, VP models over the training table."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "steps": ("INT", {"default": 20}),
                "alpha": ("FLOAT", {"default": 0.6}),
                "beta": ("FLOAT", {"default": 0.6}),
            }
        }

    RETURN_TYPES = ("SIGMAS",)
    FUNCTION = "get_sigmas"

    def get_sigmas(self, model, steps=20, alpha=0.6, beta=0.6, context=None):
        param, shift = pl.model_schedule_info(model)
        table = (
            smp._flow_sigma_table(shift)
            if param == "flow"
            else smp._vp_sigmas()
        )
        sigmas = smp.beta_spaced_sigmas(
            np.asarray(table), int(steps), float(alpha), float(beta)
        )
        return (_terminal_zero(np.asarray(sigmas, np.float32)),)


@register_node
class SDTurboScheduler:
    """Turbo/LCM-style few-step schedule (ComfyUI SDTurboScheduler
    parity): `steps` sigmas picked from the top of the training table,
    offset by (1 - denoise) * 1000 timesteps, with the terminal zero."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "steps": ("INT", {"default": 1}),
                "denoise": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("SIGMAS",)
    FUNCTION = "get_sigmas"

    def get_sigmas(self, model, steps=1, denoise=1.0, context=None):
        param, _shift = pl.model_schedule_info(model)
        if param == "flow":
            raise ValueError(
                "SDTurboScheduler indexes the VP training table; use "
                "BasicScheduler for flow-family models"
            )
        n = int(steps)
        if not 1 <= n <= 10:
            raise ValueError("SDTurboScheduler takes 1-10 steps")
        # the reference convention: timesteps 999, 899, ..., 99 (one
        # per denoising decade), windowed by (1 - denoise) decades
        start = 10 - int(10 * max(0.0, min(1.0, float(denoise))))
        decades = [999 - 100 * i for i in range(10)]
        chosen = decades[start:start + n]
        table = smp._vp_sigmas()  # ascending, index = timestep
        sigmas = np.asarray([table[i] for i in chosen], np.float32)
        return (_terminal_zero(sigmas),)


@register_node
class SplitSigmas:
    """Split a schedule at a step boundary (ComfyUI SplitSigmas
    parity): high = sigmas[:step+1], low = sigmas[step:] — the shared
    point appears in both halves so chained SamplerCustomAdvanced
    passes resume exactly where the first stopped."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "sigmas": ("SIGMAS",),
                "step": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("SIGMAS", "SIGMAS")
    RETURN_NAMES = ("high_sigmas", "low_sigmas")
    FUNCTION = "split"

    def split(self, sigmas, step=0, context=None):
        s = int(step)
        return (sigmas[: s + 1], sigmas[s:])


@register_node
class SplitSigmasDenoise:
    """Split a schedule by denoise fraction (ComfyUI SplitSigmasDenoise
    parity): the low half keeps the last round(steps*denoise) steps."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "sigmas": ("SIGMAS",),
                "denoise": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("SIGMAS", "SIGMAS")
    RETURN_NAMES = ("high_sigmas", "low_sigmas")
    FUNCTION = "split"

    def split(self, sigmas, denoise=1.0, context=None):
        steps = max(int(sigmas.shape[0]) - 1, 0)
        # round half-up, not int(): a workflow ported from the
        # reference stack must resume its refine pass at the same step
        kept = int(steps * max(0.0, min(1.0, float(denoise))) + 0.5)
        s = steps - kept
        return (sigmas[: s + 1], sigmas[s:])


@register_node
class FlipSigmas:
    """Reverse a schedule for unsampling/noising workflows (ComfyUI
    FlipSigmas parity); a leading zero becomes 1e-4 so the first step
    has a nonzero sigma to start from."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"sigmas": ("SIGMAS",)}}

    RETURN_TYPES = ("SIGMAS",)
    FUNCTION = "flip"

    def flip(self, sigmas, context=None):
        if int(sigmas.shape[0]) == 0:
            return (sigmas,)
        flipped = jnp.flip(sigmas, axis=0)
        return (
            jnp.where(
                jnp.arange(flipped.shape[0]) == 0,
                jnp.maximum(flipped, 1e-4),
                flipped,
            ),
        )


@register_node
class RandomNoise:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"noise_seed": ("INT", {"default": 0})}}

    RETURN_TYPES = ("NOISE",)
    FUNCTION = "get_noise"

    def get_noise(self, noise_seed, context=None):
        return (NoiseSpec(seed=resolve_seed(noise_seed), add_noise=True),)


@register_node
class DisableNoise:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {}}

    RETURN_TYPES = ("NOISE",)
    FUNCTION = "get_noise"

    def get_noise(self, context=None):
        return (NoiseSpec(seed=SeedSpec(0), add_noise=False),)


@register_node
class BasicGuider:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "conditioning": ("CONDITIONING",),
            }
        }

    RETURN_TYPES = ("GUIDER",)
    FUNCTION = "get_guider"

    def get_guider(self, model, conditioning, context=None):
        return (GuiderSpec(bundle=model, positive=conditioning),)


@register_node
class CFGGuider:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "cfg": ("FLOAT", {"default": 8.0}),
            }
        }

    RETURN_TYPES = ("GUIDER",)
    FUNCTION = "get_guider"

    def get_guider(self, model, positive, negative, cfg=8.0, context=None):
        return (
            GuiderSpec(
                bundle=model, positive=positive, negative=negative,
                cfg=float(cfg),
            ),
        )


@register_node
class DualCFGGuider:
    """Dual-conditioning CFG (ComfyUI DualCFGGuider role): one
    3B-batched model eval per step composing cond1/cond2/negative.
    style='regular' (default) guides cond2 against negative at
    cfg_cond2_negative and adds cfg_conds * (eps1 - eps2) on top;
    style='nested' guides cond1 against cond2 first, then the result
    against negative (exact formulas: smp.dual_cfg_model). The dual
    composition rides on the bundle like the SLG and RescaleCFG
    patches, so every sampling path dispatches it."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "cond1": ("CONDITIONING",),
                "cond2": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "cfg_conds": ("FLOAT", {"default": 8.0}),
                "cfg_cond2_negative": ("FLOAT", {"default": 8.0}),
                "style": ("STRING", {"default": "regular"}),
            }
        }

    RETURN_TYPES = ("GUIDER",)
    FUNCTION = "get_guider"

    def get_guider(self, model, cond1, cond2, negative, cfg_conds=8.0,
                   cfg_cond2_negative=8.0, style="regular", context=None):
        if str(style) not in ("regular", "nested"):
            raise ValueError(
                f"unknown style {style!r}; use 'regular' or 'nested'"
            )
        pl.reject_existing_guidance_patches(model, "DualCFGGuider")
        bundle = dataclasses.replace(
            model,
            dual_cfg=pl.DualCFGSpec(
                cfg_cond2_negative=float(cfg_cond2_negative),
                nested=(str(style) == "nested"),
            ),
        )
        return (
            GuiderSpec(
                bundle=bundle, positive=(cond1, cond2), negative=negative,
                cfg=float(cfg_conds),
            ),
        )


@register_node
class PerpNegGuider:
    """Perpendicular negative guidance (ComfyUI PerpNegGuider parity,
    Armandpour et al. 2023): only the component of the negative
    orthogonal to the positive pushes away, so a negative aligned
    with the positive no longer cancels it. One 3B-batched eval per
    step over (positive, negative, empty); formulas:
    smp.perp_neg_model."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "empty_conditioning": ("CONDITIONING",),
                "cfg": ("FLOAT", {"default": 8.0}),
                "neg_scale": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("GUIDER",)
    FUNCTION = "get_guider"

    def get_guider(self, model, positive, negative, empty_conditioning,
                   cfg=8.0, neg_scale=1.0, context=None):
        pl.reject_existing_guidance_patches(model, "PerpNegGuider")
        bundle = dataclasses.replace(
            model, perp_neg=pl.PerpNegSpec(neg_scale=float(neg_scale))
        )
        return (
            GuiderSpec(
                bundle=bundle,
                positive=(positive, negative),
                negative=empty_conditioning,
                cfg=float(cfg),
            ),
        )


def _run_custom(
    noise: NoiseSpec,
    guider: GuiderSpec,
    sampler: SamplerSpec,
    sigmas,
    latent_image: dict,
    context,
) -> tuple[dict, dict]:
    """Shared SamplerCustom / SamplerCustomAdvanced core. Routes the
    per-participant-seed + noise-adding case through the one-SPMD-
    program mesh path (nodes_core._sample_mesh); everything else
    through pipeline.sample_custom_sigmas. Both paths honor the
    two-output contract: when the grid stops above sigma 0, the second
    output is the model's x0 prediction at the final point (the mesh
    path computes it with one extra guided eval over the gathered
    participant-major batch)."""
    bundle = guider.bundle
    latents, noise_mask, extras = _prep_latents(bundle, latent_image)
    fixed = bool(latent_image.get("batch_index_fixed", False))
    if int(sigmas.shape[0]) == 0:
        out = {**extras, "samples": latents}
        return out, dict(out)
    positive = guider.positive
    negative = guider.negative if guider.negative is not None else positive
    cfg = guider.cfg if guider.negative is not None else 1.0
    spec = noise.seed

    mesh = getattr(context, "mesh", None) if context is not None else None
    if (
        noise.add_noise
        and spec.per_participant
        and mesh is not None
        and data_axis_size(mesh) > 1
    ):
        from .nodes_core import _reject_fixed_on_mesh

        _reject_fixed_on_mesh(fixed)
        result = _sample_mesh(
            bundle, mesh, spec, jnp.asarray(sigmas, jnp.float32), cfg,
            sampler.name, positive, negative, latents, noise_mask,
        )
        out = {**extras, **result}
        final_sigma = float(np.asarray(sigmas)[-1])
        if final_sigma == 0.0:
            return out, dict(out)
        denoised = pl.denoised_prediction(
            bundle, result["samples"], positive, negative, cfg, final_sigma
        )
        if noise_mask is not None:
            mask = jnp.clip(noise_mask.astype(jnp.float32), 0.0, 1.0)
            denoised = denoised * mask + latents * (1.0 - mask)
        return out, {**out, "samples": denoised}

    effective_seed = spec.effective_seed()
    out_l, denoised_l = pl.sample_custom_sigmas(
        bundle,
        latents,
        positive,
        negative,
        sigmas,
        sampler=sampler.name,
        cfg_scale=cfg,
        seed=int(effective_seed),
        add_noise=noise.add_noise,
        noise_mask=noise_mask,
        batch_fixed_noise=fixed,
    )
    return ({**extras, "samples": out_l}, {**extras, "samples": denoised_l})


@register_node
class SamplerCustom:
    """Explicit-schedule sampler (ComfyUI SamplerCustom parity): the
    KSampler knobs, but sampler and sigma grid arrive as values.
    Outputs (output, denoised_output) — they differ only when the grid
    stops above sigma 0 (leftover-noise two-stage workflows)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "add_noise": ("BOOLEAN", {"default": True}),
                "noise_seed": ("INT", {"default": 0}),
                "cfg": ("FLOAT", {"default": 8.0}),
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "sampler": ("SAMPLER",),
                "sigmas": ("SIGMAS",),
                "latent_image": ("LATENT",),
            }
        }

    RETURN_TYPES = ("LATENT", "LATENT")
    RETURN_NAMES = ("output", "denoised_output")
    FUNCTION = "sample"

    def sample(self, model, add_noise, noise_seed, cfg, positive, negative,
               sampler, sigmas, latent_image, context=None):
        noise = NoiseSpec(
            seed=resolve_seed(noise_seed), add_noise=bool(add_noise)
        )
        guider = GuiderSpec(
            bundle=model, positive=positive, negative=negative,
            cfg=float(cfg),
        )
        return _run_custom(noise, guider, sampler, sigmas, latent_image,
                           context)


@register_node
class SamplerCustomAdvanced:
    """Fully decomposed sampler (ComfyUI SamplerCustomAdvanced parity):
    NOISE + GUIDER + SAMPLER + SIGMAS in, (output, denoised_output)
    out. The standard Flux workflow terminal node."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "noise": ("NOISE",),
                "guider": ("GUIDER",),
                "sampler": ("SAMPLER",),
                "sigmas": ("SIGMAS",),
                "latent_image": ("LATENT",),
            }
        }

    RETURN_TYPES = ("LATENT", "LATENT")
    RETURN_NAMES = ("output", "denoised_output")
    FUNCTION = "sample"

    def sample(self, noise, guider, sampler, sigmas, latent_image,
               context=None):
        return _run_custom(noise, guider, sampler, sigmas, latent_image,
                           context)
