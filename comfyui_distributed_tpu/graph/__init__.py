"""Workflow graph layer: JSON prompt graphs, node registry, executor.

The framework's equivalent of ComfyUI's prompt/executor surface that
the reference is parasitic on (reference SURVEY: "no standalone
runtime ... parasitic on ComfyUI's PromptServer"). Here it is a
standalone component: prompt graphs use the same JSON shape as
ComfyUI API prompts ({id: {class_type, inputs}}, links as
[node_id, output_index]) so the reference's bundled workflows port
directly, but execution compiles onto JAX.
"""

from .executor import ExecutionContext, GraphExecutor, validate_prompt  # noqa: F401
from .prompt import PromptIndex  # noqa: F401
from .registry import NODE_REGISTRY, register_node  # noqa: F401

# Importing the node modules registers the node classes.
from . import nodes_core  # noqa: F401,E402
from . import nodes_distributed  # noqa: F401,E402
from . import nodes_upscale  # noqa: F401,E402
from . import nodes_video  # noqa: F401,E402
from . import nodes_audio  # noqa: F401,E402
from . import nodes_controlnet  # noqa: F401,E402
from . import nodes_mask  # noqa: F401,E402
from . import nodes_custom_sampling  # noqa: F401,E402
from . import nodes_loaders  # noqa: F401,E402
from . import nodes_transform  # noqa: F401,E402
