"""Distributed workflow nodes.

Parity set with the reference's node inventory (reference
nodes/utilities.py + nodes/collector.py): DistributedSeed,
DistributedValue, DistributedModelName, Image/AudioBatchDivider,
DistributedEmptyImage, DistributedCollector. Roles:

- On a mesh run, DistributedSeed emits a per-participant SeedSpec and
  the collector just materialises the participant-major sharded batch
  (the all-gather IS the collection).
- On the elastic (HTTP) tier, the same nodes behave like the
  reference's: workers POST per-image envelopes to the master's
  /distributed/job_complete; the master's collector drains its job
  queue with sliced waits, busy-probe grace on stalls, dedup, and
  deterministic master-first ordering.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.collective import host_collect, reorder_participant_first
from ..utils import audio_payload as audio_utils
from ..utils import image as img_utils
from ..utils.async_helpers import run_async_in_server_loop
from ..utils.constants import (
    COLLECTOR_WAIT_SLICES,
    JOB_INIT_GRACE_SECONDS,
    REQUEST_RETRY_BACKOFF,
    REQUEST_RETRY_COUNT,
)
from ..utils.logging import debug_log, log
from ..utils.network import build_worker_url, get_client_session, probe_worker
from .nodes_core import SeedSpec
from .registry import register_node


@register_node
class DistributedSeed:
    """Master passes the seed through; worker i gets seed + i + 1
    (reference nodes/utilities.py:52-75). On mesh runs emits a
    per-participant SeedSpec so KSampler runs one SPMD program."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"seed": ("INT", {"default": 0})},
            "hidden": {
                "is_worker": ("BOOLEAN", {"default": False}),
                "worker_index": ("INT", {"default": -1}),
            },
        }

    RETURN_TYPES = ("INT",)
    FUNCTION = "get_seed"

    def get_seed(self, seed, is_worker=False, worker_index=-1,
                 enabled_worker_ids=None, context=None):
        mesh = getattr(context, "mesh", None) if context is not None else None
        if not is_worker and mesh is not None:
            from ..parallel.mesh import data_axis_size

            if data_axis_size(mesh) > 1:
                return (SeedSpec(base_seed=int(seed), per_participant=True),)
        if is_worker and worker_index >= 0:
            return (SeedSpec(base_seed=int(seed), worker_index=int(worker_index)),)
        return (SeedSpec(base_seed=int(seed)),)


@register_node
class DistributedValue:
    """Typed per-worker value override: master keeps `value`; worker i
    looks up overrides[str(i+1)] coerced to overrides['_type']
    (reference nodes/utilities.py:86-162). The override application
    happens at prompt-rewrite time (graph/prompt.py); this node just
    surfaces the resolved value."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"value": ("STRING", {"default": ""})},
            "optional": {"overrides": ("DICT", {"default": None})},
            "hidden": {
                "is_worker": ("BOOLEAN", {"default": False}),
                "worker_index": ("INT", {"default": -1}),
            },
        }

    RETURN_TYPES = ("*",)
    FUNCTION = "get_value"

    def get_value(self, value, overrides=None, is_worker=False, worker_index=-1,
                  enabled_worker_ids=None, context=None):
        return (value,)


@register_node
class DistributedModelName:
    """Stringify a model reference so delegate-only masters can patch
    model names into workflows they don't execute themselves
    (reference nodes/utilities.py:164-224)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"model": ("MODEL",)}}

    RETURN_TYPES = ("STRING",)
    FUNCTION = "name_of"
    OUTPUT_NODE = True

    def name_of(self, model, context=None):
        name = getattr(model, "model_name", str(model))
        return (name,)


def _chunk_bounds(total: int, parts: int) -> list[tuple[int, int]]:
    """Contiguous near-equal split (divmod distribution, reference
    nodes/utilities.py:7-20)."""
    parts = max(1, min(parts, total)) if total > 0 else 1
    base, extra = divmod(total, parts)
    bounds = []
    start = 0
    for i in range(parts):
        size = base + (1 if i < extra else 0)
        bounds.append((start, start + size))
        start += size
    return bounds


MAX_DIVIDER_OUTPUTS = 10


@register_node
class ImageBatchDivider:
    """Split an IMAGE batch into up to 10 contiguous chunks (reference
    nodes/utilities.py:235-268) — the video-frame fan-out primitive."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "images": ("IMAGE",),
                "divide_by": ("INT", {"default": 2}),
            }
        }

    RETURN_TYPES = tuple(["IMAGE"] * MAX_DIVIDER_OUTPUTS)
    FUNCTION = "divide"

    def divide(self, images, divide_by=2, context=None):
        parts = max(1, min(int(divide_by), MAX_DIVIDER_OUTPUTS))
        total = images.shape[0]
        outs = []
        for start, end in _chunk_bounds(total, parts):
            outs.append(images[start:end])
        while len(outs) < MAX_DIVIDER_OUTPUTS:
            outs.append(images[0:0])
        return tuple(outs)


@register_node
class AudioBatchDivider:
    """Split AUDIO samples into up to 10 contiguous chunks along the
    sample axis (reference nodes/utilities.py:271-329). AUDIO contract:
    {"waveform": [B, C, S], "sample_rate": int}."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "audio": ("AUDIO",),
                "divide_by": ("INT", {"default": 2}),
            }
        }

    RETURN_TYPES = tuple(["AUDIO"] * MAX_DIVIDER_OUTPUTS)
    FUNCTION = "divide"

    def divide(self, audio, divide_by=2, context=None):
        wave = audio["waveform"]
        rate = audio["sample_rate"]
        parts = max(1, min(int(divide_by), MAX_DIVIDER_OUTPUTS))
        outs = []
        for start, end in _chunk_bounds(wave.shape[-1], parts):
            outs.append({"waveform": wave[..., start:end], "sample_rate": rate})
        empty = {"waveform": wave[..., 0:0], "sample_rate": rate}
        while len(outs) < MAX_DIVIDER_OUTPUTS:
            outs.append(dict(empty))
        return tuple(outs)


@register_node
class DistributedEmptyImage:
    """Zero-batch IMAGE placeholder feeding delegate-mode collectors
    (reference nodes/utilities.py:332-354)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {}}

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "empty"

    def empty(self, context=None):
        return (jnp.zeros((0, 64, 64, 3)),)


# --------------------------------------------------------------------------


@register_node
class DistributedCollector:
    """THE gather op (reference nodes/collector.py).

    Worker role: serialize each image to a base64-PNG envelope and POST
    to the master per image (is_last marks the final one). Master role:
    mesh-tier results are materialised directly from the sharded array;
    elastic-tier results are drained from the job queue with sliced
    waits, worker probes on stall (busy ⇒ grace), dedup, and
    deterministic ordering (master batch first, then enabled workers in
    configured order, then stragglers sorted)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"images": ("IMAGE",)},
            "optional": {
                "audio": ("AUDIO", {"default": None}),
                "pass_through": ("BOOLEAN", {"default": False}),
                "load_balance": ("BOOLEAN", {"default": False}),
            },
            "hidden": {
                "is_worker": ("BOOLEAN", {"default": False}),
                "worker_id": ("STRING", {"default": ""}),
                "master_url": ("STRING", {"default": ""}),
                "job_id": ("STRING", {"default": ""}),
            },
        }

    RETURN_TYPES = ("IMAGE", "AUDIO")
    FUNCTION = "run"
    NEVER_CACHE = True  # network gather; reference forces re-exec

    def run(
        self,
        images,
        audio=None,
        pass_through=False,
        load_balance=False,
        is_worker=False,
        worker_id="",
        master_url="",
        job_id="",
        enabled_worker_ids=None,
        context=None,
    ):
        if pass_through:
            return (images, audio)
        if is_worker:
            self._send_to_master(images, audio, worker_id, master_url, job_id)
            return (images, audio)
        return self._collect_master(
            images, audio, job_id, enabled_worker_ids or [], context
        )

    # --- worker side ------------------------------------------------------

    def _send_to_master(self, images, audio, worker_id, master_url, job_id):
        arr = img_utils.ensure_numpy(images)
        batch = arr.shape[0]
        # Capture the active trace on the executor thread: send_all runs
        # on the server loop, where the context is not set.
        from ..telemetry import TRACE_HEADER, current_trace_id

        trace_id = current_trace_id()
        headers = {TRACE_HEADER: trace_id} if trace_id else {}

        async def send_all():
            session = await get_client_session()
            if batch == 0:
                # An empty batch still needs an is_last envelope or the
                # master waits a full timeout for this worker. The 1px
                # placeholder satisfies envelope validation; "empty"
                # tells the collector to discard the tensor.
                envelope: dict[str, Any] = {
                    "job_id": job_id,
                    "worker_id": worker_id,
                    "batch_idx": 0,
                    "image": img_utils.encode_image_data_url(
                        np.zeros((1, 1, 3), np.float32)
                    ),
                    "is_last": True,
                    "empty": True,
                }
                if audio is not None:
                    envelope["audio"] = audio_utils.encode_audio_payload(
                        audio["waveform"], audio["sample_rate"]
                    )
                await self._post_with_retry(
                    session, f"{master_url}/distributed/job_complete", envelope,
                    headers,
                )
                return
            for idx in range(batch):
                envelope = {
                    "job_id": job_id,
                    "worker_id": worker_id,
                    "batch_idx": idx,
                    "image": img_utils.encode_image_data_url(arr[idx]),
                    "is_last": idx == batch - 1,
                }
                if audio is not None and idx == batch - 1:
                    envelope["audio"] = audio_utils.encode_audio_payload(
                        audio["waveform"], audio["sample_rate"]
                    )
                await self._post_with_retry(
                    session, f"{master_url}/distributed/job_complete", envelope,
                    headers,
                )

        run_async_in_server_loop(send_all(), timeout=300)

    @staticmethod
    async def _post_with_retry(session, url, payload, headers=None):
        last_exc: Exception | None = None
        for attempt in range(REQUEST_RETRY_COUNT):
            try:
                async with session.post(url, json=payload, headers=headers or {}) as resp:
                    if resp.status == 200:
                        return
                    last_exc = RuntimeError(f"HTTP {resp.status}")
            except Exception as exc:  # noqa: BLE001 - retried
                last_exc = exc
            await __import__("asyncio").sleep(REQUEST_RETRY_BACKOFF * (2**attempt))
        raise last_exc if last_exc else RuntimeError("send failed")

    # --- master side --------------------------------------------------------

    def _collect_master(self, images, audio, job_id, enabled_worker_ids, context):
        server = getattr(context, "server", None) if context is not None else None

        # Mesh tier: the sharded participant-major array IS the collected
        # batch — just materialise it.
        mesh_collected = host_collect(images) if isinstance(images, jax.Array) else (
            img_utils.ensure_numpy(images)
        )

        if not enabled_worker_ids or server is None:
            combined_audio = audio
            return (jnp.asarray(mesh_collected), combined_audio)

        # Elastic tier: drain the HTTP job queue for remote workers.
        collected = self._drain_worker_results(
            server, job_id, enabled_worker_ids, context
        )
        batches: dict[int, np.ndarray] = {0: mesh_collected}
        audio_parts: list[tuple[np.ndarray, int]] = []
        if audio is not None:
            audio_parts.append(
                (img_utils.ensure_numpy(audio["waveform"]), audio["sample_rate"])
            )
        order: dict[str, int] = {
            wid: i + 1 for i, wid in enumerate(enabled_worker_ids)
        }
        per_worker: dict[str, list[tuple[int, np.ndarray]]] = {}
        for item in collected:
            wid = str(item["worker_id"])
            if item.get("audio") is not None:
                audio_parts.append(item["audio"])
            if item.get("empty"):
                continue  # zero-batch marker: worker finished, no images
            per_worker.setdefault(wid, []).append(
                (int(item.get("batch_idx", 0)), item["tensor"])
            )
        next_straggler = len(enabled_worker_ids) + 1
        for wid in sorted(per_worker, key=lambda w: order.get(w, 10**6)):
            imgs = [t for _, t in sorted(per_worker[wid], key=lambda p: p[0])]
            idx = order.get(wid)
            if idx is None:
                idx = next_straggler
                next_straggler += 1
            batches[idx] = np.stack(imgs, axis=0)

        ordered = reorder_participant_first(batches, list(range(1, next_straggler)))
        nonempty = [a for a in ordered if a.size]
        sizes = {a.shape[1:] for a in nonempty}
        if len(sizes) > 1:
            # keep the majority/first NON-empty size (the master batch may
            # be an empty delegate placeholder whose nominal size is moot)
            target = nonempty[0].shape[1:]
            log(f"collector: mismatched image sizes {sizes}; keeping {target}")
            nonempty = [a for a in nonempty if a.shape[1:] == target]
        if nonempty:
            combined = np.concatenate(nonempty, axis=0)
        else:
            # every participant returned empty (or all workers dropped):
            # surface the master's (possibly zero-batch) images unchanged
            combined = mesh_collected

        combined_audio = None
        if audio_parts:
            wave, rate = audio_utils.combine_audio(audio_parts)
            combined_audio = {"waveform": wave, "sample_rate": rate}
        return (jnp.asarray(combined), combined_audio)

    def _drain_worker_results(self, server, job_id, enabled_worker_ids, context):
        """Sliced-wait drain with busy-probe grace (reference
        nodes/collector.py:322-440)."""
        from ..utils.config import get_worker_timeout_seconds

        timeout = get_worker_timeout_seconds()
        slice_timeout = max(timeout / COLLECTOR_WAIT_SLICES, 0.05)
        expected = set(map(str, enabled_worker_ids))
        collected: list[dict[str, Any]] = []
        deadline_stall = time.monotonic() + timeout
        seen_keys: set[tuple[str, int]] = set()

        async def get_one(slice_s: float):
            import asyncio

            job = await server.job_store.wait_for_collector(
                job_id, JOB_INIT_GRACE_SECONDS
            )
            try:
                return await asyncio.wait_for(job.queue.get(), slice_s), job
            except asyncio.TimeoutError:
                return None, job

        while True:
            if context is not None:
                context.check_interrupted()
            item, job = run_async_in_server_loop(
                get_one(slice_timeout), timeout=slice_timeout + JOB_INIT_GRACE_SECONDS + 5
            )
            if item is not None:
                deadline_stall = time.monotonic() + timeout
                key = (str(item.get("worker_id")), int(item.get("batch_idx", 0)))
                if key in seen_keys:
                    debug_log(f"collector dedup {key}")
                    continue
                seen_keys.add(key)
                collected.append(item)
            finished = job.finished_workers & expected
            if finished == expected:
                break
            if time.monotonic() >= deadline_stall:
                missing = expected - finished
                busy = self._probe_any_busy(missing, context)
                if busy:
                    debug_log(f"collector stall: {missing} busy; extending grace")
                    deadline_stall = time.monotonic() + timeout
                    continue
                log(f"collector: giving up on workers {sorted(missing)}")
                break
        return collected

    @staticmethod
    def _probe_any_busy(worker_ids, context) -> bool:
        config = getattr(context, "config", None) or {}
        workers = {str(w.get("id")): w for w in config.get("workers", [])}

        async def probe_all():
            for wid in worker_ids:
                worker = workers.get(str(wid))
                if worker is None:
                    continue
                result = await probe_worker(build_worker_url(worker))
                if result["online"] and (result["queue_remaining"] or 0) > 0:
                    return True
            return False

        try:
            return run_async_in_server_loop(probe_all(), timeout=30)
        except Exception:
            return False
