"""Standalone component loaders + model-sampling patch nodes.

The real Flux/SD3 distribution format ships the diffusion transformer,
text encoders, and VAE as separate files; published workflows load
them with UNETLoader / CLIPLoader / DualCLIPLoader / TripleCLIPLoader
and patch schedule shape with the ModelSampling* nodes. The reference
free-rides on ComfyUI for this whole surface (SURVEY §2: zero model
code of its own); here it is built on models/pipeline.load_unet /
load_clip and per-bundle schedule overrides (PipelineBundle
.flow_shift_override / .parameterization_override — a replaced bundle
recompiles the jitted samplers exactly once, the jit-friendly analog
of ComfyUI's model_sampling object patch).
"""

from __future__ import annotations

import dataclasses
import math
import os

import jax.numpy as jnp

from ..models import pipeline as pl
from .registry import register_node


def _stem(name: str) -> str:
    """Workflow values carry filenames ('clip_l.safetensors'); registry
    names are stems. Underscores normalize to the registry's hyphens
    only when the literal name is unknown."""
    from ..models.registry import MODEL_REGISTRY

    base = os.path.splitext(str(name))[0]
    if base in MODEL_REGISTRY:
        return base
    hyphenated = base.replace("_", "-")
    return hyphenated if hyphenated in MODEL_REGISTRY else base


@register_node
class UNETLoader:
    """Load a diffusion backbone only (ComfyUI UNETLoader parity).
    weight_dtype accepts the ComfyUI values; on TPU the compute dtype
    is the XLA program's concern, so anything but 'default' is a
    no-op recorded for workflow compatibility."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "unet_name": ("STRING", {"default": "tiny-unet"}),
                "weight_dtype": ("STRING", {"default": "default"}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "load_unet"

    def load_unet(self, unet_name, weight_dtype="default", context=None):
        name = _stem(unet_name)
        cache_key = f"unet:{name}"
        cache = getattr(context, "pipelines", {}) if context is not None else {}
        if cache_key not in cache:
            cache[cache_key] = pl.load_unet(name)
        return (cache[cache_key],)


def _load_clip_cached(names: list[str], layout: str, context):
    cache_key = f"clip:{layout}:" + ",".join(names)
    cache = getattr(context, "pipelines", {}) if context is not None else {}
    if cache_key not in cache:
        cache[cache_key] = pl.load_clip(names, layout=layout)
    return cache[cache_key]


# ComfyUI type values → load_clip layout names
_CLIP_TYPE_MAP = {
    "stable_diffusion": "sd",
    "sdxl": "sdxl",
    "flux": "flux",
    "sd3": "sd3",
}


@register_node
class CLIPLoader:
    """Load a single text encoder (ComfyUI CLIPLoader parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name": ("STRING", {"default": "clip-l"}),
                "type": ("STRING", {"default": "stable_diffusion"}),
            }
        }

    RETURN_TYPES = ("CLIP",)
    FUNCTION = "load_clip"

    def load_clip(self, clip_name, type="stable_diffusion", context=None):
        if str(type) != "stable_diffusion":
            raise ValueError(
                "CLIPLoader loads one encoder; type must be "
                "'stable_diffusion' (use DualCLIPLoader/TripleCLIPLoader "
                "for sdxl/flux/sd3 layouts)"
            )
        return (_load_clip_cached([_stem(clip_name)], "sd", context),)


@register_node
class DualCLIPLoader:
    """Load two text encoders (ComfyUI DualCLIPLoader parity):
    type sdxl (CLIP-L + CLIP-G), flux (CLIP + T5, either order), or
    sd3 (CLIP-L + CLIP-G, T5-less low-memory mode)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name1": ("STRING", {"default": "clip-l"}),
                "clip_name2": ("STRING", {"default": "clip-g"}),
                "type": ("STRING", {"default": "sdxl"}),
            }
        }

    RETURN_TYPES = ("CLIP",)
    FUNCTION = "load_clip"

    def load_clip(self, clip_name1, clip_name2, type="sdxl", context=None):
        layout = _CLIP_TYPE_MAP.get(str(type))
        if layout is None or layout == "sd":
            raise ValueError(
                f"DualCLIPLoader type must be sdxl, flux, or sd3; "
                f"got {type!r}"
            )
        names = [_stem(clip_name1), _stem(clip_name2)]
        return (_load_clip_cached(names, layout, context),)


@register_node
class TripleCLIPLoader:
    """Load the full SD3 encoder set (ComfyUI TripleCLIPLoader parity:
    CLIP-L + CLIP-G + T5; the T5 is sniffed by family, so argument
    order beyond the two CLIPs doesn't matter)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name1": ("STRING", {"default": "clip-l-sd3"}),
                "clip_name2": ("STRING", {"default": "clip-g"}),
                "clip_name3": ("STRING", {"default": "t5-xxl-sd3"}),
            }
        }

    RETURN_TYPES = ("CLIP",)
    FUNCTION = "load_clip"

    def load_clip(self, clip_name1, clip_name2, clip_name3, context=None):
        names = [_stem(clip_name1), _stem(clip_name2), _stem(clip_name3)]
        return (_load_clip_cached(names, "sd3", context),)


@register_node
class EmptySD3LatentImage:
    """16-channel empty latents (ComfyUI EmptySD3LatentImage parity —
    the SD3/Flux workflow starting point). Carries the same PLACEHOLDER
    marker EmptyLatentImage uses, so KSampler still rebuilds against
    the actual bundle's latent layout if it differs."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 1024}),
                "height": ("INT", {"default": 1024}),
                "batch_size": ("INT", {"default": 1}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "generate"

    def generate(self, width=1024, height=1024, batch_size=1, context=None):
        return (
            {
                "samples": jnp.zeros(
                    (int(batch_size), int(height) // 8, int(width) // 8, 16)
                ),
                "width": int(width),
                "height": int(height),
                "empty": True,
            },
        )


def _patch_freeu(model, b1, b2, s1, s2, v2: bool):
    from ..models.registry import model_family
    from ..models.unet import UNet

    if model_family(model.model_name) != "unet":
        raise ValueError(
            "FreeU patches SD-class UNets (skip-connection joins); "
            f"{model.model_name!r} is not one"
        )
    # patch the LIVE module's config (keeps any earlier config-level
    # patches), not the registry's pristine copy
    cfg = dataclasses.replace(
        model.unet.config,
        freeu=(float(b1), float(b2), float(s1), float(s2), bool(v2)),
    )
    # same weights, new module: the patch adds no parameters, so the
    # existing param tree applies unchanged and the jitted samplers
    # recompile exactly once for the patched bundle
    return dataclasses.replace(model, unet=UNet(cfg))


@register_node
class FreeU:
    """FreeU backbone/skip re-weighting (ComfyUI FreeU parity): at the
    model_channels*4 / *2 up-path joins, the first half of the
    backbone channels scales by b1/b2 and the skip's low-frequency
    Fourier box scales by s1/s2."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "b1": ("FLOAT", {"default": 1.1}),
                "b2": ("FLOAT", {"default": 1.2}),
                "s1": ("FLOAT", {"default": 0.9}),
                "s2": ("FLOAT", {"default": 0.2}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, b1=1.1, b2=1.2, s1=0.9, s2=0.2, context=None):
        return (_patch_freeu(model, b1, b2, s1, s2, v2=False),)


@register_node
class FreeU_V2:
    """FreeU v2 (ComfyUI FreeU_V2 parity): the backbone scale adapts
    per pixel via the normalized hidden-mean map instead of a
    constant."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "b1": ("FLOAT", {"default": 1.3}),
                "b2": ("FLOAT", {"default": 1.4}),
                "s1": ("FLOAT", {"default": 0.9}),
                "s2": ("FLOAT", {"default": 0.2}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, b1=1.3, b2=1.4, s1=0.9, s2=0.2, context=None):
        return (_patch_freeu(model, b1, b2, s1, s2, v2=True),)


@register_node
class PerturbedAttentionGuidance:
    """PAG model patch (ComfyUI PerturbedAttentionGuidance parity,
    Ahn et al. 2024): each step gains scale * (cond - cond with the
    middle-block self-attention replaced by identity). UNet family
    only — DiT-class models use SkipLayerGuidance instead."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "scale": ("FLOAT", {"default": 3.0}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, scale=3.0, context=None):
        from ..models.registry import model_family

        family = model_family(model.model_name)
        if family != "unet":
            raise ValueError(
                f"PerturbedAttentionGuidance patches UNet self-attention; "
                f"{model.model_name!r} is {family}-family (use "
                "SkipLayerGuidanceSD3 for DiT-class models)"
            )
        pl.reject_existing_guidance_patches(
            model, "PerturbedAttentionGuidance"
        )
        return (
            dataclasses.replace(model, pag=pl.PAGSpec(scale=float(scale))),
        )


@register_node
class SelfAttentionGuidance:
    """SAG model patch (ComfyUI SelfAttentionGuidance parity, Hong et
    al. 2023): gaussian-blur the uncond x0 estimate where the
    middle-block self-attention concentrates, re-noise, and guide away
    from the degraded prediction (ops/samplers.sag_cfg_model). UNet
    family only."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "scale": ("FLOAT", {"default": 0.5}),
                "blur_sigma": ("FLOAT", {"default": 2.0}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, scale=0.5, blur_sigma=2.0, context=None):
        from ..models.registry import model_family

        family = model_family(model.model_name)
        if family != "unet":
            raise ValueError(
                f"SelfAttentionGuidance captures UNet middle-block "
                f"attention; {model.model_name!r} is {family}-family"
            )
        pl.reject_existing_guidance_patches(model, "SelfAttentionGuidance")
        return (
            dataclasses.replace(
                model,
                sag=pl.SAGSpec(
                    scale=float(scale), blur_sigma=float(blur_sigma)
                ),
            ),
        )


@register_node
class RescaleCFG:
    """Std-rescaled guidance (ComfyUI RescaleCFG parity): the guided
    x0 prediction rescales to the cond-only prediction's per-sample
    std, lerped by `multiplier` — the standard companion to
    v-prediction/zero-terminal-SNR finetunes. Implemented as a bundle
    patch composed in pipeline.guided_model; combining with
    SkipLayerGuidanceSD3 is rejected (the two patches both own the
    guidance composition)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "multiplier": ("FLOAT", {"default": 0.7}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, multiplier=0.7, context=None):
        pl.reject_existing_guidance_patches(model, "RescaleCFG")
        return (
            dataclasses.replace(model, cfg_rescale=float(multiplier)),
        )


@register_node
class ModelSamplingDiscrete:
    """Override the VP parameterization (ComfyUI ModelSamplingDiscrete
    parity): eps or v_prediction. zsnr rescaling is not implemented —
    it errors rather than silently sampling the wrong schedule."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "sampling": ("STRING", {"default": "eps"}),
                "zsnr": ("BOOLEAN", {"default": False}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, sampling="eps", zsnr=False, context=None):
        mapping = {"eps": "eps", "v_prediction": "v"}
        if str(sampling) not in mapping:
            raise ValueError(
                f"sampling must be one of {sorted(mapping)}; got {sampling!r}"
            )
        if zsnr:
            raise ValueError(
                "zsnr rescaling is not implemented in this framework"
            )
        return (
            dataclasses.replace(
                model, parameterization_override=mapping[str(sampling)]
            ),
        )


def _require_flow(model, node: str):
    if pl.model_schedule_info(model)[0] != "flow":
        raise ValueError(
            f"{node} patches flow-matching models (Flux/SD3 class); "
            f"{model.model_name!r} is not one"
        )


@register_node
class ModelSamplingSD3:
    """Set the rectified-flow shift (ComfyUI ModelSamplingSD3 parity;
    also the AuraFlow-style plain-shift knob)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "shift": ("FLOAT", {"default": 3.0}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, shift=3.0, context=None):
        _require_flow(model, "ModelSamplingSD3")
        return (
            dataclasses.replace(model, flow_shift_override=float(shift)),
        )


@register_node
class ModelSamplingFlux:
    """Resolution-dependent flow shift (ComfyUI ModelSamplingFlux
    parity): mu interpolates linearly in image-token count between
    base_shift at 256 tokens and max_shift at 4096, and the effective
    multiplicative shift is exp(mu) — Flux's time_shift(mu, t) equals
    the shifted-sigma form sigma' = s*t/(1+(s-1)t) with s = e^mu."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "max_shift": ("FLOAT", {"default": 1.15}),
                "base_shift": ("FLOAT", {"default": 0.5}),
                "width": ("INT", {"default": 1024}),
                "height": ("INT", {"default": 1024}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "patch"

    def patch(self, model, max_shift=1.15, base_shift=0.5, width=1024,
              height=1024, context=None):
        _require_flow(model, "ModelSamplingFlux")
        # image tokens at the 2x2-patch latent grid (pixels/16 per side)
        seq = (int(width) // 16) * (int(height) // 16)
        mu = float(base_shift) + (float(max_shift) - float(base_shift)) * (
            (seq - 256) / (4096 - 256)
        )
        return (
            dataclasses.replace(model, flow_shift_override=math.exp(mu)),
        )


@register_node
class CLIPVisionLoader:
    """Load a standalone CLIP-vision tower (ComfyUI CLIPVisionLoader
    parity): a registry name (clip-vision-h, tiny-clip-vision) whose
    real weights resolve through CDT_CHECKPOINT_DIR, exactly like the
    WAN i2v bundled path (models/clip_vision.load_clip_vision)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_name": ("STRING", {"default": "clip-vision-h"}),
            }
        }

    RETURN_TYPES = ("CLIP_VISION",)
    FUNCTION = "load_clip"

    def load_clip(self, clip_name: str, context=None):
        from ..models.clip_vision import load_clip_vision

        name = _stem(clip_name)
        cache_key = f"clip_vision:{name}"
        cache = getattr(context, "pipelines", {}) if context is not None else {}
        if cache_key not in cache:
            cache[cache_key] = load_clip_vision(name)
        return (cache[cache_key],)


@dataclasses.dataclass(frozen=True)
class ClipVisionOutput:
    """A CLIP_VISION_OUTPUT value: hidden-state tokens [B, T, width],
    class token first. Deliberately NO `pooled`/`image_embeds`
    accessor: the default towers run penultimate_hidden=True (no
    final block, post-LN, or projection — clip_vision.py), so a raw
    class token would be a plausible-but-wrong stand-in for the CLIP
    pooled embedding. Add the projected path before exposing one."""

    tokens: object


@register_node
class CLIPVisionEncode:
    """Encode an image batch through a CLIP-vision tower (ComfyUI
    CLIPVisionEncode parity). The tower preprocesses internally
    (short-side scale + center crop + CLIP normalization — see
    ClipVisionEncoder.__call__), which matches the 'center' crop
    convention; crop='none' is rejected rather than silently behaving
    like center."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip_vision": ("CLIP_VISION",),
                "image": ("IMAGE",),
                "crop": ("STRING", {"default": "center"}),
            }
        }

    RETURN_TYPES = ("CLIP_VISION_OUTPUT",)
    FUNCTION = "encode"

    def encode(self, clip_vision, image, crop="center", context=None):
        if str(crop) != "center":
            raise ValueError(
                "only crop='center' is implemented (the tower's "
                "preprocessing is short-side scale + center crop)"
            )
        return (ClipVisionOutput(tokens=clip_vision.encode(image)),)


@register_node(name="unCLIPConditioning")
class UnCLIPConditioning:
    """Attach CLIP-vision image embeds to conditioning (ComfyUI
    unCLIPConditioning shape). NOTE: no registered backbone has an
    unCLIP adm head yet, so sampling with this conditioning raises at
    trace time (ops/samplers._reject_unsupported_cond) instead of
    silently dropping the image condition — the node exists so
    unCLIP workflows load and fail with a clear message, and so the
    conditioning plumbing is ready when an unCLIP backbone lands."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "clip_vision_output": ("CLIP_VISION_OUTPUT",),
                "strength": ("FLOAT", {"default": 1.0}),
                "noise_augmentation": ("FLOAT", {"default": 0.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "apply_adm"

    def apply_adm(self, conditioning, clip_vision_output, strength=1.0,
                  noise_augmentation=0.0, context=None):
        from ..ops.conditioning import map_conditioning

        def patch(cond):
            cond.unclip_embeds = clip_vision_output.tokens
            cond.unclip_strength = float(strength)
            cond.unclip_noise_aug = float(noise_augmentation)
            return cond

        return (map_conditioning(conditioning, patch),)


def _merge_trees(t1, t2, ratio: float, what: str):
    """ratio * t1 + (1 - ratio) * t2 over matching param trees (the
    ComfyUI merge convention: ratio 1.0 = pure model1). Mismatched
    architectures fail on treedef/shape, loudly."""
    import jax

    d1 = jax.tree_util.tree_structure(t1)
    d2 = jax.tree_util.tree_structure(t2)
    if d1 != d2:
        raise ValueError(
            f"{what}: param trees differ — merging needs two checkpoints "
            "of the same architecture"
        )
    r = float(ratio)

    def lerp(a, b):
        if a.shape != b.shape:
            raise ValueError(
                f"{what}: shape mismatch {a.shape} vs {b.shape}"
            )
        if jnp.issubdtype(a.dtype, jnp.floating):
            return (a.astype(jnp.float32) * r
                    + b.astype(jnp.float32) * (1.0 - r)).astype(a.dtype)
        return a

    return jax.tree_util.tree_map(lerp, t1, t2)


@register_node
class ModelMergeSimple:
    """Weighted average of two diffusion backbones (ComfyUI
    ModelMergeSimple parity): ratio weights model1. The merged bundle
    keeps model1's config/patches — only the unet params blend."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model1": ("MODEL",),
                "model2": ("MODEL",),
                "ratio": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "merge"

    def merge(self, model1, model2, ratio=1.0, context=None):
        merged = _merge_trees(
            model1.params["unet"], model2.params["unet"], ratio,
            "ModelMergeSimple",
        )
        params = dict(model1.params)
        params["unet"] = merged
        return (dataclasses.replace(model1, params=params),)


@register_node
class CLIPMergeSimple:
    """Weighted average of two text-encoder stacks (ComfyUI
    CLIPMergeSimple parity): every te/te2/te3 part present in clip1
    blends with clip2's matching part."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip1": ("CLIP",),
                "clip2": ("CLIP",),
                "ratio": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CLIP",)
    FUNCTION = "merge"

    def merge(self, clip1, clip2, ratio=1.0, context=None):
        params = dict(clip1.params)
        for part in ("te", "te2", "te3"):
            if part in clip1.params:
                if part not in clip2.params:
                    raise ValueError(
                        f"CLIPMergeSimple: clip2 has no {part!r} part"
                    )
                params[part] = _merge_trees(
                    clip1.params[part], clip2.params[part], ratio,
                    f"CLIPMergeSimple[{part}]",
                )
        return (dataclasses.replace(clip1, params=params),)
