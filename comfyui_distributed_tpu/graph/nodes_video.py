"""Video workflow nodes (WAN-class t2v).

The node surface for the reference's WAN workflows (reference
workflows/distributed-wan.json drives WAN through ComfyUI loaders +
KSampler + VHS video combine): a video checkpoint loader, an empty
video latent, a flow-matching video sampler that goes seed-parallel
across the mesh when fed a per-participant SeedSpec, a frame decoder,
and a frame-sequence saver.

VIDEO_LATENT contract: {"samples": [B, F, h, w, C]}.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..models import video_pipeline as vp
from ..ops import samplers as smp
from ..parallel.mesh import DATA_AXIS, data_axis_size
from ..utils import image as img_utils
from ..utils.logging import log
from .nodes_core import SeedSpec, resolve_seed
from .registry import register_node


def _get_video_bundle(context, model_name: str) -> vp.VideoPipelineBundle:
    cache_key = f"video:{model_name}"
    if cache_key not in context.pipelines:
        log(f"loading video pipeline {model_name!r}")
        context.pipelines[cache_key] = vp.load_video_pipeline(model_name)
    return context.pipelines[cache_key]


@register_node
class VideoCheckpointLoader:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"ckpt_name": ("STRING", {"default": "tiny-dit"})}}

    RETURN_TYPES = ("MODEL", "CLIP", "VAE")
    FUNCTION = "load"

    def load(self, ckpt_name: str, context=None):
        name = os.path.splitext(str(ckpt_name))[0]
        bundle = _get_video_bundle(context, name)
        return (bundle, bundle, bundle)


@register_node
class VideoCLIPTextEncode:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"text": ("STRING", {"default": ""}), "clip": ("CLIP",)}}

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "encode"

    def encode(self, text, clip, context=None):
        return (vp.encode_video_text(clip, [str(text)]),)


@register_node
class EmptyVideoLatent:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 256}),
                "height": ("INT", {"default": 256}),
                "frames": ("INT", {"default": 17}),
                "batch_size": ("INT", {"default": 1}),
            }
        }

    RETURN_TYPES = ("VIDEO_LATENT",)
    FUNCTION = "generate"

    def generate(self, width, height, frames, batch_size=1, context=None):
        return (
            {
                "samples": None,  # allocated by the sampler (needs model dims)
                "width": int(width),
                "height": int(height),
                "frames": int(frames),
                "batch_size": int(batch_size),
            },
        )


@register_node
class VideoFlowSampler:
    """Flow-matching t2v sampler. With a per-participant SeedSpec on a
    mesh, all participants sample concurrently in one SPMD program and
    the output batch is participant-major."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "seed": ("INT", {"default": 0}),
                "steps": ("INT", {"default": 20}),
                "cfg": ("FLOAT", {"default": 5.0}),
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "latent": ("VIDEO_LATENT",),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "sample"

    def sample(self, model, seed, steps, cfg, positive, negative, latent,
               context=None):
        spec = resolve_seed(seed)
        bundle: vp.VideoPipelineBundle = model
        mesh = getattr(context, "mesh", None) if context is not None else None
        frames = int(latent.get("frames", 17))
        height = int(latent.get("height", 256))
        width = int(latent.get("width", 256))

        if spec.per_participant and mesh is not None and data_axis_size(mesh) > 1:
            out = self._parallel_with_cond(
                bundle, mesh, positive, negative, frames, height, width,
                int(steps), float(cfg), spec.base_seed,
            )
            b, f = out.shape[0], out.shape[1]
            return (out.reshape((b * f,) + out.shape[2:]),)

        effective_seed = spec.effective_seed()
        out = vp._t2v_jit(
            vp._Static(bundle), bundle.params, positive, negative,
            jax.random.key(int(effective_seed)), frames, height, width,
            int(steps), float(cfg), positive.shape[0],
        )
        b, f = out.shape[0], out.shape[1]
        return (out.reshape((b * f,) + out.shape[2:]),)

    @staticmethod
    def _parallel_with_cond(
        bundle, mesh, pos, neg, frames, height, width, steps, cfg, seed
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.seeds import participant_keys

        n = data_axis_size(mesh)
        keys = participant_keys(jax.random.key(seed), n)
        keys = jax.device_put(keys, NamedSharding(mesh, P(DATA_AXIS)))
        params = jax.device_put(bundle.params, NamedSharding(mesh, P()))
        return vp._t2v_parallel_jit(
            vp._Static(bundle), vp._Static(mesh), params, keys,
            jax.device_put(pos, NamedSharding(mesh, P())),
            jax.device_put(neg, NamedSharding(mesh, P())),
            frames, height, width, steps, float(cfg),
        )


@register_node
class WanImageToVideo:
    """Image→video (the reference's WAN i2v workflow role; ComfyUI
    WanImageToVideo parity in spirit — prompts ride as strings because
    the WAN text encoder lives in the video bundle). i2v-layout models
    run the native conditioning (channel-concat mask + reference
    latent + CLIP-vision tokens); other video models fall back to
    clamping frame 0 along the flow path. Seed fan-out across
    participants rides the elastic tier (per-worker seed offsets), not
    the mesh: the i2v conditioning batch is per-reference-image."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "image": ("IMAGE",),
                "prompt": ("STRING", {"default": ""}),
                "negative_prompt": ("STRING", {"default": ""}),
                "frames": ("INT", {"default": 17}),
                "steps": ("INT", {"default": 20}),
                "cfg": ("FLOAT", {"default": 5.0}),
                "seed": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "generate"

    def generate(self, model, image, prompt="", negative_prompt="",
                 frames=17, steps=20, cfg=5.0, seed=0, context=None):
        from ..models.registry import get_config

        spec = resolve_seed(seed)
        mesh = getattr(context, "mesh", None) if context is not None else None
        if spec.per_participant and mesh is not None and (
            data_axis_size(mesh) > 1
        ):
            # loud like the codebase's other unsupported combinations —
            # silently collapsing to one seed would read as fan-out
            raise ValueError(
                "WanImageToVideo does not fan out per-participant seeds "
                "on a mesh (the i2v conditioning batch is per reference "
                "image); distribute i2v via the elastic tier's "
                "per-worker seed offsets instead"
            )
        bundle: vp.VideoPipelineBundle = model
        n_frames = int(frames)
        if getattr(get_config(bundle.model_name), "i2v", False) and (
            n_frames % 4 != 1
        ):
            # the WAN causal-VAE stride constraint (reference 4n+1
            # batch validation); the non-i2v fallback has no stride
            raise ValueError(
                f"frame count must be 4n+1 for i2v-layout models; "
                f"got {n_frames}"
            )
        out = vp.i2v(
            bundle,
            image,
            str(prompt),
            negative_prompt=str(negative_prompt),
            frames=n_frames,
            steps=int(steps),
            cfg_scale=float(cfg),
            seed=int(spec.effective_seed()),
        )
        b, f = out.shape[0], out.shape[1]
        return (out.reshape((b * f,) + out.shape[2:]),)


@register_node
class SaveVideoFrames:
    """Persist a frame sequence as numbered PNGs + a manifest (the
    VHS-video-combine role in reference workflows, minus containers —
    ffmpeg is not in the image, so frames + manifest is the portable
    output)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "images": ("IMAGE",),
                "filename_prefix": ("STRING", {"default": "video"}),
                "fps": ("INT", {"default": 8}),
            }
        }

    RETURN_TYPES = ()
    FUNCTION = "save"
    OUTPUT_NODE = True

    def save(self, images, filename_prefix="video", fps=8, context=None):
        import json

        from .io_dirs import get_output_dir

        out_dir = get_output_dir(context)
        os.makedirs(out_dir, exist_ok=True)
        arr = img_utils.ensure_numpy(images)
        saved = []
        for i in range(arr.shape[0]):
            name = f"{filename_prefix}_{i:05d}.png"
            with open(os.path.join(out_dir, name), "wb") as fh:
                fh.write(img_utils.encode_png(arr[i], compress_level=4))
            saved.append(name)
        manifest = {"frames": saved, "fps": int(fps)}
        with open(
            os.path.join(out_dir, f"{filename_prefix}_manifest.json"), "w"
        ) as fh:
            json.dump(manifest, fh)
        return ({"ui": {"images": saved, "fps": fps}, "images": images},)


def _save_animated(images, filename_prefix, fps, fmt, save_kwargs, context):
    """Shared APNG/WEBP writer (SaveAnimatedPNG / SaveAnimatedWEBP):
    PIL's save_all path, counter-scanned filenames like SaveImage."""
    from ..utils import image as img_utils
    from .io_dirs import get_output_dir, next_counter

    out_dir = get_output_dir(context)
    os.makedirs(out_dir, exist_ok=True)
    arr = img_utils.ensure_numpy(images)
    frames = [img_utils.array_to_pil(arr[i]) for i in range(arr.shape[0])]
    name = (
        f"{filename_prefix}_{next_counter(out_dir, filename_prefix, fmt):05d}"
        f".{fmt}"
    )
    duration_ms = int(round(1000.0 / max(int(fps), 1)))
    frames[0].save(
        os.path.join(out_dir, name),
        save_all=True,
        append_images=frames[1:],
        duration=duration_ms,
        loop=0,
        **save_kwargs,
    )
    return ({"ui": {"images": [name], "fps": int(fps)}, "images": images},)


@register_node
class SaveAnimatedPNG:
    """Animated PNG (ComfyUI SaveAnimatedPNG parity): one APNG file,
    all frames, loop forever."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "images": ("IMAGE",),
                "filename_prefix": ("STRING", {"default": "animated"}),
                "fps": ("INT", {"default": 8}),
                "compress_level": ("INT", {"default": 4}),
            }
        }

    RETURN_TYPES = ()
    FUNCTION = "save"
    OUTPUT_NODE = True

    def save(self, images, filename_prefix="animated", fps=8,
             compress_level=4, context=None):
        return _save_animated(
            images, str(filename_prefix), fps, "png",
            {"compress_level": int(compress_level)}, context,
        )


@register_node
class SaveAnimatedWEBP:
    """Animated WEBP (ComfyUI SaveAnimatedWEBP parity): lossy or
    lossless, quality 0-100."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "images": ("IMAGE",),
                "filename_prefix": ("STRING", {"default": "animated"}),
                "fps": ("INT", {"default": 8}),
                "lossless": ("BOOLEAN", {"default": True}),
                "quality": ("INT", {"default": 80}),
            }
        }

    RETURN_TYPES = ()
    FUNCTION = "save"
    OUTPUT_NODE = True

    def save(self, images, filename_prefix="animated", fps=8,
             lossless=True, quality=80, context=None):
        return _save_animated(
            images, str(filename_prefix), fps, "webp",
            {"lossless": bool(lossless), "quality": int(quality)}, context,
        )
