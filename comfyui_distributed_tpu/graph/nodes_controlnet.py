"""ControlNet nodes (loader + apply), ComfyUI-shaped.

Covers the ControlNet-tile role in the reference's upscale workflow
(reference workflows/*.json ControlNetLoader/ControlNetApply); the
hint rides in the Conditioning structure and is cropped per tile by
the USDU pipeline (ops/conditioning.crop_to_tile).
"""

from __future__ import annotations

from ..models.controlnet import load_controlnet
from ..models.registry import get_config
from ..ops.conditioning import Conditioning, as_conditioning, map_conditioning
from .registry import register_node


@register_node
class ControlNetLoader:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"control_net_name": ("STRING", {"default": "tile"})},
            "optional": {"model": ("MODEL", {"default": None})},
        }

    RETURN_TYPES = ("CONTROL_NET",)
    FUNCTION = "load"

    def load(self, control_net_name: str, model=None, context=None):
        model_channels, downscale = 320, 8
        if model is not None:
            try:
                unet_cfg = get_config(model.model_name)
                model_channels = unet_cfg.model_channels
                downscale = model.latent_scale
            except (KeyError, AttributeError):
                pass
        cache_key = f"controlnet:{control_net_name}:{model_channels}:{downscale}"
        cache = getattr(context, "pipelines", {}) if context is not None else {}
        if cache_key not in cache:
            cache[cache_key] = load_controlnet(
                str(control_net_name), model_channels, downscale
            )
        return (cache[cache_key],)


@register_node
class ControlNetApply:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "control_net": ("CONTROL_NET",),
                "image": ("IMAGE",),
                "strength": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "apply"

    def apply(self, conditioning, control_net, image, strength=1.0, context=None):
        def patch(cond):
            cond.control_hint = image
            cond.control_strength = float(strength)
            cond.control_params = control_net.params
            cond.control_module = control_net.module
            return cond

        return (map_conditioning(conditioning, patch),)


@register_node
class ControlNetApplyAdvanced:
    """Scheduled ControlNet application (ComfyUI ControlNetApplyAdvanced
    parity): the hint applies to BOTH the positive and negative
    conditioning, weighted by strength, and only while sampling
    progress is inside [start_percent, end_percent) — the window gate
    rides on the conditioning (Conditioning.control_range) and is
    resolved against the model's schedule at sampling time."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "control_net": ("CONTROL_NET",),
                "image": ("IMAGE",),
                "strength": ("FLOAT", {"default": 1.0}),
                "start_percent": ("FLOAT", {"default": 0.0}),
                "end_percent": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING", "CONDITIONING")
    RETURN_NAMES = ("positive", "negative")
    FUNCTION = "apply"

    def apply(self, positive, negative, control_net, image, strength=1.0,
              start_percent=0.0, end_percent=1.0, context=None):
        if float(strength) == 0.0:
            return (positive, negative)

        def patch(cond):
            cond.control_hint = image
            cond.control_strength = float(strength)
            cond.control_params = control_net.params
            cond.control_module = control_net.module
            cond.control_range = (float(start_percent), float(end_percent))
            return cond

        return (
            map_conditioning(positive, patch),
            map_conditioning(negative, patch),
        )


@register_node
class ConditioningSetArea:
    """Restrict conditioning to a pixel-space region (ComfyUI
    ConditioningSetArea parity): the entry's prediction is evaluated on
    the area crop and composited by `strength` against overlapping
    entries during sampling (samplers.composite_eps); USDU tile
    cropping intersects the same area per tile."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
                "strength": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "set_area"

    def set_area(self, conditioning, width, height, x, y, strength=1.0,
                 context=None):
        def patch(cond):
            cond.area = (int(height), int(width), int(y), int(x))
            cond.strength = float(strength)
            return cond

        return (map_conditioning(conditioning, patch),)


@register_node
class ConditioningSetAreaPercentage:
    """Area restriction in frame fractions (ComfyUI
    ConditioningSetAreaPercentage parity): the fractions ride on the
    conditioning as a ('percentage', h, w, y, x) area and resolve
    against the ACTUAL frame where it is known — at trace time in the
    sampler's composition (latent shape is concrete there) and against
    image dims in tile cropping (ops/conditioning.resolve_area)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "width": ("FLOAT", {"default": 1.0}),
                "height": ("FLOAT", {"default": 1.0}),
                "x": ("FLOAT", {"default": 0.0}),
                "y": ("FLOAT", {"default": 0.0}),
                "strength": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "set_area"

    def set_area(self, conditioning, width=1.0, height=1.0, x=0.0, y=0.0,
                 strength=1.0, context=None):
        def patch(cond):
            cond.area = (
                "percentage", float(height), float(width), float(y),
                float(x),
            )
            cond.strength = float(strength)
            return cond

        return (map_conditioning(conditioning, patch),)


@register_node
class ConditioningCombine:
    """Combine two CONDITIONING values into a multi-entry list (ComfyUI
    ConditioningCombine parity): each entry keeps its own area / mask /
    strength / timestep window and the sampler composites their
    predictions (samplers.composite_eps) — the regional-prompting
    substrate."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning_1": ("CONDITIONING",),
                "conditioning_2": ("CONDITIONING",),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "combine"

    def combine(self, conditioning_1, conditioning_2, context=None):
        def entries(v):
            if isinstance(v, (list, tuple)):
                return [as_conditioning(e) for e in v]
            return [as_conditioning(v)]

        return (entries(conditioning_1) + entries(conditioning_2),)


@register_node
class ConditioningAverage:
    """Weighted token-space interpolation (ComfyUI ConditioningAverage
    parity): context and pooled lerp toward conditioning_to by
    conditioning_to_strength; every other payload rides from the `to`
    side. Applies per entry of a multi-entry `to`, pairing with the
    first `from` entry (reference behavior)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning_to": ("CONDITIONING",),
                "conditioning_from": ("CONDITIONING",),
                "conditioning_to_strength": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "average"

    def average(self, conditioning_to, conditioning_from,
                conditioning_to_strength=1.0, context=None):
        import jax.numpy as jnp

        w = float(conditioning_to_strength)
        src = conditioning_from
        if isinstance(src, (list, tuple)):
            src = src[0]
        src = as_conditioning(src)

        def lerp(a, b):
            # token axes may differ (77 vs concat): `from` conforms to
            # `to`'s length — padded with zeros when shorter, TRUNCATED
            # when longer (reference behavior; the output always keeps
            # conditioning_to's shape)
            t = a.shape[1]
            if b.shape[1] < t:
                b = jnp.pad(b, ((0, 0), (0, t - b.shape[1]), (0, 0)))
            elif b.shape[1] > t:
                b = b[:, :t]
            return a * w + b * (1.0 - w)

        def patch(cond):
            cond.context = lerp(cond.context, src.context)
            if cond.pooled is not None and src.pooled is not None:
                cond.pooled = cond.pooled * w + src.pooled * (1.0 - w)
            return cond

        return (map_conditioning(conditioning_to, patch),)


@register_node
class ConditioningZeroOut:
    """Zero the context and pooled payloads (ComfyUI ConditioningZeroOut
    parity — the Flux-style 'no negative' input)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"conditioning": ("CONDITIONING",)}}

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "zero_out"

    def zero_out(self, conditioning, context=None):
        import jax.numpy as jnp

        def patch(cond):
            cond.context = jnp.zeros_like(cond.context)
            if cond.pooled is not None:
                cond.pooled = jnp.zeros_like(cond.pooled)
            return cond

        return (map_conditioning(conditioning, patch),)


@register_node
class ConditioningSetTimestepRange:
    """Gate conditioning to a sampling-progress window (ComfyUI
    ConditioningSetTimestepRange parity): the entry contributes only
    while percent is in [start, end). Combined entries with
    complementary windows are the reference stack's SD3 negative
    recipe."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "start": ("FLOAT", {"default": 0.0}),
                "end": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "set_range"

    def set_range(self, conditioning, start=0.0, end=1.0, context=None):
        if not 0.0 <= float(start) <= 1.0 or not 0.0 <= float(end) <= 1.0:
            raise ValueError("start/end must be sampling percents in [0, 1]")

        def patch(cond):
            cond.timestep_range = (float(start), float(end))
            return cond

        return (map_conditioning(conditioning, patch),)


@register_node
class FluxGuidance:
    """Set the distilled guidance scale a Flux-class model embeds
    (ComfyUI FluxGuidance parity). This is the correct guidance knob
    for guidance-distilled models — true CFG (the cfg input) doubles
    model evals and was not what flux-dev trained on."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "guidance": ("FLOAT", {"default": 3.5}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "append"

    def append(self, conditioning, guidance, context=None):
        def patch(cond):
            cond.guidance = float(guidance)
            return cond

        return (map_conditioning(conditioning, patch),)


@register_node
class SkipLayerGuidanceSD3:
    """Skip-layer guidance for SD3.5-class models (ComfyUI
    SkipLayerGuidanceSD3 parity): during the [start_percent,
    end_percent] window the guidance result gains
    scale * (cond - cond_with_listed_joint_blocks_skipped). Returns a
    patched MODEL (new bundle instance — one extra compile, then the
    whole trajectory is still a single XLA program: the window gate is
    arithmetic, the skip set is a compile-time constant)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "layers": ("STRING", {"default": "7, 8, 9"}),
                "scale": ("FLOAT", {"default": 3.0}),
                "start_percent": ("FLOAT", {"default": 0.01}),
                "end_percent": ("FLOAT", {"default": 0.15}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "skip_guidance"

    def skip_guidance(self, model, layers="7, 8, 9", scale=3.0,
                      start_percent=0.01, end_percent=0.15, context=None):
        import dataclasses

        from ..models import pipeline as pl
        from ..models.registry import model_family

        if model_family(model.model_name) != "sd3":
            raise ValueError(
                "SkipLayerGuidanceSD3 applies to SD3-class MMDiT models; "
                f"{model.model_name!r} is not one"
            )
        pl.reject_existing_guidance_patches(model, "SkipLayerGuidanceSD3")
        depth = get_config(model.model_name).depth
        layer_tuple = tuple(sorted({
            int(part) for part in str(layers).split(",") if part.strip()
        }))
        bad = [i for i in layer_tuple if not 0 <= i < depth]
        if bad:
            raise ValueError(
                f"skip layers {bad} out of range for depth-{depth} model"
            )
        if not layer_tuple or float(scale) == 0.0:
            # muted node (scale 0 / no layers): plain passthrough, no
            # further validation — existing workflows may park junk in
            # the window fields while SLG is disabled
            return (model,)
        if float(start_percent) > float(end_percent):
            # a reversed window would be a silent no-op that still pays
            # the skip-pass compile; reject it loudly
            raise ValueError(
                f"start_percent ({start_percent}) must be <= end_percent "
                f"({end_percent})"
            )
        return (
            dataclasses.replace(
                model,
                slg=pl.SLGSpec(
                    layers=layer_tuple,
                    scale=float(scale),
                    start_percent=float(start_percent),
                    end_percent=float(end_percent),
                ),
            ),
        )


@register_node
class ReferenceLatent:
    """Attach reference latents to conditioning (Flux-Kontext editing;
    ComfyUI ReferenceLatent parity). USDU windows them per tile
    (reference crop_reference_latents) and the Flux MMDiT consumes
    them as extra image-stream tokens."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "latent": ("LATENT",),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "append"

    def append(self, conditioning, latent, context=None):
        def patch(cond):
            refs = list(cond.reference_latents or [])
            refs.append(latent["samples"])
            cond.reference_latents = refs
            return cond

        return (map_conditioning(conditioning, patch),)


@register_node
class ConditioningSetMask:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "mask": ("MASK",),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "set_mask"

    def set_mask(self, conditioning, mask, context=None):
        def patch(cond):
            cond.mask = mask
            return cond

        return (map_conditioning(conditioning, patch),)
