"""ControlNet nodes (loader + apply), ComfyUI-shaped.

Covers the ControlNet-tile role in the reference's upscale workflow
(reference workflows/*.json ControlNetLoader/ControlNetApply); the
hint rides in the Conditioning structure and is cropped per tile by
the USDU pipeline (ops/conditioning.crop_to_tile).
"""

from __future__ import annotations

from ..models.controlnet import load_controlnet
from ..models.registry import get_config
from ..ops.conditioning import Conditioning, as_conditioning
from .registry import register_node


@register_node
class ControlNetLoader:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"control_net_name": ("STRING", {"default": "tile"})},
            "optional": {"model": ("MODEL", {"default": None})},
        }

    RETURN_TYPES = ("CONTROL_NET",)
    FUNCTION = "load"

    def load(self, control_net_name: str, model=None, context=None):
        model_channels, downscale = 320, 8
        if model is not None:
            try:
                unet_cfg = get_config(model.model_name)
                model_channels = unet_cfg.model_channels
                downscale = model.latent_scale
            except (KeyError, AttributeError):
                pass
        cache_key = f"controlnet:{control_net_name}:{model_channels}:{downscale}"
        cache = getattr(context, "pipelines", {}) if context is not None else {}
        if cache_key not in cache:
            cache[cache_key] = load_controlnet(
                str(control_net_name), model_channels, downscale
            )
        return (cache[cache_key],)


@register_node
class ControlNetApply:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "control_net": ("CONTROL_NET",),
                "image": ("IMAGE",),
                "strength": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "apply"

    def apply(self, conditioning, control_net, image, strength=1.0, context=None):
        cond = as_conditioning(conditioning).clone()
        cond.control_hint = image
        cond.control_strength = float(strength)
        cond.control_params = control_net.params
        cond.control_module = control_net.module
        return (cond,)


@register_node
class ConditioningSetArea:
    """Restrict a conditioning entry to a pixel-space region (reference
    crop_cond area handling)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "set_area"

    def set_area(self, conditioning, width, height, x, y, context=None):
        cond = as_conditioning(conditioning).clone()
        cond.area = (int(height), int(width), int(y), int(x))
        return (cond,)


@register_node
class FluxGuidance:
    """Set the distilled guidance scale a Flux-class model embeds
    (ComfyUI FluxGuidance parity). This is the correct guidance knob
    for guidance-distilled models — true CFG (the cfg input) doubles
    model evals and was not what flux-dev trained on."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "guidance": ("FLOAT", {"default": 3.5}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "append"

    def append(self, conditioning, guidance, context=None):
        cond = as_conditioning(conditioning).clone()
        cond.guidance = float(guidance)
        return (cond,)


@register_node
class SkipLayerGuidanceSD3:
    """Skip-layer guidance for SD3.5-class models (ComfyUI
    SkipLayerGuidanceSD3 parity): during the [start_percent,
    end_percent] window the guidance result gains
    scale * (cond - cond_with_listed_joint_blocks_skipped). Returns a
    patched MODEL (new bundle instance — one extra compile, then the
    whole trajectory is still a single XLA program: the window gate is
    arithmetic, the skip set is a compile-time constant)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "layers": ("STRING", {"default": "7, 8, 9"}),
                "scale": ("FLOAT", {"default": 3.0}),
                "start_percent": ("FLOAT", {"default": 0.01}),
                "end_percent": ("FLOAT", {"default": 0.15}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "skip_guidance"

    def skip_guidance(self, model, layers="7, 8, 9", scale=3.0,
                      start_percent=0.01, end_percent=0.15, context=None):
        import dataclasses

        from ..models import pipeline as pl
        from ..models.registry import model_family

        if model_family(model.model_name) != "sd3":
            raise ValueError(
                "SkipLayerGuidanceSD3 applies to SD3-class MMDiT models; "
                f"{model.model_name!r} is not one"
            )
        depth = get_config(model.model_name).depth
        layer_tuple = tuple(sorted({
            int(part) for part in str(layers).split(",") if part.strip()
        }))
        bad = [i for i in layer_tuple if not 0 <= i < depth]
        if bad:
            raise ValueError(
                f"skip layers {bad} out of range for depth-{depth} model"
            )
        if not layer_tuple or float(scale) == 0.0:
            # muted node (scale 0 / no layers): plain passthrough, no
            # further validation — existing workflows may park junk in
            # the window fields while SLG is disabled
            return (model,)
        if float(start_percent) > float(end_percent):
            # a reversed window would be a silent no-op that still pays
            # the skip-pass compile; reject it loudly
            raise ValueError(
                f"start_percent ({start_percent}) must be <= end_percent "
                f"({end_percent})"
            )
        return (
            dataclasses.replace(
                model,
                slg=pl.SLGSpec(
                    layers=layer_tuple,
                    scale=float(scale),
                    start_percent=float(start_percent),
                    end_percent=float(end_percent),
                ),
            ),
        )


@register_node
class ReferenceLatent:
    """Attach reference latents to conditioning (Flux-Kontext editing;
    ComfyUI ReferenceLatent parity). USDU windows them per tile
    (reference crop_reference_latents) and the Flux MMDiT consumes
    them as extra image-stream tokens."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "latent": ("LATENT",),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "append"

    def append(self, conditioning, latent, context=None):
        cond = as_conditioning(conditioning).clone()
        refs = list(cond.reference_latents or [])
        refs.append(latent["samples"])
        cond.reference_latents = refs
        return (cond,)


@register_node
class ConditioningSetMask:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning": ("CONDITIONING",),
                "mask": ("MASK",),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "set_mask"

    def set_mask(self, conditioning, mask, context=None):
        cond = as_conditioning(conditioning).clone()
        cond.mask = mask
        return (cond,)
