"""Core workflow nodes (checkpoint → encode → sample → decode → save).

The minimum node set the reference's bundled workflows assume from
ComfyUI (reference workflows/*.json: CheckpointLoaderSimple,
CLIPTextEncode, EmptyLatentImage, KSampler, VAEDecode/Encode,
SaveImage/PreviewImage, LoadImage, ImageScale). Data contracts:

    MODEL / CLIP / VAE — views over a models.pipeline.PipelineBundle
    CONDITIONING       — jnp array [B, T, context_dim]
    LATENT             — {"samples": [B, h, w, C]} dict (ComfyUI parity)
    IMAGE              — [B, H, W, C] float array in [0, 1]

A `SeedSpec` flows out of DistributedSeed in mesh-parallel runs: it
tells KSampler to generate one sample per mesh participant in a single
SPMD program instead of replaying the graph N times (the TPU-native
collapse of the reference's prompt replication).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..models import pipeline as pl
from ..ops import samplers as smp
from ..parallel.mesh import DATA_AXIS, data_axis_size, shard_map_compat
from ..utils import image as img_utils
from ..utils.logging import log
from .registry import register_node


@dataclasses.dataclass(frozen=True)
class SeedSpec:
    """A seed plus how to spread it across participants."""

    base_seed: int
    per_participant: bool = False  # True ⇒ fold over the mesh data axis
    worker_index: int = -1         # elastic tier: fixed offset applied

    def effective_seed(self) -> int:
        """The single-device seed: base plus the elastic-tier worker
        offset (reference DistributedSeed's seed + worker_index + 1;
        master / non-worker runs use the base seed unchanged). The one
        place the offset rule lives — every sampler node calls this."""
        return self.base_seed + (
            self.worker_index + 1 if self.worker_index >= 0 else 0
        )


def resolve_seed(seed: Any) -> SeedSpec:
    if isinstance(seed, SeedSpec):
        return seed
    return SeedSpec(base_seed=int(seed))


def _get_bundle(context, model_name: str) -> pl.PipelineBundle:
    if model_name not in context.pipelines:
        log(f"loading pipeline {model_name!r}")
        context.pipelines[model_name] = pl.load_pipeline(model_name)
    return context.pipelines[model_name]


@register_node
class CheckpointLoaderSimple:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"ckpt_name": ("STRING", {"default": "tiny-unet"})}}

    RETURN_TYPES = ("MODEL", "CLIP", "VAE")
    FUNCTION = "load"

    def load(self, ckpt_name: str, context=None):
        # strip file extensions so ComfyUI workflow values map to registry names
        name = os.path.splitext(str(ckpt_name))[0]
        bundle = _get_bundle(context, name)
        return (bundle, bundle, bundle)


@register_node
class LoraLoader:
    """Merge a kohya-format LoRA into the model + text-encoder weights
    (ComfyUI LoraLoader parity; the reference free-rides on ComfyUI
    for this). LoRA files resolve from CDT_LORA_DIR (or an absolute
    path). Merging clones the bundle so other graph branches keep the
    unpatched weights."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "clip": ("CLIP",),
                "lora_name": ("STRING", {"default": ""}),
                "strength_model": ("FLOAT", {"default": 1.0}),
                "strength_clip": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("MODEL", "CLIP")
    FUNCTION = "load_lora"

    @staticmethod
    def _resolve_lora_path(name: str) -> str:
        """LoRA file resolution shared with LoraLoaderModelOnly:
        absolute path, or CDT_LORA_DIR/<name>[.safetensors]."""
        path = str(name)
        if not os.path.isabs(path):
            root = os.environ.get("CDT_LORA_DIR", "")
            candidate = os.path.join(root, path) if root else path
            if not os.path.exists(candidate) and not candidate.endswith(
                ".safetensors"
            ):
                candidate += ".safetensors"
            path = candidate
        if not os.path.exists(path):
            raise FileNotFoundError(f"LoRA not found: {path}")
        return path

    def load_lora(self, model: pl.PipelineBundle, clip, lora_name,
                  strength_model=1.0, strength_clip=1.0, context=None):
        from ..models import get_config
        from ..models.lora import apply_lora, read_lora
        from ..models.registry import DUAL_TEXT_ENCODERS

        path = self._resolve_lora_path(str(lora_name))
        lora_sd = read_lora(path)
        # UNet weights come from the MODEL input, text-encoder weights
        # from the CLIP input — the two may be different bundles
        # (ComfyUI semantics: each output patches its own input). The
        # bundle records the encoder registry names it was built with;
        # the name heuristics only cover bundles from older callers.
        te_name = clip.te_name
        te2_name = clip.te2_name
        if te_name is None:
            dual = DUAL_TEXT_ENCODERS.get(clip.model_name)
            if dual:
                te_name, te2_name = dual
            else:
                te_name = ("tiny-te" if clip.model_name.startswith("tiny")
                           else "clip-l")
        parts = {"unet": model.params["unet"], "te": clip.params["te"]}
        has_te2 = te2_name is not None and "te2" in clip.params
        if has_te2:
            parts["te2"] = clip.params["te2"]
        patched, unmatched = apply_lora(
            parts,
            lora_sd,
            get_config(model.model_name),
            get_config(te_name),
            te2_cfg=get_config(te2_name) if has_te2 else None,
            strength=float(strength_model),
            te_strength=float(strength_clip),
        )
        if unmatched:
            log(f"LoRA {os.path.basename(path)}: {len(unmatched)} "
                f"unmatched module(s), e.g. {unmatched[:3]}")
        model_params = dict(model.params)
        model_params["unet"] = patched["unet"]
        clip_params = dict(clip.params)
        clip_params["te"] = patched["te"]
        if has_te2:
            clip_params["te2"] = patched["te2"]
        return (
            dataclasses.replace(model, params=model_params),
            dataclasses.replace(clip, params=clip_params),
        )


@register_node
class CLIPTextEncode:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "text": ("STRING", {"default": ""}),
                "clip": ("CLIP",),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "encode"

    def encode(self, text: str, clip: pl.PipelineBundle, context=None):
        # Conditioning carrying the pooled vector: SDXL-class adm and
        # Flux-class vector_in models consume it; families without
        # pooled conditioning ignore the field (pipeline._make_model_fn)
        return (pl.encode_text_pooled(clip, [str(text)]),)


@register_node
class CLIPTextEncodeFlux:
    """Flux dual-prompt encoding (ComfyUI CLIPTextEncodeFlux parity):
    t5xxl text feeds the T5 context, clip_l text the CLIP pooled
    vector, and guidance rides on the conditioning exactly like the
    FluxGuidance node writes it (pipeline.encode_text_pooled_flux)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP",),
                "clip_l": ("STRING", {"default": ""}),
                "t5xxl": ("STRING", {"default": ""}),
                "guidance": ("FLOAT", {"default": 3.5}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "encode"

    def encode(self, clip, clip_l="", t5xxl="", guidance=3.5, context=None):
        return (
            pl.encode_text_pooled_flux(
                clip, [str(t5xxl)], [str(clip_l)], guidance=float(guidance)
            ),
        )


@register_node
class CLIPTextEncodeSDXL:
    """SDXL dual-prompt encoding (ComfyUI CLIPTextEncodeSDXL parity):
    text_l feeds the CLIP-L tower, text_g the CLIP-G tower, and the
    six size ints ride on the conditioning as the adm Fourier size
    embeddings (orig h/w, crop t/l, target h/w) — overriding the
    KSampler default of zero crops + latent-derived sizes."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP",),
                "width": ("INT", {"default": 1024}),
                "height": ("INT", {"default": 1024}),
                "crop_w": ("INT", {"default": 0}),
                "crop_h": ("INT", {"default": 0}),
                "target_width": ("INT", {"default": 1024}),
                "target_height": ("INT", {"default": 1024}),
                "text_g": ("STRING", {"default": ""}),
                "text_l": ("STRING", {"default": ""}),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "encode"

    def encode(self, clip: pl.PipelineBundle, width=1024, height=1024,
               crop_w=0, crop_h=0, target_width=1024, target_height=1024,
               text_g="", text_l="", context=None):
        size_cond = (
            int(height), int(width), int(crop_h), int(crop_w),
            int(target_height), int(target_width),
        )
        return (
            pl.encode_text_pooled_sdxl(
                clip, [str(text_g)], [str(text_l)], size_cond=size_cond
            ),
        )


@register_node
class ConditioningConcat:
    """Concatenate two conditionings along the TOKEN axis (ComfyUI
    ConditioningConcat parity): the model cross-attends over both
    prompts' tokens in one pass. Everything else (pooled, hints,
    masks) rides from conditioning_to."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "conditioning_to": ("CONDITIONING",),
                "conditioning_from": ("CONDITIONING",),
            }
        }

    RETURN_TYPES = ("CONDITIONING",)
    FUNCTION = "concat"

    def concat(self, conditioning_to, conditioning_from, context=None):
        from ..ops.conditioning import as_conditioning, map_conditioning

        src = conditioning_from
        if isinstance(src, (list, tuple)):
            src = src[0]  # reference behavior: first `from` entry
        from_c = as_conditioning(src)

        def patch(to_c):
            to_c.context = jnp.concatenate(
                [to_c.context, from_c.context], axis=1
            )
            return to_c

        return (map_conditioning(conditioning_to, patch),)


@register_node
class ImageBatch:
    """Batch-concatenate two images (ComfyUI ImageBatch parity): the
    second image resizes to the first's geometry when they differ."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"image1": ("IMAGE",), "image2": ("IMAGE",)}
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "batch"

    def batch(self, image1, image2, context=None):
        if image1.shape[1:3] != image2.shape[1:3]:
            # reference semantics: center-crop to the target aspect,
            # THEN bilinear-resize (common_upscale 'center') — a raw
            # stretch would squash aspect-mismatched frames
            from ..ops import upscale as up_ops

            h, w = image1.shape[1], image1.shape[2]
            (image2,) = up_ops.center_crop_to_aspect([image2], h, w)
            image2 = up_ops.resize_image(image2, h, w, "bilinear")
        return (jnp.concatenate([image1, image2], axis=0),)


@register_node
class ImageCrop:
    """Crop a pixel region (ComfyUI ImageCrop parity): x/y clamp into
    the frame, width/height clamp to the remaining extent."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "crop"

    def crop(self, image, width=512, height=512, x=0, y=0, context=None):
        h, w = image.shape[1], image.shape[2]
        x0 = min(max(int(x), 0), w - 1)
        y0 = min(max(int(y), 0), h - 1)
        x1 = min(x0 + max(int(width), 1), w)
        y1 = min(y0 + max(int(height), 1), h)
        return (image[:, y0:y1, x0:x1, :],)


@register_node
class LatentComposite:
    """Paste one latent into another at a pixel offset (ComfyUI
    LatentComposite parity): offsets are pixels, converted to latent
    cells by the nominal 8x node convention; `feather` blends a linear
    ramp that many pixels into the pasted region's interior edges."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples_to": ("LATENT",),
                "samples_from": ("LATENT",),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
                "feather": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "composite"

    def composite(self, samples_to: dict, samples_from: dict, x=0, y=0,
                  feather=0, context=None):
        dst = samples_to["samples"]
        src = samples_from["samples"]
        lx = max(int(x), 0) // 8
        ly = max(int(y), 0) // 8
        fe = max(int(feather), 0) // 8
        h = min(src.shape[1], dst.shape[1] - ly)
        w = min(src.shape[2], dst.shape[2] - lx)
        out = dict(samples_to)
        if h <= 0 or w <= 0:
            return (out,)
        region = src[:, :h, :w, :]
        if fe > 0:
            # linear ramp into the pasted interior; an edge flush with
            # the destination border keeps full weight (the reference
            # skips the ramp there). Opposing edges MULTIPLY (the
            # reference composes each edge's factor), so a region
            # narrower than 2*fe blends weaker than either ramp alone
            ramp_y = jnp.ones((h,), jnp.float32)
            ramp_x = jnp.ones((w,), jnp.float32)
            idx_y = jnp.arange(h, dtype=jnp.float32)
            idx_x = jnp.arange(w, dtype=jnp.float32)
            if ly > 0:
                ramp_y = ramp_y * jnp.clip((idx_y + 1) / fe, 0.0, 1.0)
            if ly + h < dst.shape[1]:
                ramp_y = ramp_y * jnp.clip((h - idx_y) / fe, 0.0, 1.0)
            if lx > 0:
                ramp_x = ramp_x * jnp.clip((idx_x + 1) / fe, 0.0, 1.0)
            if lx + w < dst.shape[2]:
                ramp_x = ramp_x * jnp.clip((w - idx_x) / fe, 0.0, 1.0)
            mask = (ramp_y[:, None] * ramp_x[None, :])[None, :, :, None]
        else:
            mask = 1.0
        patch = dst[:, ly:ly + h, lx:lx + w, :]
        blended = region * mask + patch * (1.0 - mask)
        out["samples"] = dst.at[:, ly:ly + h, lx:lx + w, :].set(blended)
        return (out,)


@register_node
class RepeatLatentBatch:
    """Repeat latents along the batch axis (ComfyUI RepeatLatentBatch
    parity); the noise_mask repeats with them."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "amount": ("INT", {"default": 1}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "repeat"

    def repeat(self, samples: dict, amount=1, context=None):
        n = max(1, int(amount))
        out = dict(samples)
        out["samples"] = jnp.concatenate([samples["samples"]] * n, axis=0)
        mask = samples.get("noise_mask")
        if mask is not None and getattr(mask, "ndim", 0) >= 3 and (
            mask.shape[0] == samples["samples"].shape[0]
        ):
            out["noise_mask"] = jnp.concatenate([mask] * n, axis=0)
        return (out,)


@register_node
class CLIPSetLastLayer:
    """Clip-skip (ComfyUI CLIPSetLastLayer parity): stop the CLIP
    tower stop_at_clip_layer blocks from the end when producing the
    conditioning context (-1 = the full stack, -2 = the classic
    "clip skip 2", ...). Applies to every CLIP tower in the bundle;
    T5-class towers are unaffected. The pooled vector always comes
    from the full stack (reference semantics)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "clip": ("CLIP",),
                "stop_at_clip_layer": ("INT", {"default": -1}),
            }
        }

    RETURN_TYPES = ("CLIP",)
    FUNCTION = "set_last_layer"

    def set_last_layer(self, clip: pl.PipelineBundle,
                       stop_at_clip_layer=-1, context=None):
        stop = int(stop_at_clip_layer)
        if stop >= 0:
            raise ValueError(
                "stop_at_clip_layer counts from the end and must be "
                "negative (-1 = last layer)"
            )
        return (dataclasses.replace(clip, clip_skip=-stop - 1),)


@register_node
class EmptyLatentImage:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
                "batch_size": ("INT", {"default": 1}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "generate"

    def generate(self, width: int, height: int, batch_size: int, context=None):
        # latent geometry fixed at the SD 8x factor; KSampler rescales
        # PLACEHOLDER latents (the "empty" marker) against the bundle's
        # actual latent layout if it differs — real content (VAEEncode,
        # chained samplers, LatentUpscale) is never rebuilt
        return (
            {
                "samples": jnp.zeros(
                    (int(batch_size), int(height) // 8, int(width) // 8, 4)
                ),
                "width": int(width),
                "height": int(height),
                "empty": True,
            },
        )


@register_node
class KSampler:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "seed": ("INT", {"default": 0}),
                "steps": ("INT", {"default": 20}),
                "cfg": ("FLOAT", {"default": 7.0}),
                "sampler_name": ("STRING", {"default": "euler"}),
                "scheduler": ("STRING", {"default": "karras"}),
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "latent_image": ("LATENT",),
                "denoise": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "sample"

    def sample(
        self,
        model: pl.PipelineBundle,
        seed,
        steps: int,
        cfg: float,
        sampler_name: str,
        scheduler: str,
        positive,
        negative,
        latent_image: dict,
        denoise: float = 1.0,
        context=None,
    ):
        spec = resolve_seed(seed)
        bundle = model
        latents, noise_mask, extras = _prep_latents(bundle, latent_image)
        fixed = bool(latent_image.get("batch_index_fixed", False))

        mesh = getattr(context, "mesh", None) if context is not None else None
        if spec.per_participant and mesh is not None and data_axis_size(mesh) > 1:
            _reject_fixed_on_mesh(fixed)
            param, shift = pl.model_schedule_info(bundle)
            sigmas = smp.get_model_sigmas(
                param, scheduler, int(steps), denoise=float(denoise),
                flow_shift=shift,
            )
            result = _sample_mesh(
                bundle, mesh, spec, sigmas, cfg, sampler_name,
                positive, negative, latents, noise_mask,
            )
            return ({**extras, **result},)

        effective_seed = spec.effective_seed()
        out = pl.img2img_latents(
            bundle,
            latents,
            positive,
            negative,
            steps=int(steps),
            sampler=sampler_name,
            scheduler=scheduler,
            cfg_scale=float(cfg),
            denoise=float(denoise),
            seed=int(effective_seed),
            noise_mask=noise_mask,
            batch_fixed_noise=fixed,
        )
        return ({**extras, "samples": out},)


def _prep_latents(bundle, latent_image: dict):
    """Shared KSampler/KSamplerAdvanced input normalization: rebuild
    PLACEHOLDER latents to the bundle's real latent layout (honor the
    requested pixel geometry / channel count when the bundle's VAE
    differs from the nominal 8x 4-channel layout EmptyLatentImage
    assumes — Flux-class VAEs are 8x but 16ch; real content from
    chained samplers / VAEEncode / LatentUpscale is never replaced),
    normalize the noise_mask to latent resolution, and collect the
    extras the output dict must carry forward (ComfyUI common_ksampler
    parity: chained inpaint passes stay masked; the 'empty' marker does
    NOT propagate)."""
    latents = latent_image["samples"]
    if latent_image.get("empty") and "width" in latent_image and (
        bundle.latent_scale != 8
        or latents.shape[-1] != bundle.latent_channels
    ):
        lh = latent_image["height"] // bundle.latent_scale
        lw = latent_image["width"] // bundle.latent_scale
        if (
            latents.shape[1],
            latents.shape[2],
            latents.shape[3],
        ) != (lh, lw, bundle.latent_channels):
            latents = jnp.zeros(
                (latents.shape[0], lh, lw, bundle.latent_channels)
            )
    noise_mask = latent_image.get("noise_mask")
    if noise_mask is not None:
        noise_mask = _mask_to_latent(
            noise_mask, latents.shape[1], latents.shape[2]
        )
    extras = {
        k: v for k, v in latent_image.items()
        if k not in ("samples", "empty")
    }
    return latents, noise_mask, extras


def _reject_fixed_on_mesh(fixed: bool) -> None:
    """LatentBatchSeedBehavior 'fixed' + per-participant mesh fan-out
    is contradictory (participants exist to render DIFFERENT noise);
    silently honoring one of the two would read as the other
    working."""
    if fixed:
        raise ValueError(
            "LatentBatchSeedBehavior 'fixed' cannot combine with "
            "per-participant mesh fan-out (DistributedSeed); use a "
            "plain INT seed or seed_behavior='random'"
        )


def _sample_mesh(
    bundle, mesh, spec, sigmas, cfg, sampler_name,
    positive, negative, latents, noise_mask=None,
) -> dict:
    """One SPMD program: every participant samples its folded seed over
    the given sigma grid. Output batch = participants x input batch,
    participant-major, sharded over the data axis (the collector
    materialises it). Shared by KSampler (full/denoise-truncated grid)
    and KSamplerAdvanced (windowed grid). Always noise-adding: a
    no-noise pass is deterministic in its input, so the nodes route it
    to the single-device batched path instead of fanning out."""
    from ..parallel.seeds import participant_keys
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = data_axis_size(mesh)
    keys = participant_keys(jax.random.key(spec.base_seed), n)
    keys = jax.device_put(keys, NamedSharding(mesh, P(DATA_AXIS)))
    params = jax.device_put(bundle.params, NamedSharding(mesh, P()))
    pos = jax.device_put(positive, NamedSharding(mesh, P()))
    neg = jax.device_put(negative, NamedSharding(mesh, P()))
    base = jax.device_put(latents, NamedSharding(mesh, P()))
    mask = (
        jax.device_put(
            jnp.clip(noise_mask.astype(jnp.float32), 0.0, 1.0),
            NamedSharding(mesh, P()),
        )
        if noise_mask is not None
        else None
    )

    param, _shift = pl.model_schedule_info(bundle)

    def per_chip(keys_shard, params, pos, neg, base, *maybe_mask):
        mask_arr = maybe_mask[0] if maybe_mask else None
        key = keys_shard[0]
        noise_key, anc_key = jax.random.split(key)
        noise = jax.random.normal(noise_key, base.shape)
        x = smp.noise_latents(param, base, noise, sigmas[0])
        model_fn = pl.guided_model(bundle, params, float(cfg))
        if mask_arr is not None:
            model_fn = smp.masked_inpaint_model(
                model_fn, param, base, noise, mask_arr
            )

        out = smp.sample(
            model_fn, x, sigmas, (pos, neg), sampler_name, anc_key,
            flow=(param == "flow"),
        )
        if mask_arr is not None:
            out = out * mask_arr + base * (1.0 - mask_arr)
        return out

    extra = () if mask is None else (mask,)
    in_specs = [P(DATA_AXIS), P(), P(), P(), P()] + (
        [P()] if mask is not None else []
    )
    out = jax.jit(
        shard_map_compat(
            per_chip,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=P(DATA_AXIS),
            check=False,
        )
    )(keys, params, pos, neg, base, *extra)
    return {"samples": out, "participant_major": True}


@register_node
class KSamplerAdvanced:
    """Windowed-schedule sampler (ComfyUI KSamplerAdvanced parity):
    sample steps [start_at_step, end_at_step] of the full schedule,
    optionally without adding noise (the refine pass of a two-pass
    workflow consuming a leftover-noise latent) and optionally leaving
    leftover noise for a later pass
    (return_with_leftover_noise="enable")."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "add_noise": ("STRING", {"default": "enable"}),
                "noise_seed": ("INT", {"default": 0}),
                "steps": ("INT", {"default": 20}),
                "cfg": ("FLOAT", {"default": 7.0}),
                "sampler_name": ("STRING", {"default": "euler"}),
                "scheduler": ("STRING", {"default": "karras"}),
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "latent_image": ("LATENT",),
                "start_at_step": ("INT", {"default": 0}),
                "end_at_step": ("INT", {"default": 10000}),
                "return_with_leftover_noise": ("STRING", {"default": "disable"}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "sample"

    def sample(
        self,
        model: pl.PipelineBundle,
        add_noise,
        noise_seed,
        steps: int,
        cfg: float,
        sampler_name: str,
        scheduler: str,
        positive,
        negative,
        latent_image: dict,
        start_at_step: int = 0,
        end_at_step: int = 10000,
        return_with_leftover_noise="disable",
        context=None,
    ):
        def flag(value, name):
            value = str(value)
            if value not in ("enable", "disable"):
                raise ValueError(f"{name} must be 'enable' or 'disable'")
            return value == "enable"

        do_noise = flag(add_noise, "add_noise")
        force_full = not flag(
            return_with_leftover_noise, "return_with_leftover_noise"
        )
        spec = resolve_seed(noise_seed)
        bundle = model
        latents, noise_mask, extras = _prep_latents(bundle, latent_image)
        fixed = bool(latent_image.get("batch_index_fixed", False))

        mesh = getattr(context, "mesh", None) if context is not None else None
        # mesh fan-out only when noise IS added: participant diversity
        # comes from per-chip folded noise keys. A no-noise refine pass
        # is deterministic in its input — replicating it across chips
        # would stack identical copies and square the batch; the
        # single-device path below processes the (participant-major)
        # input batch in one batched program instead.
        if (
            spec.per_participant
            and mesh is not None
            and data_axis_size(mesh) > 1
            and do_noise
        ):
            _reject_fixed_on_mesh(fixed)
            param, shift = pl.model_schedule_info(bundle)
            sigmas = pl.advanced_window_sigmas(
                param, scheduler, int(steps), int(start_at_step),
                int(end_at_step), force_full, shift,
            )
            result = _sample_mesh(
                bundle, mesh, spec, sigmas, cfg, sampler_name,
                positive, negative, latents, noise_mask,
            )
            return ({**extras, **result},)

        effective_seed = spec.effective_seed()
        out = pl.img2img_latents_advanced(
            bundle,
            latents,
            positive,
            negative,
            steps=int(steps),
            sampler=sampler_name,
            scheduler=scheduler,
            cfg_scale=float(cfg),
            seed=int(effective_seed),
            start_at_step=int(start_at_step),
            end_at_step=int(end_at_step),
            add_noise=do_noise,
            force_full_denoise=force_full,
            noise_mask=noise_mask,
            batch_fixed_noise=fixed,
        )
        return ({**extras, "samples": out},)


@register_node
class VAELoader:
    """Load a standalone VAE (ComfyUI VAELoader parity): a registry
    VAE name (vae-sd, vae-flux, vae-sd3, ...) whose real weights
    resolve through CDT_CHECKPOINT_DIR/<name>.{safetensors,ckpt} —
    standalone bare-key files and full-checkpoint first_stage_model
    layouts both map. The output plugs into any VAE input, replacing
    the checkpoint's bundled VAE."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"vae_name": ("STRING", {"default": "vae-sd"})}}

    RETURN_TYPES = ("VAE",)
    FUNCTION = "load_vae"

    def load_vae(self, vae_name: str, context=None):
        # real ComfyUI workflows carry filenames ("vae-sd.safetensors")
        # — resolve by stem like CheckpointLoaderSimple
        return (pl.load_vae(os.path.splitext(str(vae_name))[0]),)


@register_node
class VAEDecode:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"samples": ("LATENT",), "vae": ("VAE",)}}

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "decode"

    def decode(self, samples: dict, vae: pl.PipelineBundle, context=None):
        imgs = vae.vae.apply(vae.params["vae"], samples["samples"], method="decode")
        return (imgs,)


@register_node
class VAEEncode:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"pixels": ("IMAGE",), "vae": ("VAE",)}}

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "encode"

    def encode(self, pixels, vae: pl.PipelineBundle, context=None):
        z = vae.vae.apply(vae.params["vae"], pixels, method="encode")
        return ({"samples": z},)


def _mask_to_latent(mask, lh: int, lw: int) -> jax.Array:
    """MASK ([H,W], [B,H,W] or [B,H,W,1]; 1 = regenerate) →
    [B, lh, lw, 1]."""
    m = jnp.asarray(mask, jnp.float32)
    if m.ndim == 4:
        m = m[..., 0]
    if m.ndim == 2:
        m = m[None]
    if m.shape[1:] != (lh, lw):
        m = jax.image.resize(m, (m.shape[0], lh, lw), method="linear")
    return jnp.clip(m, 0.0, 1.0)[..., None]


@register_node
class VAEEncodeForInpaint:
    """Encode pixels for inpainting (reference-substrate ComfyUI node):
    the masked region is neutralized to mid-gray before encoding, the
    mask is grown by `grow_mask_by` pixels of context and attached at
    latent resolution as the latent's noise_mask (1 = regenerate;
    consumed by KSampler's pinned-region sampling)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "pixels": ("IMAGE",),
                "vae": ("VAE",),
                "mask": ("MASK",),
            },
            "optional": {"grow_mask_by": ("INT", {"default": 6})},
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "encode"

    def encode(self, pixels, vae: pl.PipelineBundle, mask, grow_mask_by=6,
               context=None):
        b, h, w, _ = pixels.shape
        m = jnp.asarray(mask, jnp.float32)
        if m.ndim == 2:
            m = m[None]
        if m.shape[1:] != (h, w):
            m = jax.image.resize(m, (m.shape[0], h, w), method="linear")
        m = jnp.clip(m, 0.0, 1.0)
        # Pixels are neutralized with the UN-grown rounded mask; only
        # the emitted noise_mask is dilated, with a g x g max window
        # (~radius g/2) — the reference-stack kernel. Growing the
        # gray-filled region too would erase usable context around the
        # mask boundary (ADVICE r4).
        hard = (m > 0.5).astype(jnp.float32)
        g = int(grow_mask_by)
        grown = hard
        if g > 0:
            # reference convs with padding=ceil((g-1)/2) then crops to
            # [:h,:w]: output pixel i covers [i-ceil((g-1)/2),
            # i+floor((g-1)/2)] — for even g that's one extra pixel
            # toward -y/-x, which SAME padding would mirror
            lo, hi = (g - 1 + 1) // 2, (g - 1) // 2
            grown = jax.lax.reduce_window(
                hard, -jnp.inf, jax.lax.max, (1, g, g), (1, 1, 1),
                ((0, 0), (lo, hi), (lo, hi)),
            )
        neutral = pixels * (1.0 - hard[..., None]) + 0.5 * hard[..., None]
        z = vae.vae.apply(vae.params["vae"], neutral, method="encode")
        return (
            {
                "samples": z,
                "noise_mask": _mask_to_latent(grown, z.shape[1], z.shape[2]),
                "width": int(w),
                "height": int(h),
            },
        )


@register_node
class ImagePadForOutpaint:
    """Pad an image for outpainting (reference-substrate ComfyUI
    node): extends the canvas with edge-replicated pixels and emits
    the matching MASK — 1 over the new region, with a squared
    feathering ramp reaching `feathering` pixels into the original
    image so the inpaint transition blends."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "left": ("INT", {"default": 0}),
                "top": ("INT", {"default": 0}),
                "right": ("INT", {"default": 0}),
                "bottom": ("INT", {"default": 0}),
                "feathering": ("INT", {"default": 40}),
            }
        }

    RETURN_TYPES = ("IMAGE", "MASK")
    FUNCTION = "expand"

    def expand(self, image, left=0, top=0, right=0, bottom=0,
               feathering=40, context=None):
        lf, tp, rt, bt = int(left), int(top), int(right), int(bottom)
        fe = int(feathering)
        padded = jnp.pad(
            image, ((0, 0), (tp, bt), (lf, rt), (0, 0)), mode="edge"
        )
        b, h, w, _ = padded.shape
        mask = np.ones((h, w), np.float32)
        y0, y1 = tp, h - bt
        x0, x1 = lf, w - rt
        inner = np.zeros((y1 - y0, x1 - x0), np.float32)
        if fe > 0:
            # distance of each original pixel to the nearest NEW edge
            yy = np.arange(y1 - y0, dtype=np.float32)[:, None]
            xx = np.arange(x1 - x0, dtype=np.float32)[None, :]
            d = np.full(inner.shape, np.inf, np.float32)
            if tp:
                d = np.minimum(d, yy)
            if bt:
                d = np.minimum(d, (y1 - y0 - 1) - yy)
            if lf:
                d = np.minimum(d, xx)
            if rt:
                d = np.minimum(d, (x1 - x0 - 1) - xx)
            ramp = np.clip((fe - d) / fe, 0.0, 1.0)
            inner = (ramp**2).astype(np.float32)
        mask[y0:y1, x0:x1] = inner
        return (padded, jnp.broadcast_to(jnp.asarray(mask)[None], (b, h, w)))


@register_node
class SetLatentNoiseMask:
    """Attach an inpainting mask to existing latents (reference
    substrate: ComfyUI SetLatentNoiseMask)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"samples": ("LATENT",), "mask": ("MASK",)}}

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "set_mask"

    def set_mask(self, samples: dict, mask, context=None):
        z = samples["samples"]
        out = dict(samples)
        out["noise_mask"] = _mask_to_latent(mask, z.shape[1], z.shape[2])
        return (out,)


@register_node
class ImageScale:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "upscale_method": ("STRING", {"default": "bilinear"}),
                "width": ("INT", {"default": 1024}),
                "height": ("INT", {"default": 1024}),
                "crop": ("STRING", {"default": "disabled"}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "scale"

    def scale(self, image, upscale_method, width, height, crop="disabled", context=None):
        from ..ops import upscale as up_ops

        height, width = up_ops.resolve_resize_dims(
            image.shape[1], image.shape[2], int(width), int(height)
        )
        if str(crop) == "center":
            (image,) = up_ops.center_crop_to_aspect([image], height, width)
        elif str(crop) != "disabled":
            raise ValueError(f"unknown crop mode {crop!r}; use disabled|center")
        out = up_ops.resize_image(image, height, width, str(upscale_method))
        return (jnp.clip(out, 0.0, 1.0),)


@register_node
class ImageScaleBy:
    """Scale an image by a factor (ComfyUI ImageScaleBy parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "upscale_method": ("STRING", {"default": "bilinear"}),
                "scale_by": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "scale"

    def scale(self, image, upscale_method="bilinear", scale_by=1.0,
              context=None):
        from ..ops import upscale as up_ops

        h, w = up_ops.scale_dims(image.shape[1], image.shape[2], scale_by)
        return ImageScale().scale(image, upscale_method, w, h)


@register_node
class ImageInvert:
    """Invert pixel values (ComfyUI ImageInvert parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("IMAGE",)}}

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "invert"

    def invert(self, image, context=None):
        return (1.0 - image,)


@register_node
class LatentUpscale:
    """Resize latents to a target pixel size (the hi-res-fix substrate;
    ComfyUI LatentUpscale parity — latent grid = pixels/8 by the node
    convention, independent of the bundle's actual VAE factor)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "upscale_method": ("STRING", {"default": "nearest-exact"}),
                "width": ("INT", {"default": 1024}),
                "height": ("INT", {"default": 1024}),
                "crop": ("STRING", {"default": "disabled"}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "upscale"

    def upscale(self, samples: dict, upscale_method="nearest-exact",
                width=1024, height=1024, crop="disabled", context=None):
        from ..ops import upscale as up_ops

        z = samples["samples"]
        mask = samples.get("noise_mask")
        h, w = z.shape[1], z.shape[2]
        # latent cells = pixels // 8 (the node convention); 0 stays 0
        # so resolve_resize_dims applies the preserve-aspect rule
        lh, lw = up_ops.resolve_resize_dims(
            h, w, int(width) // 8, int(height) // 8
        )
        if str(crop) == "center":
            # the crop path slices mask and latents together, so the
            # mask normalizes to the source grid first (the no-crop
            # path resizes it once, directly to the target)
            if mask is not None:
                mask = _mask_to_latent(mask, h, w)
                z, mask = up_ops.center_crop_to_aspect([z, mask], lh, lw)
            else:
                (z,) = up_ops.center_crop_to_aspect([z], lh, lw)
        elif str(crop) != "disabled":
            raise ValueError(f"unknown crop mode {crop!r}; use disabled|center")
        out = dict(samples)
        out["samples"] = up_ops.resize_image(z, lh, lw, str(upscale_method))
        out["width"] = lw * 8
        out["height"] = lh * 8
        if mask is not None:
            out["noise_mask"] = _mask_to_latent(mask, lh, lw)
        return (out,)


@register_node
class LatentUpscaleBy:
    """Scale latents by a factor (ComfyUI LatentUpscaleBy parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "upscale_method": ("STRING", {"default": "nearest-exact"}),
                "scale_by": ("FLOAT", {"default": 1.5}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "upscale"

    def upscale(self, samples: dict, upscale_method="nearest-exact",
                scale_by=1.5, context=None):
        from ..ops import upscale as up_ops

        z = samples["samples"]
        lh, lw = up_ops.scale_dims(z.shape[1], z.shape[2], scale_by)
        return LatentUpscale().upscale(
            samples, upscale_method, width=lw * 8, height=lh * 8
        )


@register_node
class LoadImage:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("STRING", {"default": ""})}}

    RETURN_TYPES = ("IMAGE", "MASK")
    FUNCTION = "load"
    NEVER_CACHE = True  # backing file can change between runs

    def load(self, image: str, context=None):
        from .io_dirs import resolve_input_path

        path = resolve_input_path(str(image), context)
        arr = img_utils.pil_to_array(__import__("PIL.Image", fromlist=["Image"]).open(path))
        rgb = arr[..., :3]
        # mask = 1 - alpha (the ComfyUI convention the bundled inpaint
        # workflow depends on: transparent hole -> 1 -> regenerate,
        # matching the noise_mask polarity); no alpha -> all zeros
        # (nothing to regenerate)
        mask = (
            1.0 - arr[..., 3]
            if arr.shape[-1] == 4
            else np.zeros(arr.shape[:2], np.float32)
        )
        return (jnp.asarray(rgb)[None], jnp.asarray(mask)[None])


@register_node
class SaveImage:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "images": ("IMAGE",),
                "filename_prefix": ("STRING", {"default": "output"}),
            }
        }

    RETURN_TYPES = ()
    FUNCTION = "save"
    OUTPUT_NODE = True

    def save(self, images, filename_prefix="output", context=None):
        from .io_dirs import get_output_dir

        out_dir = get_output_dir(context)
        os.makedirs(out_dir, exist_ok=True)
        # resume numbering after existing files so runs never clobber
        # each other (ComfyUI counter-scan behavior)
        from .io_dirs import next_counter

        start = next_counter(out_dir, filename_prefix, "png")
        saved = []
        arr = img_utils.ensure_numpy(images)
        for i in range(arr.shape[0]):
            name = f"{filename_prefix}_{start + i:05d}.png"
            path = os.path.join(out_dir, name)
            with open(path, "wb") as fh:
                fh.write(img_utils.encode_png(arr[i], compress_level=4))
            saved.append(name)
        return ({"ui": {"images": saved}, "images": images},)


@register_node
class PreviewImage(SaveImage):
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"images": ("IMAGE",)}}

    FUNCTION = "preview"
    OUTPUT_NODE = True

    def preview(self, images, context=None):
        # terminal sink; nothing persisted (worker-side pruned graphs end here)
        return ({"ui": {"images": []}, "images": images},)


@register_node
class UpscaleModelLoader:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"model_name": ("STRING", {"default": "4x-generic"})}}

    RETURN_TYPES = ("UPSCALE_MODEL",)
    FUNCTION = "load"

    def load(self, model_name: str, context=None):
        from ..models.upscaler import load_upscale_model

        cache_key = f"upscaler:{model_name}"
        cache = getattr(context, "pipelines", {}) if context is not None else {}
        if cache_key not in cache:
            cache[cache_key] = load_upscale_model(str(model_name))
        return (cache[cache_key],)


@register_node
class ImageUpscaleWithModel:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "upscale_model": ("UPSCALE_MODEL",),
                "image": ("IMAGE",),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "upscale"

    def upscale(self, upscale_model, image, context=None):
        return (upscale_model.upscale(image),)


@register_node
class VAEEncodeTiled(VAEEncode):
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "pixels": ("IMAGE",),
                "vae": ("VAE",),
                "tile_size": ("INT", {"default": 512}),
            }
        }

    FUNCTION = "encode_tiled"

    def encode_tiled(self, pixels, vae, tile_size=512, context=None):
        from ..ops.tiled_vae import encode_tiled

        pixel_tile = max(64, int(tile_size))
        z = encode_tiled(
            pl._Static(vae), vae.params["vae"], pixels,
            tile=pixel_tile, overlap=max(16, pixel_tile // 8),
        )
        return ({"samples": z},)


@register_node
class LatentFromBatch:
    """Slice a contiguous run out of a latent batch (ComfyUI
    LatentFromBatch parity); the noise_mask follows when it is
    per-sample."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "batch_index": ("INT", {"default": 0}),
                "length": ("INT", {"default": 1}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "frombatch"

    def frombatch(self, samples: dict, batch_index=0, length=1, context=None):
        z = samples["samples"]
        b = z.shape[0]
        i0 = min(max(int(batch_index), 0), b - 1)
        i1 = min(i0 + max(int(length), 1), b)
        out = dict(samples)
        out["samples"] = z[i0:i1]
        mask = samples.get("noise_mask")
        if mask is not None and getattr(mask, "ndim", 0) >= 3 and (
            mask.shape[0] == b
        ):
            out["noise_mask"] = mask[i0:i1]
        return (out,)


@register_node
class LatentBatch:
    """Batch-concatenate two latents (ComfyUI LatentBatch parity): the
    second resizes to the first's spatial grid when they differ."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples1": ("LATENT",),
                "samples2": ("LATENT",),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "batch"

    def batch(self, samples1: dict, samples2: dict, context=None):
        from ..ops import upscale as up_ops

        z1, z2 = samples1["samples"], samples2["samples"]
        if z1.shape[1:3] != z2.shape[1:3]:
            z2 = up_ops.resize_image(z2, z1.shape[1], z1.shape[2], "bilinear")
        out = dict(samples1)
        out["samples"] = jnp.concatenate([z1, z2], axis=0)
        out.pop("noise_mask", None)  # per-sample masks no longer align
        return (out,)


def _gaussian_blur(image, radius: int, sigma: float):
    """Shared separable Gaussian kernel (ops/filters.gaussian_blur):
    ImageBlur / ImageSharpen here, the SAG degraded pass in
    ops/samplers."""
    from ..ops.filters import gaussian_blur

    return gaussian_blur(image, radius, sigma)


@register_node
class ImageBlur:
    """Gaussian blur (ComfyUI ImageBlur parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "blur_radius": ("INT", {"default": 1}),
                "sigma": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "blur"

    def blur(self, image, blur_radius=1, sigma=1.0, context=None):
        if int(blur_radius) <= 0:
            return (image,)
        return (_gaussian_blur(image, blur_radius, sigma),)


@register_node
class ImageSharpen:
    """Unsharp-mask sharpening (ComfyUI ImageSharpen parity):
    img + alpha * (img - blur)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "sharpen_radius": ("INT", {"default": 1}),
                "sigma": ("FLOAT", {"default": 1.0}),
                "alpha": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "sharpen"

    def sharpen(self, image, sharpen_radius=1, sigma=1.0, alpha=1.0,
                context=None):
        if int(sharpen_radius) <= 0:
            return (image,)
        blurred = _gaussian_blur(image, sharpen_radius, sigma)
        return (
            jnp.clip(image + float(alpha) * (image - blurred), 0.0, 1.0),
        )


@register_node
class LoraLoaderModelOnly:
    """LoRA merge into the diffusion weights only (ComfyUI
    LoraLoaderModelOnly parity) — for UNETLoader bundles that carry no
    text encoders. Text-encoder modules in the file are reported as
    unmatched, not fatal (partial-LoRA semantics)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "model": ("MODEL",),
                "lora_name": ("STRING", {"default": ""}),
                "strength_model": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("MODEL",)
    FUNCTION = "load_lora_model_only"

    def load_lora_model_only(self, model: pl.PipelineBundle, lora_name,
                             strength_model=1.0, context=None):
        from ..models import get_config
        from ..models.lora import apply_lora, read_lora

        path = LoraLoader._resolve_lora_path(str(lora_name))
        lora_sd = read_lora(path)
        patched, unmatched = apply_lora(
            {"unet": model.params["unet"]},
            lora_sd,
            get_config(model.model_name),
            strength=float(strength_model),
        )
        if unmatched:
            log(f"LoRA {os.path.basename(path)}: {len(unmatched)} "
                f"unmatched module(s), e.g. {unmatched[:3]}")
        model_params = dict(model.params)
        model_params["unet"] = patched["unet"]
        return (dataclasses.replace(model, params=model_params),)


@register_node
class InpaintModelConditioning:
    """Conditioning assembly for inpaint-specialized checkpoints
    (ComfyUI InpaintModelConditioning parity; sd15-inpaint-class
    9-channel UNets): the masked-out pixels are neutralized and
    encoded as the concat channels (mask ++ masked-image latents,
    joined to the model input at every step), the original pixels
    encode as the starting latents, and the mask optionally rides as
    the latent noise_mask."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "positive": ("CONDITIONING",),
                "negative": ("CONDITIONING",),
                "vae": ("VAE",),
                "pixels": ("IMAGE",),
                "mask": ("MASK",),
            },
            "optional": {"noise_mask": ("BOOLEAN", {"default": True})},
        }

    RETURN_TYPES = ("CONDITIONING", "CONDITIONING", "LATENT")
    RETURN_NAMES = ("positive", "negative", "latent")
    FUNCTION = "encode"

    def encode(self, positive, negative, vae: pl.PipelineBundle, pixels,
               mask, noise_mask=True, context=None):
        from ..ops.conditioning import map_conditioning

        b, h, w, _ = pixels.shape
        # MASK contract: [H,W], [B,H,W] or [B,H,W,1] (same preamble as
        # _mask_to_latent)
        m = jnp.asarray(mask, jnp.float32)
        if m.ndim == 4:
            m = m[..., 0]
        if m.ndim == 2:
            m = m[None]
        if m.shape[1:] != (h, w):
            m = jax.image.resize(m, (m.shape[0], h, w), method="linear")
        m = jnp.clip(m, 0.0, 1.0)
        hard = (m > 0.5).astype(jnp.float32)
        # reference pixel neutralization: (p - 0.5) * keep + 0.5
        neutral = (pixels - 0.5) * (1.0 - hard[..., None]) + 0.5
        z_orig = vae.vae.apply(vae.params["vae"], pixels, method="encode")
        z_masked = vae.vae.apply(vae.params["vae"], neutral, method="encode")
        mask_lat = _mask_to_latent(m, z_orig.shape[1], z_orig.shape[2])
        concat = jnp.concatenate([mask_lat, z_masked], axis=-1)

        def patch(cond):
            cond.concat_latent = concat
            return cond

        latent = {"samples": z_orig, "width": int(w), "height": int(h)}
        if noise_mask:
            latent["noise_mask"] = mask_lat
        return (
            map_conditioning(positive, patch),
            map_conditioning(negative, patch),
            latent,
        )


@register_node
class VAEDecodeTiled(VAEDecode):
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "vae": ("VAE",),
                "tile_size": ("INT", {"default": 512}),
            }
        }

    FUNCTION = "decode_tiled"

    def decode_tiled(self, samples, vae, tile_size=512, context=None):
        from ..ops.tiled_vae import decode_tiled

        latent_tile = max(16, int(tile_size) // vae.latent_scale)
        out = decode_tiled(
            pl._Static(vae), vae.params["vae"], samples["samples"],
            tile=latent_tile, overlap=max(4, latent_tile // 8),
        )
        return (out,)
