"""Pipelined, batched tile execution for the elastic USDU tier.

The elastic hot loop used to be fully serial and batch-1: sample one
tile, block on the host readback, PNG-encode, flush over HTTP, and only
then touch the device again. This module decouples those stages:

- **GrantSampler** — runs a placement grant (``tile_idxs``) through a
  vmapped K-tile processor instead of per-tile ``process`` calls.
  Batch-1 convs leave most of a TPU's 128x128 systolic array idle;
  K=8 measured +4% tiles/s on v5e (BENCH_NOTES r5). Grant sizes are
  padded up to a bounded set of shape buckets (powers of two plus
  K_max — ``ops.upscale.grant_buckets``) via the wraparound-duplicate
  trick with folded keys, so a ragged tail never triggers a fresh
  compile mid-job.
- **TilePipeline** — a three-stage pipeline over any grant source:
  pull prefetch (one grant ahead), device sampling (dispatch runs
  ahead of the I/O stage by a bounded number of batches), and host
  readback + encode + submit flush on a dedicated I/O thread. The next
  grant's sampling is dispatched while the previous grant's results
  ride the tunnel back (~0.35 s RTT per readback measured r5 — time
  that previously sat squarely between device dispatches). Heartbeats
  flow from the I/O stage — including while a device batch is in
  flight — rather than from per-tile compute.

Determinism: batching and pipelining change WHEN and HOW MANY tiles
share a dispatch, never the per-tile inputs — keys fold the GLOBAL
tile index and the deterministic blend canvas is order-independent, so
the canvas stays bit-identical to the serial path (asserted by
tests/test_chaos_usdu.py parity scenarios).

Interrupt semantics: an interrupted in-flight grant must requeue
cleanly. Claimed-but-unsubmitted tiles are handed to the ``release``
callback on interrupt (InterruptedError by default) so they return to
the pending queue immediately; any other death leaves them to the
master's heartbeat-timeout / watchdog requeue path, exactly like a
crashed worker process.
"""

from __future__ import annotations

import contextlib
import queue
import threading
import time
from typing import Any, Callable, Optional, Sequence

from ..telemetry import current_trace_id, get_tracer
from ..telemetry.instruments import (
    pipeline_batches_total,
    pipeline_inflight,
    pipeline_padded_tiles_total,
    tile_stage_seconds,
)
from ..telemetry.profiling import (
    D2H,
    H2D,
    STAGE_HOST_BUCKETS,
    ledger_if_enabled,
    transfer_nbytes,
)
from ..utils.constants import (
    HEARTBEAT_INTERVAL_SECONDS,
    PIPELINE_DEPTH,
    PIPELINE_PREFETCH,
)
from ..utils.logging import debug_log


@contextlib.contextmanager
def stage_span(stage: str, role: str, tile_idx: int | None = None, **attrs):
    """Span + latency histogram around one tile pipeline stage
    (pull | sample | readback | encode | submit | decode | blend). The
    span clock is the tracer's (injectable, deterministic in chaos
    runs); the histogram always uses the wall monotonic clock.

    A pull that drains empty (caller sets ``outcome="empty"`` on the
    yielded span) is excluded from the histogram: empty polls last the
    full poll timeout by construction and would drag the pull stage's
    p95 toward the timeout instead of the real dequeue latency (the
    store's pulls_total{outcome="empty"} counter tracks them)."""
    span_attrs: dict[str, Any] = {"stage": stage, "role": role, **attrs}
    if tile_idx is not None:
        span_attrs["tile_idx"] = int(tile_idx)
    started = time.monotonic()
    span = None
    try:
        with get_tracer().span(f"tile.{stage}", **span_attrs) as span:
            yield span
    finally:
        if span is None or span.attrs.get("outcome") != "empty":
            elapsed = time.monotonic() - started
            tile_stage_seconds().observe(elapsed, stage=stage, role=role)
            # host-tax attribution: readback/encode/submit wall rides
            # into the transfer ledger's gather/encode/ship buckets —
            # ONE seam instruments both execution tiers (the cross-job
            # executor emits the same stage vocabulary)
            bucket = STAGE_HOST_BUCKETS.get(stage)
            if bucket is not None:
                ledger = ledger_if_enabled()
                if ledger is not None:
                    ledger.note_host(bucket, elapsed)


class GrantSampler:
    """Bucketed vmapped K-tile processor over a prepared tile set.

    ``process(params, tile, key, pos, neg, yx)`` is the per-tile
    processor (jitted or not — the chaos harness substitutes a stub).
    ``sample(idxs)`` returns the processed tiles ``[n, B, th, tw, C]``:
    serially for ``k_max == 1`` (reference numerics, one dispatch per
    tile) or as ONE vmapped dispatch padded to the grant bucket for
    ``k_max > 1``. Wraparound duplicates share the folded keys of their
    originals, so they compute identical results and the surplus is
    sliced off — numerics never depend on the padding.

    ``mesh``: a local device mesh (parallel/mesh.py) turns each
    bucketed dispatch into a mesh-parallel one — the batch axis is
    sharded across the mesh's data axis with ``NamedSharding``, so a
    D-chip worker computes D tiles' worth of the bucket concurrently
    (and the caller scales ``k_max`` by D: ``tile_scan_batch() × D``).
    Buckets are rounded up to multiples of D so every participant holds
    an equal slice; the extra padding rides the same wraparound-
    duplicate/folded-key idiom, so compile counts stay bounded and
    per-tile outputs stay bit-identical to the single-device path
    (asserted by tests/parallel/test_mesh_tiles.py). ``collect``
    gathers a sharded result host-side via
    ``parallel/collective.host_collect``.
    """

    def __init__(
        self,
        process: Callable,
        params: Any,
        extracted: Any,
        base_key: Any,
        positions: Any,
        pos: Any,
        neg: Any,
        k_max: int = 1,
        role: str = "worker",
        mesh: Any = None,
        job_id: str = "",
        tenant: str = "",
        usage_meter: Any = None,
    ) -> None:
        import jax

        from ..ops.upscale import grant_buckets
        from ..utils.constants import USAGE_ENABLED

        self.process = process
        self.params = params
        self.extracted = extracted
        self.base_key = base_key
        self.positions = positions
        self.pos = pos
        self.neg = neg
        self.k_max = max(1, int(k_max))
        self.role = role
        self.mesh = mesh
        # chip-time attribution (telemetry/usage.py): every sample()
        # dispatch emits a slot-exact usage record charging this job
        # (None = metering disabled)
        self.job_id = str(job_id)
        self.tenant = str(tenant)
        if usage_meter is not None:
            self.usage = usage_meter
        elif USAGE_ENABLED:
            from ..telemetry.usage import get_usage_meter

            self.usage = get_usage_meter()
        else:
            self.usage = None
        self.data_parallel = 1
        self._data_shardings: Optional[tuple] = None
        if mesh is not None:
            from ..parallel.mesh import data_axis_size, mesh_summary
            from ..telemetry.instruments import mesh_devices

            self.data_parallel = max(1, data_axis_size(mesh))
            # gauge the full mesh shape for ANY mesh — a TP-only mesh
            # (data=1, model>1: the over-HBM sharded checkpoint) must
            # still show up on /distributed/metrics
            summary = mesh_summary(mesh)
            for axis in ("data", "model"):
                mesh_devices().set(summary[axis], role=role, axis=axis)
            mesh_devices().set(summary["devices"], role=role, axis="total")
            if self.data_parallel > 1:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel.mesh import DATA_AXIS

                # every dispatch must give each participant at least
                # one tile; callers normally pass K x D already
                self.k_max = max(self.k_max, self.data_parallel)
                # batched tiles keep extracted's rank (leading axis
                # becomes the bucket); shard that leading axis only
                ndim = len(getattr(extracted, "shape", (0, 0, 0, 0)))
                self._data_shardings = (
                    NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1)))),
                    NamedSharding(mesh, P(DATA_AXIS)),  # folded keys
                    NamedSharding(mesh, P(DATA_AXIS, None)),  # yx positions
                )
        if self.data_parallel > 1:
            # round every bucket up to a multiple of the data-axis
            # width so the NamedSharding splits evenly; the set stays
            # bounded (≤ the original bucket count) and the extra
            # padding is wraparound duplicates, numerics-free
            dp = self.data_parallel
            self.buckets = tuple(
                sorted({max(dp, -(-b // dp) * dp) for b in grant_buckets(self.k_max)})
            )
        else:
            self.buckets = grant_buckets(self.k_max)
        # observability + the shape-bucket test: which compiled shapes
        # this job actually exercised, and how much padding it cost
        self.buckets_used: set[int] = set()
        self.padded_tiles = 0
        # device/host attribution (telemetry/profiling.py): a compiled
        # processor's dispatch is device-execute time; an eager stub
        # (chaos harness) never touched a chip, so its dispatches stay
        # out of device_ns and the run's host-tax reads 1.0
        self._device = hasattr(process, "lower")
        self._batched = None
        if self.k_max > 1:
            vmapped = jax.vmap(process, in_axes=(None, 0, 0, None, None, 0))
            # jit the batched program only when the per-tile processor
            # is itself a compiled function (production — it always
            # is). Raw Python stubs (the chaos harness) stay eager:
            # XLA's divide-by-constant rewrite perturbs the last ulp
            # relative to the eager serial path, which would break the
            # bit-identical parity the chaos suite asserts.
            self._batched = (
                jax.jit(vmapped) if hasattr(process, "lower") else vmapped
            )

    # --- helpers ----------------------------------------------------------

    def chunks(self, grant: Sequence[int]) -> list[list[int]]:
        """Split a grant into dispatch-sized chunks (<= k_max each)."""
        grant = [int(t) for t in grant]
        return [
            grant[i : i + self.k_max] for i in range(0, len(grant), self.k_max)
        ]

    def _keys_for(self, idxs: Sequence[int]):
        import jax
        import jax.numpy as jnp

        return jax.vmap(lambda g: jax.random.fold_in(self.base_key, g))(
            jnp.asarray(list(idxs))
        )

    def _bucket_for(self, n: int) -> int:
        """Smallest of this sampler's buckets that fits ``n`` tiles
        (mesh-aware: buckets are pre-rounded to multiples of the
        data-axis width)."""
        from ..ops.upscale import bucket_for

        return bucket_for(n, self.k_max, self.buckets)

    def _place(self, tiles, keys, yxs):
        """Pin the batch inputs' leading axis across the mesh's data
        axis. Placement must be identical between warmup and sample —
        jit caches on input shardings, so a replicated warmup would
        compile a program sample() never runs."""
        if self._data_shardings is None:
            return tiles, keys, yxs
        import jax

        tile_s, key_s, yx_s = self._data_shardings
        started = time.monotonic()
        placed = (
            jax.device_put(tiles, tile_s),
            jax.device_put(keys, key_s),
            jax.device_put(yxs, yx_s),
        )
        ledger = ledger_if_enabled()
        if ledger is not None:
            nbytes = sum(transfer_nbytes(a) for a in (tiles, keys, yxs))
            ledger.note_transfer(H2D, nbytes, time.monotonic() - started)
        return placed

    def collect(self, result, keep_device: bool = False):
        """Materialise a sample() result on the host. Sharded results
        gather via parallel/collective.host_collect (cross-device over
        ICI, cross-process over DCN); unsharded results take the plain
        numpy path. Wired as the TilePipeline's ``to_host`` stage.

        ``keep_device=True`` is the device-canvas route (master-local
        grants composite on-device; the flush pays ONE composited d2h
        instead of one readback per tile): the device array is handed
        straight back. Only honoured for unsharded results — a sharded
        result must gather across the mesh regardless."""
        if keep_device and self.data_parallel <= 1:
            return result
        ledger = ledger_if_enabled()
        if self.data_parallel <= 1:
            from ..utils import image as img_utils

            started = time.monotonic()
            host = img_utils.ensure_numpy(result)  # cdt: noqa[CDT007] - the ledger-bracketed readback seam
            if ledger is not None:
                ledger.note_transfer(
                    D2H,
                    int(getattr(host, "nbytes", 0)),
                    time.monotonic() - started,
                )
            return host
        from ..parallel.collective import host_collect
        from ..telemetry.instruments import mesh_gather_seconds

        started = time.monotonic()
        # host_collect notes the d2h transfer on the ledger itself (the
        # seam is shared with nodes_distributed) — no second note here.
        host = host_collect(result)
        mesh_gather_seconds().observe(
            time.monotonic() - started, role=self.role
        )
        return host

    # --- usage attribution ------------------------------------------------

    def _dispatch_span(self, idxs: Sequence[int], real: int, bucket: int):
        """One ``tile.dispatch`` span per device dispatch — the same
        vocabulary the cross-job executor emits, so perf_report's
        batch-fill and --usage columns read both tiers uniformly."""
        attrs: dict[str, Any] = {
            "real": int(real), "bucket": int(bucket), "jobs": 1,
            "device": bool(self._device),
        }
        if self.job_id:
            attrs["slot_jobs"] = {self.job_id: int(real)}
        if self.tenant:
            attrs["slot_tenants"] = {self.tenant: int(real)}
        return stage_span("dispatch", self.role, int(idxs[0]), **attrs)

    def _note_usage(self, elapsed_s: float, real: int, bucket: int) -> None:
        """Slot-exact attribution record for one dispatch: ``real``
        slots charge this job (a scan-tier slot runs a full
        trajectory), wraparound-padding slots charge the padding waste
        bucket; the scan tier has no step granularity, so tiles count
        here too (each real slot IS a finished tile)."""
        if self.usage is None:
            return
        from ..telemetry.usage import SLOT_PADDING, SLOT_REAL

        slots = [{"job_id": self.job_id, "kind": SLOT_REAL}] * int(real) + [
            {"job_id": "", "kind": SLOT_PADDING}
        ] * int(bucket - real)
        self.usage.note_dispatch(
            tier="scan",
            role=self.role,
            elapsed_s=elapsed_s,
            chips=self.data_parallel,
            slots=slots,
        )
        self.usage.note_tiles(self.role, self.job_id, int(real))

    def _note_profiling(self, elapsed_s: float, real: int) -> None:
        """Feed the transfer ledger: dispatch wall goes to device time
        only when a compiled program ran — eager stubs (chaos harness)
        are host work, so they honestly read host_tax = 1.0."""
        ledger = ledger_if_enabled()
        if ledger is None:
            return
        ledger.note_dispatch(
            elapsed_s, tier="scan", role=self.role, device=self._device
        )
        ledger.note_tiles(int(real))

    # --- execution --------------------------------------------------------

    def sample(self, idxs: Sequence[int]):
        """Process ``idxs`` (one chunk, len <= k_max) -> [n, B, ...]."""
        import jax.numpy as jnp

        idxs = [int(t) for t in idxs]
        n = len(idxs)
        # the batches metric records the COMPILED SHAPE that ran (the
        # runbook's recompile-storm triage reads it as "which shapes
        # exist"), not the raw chunk size — ragged chunks pad up to
        # their bucket before dispatch
        if self._batched is None:
            import jax

            pipeline_batches_total().inc(n, role=self.role, bucket="1")
            # direct fold_in (not the vmapped form): byte-identical to
            # the historical serial loop's key derivation
            started = time.monotonic()
            with self._dispatch_span(idxs, real=n, bucket=n):
                outs = [
                    self.process(
                        self.params,
                        self.extracted[i],
                        jax.random.fold_in(self.base_key, i),
                        self.pos,
                        self.neg,
                        self.positions[i],
                    )
                    for i in idxs
                ]
                if self._device and ledger_if_enabled() is not None:
                    # profiling wants honest device-execute wall: JAX
                    # dispatch is async, so block inside the bracket
                    outs = jax.block_until_ready(outs)  # cdt: noqa[CDT007]
            elapsed = time.monotonic() - started
            self._note_usage(elapsed, real=n, bucket=n)
            self._note_profiling(elapsed, real=n)
            self.buckets_used.add(1)
            return jnp.stack(outs, axis=0)
        bucket = self._bucket_for(n)
        reps = -(-bucket // n)
        padded = (idxs * reps)[:bucket]
        sel = jnp.asarray(padded)
        tiles = jnp.take(self.extracted, sel, axis=0)
        keys = self._keys_for(padded)
        yxs = jnp.take(self.positions, sel, axis=0)
        tiles, keys, yxs = self._place(tiles, keys, yxs)
        started = time.monotonic()
        with self._dispatch_span(idxs, real=n, bucket=bucket):
            out = self._batched(
                self.params, tiles, keys, self.pos, self.neg, yxs
            )
            if self._device and ledger_if_enabled() is not None:
                import jax

                out = jax.block_until_ready(out)  # cdt: noqa[CDT007]
        elapsed = time.monotonic() - started
        self._note_usage(elapsed, real=n, bucket=bucket)
        self._note_profiling(elapsed, real=n)
        self.buckets_used.add(bucket)
        pipeline_batches_total().inc(role=self.role, bucket=str(bucket))
        if self.data_parallel > 1:
            from ..telemetry.instruments import mesh_batch_share

            mesh_batch_share().set(
                bucket // self.data_parallel, role=self.role
            )
        if bucket > n:
            self.padded_tiles += bucket - n
            pipeline_padded_tiles_total().inc(bucket - n, role=self.role)
        return out[:n]

    # --- warmup -----------------------------------------------------------

    def warmup(self, buckets: Sequence[int] | None = None) -> None:
        """Compile the tile processor ahead of the first pull (run
        during the worker's ready-poll window, so with a warm
        persistent cache the first grant starts sampling immediately).
        AOT-lowers when the processor supports it; otherwise executes
        one throwaway dispatch per shape. Failures are non-fatal — the
        first real grant just pays the compile like before."""
        import jax.numpy as jnp

        if buckets is None:
            # largest bucket = the steady-state grant shape; 1 = the
            # serial path every deadline/recovery fallback uses
            buckets = (self.buckets[-1],) if self._batched else (1,)
        for bucket in buckets:
            try:
                if self._batched is not None:
                    idxs = [0] * int(bucket)
                    sel = jnp.asarray(idxs)
                    tiles, keys, yxs = self._place(
                        jnp.take(self.extracted, sel, axis=0),
                        self._keys_for(idxs),
                        jnp.take(self.positions, sel, axis=0),
                    )
                    args = (self.params, tiles, keys, self.pos, self.neg, yxs)
                    fn = self._batched
                else:
                    args = (
                        self.params,
                        self.extracted[0],
                        self._keys_for([0])[0],
                        self.pos,
                        self.neg,
                        self.positions[0],
                    )
                    fn = self.process
                lower = getattr(fn, "lower", None)
                if lower is not None:
                    lower(*args).compile()
                else:
                    fn(*args)
            except Exception as exc:  # noqa: BLE001 - warmup is best effort
                debug_log(f"tile-processor warmup (bucket {bucket}) failed: {exc}")


class _Stop:
    pass


_STOP = _Stop()


class TilePipeline:
    """Staged executor over a grant source; see module docstring.

    Callbacks:
      pull()                -> list[int] | None   (None/[] = drained)
      sample(idxs)          -> device result [n, B, ...] (dispatch)
      to_host(result)       -> host ndarray (default: asks the result)
      emit(tile_idx, arr)   per-tile encode/queue (arr is [B, h, w, C])
      flush(final: bool)    submit pending results (thresholds inside)
      heartbeat()           optional liveness ping (I/O stage owns it)
      check_interrupted()   optional; raising stops the pipeline
      release(idxs)         optional; claimed-but-unsubmitted tiles on
                            interrupt (interrupt_types exceptions only)
    """

    def __init__(
        self,
        *,
        pull: Callable[[], Optional[Sequence[int]]],
        sample: Callable[[Sequence[int]], Any],
        emit: Callable[[int, Any], None],
        flush: Callable[[bool], None],
        chunks: Callable[[Sequence[int]], list[list[int]]] | None = None,
        to_host: Callable[[Any], Any] | None = None,
        heartbeat: Callable[[], None] | None = None,
        check_interrupted: Callable[[], None] | None = None,
        release: Callable[[list[int]], None] | None = None,
        interrupt_types: tuple = (InterruptedError,),
        depth: int | None = None,
        prefetch: bool | None = None,
        threaded: bool = True,
        role: str = "worker",
        span_attrs: dict[str, Any] | None = None,
        heartbeat_interval: float | None = None,
    ) -> None:
        self._pull = pull
        self._sample = sample
        self._emit = emit
        self._flush = flush
        self._chunks = chunks or (lambda grant: [list(grant)])
        self._to_host = to_host or self._default_to_host
        self._heartbeat = heartbeat
        self._check_interrupted = check_interrupted
        self._release = release
        self._interrupt_types = tuple(interrupt_types)
        self.depth = max(1, depth if depth is not None else PIPELINE_DEPTH)
        self.threaded = bool(threaded)
        self.prefetch = (
            (PIPELINE_PREFETCH if prefetch is None else bool(prefetch))
            and self.threaded
        )
        self.role = role
        self.span_attrs = dict(span_attrs or {})
        self.heartbeat_interval = (
            heartbeat_interval
            if heartbeat_interval is not None
            else HEARTBEAT_INTERVAL_SECONDS
        )
        self._stop = threading.Event()
        self._errors: list[BaseException] = []
        self._error_lock = threading.Lock()
        self._claimed: list[int] = []
        self._emitted: set[int] = set()
        self.batches = 0
        self.tiles = 0

    # --- plumbing ---------------------------------------------------------

    @staticmethod
    def _default_to_host(result):
        from ..utils import image as img_utils

        # the I/O stage's readback — bracketed by _drain_item's
        # stage_span("readback"), which rides the ledger's host buckets
        return img_utils.ensure_numpy(result)  # cdt: noqa[CDT007]

    def _record_error(self, exc: BaseException) -> None:
        with self._error_lock:
            self._errors.append(exc)
        self._stop.set()

    def _first_error(self) -> Optional[BaseException]:
        with self._error_lock:
            return self._errors[0] if self._errors else None

    def _put(self, q: queue.Queue, item: Any) -> bool:
        """Bounded put that stays responsive to stop; False = stopped."""
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # --- stages -----------------------------------------------------------

    def _pull_grant(self) -> Optional[list[int]]:
        with stage_span("pull", self.role, **self.span_attrs) as span:
            grant = self._pull()
            if not grant:
                span.attrs["outcome"] = "empty"
                return None
            grant = [int(t) for t in grant]
            span.attrs["tile_idx"] = grant[0]
            if len(grant) > 1:
                span.attrs["batch"] = list(grant)
        return grant

    def _puller_body(self, grant_q: queue.Queue, trace_token: Any) -> None:
        tracer = get_tracer()
        token = tracer.activate(trace_token) if trace_token else None
        try:
            while not self._stop.is_set():
                grant = self._pull_grant()
                if grant is None:
                    self._put(grant_q, _STOP)
                    return
                self._claimed.extend(grant)
                if not self._put(grant_q, grant):
                    return
        except BaseException as exc:  # noqa: BLE001 - forwarded to run()
            self._record_error(exc)
            with contextlib.suppress(queue.Full):
                grant_q.put_nowait(_STOP)
        finally:
            if token is not None:
                tracer.deactivate(token)

    def _io_body(self, work_q: queue.Queue, trace_token: Any) -> None:
        tracer = get_tracer()
        token = tracer.activate(trace_token) if trace_token else None
        try:
            while True:
                try:
                    item = work_q.get(timeout=self.heartbeat_interval)
                except queue.Empty:
                    # drained + stopping (the STOP sentinel can be lost
                    # to a full queue during an abort): exit
                    if self._stop.is_set():
                        return
                    # the device stage is mid-batch (or the puller is
                    # waiting on the master): keep liveness flowing so
                    # a long compile or a big batch never reads as a
                    # dead worker
                    if self._heartbeat is not None:
                        self._heartbeat()
                    continue
                if isinstance(item, _Stop):
                    return
                idxs, result = item
                # +1: the batch just popped is dispatched-but-not-read-
                # back — exactly what this gauge counts; qsize() alone
                # would read 0 through a fully loaded depth-1 pipeline
                pipeline_inflight().set(work_q.qsize() + 1, role=self.role)
                try:
                    self._drain_item(idxs, result)
                finally:
                    work_q.task_done()
                    pipeline_inflight().set(work_q.qsize(), role=self.role)
        except BaseException as exc:  # noqa: BLE001 - forwarded to run()
            self._record_error(exc)
        finally:
            if token is not None:
                tracer.deactivate(token)

    def _drain_item(self, idxs: list[int], result: Any) -> None:
        """Readback + per-tile encode + flush for one device batch.
        The flush callback is consulted after EVERY tile (it applies
        its size thresholds internally), exactly like the historical
        serial loop — consulting it once per K-tile batch would let a
        payload overshoot the size budget by up to K-1 tiles."""
        with stage_span(
            "readback", self.role, idxs[0], batch=list(idxs),
            **self.span_attrs,
        ):
            host = self._to_host(result)
        for i, tile_idx in enumerate(idxs):
            with stage_span(
                "encode", self.role, tile_idx, **self.span_attrs
            ):
                self._emit(tile_idx, host[i])
            self._emitted.add(int(tile_idx))
            self.tiles += 1
            if self._heartbeat is not None:
                self._heartbeat()
            self._flush(False)

    def _sample_chunk(self, chunk: list[int]) -> Any:
        # the cdt_pipeline_batches_total metric is incremented by the
        # GrantSampler (which knows the COMPILED bucket a ragged chunk
        # padded up to); the pipeline only tracks its own batch count
        with stage_span(
            "sample", self.role, chunk[0], batch=list(chunk),
            **self.span_attrs,
        ):
            result = self._sample(chunk)
        self.batches += 1
        return result

    # --- main loop --------------------------------------------------------

    def _run_sync(self) -> None:
        """CDT_PIPELINE=0 fallback: the same stages, strictly serial on
        the calling thread — the historical loop shape, batching aside."""
        while True:
            if self._check_interrupted is not None:
                self._check_interrupted()
            grant = self._pull_grant()
            if grant is None:
                return
            self._claimed.extend(grant)
            for chunk in self._chunks(grant):
                if self._check_interrupted is not None:
                    self._check_interrupted()
                result = self._sample_chunk(chunk)
                self._drain_item(list(chunk), result)

    def _run_threaded(self) -> None:
        trace_token = current_trace_id()
        work_q: queue.Queue = queue.Queue(maxsize=self.depth)
        io_thread = threading.Thread(
            target=self._io_body,
            args=(work_q, trace_token),
            name="cdt-tile-io",
            daemon=True,
        )
        io_thread.start()
        grant_q: queue.Queue = queue.Queue(maxsize=1)
        puller: Optional[threading.Thread] = None
        if self.prefetch:
            puller = threading.Thread(
                target=self._puller_body,
                args=(grant_q, trace_token),
                name="cdt-tile-pull",
                daemon=True,
            )
            puller.start()
        try:
            while True:
                if self._check_interrupted is not None:
                    self._check_interrupted()
                if self._first_error() is not None:
                    break
                if puller is not None:
                    try:
                        grant = grant_q.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if isinstance(grant, _Stop):
                        break
                else:
                    grant = self._pull_grant()
                    if grant is None:
                        break
                    self._claimed.extend(grant)
                for chunk in self._chunks(grant):
                    if self._check_interrupted is not None:
                        self._check_interrupted()
                    if self._first_error() is not None:
                        break
                    result = self._sample_chunk(chunk)
                    if not self._put(work_q, (list(chunk), result)):
                        break
                if self._first_error() is not None:
                    break
        except BaseException as exc:
            self._record_error(exc)
        finally:
            self._stop.set()
            # deliver the sentinel even when the queue is momentarily
            # full — losing it would stall shutdown for a whole idle
            # heartbeat interval
            while io_thread.is_alive():
                try:
                    work_q.put(_STOP, timeout=0.1)
                    break
                except queue.Full:
                    continue
            io_thread.join(timeout=30)
            if puller is not None:
                puller.join(timeout=30)
            pipeline_inflight().set(0, role=self.role)

    def run(self) -> dict[str, Any]:
        """Run the pipeline until the grant source drains; returns
        summary stats. Raises the first stage error (a puller fault, an
        I/O submit failure, an interrupt) after shutting the stages
        down; on interrupt-type errors, claimed-but-unsubmitted tiles
        are handed to ``release`` first so they requeue immediately."""
        if self.threaded:
            self._run_threaded()
        else:
            try:
                self._run_sync()
            except BaseException as exc:  # noqa: BLE001 - unified exit below
                self._record_error(exc)

        error = self._first_error()
        if error is None:
            # drained cleanly: the final flush marks this worker done
            self._flush(True)
            return {"batches": self.batches, "tiles": self.tiles}
        if isinstance(error, self._interrupt_types):
            # Graceful interrupt: ship what is already encoded (those
            # tiles count as emitted), then hand every claimed-but-
            # unsubmitted tile back so the master requeues it NOW
            # instead of waiting out the heartbeat timeout. Any other
            # death (crash, fault) leaves recovery to the master's
            # requeue/watchdog paths, exactly like a dead process.
            try:
                self._flush(True)
            except Exception as exc:  # noqa: BLE001 - best effort
                debug_log(f"final flush after interrupt failed: {exc}")
            if self._release is not None:
                orphaned = sorted(set(self._claimed) - self._emitted)
                if orphaned:
                    try:
                        self._release(orphaned)
                    except Exception as exc:  # noqa: BLE001 - best effort
                        debug_log(f"grant release after interrupt failed: {exc}")
        raise error
