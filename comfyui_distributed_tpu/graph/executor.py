"""Graph validation and execution.

Standalone replacement for the ComfyUI executor the reference rides on
(reference utils/async_helpers.py:108-140 validates via ComfyUI's
execution.validate_prompt then enqueues into its prompt queue). Here:
`validate_prompt` gives the same node-error summarization contract and
`GraphExecutor.execute` runs the graph topologically with per-run
result caching on a compute thread.
"""

from __future__ import annotations

import dataclasses
import inspect
import threading
from typing import Any, Optional

from ..utils.exceptions import PromptValidationError
from .prompt import Prompt, is_link
from .registry import NODE_REGISTRY, get_node_class


@dataclasses.dataclass
class ExecutionContext:
    """Everything a node can reach at run time."""

    mesh: Any = None                     # jax.sharding.Mesh or None
    participant: Any = None              # graph.prompt.ParticipantInfo
    config: dict[str, Any] | None = None
    server: Any = None                   # api server state (elastic tier)
    interrupt_event: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )
    # caches shared across nodes in one process
    pipelines: dict[str, Any] = dataclasses.field(default_factory=dict)
    extras: dict[str, Any] = dataclasses.field(default_factory=dict)

    def check_interrupted(self) -> None:
        if self.interrupt_event.is_set():
            raise InterruptedError("execution interrupted")


def validate_prompt(prompt: Prompt) -> None:
    """Validate a prompt graph; raises PromptValidationError carrying
    per-node error summaries (parity with the reference's
    PromptValidationError surface)."""
    node_errors: dict[str, list[str]] = {}
    if not isinstance(prompt, dict) or not prompt:
        raise PromptValidationError("prompt must be a non-empty dict", {})

    for node_id, node in prompt.items():
        errors: list[str] = []
        if not isinstance(node, dict) or "class_type" not in node:
            node_errors[str(node_id)] = ["node must be a dict with class_type"]
            continue
        class_type = node["class_type"]
        if class_type not in NODE_REGISTRY:
            errors.append(f"unknown class_type {class_type!r}")
            node_errors[str(node_id)] = errors
            continue
        schema = get_node_class(class_type).INPUT_TYPES()
        inputs = node.get("inputs", {})
        for name, spec in schema.get("required", {}).items():
            if name not in inputs:
                if _spec_default(spec) is None:
                    errors.append(f"missing required input {name!r}")
        for name, value in inputs.items():
            if is_link(value):
                if value[0] not in prompt:
                    errors.append(f"input {name!r} links to missing node {value[0]!r}")
                else:
                    src = prompt[value[0]]
                    src_cls = (
                        NODE_REGISTRY.get(src.get("class_type", ""))
                        if isinstance(src, dict)
                        else None
                    )
                    if src_cls is not None:
                        n_outputs = len(getattr(src_cls, "RETURN_TYPES", ()))
                        if value[1] >= n_outputs:
                            errors.append(
                                f"input {name!r} links to output {value[1]} of "
                                f"node {value[0]!r} which has {n_outputs} output(s)"
                            )
        if errors:
            node_errors[str(node_id)] = errors

    if node_errors:
        summary = "; ".join(
            f"node {nid}: {', '.join(errs)}" for nid, errs in sorted(node_errors.items())
        )
        raise PromptValidationError(f"invalid prompt: {summary}", node_errors)

    _toposort(prompt)  # raises on cycles


def _spec_default(spec: Any) -> Any:
    if isinstance(spec, (tuple, list)) and len(spec) > 1 and isinstance(spec[1], dict):
        return spec[1].get("default")
    return None


def _toposort(prompt: Prompt) -> list[str]:
    order: list[str] = []
    state: dict[str, int] = {}  # 0=unvisited 1=visiting 2=done

    def visit(node_id: str, chain: list[str]) -> None:
        s = state.get(node_id, 0)
        if s == 2:
            return
        if s == 1:
            cycle = " -> ".join(chain + [node_id])
            raise PromptValidationError(f"cycle in prompt graph: {cycle}", {})
        state[node_id] = 1
        for value in prompt[node_id].get("inputs", {}).values():
            if is_link(value) and value[0] in prompt:
                visit(value[0], chain + [node_id])
        state[node_id] = 2
        order.append(node_id)

    for node_id in sorted(prompt):
        visit(node_id, [])
    return order


class GraphExecutor:
    """Execute a validated prompt graph."""

    def __init__(self, context: Optional[ExecutionContext] = None):
        self.context = context or ExecutionContext()
        # per-node wall times of the last execution (observability the
        # reference lacks — SURVEY §5 "no timing/profiler integration")
        self.last_timings: dict[str, float] = {}

    def execute(self, prompt: Prompt) -> dict[str, Any]:
        """Run the graph; returns {node_id: output} for OUTPUT_NODE nodes.

        Nodes re-execute only when their literal inputs or any upstream
        node changed since the previous run on this context (ComfyUI's
        incremental-execution behavior). Distributed/gather nodes and
        output sinks always re-run — the reference forces the same via
        IS_CHANGED = nan on its distributed nodes.
        """
        import json
        import time

        validate_prompt(prompt)
        order = _toposort(prompt)
        results: dict[str, tuple] = {}
        outputs: dict[str, Any] = {}
        self.last_timings = {}
        cache: dict[str, tuple[str, tuple]] = self.context.extras.setdefault(
            "node_cache", {}
        )
        content_keys: dict[str, str] = {}

        for node_id in order:
            self.context.check_interrupted()
            node_def = prompt[node_id]
            cls = get_node_class(node_def["class_type"])
            instance = cls()
            schema = cls.INPUT_TYPES()
            kwargs: dict[str, Any] = {}

            # content key: class + literal inputs + upstream keys
            literals = {
                k: v for k, v in node_def.get("inputs", {}).items()
                if not is_link(v)
            }
            upstream_keys = sorted(
                content_keys.get(v[0], "?")
                for v in node_def.get("inputs", {}).values()
                if is_link(v)
            )
            content_keys[node_id] = json.dumps(
                [node_def["class_type"], literals, upstream_keys],
                sort_keys=True, default=str,
            )
            cacheable = not getattr(cls, "OUTPUT_NODE", False) and not getattr(
                cls, "NEVER_CACHE", False
            )
            cached = cache.get(node_id) if cacheable else None
            if cached is not None and cached[0] == content_keys[node_id]:
                results[node_id] = cached[1]
                self.last_timings[node_id] = 0.0
                continue

            # defaults first, then literal/link inputs
            for section in ("required", "optional"):
                for name, spec in schema.get(section, {}).items():
                    default = _spec_default(spec)
                    if default is not None:
                        kwargs[name] = default
            for name, value in node_def.get("inputs", {}).items():
                if is_link(value):
                    src_id, out_idx = value
                    kwargs[name] = results[src_id][out_idx]
                else:
                    kwargs[name] = value

            fn = getattr(instance, cls.FUNCTION)
            if "context" in inspect.signature(fn).parameters:
                kwargs["context"] = self.context
            started = time.perf_counter()
            result = fn(**kwargs)
            self.last_timings[node_id] = round(time.perf_counter() - started, 4)
            if result is None:
                result = ()
            if not isinstance(result, tuple):
                result = (result,)
            results[node_id] = result
            if cacheable:
                cache[node_id] = (content_keys[node_id], result)
            if getattr(cls, "OUTPUT_NODE", False):
                outputs[node_id] = result
        # evict cache entries for node ids absent from this prompt:
        # without this a long-lived server accumulates stale results
        # (large tensors) for every node id any past prompt ever used
        for stale_id in set(cache) - set(prompt):
            del cache[stale_id]
        return outputs
