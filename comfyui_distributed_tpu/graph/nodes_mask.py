"""Mask-manipulation nodes (ComfyUI substrate parity).

The reference's inpaint/outpaint workflows free-ride on ComfyUI's
mask node set (comfy_extras/nodes_mask.py in the reference's host
application; the reference repo itself carries no mask code — its
workflows just assume these class names exist). This module provides
the TPU-native equivalents: every op is a vectorized jnp expression
(ramps, reduce_window morphology, static-slice composites) instead of
the host stack's per-pixel Python loops, so masks stay on device and
the ops fuse under jit when used inside larger programs.

Data contract (matches nodes_core): MASK is [B, H, W] float in
[0, 1] with 1 = selected/regenerate; [H, W] and [B, H, W, 1] inputs
are accepted and normalized.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_node


def as_mask(mask) -> jax.Array:
    """Normalize MASK input to [B, H, W] float32."""
    m = jnp.asarray(mask, jnp.float32)
    if m.ndim == 4:
        m = m[..., 0]
    if m.ndim == 2:
        m = m[None]
    return m


def _broadcast_batch(a: jax.Array, b: jax.Array):
    """Broadcast two batched arrays to a common leading dim."""
    n = max(a.shape[0], b.shape[0])
    if a.shape[0] != n:
        a = jnp.broadcast_to(a, (n,) + a.shape[1:])
    if b.shape[0] != n:
        b = jnp.broadcast_to(b, (n,) + b.shape[1:])
    return a, b


def composite(
    destination: jax.Array,
    source: jax.Array,
    x: int,
    y: int,
    mask: jax.Array | None = None,
    multiplier: int = 1,
    resize_source: bool = False,
) -> jax.Array:
    """Paste `source` over `destination` at pixel offset (x, y), blended
    by `mask` (1 = source shows). Channel-last [B, H, W, C]; offsets
    may be negative (source hangs off the top/left) and are given in
    pixel units — `multiplier` converts them to array units for latent
    composites (8 px per latent cell, the host stack's convention).
    """
    dest = jnp.asarray(destination, jnp.float32)
    src = jnp.asarray(source, jnp.float32)
    if resize_source:
        src = jax.image.resize(
            src,
            (src.shape[0], dest.shape[1], dest.shape[2], src.shape[3]),
            method="bilinear",
        )
    dest, src = _broadcast_batch(dest, src)
    m = None
    if mask is not None:
        m = as_mask(mask)
        if m.shape[0] > dest.shape[0]:
            # a batched mask drives the batch size even over singleton
            # images (host-stack repeat_to_batch_size semantics)
            dest = jnp.broadcast_to(dest, (m.shape[0],) + dest.shape[1:])
            src = jnp.broadcast_to(src, (m.shape[0],) + src.shape[1:])
    dh, dw = dest.shape[1], dest.shape[2]
    sh, sw = src.shape[1], src.shape[2]
    # clamp the pixel offset into the addressable range, then convert
    # to array units
    x = max(-sw * multiplier, min(int(x), dw * multiplier))
    y = max(-sh * multiplier, min(int(y), dh * multiplier))
    left, top = x // multiplier, y // multiplier

    dy0, dx0 = max(top, 0), max(left, 0)
    dy1, dx1 = min(dh, top + sh), min(dw, left + sw)
    if dy1 <= dy0 or dx1 <= dx0:
        return dest  # fully out of frame
    sy0, sx0 = dy0 - top, dx0 - left
    vh, vw = dy1 - dy0, dx1 - dx0

    src_crop = src[:, sy0 : sy0 + vh, sx0 : sx0 + vw, :]
    if m is None:
        m_crop = jnp.ones((1, vh, vw, 1), jnp.float32)
    else:
        if m.shape[1:] != (sh, sw):
            m = jax.image.resize(
                m, (m.shape[0], sh, sw), method="bilinear"
            )
        m_crop = m[:, sy0 : sy0 + vh, sx0 : sx0 + vw, None]
    region = dest[:, dy0:dy1, dx0:dx1, :]
    blended = src_crop * m_crop + region * (1.0 - m_crop)
    return dest.at[:, dy0:dy1, dx0:dx1, :].set(blended)


@register_node
class SolidMask:
    """A constant-valued mask (ComfyUI SolidMask parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "value": ("FLOAT", {"default": 1.0}),
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "solid"

    def solid(self, value=1.0, width=512, height=512, context=None):
        return (
            jnp.full((1, int(height), int(width)), float(value), jnp.float32),
        )


@register_node
class InvertMask:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"mask": ("MASK",)}}

    RETURN_TYPES = ("MASK",)
    FUNCTION = "invert"

    def invert(self, mask, context=None):
        return (1.0 - as_mask(mask),)


@register_node
class CropMask:
    """Crop a mask region (ComfyUI CropMask parity): x/y clamp into
    the frame, width/height clamp to the remaining extent — the same
    convention as ImageCrop."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "mask": ("MASK",),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "crop"

    def crop(self, mask, x=0, y=0, width=512, height=512, context=None):
        m = as_mask(mask)
        h, w = m.shape[1], m.shape[2]
        x0 = min(max(int(x), 0), w - 1)
        y0 = min(max(int(y), 0), h - 1)
        x1 = min(x0 + max(int(width), 1), w)
        y1 = min(y0 + max(int(height), 1), h)
        return (m[:, y0:y1, x0:x1],)


@register_node
class MaskToImage:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"mask": ("MASK",)}}

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "mask_to_image"

    def mask_to_image(self, mask, context=None):
        m = as_mask(mask)
        return (jnp.repeat(m[..., None], 3, axis=-1),)


@register_node
class ImageToMask:
    """Extract one channel of an image as a mask."""

    CHANNELS = ("red", "green", "blue", "alpha")

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "channel": ("STRING", {"default": "red"}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "image_to_mask"

    def image_to_mask(self, image, channel="red", context=None):
        img = jnp.asarray(image, jnp.float32)
        if channel not in self.CHANNELS:
            raise ValueError(
                f"channel must be one of {self.CHANNELS}, got {channel!r}"
            )
        c = self.CHANNELS.index(channel)
        if c >= img.shape[-1]:
            raise ValueError(
                f"image has {img.shape[-1]} channel(s); no {channel!r} plane"
            )
        return (img[..., c],)


@register_node
class MaskComposite:
    """Combine two masks at an offset with an arithmetic or boolean
    operation (ComfyUI MaskComposite parity). The source is clipped to
    the destination frame; pixels outside the overlap keep the
    destination's values."""

    OPERATIONS = ("multiply", "add", "subtract", "and", "or", "xor")

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "destination": ("MASK",),
                "source": ("MASK",),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
                "operation": ("STRING", {"default": "multiply"}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "combine"

    def combine(self, destination, source, x=0, y=0, operation="multiply",
                context=None):
        if operation not in self.OPERATIONS:
            raise ValueError(
                f"operation must be one of {self.OPERATIONS}, "
                f"got {operation!r}"
            )
        dest = as_mask(destination)
        src = as_mask(source)
        dest, src = _broadcast_batch(dest, src)
        dh, dw = dest.shape[1], dest.shape[2]
        left = min(max(int(x), 0), dw)
        top = min(max(int(y), 0), dh)
        right = min(left + src.shape[2], dw)
        bottom = min(top + src.shape[1], dh)
        if bottom <= top or right <= left:
            return (dest,)
        s = src[:, : bottom - top, : right - left]
        d = dest[:, top:bottom, left:right]
        if operation == "multiply":
            out = d * s
        elif operation == "add":
            out = jnp.clip(d + s, 0.0, 1.0)
        elif operation == "subtract":
            out = jnp.clip(d - s, 0.0, 1.0)
        else:
            db = jnp.round(d).astype(bool)
            sb = jnp.round(s).astype(bool)
            if operation == "and":
                out = (db & sb).astype(jnp.float32)
            elif operation == "or":
                out = (db | sb).astype(jnp.float32)
            else:  # xor
                out = (db ^ sb).astype(jnp.float32)
        return (dest.at[:, top:bottom, left:right].set(out),)


@register_node
class FeatherMask:
    """Multiplicative linear ramps along each requested edge (ComfyUI
    FeatherMask parity: column i < left scales by (i+1)/left, etc.) —
    expressed as two per-axis ramp vectors broadcast over the mask
    instead of the host stack's per-column loop."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "mask": ("MASK",),
                "left": ("INT", {"default": 0}),
                "top": ("INT", {"default": 0}),
                "right": ("INT", {"default": 0}),
                "bottom": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "feather"

    def feather(self, mask, left=0, top=0, right=0, bottom=0, context=None):
        m = as_mask(mask)
        h, w = m.shape[1], m.shape[2]
        # feather widths clamp to the mask extent (host-stack parity:
        # an oversized ramp still reaches full weight at the far edge)
        left, right = min(int(left), w), min(int(right), w)
        top, bottom = min(int(top), h), min(int(bottom), h)

        def ramp(n: int, lo: int, hi: int) -> jax.Array:
            idx = jnp.arange(n, dtype=jnp.float32)
            r = jnp.ones((n,), jnp.float32)
            if lo > 0:
                r = r * jnp.clip((idx + 1.0) / lo, 0.0, 1.0)
            if hi > 0:
                r = r * jnp.clip((n - idx) / hi, 0.0, 1.0)
            return r

        m = m * ramp(h, int(top), int(bottom))[None, :, None]
        m = m * ramp(w, int(left), int(right))[None, None, :]
        return (m,)


@register_node
class GrowMask:
    """Dilate (expand > 0) or erode (expand < 0) a mask by |expand|
    iterations of a 3x3 structuring element (ComfyUI GrowMask parity).
    `tapered_corners` uses the cross-shaped element (corners off), so
    repeated growth spreads as a diamond; otherwise the full 3x3
    square. Each iteration is one edge-padded reduce_window — the
    TPU-native form of the host stack's per-image scipy grey
    morphology loop."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "mask": ("MASK",),
                "expand": ("INT", {"default": 0}),
                "tapered_corners": ("BOOLEAN", {"default": True}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "expand_mask"

    def expand_mask(self, mask, expand=0, tapered_corners=True, context=None):
        m = as_mask(mask)
        n = int(expand)
        if n == 0:
            return (m,)
        grow = n > 0
        tapered = bool(tapered_corners)
        # fori_loop keeps the traced graph O(1) in |expand| — a Python
        # loop would emit |expand| sequential reduce_windows at trace
        # time for an unbounded user INT
        m = jax.lax.fori_loop(
            0,
            abs(n),
            lambda _, acc: _morph_step(acc, grow=grow, tapered=tapered),
            m,
        )
        return (m,)


def _morph_step(m: jax.Array, *, grow: bool, tapered: bool) -> jax.Array:
    """One 3x3 dilation/erosion step with edge-replicated borders
    (matching reflect-mode grey morphology at radius 1)."""
    pad = jnp.pad(m, ((0, 0), (1, 1), (1, 1)), mode="edge")
    if not tapered:
        op = jax.lax.max if grow else jax.lax.min
        init = -jnp.inf if grow else jnp.inf
        return jax.lax.reduce_window(
            pad, init, op, (1, 3, 3), (1, 1, 1), "VALID"
        )
    neighborhood = jnp.stack(
        [
            m,
            pad[:, :-2, 1:-1],  # up
            pad[:, 2:, 1:-1],   # down
            pad[:, 1:-1, :-2],  # left
            pad[:, 1:-1, 2:],   # right
        ]
    )
    return neighborhood.max(axis=0) if grow else neighborhood.min(axis=0)


@register_node
class ImageCompositeMasked:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "destination": ("IMAGE",),
                "source": ("IMAGE",),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
                "resize_source": ("BOOLEAN", {"default": False}),
            },
            "optional": {"mask": ("MASK",)},
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "composite"

    def composite(self, destination, source, x=0, y=0, resize_source=False,
                  mask=None, context=None):
        return (
            composite(
                destination, source, int(x), int(y), mask,
                multiplier=1, resize_source=bool(resize_source),
            ),
        )


@register_node
class LatentCompositeMasked:
    """Masked latent paste. Offsets are in PIXEL units, converted at
    the canonical 8 px per latent cell (host-stack convention; the
    unmasked LatentComposite in nodes_core shares it)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "destination": ("LATENT",),
                "source": ("LATENT",),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
                "resize_source": ("BOOLEAN", {"default": False}),
            },
            "optional": {"mask": ("MASK",)},
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "composite"

    def composite(self, destination, source, x=0, y=0, resize_source=False,
                  mask=None, context=None):
        out = dict(destination)
        out["samples"] = composite(
            destination["samples"], source["samples"], int(x), int(y), mask,
            multiplier=8, resize_source=bool(resize_source),
        )
        return (out,)


@register_node
class ThresholdMask:
    """Binarize a mask at a threshold (ComfyUI ThresholdMask parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "mask": ("MASK",),
                "value": ("FLOAT", {"default": 0.5}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "image_to_mask"

    def image_to_mask(self, mask, value=0.5, context=None):
        return ((as_mask(mask) > float(value)).astype(jnp.float32),)


@register_node
class JoinImageWithAlpha:
    """Attach a mask as the image's alpha channel (ComfyUI
    JoinImageWithAlpha parity): alpha = 1 - mask (MASK selects the
    transparent region)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"image": ("IMAGE",), "alpha": ("MASK",)}
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "join_image_with_alpha"

    def join_image_with_alpha(self, image, alpha, context=None):
        m = as_mask(alpha)
        if m.shape[1:] != image.shape[1:3]:
            m = jax.image.resize(
                m, (m.shape[0],) + image.shape[1:3], method="linear"
            )
        rgb = image[..., :3]
        m, rgb = _broadcast_batch(m, rgb)
        return (
            jnp.concatenate([rgb, (1.0 - m)[..., None]], axis=-1),
        )


@register_node
class SplitImageWithAlpha:
    """Split an RGBA image into RGB + MASK (ComfyUI SplitImageWithAlpha
    parity; mask = 1 - alpha). Alpha-less images yield an all-zero
    mask."""

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"image": ("IMAGE",)}}

    RETURN_TYPES = ("IMAGE", "MASK")
    FUNCTION = "split_image_with_alpha"

    def split_image_with_alpha(self, image, context=None):
        rgb = image[..., :3]
        if image.shape[-1] > 3:
            mask = 1.0 - image[..., 3]
        else:
            mask = jnp.zeros(image.shape[:3], jnp.float32)
        return (rgb, mask)
