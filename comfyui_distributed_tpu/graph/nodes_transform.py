"""Geometry and blend nodes for images and latents.

The ComfyUI-substrate transform set the reference's workflows assume
(the reference itself ships no compute nodes — SURVEY §2: it rides on
ComfyUI's node base). Flips/rotations are pure jnp index permutations
(XLA lowers them to layout changes, no data movement until fused);
blends are elementwise and fuse into whatever consumes them.

Conventions shared with nodes_core: IMAGE is [B, H, W, C] float32 in
[0, 1]; LATENT is {"samples": [B, h, w, C]} with pixel offsets
converted by the nominal 8x node convention; MASK is [B, H, W].
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .registry import register_node

_FLIP_METHODS = ("x-axis: vertically", "y-axis: horizontally")
_ROTATIONS = ("none", "90 degrees", "180 degrees", "270 degrees")


def _flip(arr, method: str):
    # vertical flip mirrors rows (H axis); horizontal mirrors columns
    if str(method).startswith("x"):
        return arr[:, ::-1, :, ...]
    if str(method).startswith("y"):
        return arr[:, :, ::-1, ...]
    raise ValueError(f"unknown flip_method {method!r}; use {_FLIP_METHODS}")


def _rotate(arr, rotation: str):
    """Clockwise rotation in 90-degree steps (the node convention:
    '90 degrees' turns the top edge to the right edge). jnp.rot90 is
    counter-clockwise, so k = -quarters over the (H, W) axes."""
    rot = str(rotation)
    if rot not in _ROTATIONS:
        raise ValueError(f"unknown rotation {rotation!r}; use {_ROTATIONS}")
    quarters = _ROTATIONS.index(rot)
    if quarters == 0:
        return arr
    return jnp.rot90(arr, k=-quarters, axes=(1, 2))


@register_node
class LatentFlip:
    """Mirror a latent (ComfyUI LatentFlip parity): 'x-axis:
    vertically' reverses rows, 'y-axis: horizontally' reverses
    columns. Works in latent space, so the decoded image mirrors the
    same way (VAEs here are translation-equivariant convs)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "flip_method": ("STRING", {"default": _FLIP_METHODS[0]}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "flip"

    def flip(self, samples: dict, flip_method=_FLIP_METHODS[0], context=None):
        out = dict(samples)
        out["samples"] = _flip(samples["samples"], flip_method)
        if samples.get("noise_mask") is not None:
            out["noise_mask"] = _flip(samples["noise_mask"], flip_method)
        return (out,)


@register_node
class LatentRotate:
    """Rotate a latent clockwise in quarter turns (ComfyUI
    LatentRotate parity). Non-square latents swap their spatial
    extent on 90/270."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "rotation": ("STRING", {"default": "none"}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "rotate"

    def rotate(self, samples: dict, rotation="none", context=None):
        out = dict(samples)
        out["samples"] = _rotate(samples["samples"], rotation)
        if samples.get("noise_mask") is not None:
            out["noise_mask"] = _rotate(samples["noise_mask"], rotation)
        return (out,)


@register_node
class LatentCrop:
    """Crop a latent region addressed in pixels (ComfyUI LatentCrop
    parity): x/y/width/height are pixel values converted to latent
    cells by the nominal 8x convention, clamped into the frame the
    same way ImageCrop clamps."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
                "x": ("INT", {"default": 0}),
                "y": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "crop"

    def crop(self, samples: dict, width=512, height=512, x=0, y=0,
             context=None):
        z = samples["samples"]
        h, w = z.shape[1], z.shape[2]
        x0 = min(max(int(x) // 8, 0), w - 1)
        y0 = min(max(int(y) // 8, 0), h - 1)
        x1 = min(x0 + max(int(width) // 8, 1), w)
        y1 = min(y0 + max(int(height) // 8, 1), h)
        out = dict(samples)
        out["samples"] = z[:, y0:y1, x0:x1, :]
        if samples.get("noise_mask") is not None:
            out["noise_mask"] = samples["noise_mask"][:, y0:y1, x0:x1, :]
        return (out,)


@register_node
class LatentBlend:
    """Linear interpolation of two latents (ComfyUI LatentBlend
    parity): blend_factor weights samples1, (1 - factor) samples2.
    Shapes must match — latents have no canonical resampling."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples1": ("LATENT",),
                "samples2": ("LATENT",),
                "blend_factor": ("FLOAT", {"default": 0.5}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "blend"

    def blend(self, samples1: dict, samples2: dict, blend_factor=0.5,
              context=None):
        a, b = samples1["samples"], samples2["samples"]
        if a.shape != b.shape:
            raise ValueError(
                f"LatentBlend needs matching shapes, got {a.shape} vs "
                f"{b.shape}"
            )
        f = float(blend_factor)
        out = dict(samples1)
        out["samples"] = a * f + b * (1.0 - f)
        return (out,)


@register_node
class ImageFlip:
    """Mirror an image ('x-axis: vertically' | 'y-axis:
    horizontally')."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "flip_method": ("STRING", {"default": _FLIP_METHODS[0]}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "flip"

    def flip(self, image, flip_method=_FLIP_METHODS[0], context=None):
        return (_flip(image, flip_method),)


@register_node
class ImageRotate:
    """Rotate an image clockwise in quarter turns."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "rotation": ("STRING", {"default": "none"}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "rotate"

    def rotate(self, image, rotation="none", context=None):
        return (_rotate(image, rotation),)


_BLEND_MODES = (
    "normal", "multiply", "screen", "overlay", "soft_light", "difference"
)


@register_node
class ImageBlend:
    """Photoshop-style blend of two images (ComfyUI ImageBlend
    parity): compute the mode's composite of (image1, image2), then
    lerp image1 toward it by blend_factor. image2 is center-crop +
    bilinear resized to image1's geometry when shapes differ (the
    same 'center' upscale convention ImageBatch uses)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image1": ("IMAGE",),
                "image2": ("IMAGE",),
                "blend_factor": ("FLOAT", {"default": 0.5}),
                "blend_mode": ("STRING", {"default": "normal"}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "blend"

    def blend(self, image1, image2, blend_factor=0.5, blend_mode="normal",
              context=None):
        mode = str(blend_mode)
        if mode not in _BLEND_MODES:
            raise ValueError(
                f"unknown blend_mode {mode!r}; use {_BLEND_MODES}"
            )
        if image1.shape[1:3] != image2.shape[1:3]:
            from ..ops import upscale as up_ops

            h, w = image1.shape[1], image1.shape[2]
            (image2,) = up_ops.center_crop_to_aspect([image2], h, w)
            image2 = up_ops.resize_image(image2, h, w, "bilinear")
        a, b = image1, image2
        if mode == "normal":
            mixed = b
        elif mode == "multiply":
            mixed = a * b
        elif mode == "screen":
            mixed = 1.0 - (1.0 - a) * (1.0 - b)
        elif mode == "overlay":
            mixed = jnp.where(
                a <= 0.5, 2.0 * a * b, 1.0 - 2.0 * (1.0 - a) * (1.0 - b)
            )
        elif mode == "soft_light":
            # the W3C/Photoshop piecewise form the reference stack uses
            d = jnp.where(
                a <= 0.25,
                ((16.0 * a - 12.0) * a + 4.0) * a,
                jnp.sqrt(jnp.maximum(a, 0.0)),
            )
            mixed = jnp.where(
                b <= 0.5,
                a - (1.0 - 2.0 * b) * a * (1.0 - a),
                a + (2.0 * b - 1.0) * (d - a),
            )
        else:  # difference
            mixed = jnp.abs(a - b)
        f = float(blend_factor)
        return (jnp.clip(a * (1.0 - f) + mixed * f, 0.0, 1.0),)


@register_node
class EmptyImage:
    """Solid-color image batch (ComfyUI EmptyImage parity): color is
    a packed 0xRRGGBB int, channels scaled to [0, 1]."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "width": ("INT", {"default": 512}),
                "height": ("INT", {"default": 512}),
                "batch_size": ("INT", {"default": 1}),
                "color": ("INT", {"default": 0}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "generate"

    def generate(self, width=512, height=512, batch_size=1, color=0,
                 context=None):
        c = int(color)
        rgb = jnp.asarray(
            [(c >> 16) & 0xFF, (c >> 8) & 0xFF, c & 0xFF], jnp.float32
        ) / 255.0
        return (
            jnp.broadcast_to(
                rgb, (int(batch_size), int(height), int(width), 3)
            ),
        )


@register_node
class LoadImageMask:
    """Load one channel of an image file as a MASK (ComfyUI
    LoadImageMask parity): channel in {alpha, red, green, blue}.
    Alpha is INVERTED (mask = 1 - alpha: the transparent hole is the
    region to regenerate, matching LoadImage's mask output and the
    noise_mask polarity); a file without alpha yields all zeros.
    Missing color channels raise, like ImageToMask — a grayscale file
    has no green plane to silently substitute."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("STRING", {"default": ""}),
                "channel": ("STRING", {"default": "alpha"}),
            }
        }

    RETURN_TYPES = ("MASK",)
    FUNCTION = "load"
    NEVER_CACHE = True  # backing file can change between runs

    def load(self, image: str, channel="alpha", context=None):
        from PIL import Image

        from ..utils import image as img_utils
        from .io_dirs import resolve_input_path

        chans = {"red": 0, "green": 1, "blue": 2, "alpha": 3}
        ch = str(channel)
        if ch not in chans:
            raise ValueError(
                f"unknown channel {ch!r}; use {tuple(chans)}"
            )
        path = resolve_input_path(str(image), context)
        arr = img_utils.pil_to_array(Image.open(path))
        idx = chans[ch]
        if ch == "alpha":
            mask = (
                1.0 - arr[..., 3]
                if arr.shape[-1] == 4
                else np.zeros(arr.shape[:2], np.float32)
            )
        elif idx >= arr.shape[-1]:
            raise ValueError(
                f"image has {arr.shape[-1]} channel(s); no {ch!r} plane"
            )
        else:
            mask = arr[..., idx]
        return (jnp.asarray(mask)[None],)


def _latent_pair(samples1: dict, samples2: dict):
    a, b = samples1["samples"], samples2["samples"]
    if a.shape != b.shape:
        raise ValueError(
            f"latent math needs matching shapes, got {a.shape} vs {b.shape}"
        )
    return a, b


@register_node
class LatentAdd:
    """Elementwise latent sum (ComfyUI LatentAdd parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"samples1": ("LATENT",), "samples2": ("LATENT",)}
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "op"

    def op(self, samples1: dict, samples2: dict, context=None):
        a, b = _latent_pair(samples1, samples2)
        return ({**samples1, "samples": a + b},)


@register_node
class LatentSubtract:
    """Elementwise latent difference (ComfyUI LatentSubtract parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {"samples1": ("LATENT",), "samples2": ("LATENT",)}
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "op"

    def op(self, samples1: dict, samples2: dict, context=None):
        a, b = _latent_pair(samples1, samples2)
        return ({**samples1, "samples": a - b},)


@register_node
class LatentMultiply:
    """Scale a latent by a scalar (ComfyUI LatentMultiply parity)."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "multiplier": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "op"

    def op(self, samples: dict, multiplier=1.0, context=None):
        return (
            {**samples, "samples": samples["samples"] * float(multiplier)},
        )


@register_node
class LatentInterpolate:
    """Norm-preserving latent interpolation (ComfyUI LatentInterpolate
    parity): lerp the direction vectors, then restore the lerped
    magnitude — a plain lerp of two unit-scale latents shrinks toward
    the origin at ratio 0.5."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples1": ("LATENT",),
                "samples2": ("LATENT",),
                "ratio": ("FLOAT", {"default": 1.0}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "op"

    def op(self, samples1: dict, samples2: dict, ratio=1.0, context=None):
        a, b = _latent_pair(samples1, samples2)
        r = float(ratio)
        axes = tuple(range(1, a.ndim))
        na = jnp.sqrt(jnp.sum(a * a, axis=axes, keepdims=True))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axes, keepdims=True))
        da = a / jnp.maximum(na, 1e-8)
        db = b / jnp.maximum(nb, 1e-8)
        mixed = da * r + db * (1.0 - r)
        nm = jnp.sqrt(jnp.sum(mixed * mixed, axis=axes, keepdims=True))
        out = mixed / jnp.maximum(nm, 1e-8) * (na * r + nb * (1.0 - r))
        return ({**samples1, "samples": out},)


@register_node
class ImageQuantize:
    """Reduce an image to N levels per channel (ComfyUI ImageQuantize
    role). dither='none' only — error-diffusion dithers are inherently
    sequential per pixel (a poor fit for one XLA program) and are
    rejected rather than silently approximated."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "image": ("IMAGE",),
                "colors": ("INT", {"default": 256}),
                "dither": ("STRING", {"default": "none"}),
            }
        }

    RETURN_TYPES = ("IMAGE",)
    FUNCTION = "quantize"

    def quantize(self, image, colors=256, dither="none", context=None):
        if str(dither) != "none":
            raise ValueError(
                "only dither='none' is implemented (error-diffusion "
                "dithering is sequential per pixel)"
            )
        n = int(colors)
        if not 2 <= n <= 256:
            raise ValueError("colors must be in [2, 256]")
        levels = n - 1
        return (jnp.round(jnp.clip(image, 0.0, 1.0) * levels) / levels,)


@register_node
class LatentBatchSeedBehavior:
    """Batch noise policy (ComfyUI LatentBatchSeedBehavior parity):
    'fixed' repeats batch index 0's initial noise across the whole
    batch (every element renders the same trajectory — seed sweeps /
    prompt comparisons); 'random' (default) is fresh noise per
    element. The flag rides on the LATENT dict and every sampler
    honors it (pipeline._batch_noise); per-participant mesh fan-out
    rejects 'fixed' loudly — participants exist to render DIFFERENT
    noise."""

    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "samples": ("LATENT",),
                "seed_behavior": ("STRING", {"default": "fixed"}),
            }
        }

    RETURN_TYPES = ("LATENT",)
    FUNCTION = "op"

    def op(self, samples: dict, seed_behavior="fixed", context=None):
        mode = str(seed_behavior)
        if mode not in ("fixed", "random"):
            raise ValueError(
                f"seed_behavior must be 'fixed' or 'random', got {mode!r}"
            )
        out = dict(samples)
        if mode == "fixed":
            out["batch_index_fixed"] = True
        else:
            out.pop("batch_index_fixed", None)
        return (out,)
