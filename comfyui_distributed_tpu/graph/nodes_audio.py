"""Audio workflow nodes.

AUDIO contract: {"waveform": [B, C, S] float32, "sample_rate": int} —
the shape the collector's audio combine and the AudioBatchDivider
already speak (reference collector audio path, nodes/collector.py
_combine_audio).
"""

from __future__ import annotations

import os

import numpy as np

from .registry import register_node


@register_node
class LoadAudio:
    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"audio": ("STRING", {"default": ""})}}

    RETURN_TYPES = ("AUDIO",)
    FUNCTION = "load"
    NEVER_CACHE = True  # backing file can change between runs

    def load(self, audio: str, context=None):
        from .io_dirs import resolve_input_path

        path = resolve_input_path(str(audio), context)
        if path.endswith(".npz"):
            data = np.load(path)
            wave = np.asarray(data["waveform"], np.float32)
            rate = int(data["sample_rate"])
        else:
            import wave as wave_mod

            with wave_mod.open(path, "rb") as wf:
                rate = wf.getframerate()
                n = wf.getnframes()
                raw = wf.readframes(n)
                width = wf.getsampwidth()
                channels = wf.getnchannels()
            dtype = {1: np.int8, 2: np.int16, 4: np.int32}[width]
            pcm = np.frombuffer(raw, dtype=dtype).astype(np.float32)
            pcm /= float(np.iinfo(dtype).max)
            wave = pcm.reshape(-1, channels).T[None]  # [1, C, S]
        return ({"waveform": wave, "sample_rate": rate},)


@register_node
class SaveAudio:
    @classmethod
    def INPUT_TYPES(cls):
        return {
            "required": {
                "audio": ("AUDIO",),
                "filename_prefix": ("STRING", {"default": "audio"}),
            }
        }

    RETURN_TYPES = ()
    FUNCTION = "save"
    OUTPUT_NODE = True

    def save(self, audio: dict, filename_prefix="audio", context=None):
        from .io_dirs import get_output_dir

        out_dir = get_output_dir(context)
        os.makedirs(out_dir, exist_ok=True)
        name = f"{filename_prefix}.npz"
        np.savez(
            os.path.join(out_dir, name),
            waveform=np.asarray(audio["waveform"], np.float32),
            sample_rate=audio["sample_rate"],
        )
        return ({"ui": {"audio": [name]}, "audio": audio},)
