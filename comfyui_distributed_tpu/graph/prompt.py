"""Prompt-graph indexing and the distributed rewrite passes.

Pure-JSON algorithms, re-implemented from the behavior of reference
api/orchestration/prompt_transform.py:

- PromptIndex: class/node lookup tables + upstream-reachability cache.
- prune_prompt_for_worker: workers only need the distributed nodes and
  everything upstream of them; downstream-only nodes (previews, saves)
  are dropped and a terminal output node is appended so the worker
  executor has something to run toward.
- prepare_delegate_master_prompt: orchestrator-only master keeps the
  collector and downstream; upstream compute is stripped and dangling
  links replaced (empty-image placeholder feeding the collector).
- generate_job_id_map / apply_participant_overrides: per-participant
  seed offsets, per-worker value overrides, job-id + role injection.
"""

from __future__ import annotations

import copy
import time
import uuid
from typing import Any, Callable

Prompt = dict[str, dict[str, Any]]

# Node classes that mark a graph as distributed (parity with the node
# names of the reference so its workflows port unchanged).
COLLECTOR_CLASSES = ("DistributedCollector",)
UPSCALER_CLASSES = ("UltimateSDUpscaleDistributed",)
SEED_CLASSES = ("DistributedSeed",)
VALUE_CLASSES = ("DistributedValue",)
DISTRIBUTED_CLASSES = (
    COLLECTOR_CLASSES + UPSCALER_CLASSES + SEED_CLASSES + VALUE_CLASSES
)
TERMINAL_OUTPUT_CLASS = "PreviewImage"
EMPTY_IMAGE_CLASS = "DistributedEmptyImage"


def is_link(value: Any) -> bool:
    """A link is [node_id, output_index]."""
    return (
        isinstance(value, (list, tuple))
        and len(value) == 2
        and isinstance(value[0], str)
        and isinstance(value[1], int)
    )


class PromptIndex:
    """Lookup tables over a prompt graph + cached upstream reachability."""

    def __init__(self, prompt: Prompt):
        self.prompt = prompt
        self.by_class: dict[str, list[str]] = {}
        for node_id, node in prompt.items():
            self.by_class.setdefault(node.get("class_type", ""), []).append(node_id)
        self._upstream_cache: dict[str, frozenset[str]] = {}

    def nodes_of_class(self, *class_names: str) -> list[str]:
        out: list[str] = []
        for name in class_names:
            out.extend(self.by_class.get(name, []))
        return sorted(out)

    def inputs_of(self, node_id: str) -> dict[str, Any]:
        return self.prompt.get(node_id, {}).get("inputs", {})

    def direct_upstream(self, node_id: str) -> list[str]:
        return [
            value[0]
            for value in self.inputs_of(node_id).values()
            if is_link(value) and value[0] in self.prompt
        ]

    def upstream_closure(self, node_id: str) -> frozenset[str]:
        """All nodes reachable following input links (incl. the node)."""
        cached = self._upstream_cache.get(node_id)
        if cached is not None:
            return cached
        seen: set[str] = set()
        stack = [node_id]
        while stack:
            current = stack.pop()
            if current in seen or current not in self.prompt:
                continue
            seen.add(current)
            stack.extend(self.direct_upstream(current))
        result = frozenset(seen)
        self._upstream_cache[node_id] = result
        return result

    def downstream_closure(self, node_id: str) -> frozenset[str]:
        """All nodes reachable following output links (incl. the node)."""
        consumers: dict[str, list[str]] = {}
        for nid in self.prompt:
            for up in self.direct_upstream(nid):
                consumers.setdefault(up, []).append(nid)
        seen: set[str] = set()
        stack = [node_id]
        while stack:
            current = stack.pop()
            if current in seen:
                continue
            seen.add(current)
            stack.extend(consumers.get(current, []))
        return frozenset(seen)

    def has_distributed_nodes(self) -> bool:
        return bool(self.nodes_of_class(*DISTRIBUTED_CLASSES))


def fresh_node_id(*prompts: Prompt) -> str:
    """An id unused in ALL given prompts (pass the original alongside a
    pruned copy so injected nodes never alias an id that meant
    something else upstream)."""
    numeric = [int(k) for p in prompts for k in p if k.isdigit()]
    return str(max(numeric, default=0) + 1)


def prune_prompt_for_worker(prompt: Prompt, index: PromptIndex | None = None) -> Prompt:
    """Keep only distributed nodes + their upstream closure.

    Workers render and ship results back; they never save/preview on
    their own. If nothing remains terminal (no OUTPUT-style node), a
    terminal PreviewImage is appended on the first collector/upscaler
    so the executor has a sink (reference prompt_transform behavior).
    """
    index = index or PromptIndex(prompt)
    anchors = index.nodes_of_class(*(COLLECTOR_CLASSES + UPSCALER_CLASSES))
    if not anchors:
        return copy.deepcopy(prompt)
    keep: set[str] = set()
    for anchor in anchors:
        keep |= index.upstream_closure(anchor)
    pruned = {nid: copy.deepcopy(prompt[nid]) for nid in keep}
    sink_id = fresh_node_id(pruned, prompt)
    pruned[sink_id] = {
        "class_type": TERMINAL_OUTPUT_CLASS,
        "inputs": {"images": [anchors[0], 0]},
    }
    return pruned


def prepare_delegate_master_prompt(
    prompt: Prompt, index: PromptIndex | None = None
) -> Prompt:
    """Orchestrator-only master: keep collectors + downstream, replace
    the collector's upstream feed with an empty-image placeholder, drop
    any other dangling links."""
    index = index or PromptIndex(prompt)
    collectors = index.nodes_of_class(*COLLECTOR_CLASSES)
    if not collectors:
        return copy.deepcopy(prompt)
    keep: set[str] = set()
    for coll in collectors:
        keep |= index.downstream_closure(coll)
    delegate = {nid: copy.deepcopy(prompt[nid]) for nid in keep}

    placeholder_id = fresh_node_id(delegate, prompt)
    delegate[placeholder_id] = {"class_type": EMPTY_IMAGE_CLASS, "inputs": {}}

    for nid, node in delegate.items():
        if nid == placeholder_id:
            continue
        for key, value in list(node.get("inputs", {}).items()):
            if is_link(value) and value[0] not in delegate:
                if node["class_type"] in COLLECTOR_CLASSES and key == "images":
                    node["inputs"][key] = [placeholder_id, 0]
                else:
                    # dangling non-collector link: strip the input; the
                    # node schema's default takes over at validation
                    del node["inputs"][key]
    return delegate


def generate_job_id_map(prompt: Prompt, index: PromptIndex | None = None) -> dict[str, str]:
    """One job id per distributed gather node: exec_<ms>_<uuid6>_<node>."""
    index = index or PromptIndex(prompt)
    base = f"exec_{int(time.time() * 1000)}_{uuid.uuid4().hex[:6]}"
    return {
        node_id: f"{base}_{node_id}"
        for node_id in index.nodes_of_class(*(COLLECTOR_CLASSES + UPSCALER_CLASSES))
    }


# --- participant overrides ------------------------------------------------

def _coerce(value: Any, type_name: str) -> Any:
    try:
        if type_name == "INT":
            return int(value)
        if type_name == "FLOAT":
            return float(value)
        if type_name == "BOOLEAN":
            if isinstance(value, str):
                return value.strip().lower() in ("1", "true", "yes", "on")
            return bool(value)
        return str(value)
    except (TypeError, ValueError):
        return None


def _override_distributed_seed(
    node: dict[str, Any], participant: "ParticipantInfo"
) -> None:
    node["inputs"]["is_worker"] = participant.is_worker
    node["inputs"]["worker_index"] = participant.worker_index


def _override_distributed_value(
    node: dict[str, Any], participant: "ParticipantInfo"
) -> None:
    """Apply a per-worker typed value: the node's `overrides` input is a
    JSON-ish map {"_type": "INT", "1": "100", ...} keyed by 1-based
    worker position; master keeps the node's base value."""
    node["inputs"]["is_worker"] = participant.is_worker
    node["inputs"]["worker_index"] = participant.worker_index
    if not participant.is_worker:
        return
    overrides = node["inputs"].get("overrides")
    if not isinstance(overrides, dict):
        return
    type_name = overrides.get("_type", "STRING")
    raw = overrides.get(str(participant.worker_index + 1))
    if raw is None:
        return
    coerced = _coerce(raw, type_name)
    if coerced is not None:
        node["inputs"]["value"] = coerced


def _override_collector(node: dict[str, Any], participant: "ParticipantInfo") -> None:
    node["inputs"]["is_worker"] = participant.is_worker
    node["inputs"]["worker_id"] = participant.worker_id
    node["inputs"]["master_url"] = participant.master_url
    node["inputs"]["job_id"] = participant.job_ids.get(
        participant.current_node_id, ""
    )


_OVERRIDE_FNS: dict[str, Callable[[dict[str, Any], "ParticipantInfo"], None]] = {}
for _cls in SEED_CLASSES:
    _OVERRIDE_FNS[_cls] = _override_distributed_seed
for _cls in VALUE_CLASSES:
    _OVERRIDE_FNS[_cls] = _override_distributed_value
for _cls in COLLECTOR_CLASSES + UPSCALER_CLASSES:
    _OVERRIDE_FNS[_cls] = _override_collector


class ParticipantInfo:
    """Identity of one participant for a given execution."""

    def __init__(
        self,
        is_worker: bool,
        worker_index: int = -1,
        worker_id: str = "",
        master_url: str = "",
        job_ids: dict[str, str] | None = None,
        enabled_worker_ids: list[str] | None = None,
    ):
        self.is_worker = is_worker
        self.worker_index = worker_index
        self.worker_id = worker_id
        self.master_url = master_url
        self.job_ids = job_ids or {}
        self.enabled_worker_ids = enabled_worker_ids or []
        self.current_node_id = ""


def apply_participant_overrides(prompt: Prompt, participant: ParticipantInfo) -> Prompt:
    """Return a deep-copied prompt with role/seed/value/job-id overrides
    applied for one participant."""
    out = copy.deepcopy(prompt)
    for node_id, node in out.items():
        fn = _OVERRIDE_FNS.get(node.get("class_type", ""))
        if fn is not None:
            participant.current_node_id = node_id
            node.setdefault("inputs", {})
            fn(node, participant)
            # every distributed node also learns the full participant roster
            node["inputs"]["enabled_worker_ids"] = list(
                participant.enabled_worker_ids
            )
    return out
