"""Node registry: class name → node implementation.

Node classes follow a ComfyUI-compatible contract so the reference's
bundled workflows (reference workflows/*.json) load directly:

    class MyNode:
        @classmethod
        def INPUT_TYPES(cls) -> {"required": {name: (type, opts)},
                                 "optional": {...}, "hidden": {...}}
        RETURN_TYPES: tuple[str, ...]
        FUNCTION: str            # method name to call
        OUTPUT_NODE: bool        # terminal sink (its run marks outputs)

The executor instantiates per graph run and calls
`getattr(node, FUNCTION)(**inputs, context=ctx)` where `context` is
the ExecutionContext (mesh, pipeline cache, participant identity).
"""

from __future__ import annotations

from typing import Any, Type

NODE_REGISTRY: dict[str, Type[Any]] = {}


def register_node(cls: Type[Any] | None = None, *, name: str | None = None):
    """Class decorator: @register_node or @register_node(name=...)."""

    def wrap(klass: Type[Any]) -> Type[Any]:
        NODE_REGISTRY[name or klass.__name__] = klass
        return klass

    if cls is not None:
        return wrap(cls)
    return wrap


def get_node_class(class_type: str) -> Type[Any]:
    if class_type not in NODE_REGISTRY:
        raise KeyError(
            f"unknown node class {class_type!r}; registered: {sorted(NODE_REGISTRY)}"
        )
    return NODE_REGISTRY[class_type]
