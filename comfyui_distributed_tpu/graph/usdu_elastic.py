"""Elastic-tier USDU: master/worker tile-queue loops over HTTP.

The cross-host protocol of the reference (reference
upscale/modes/static.py + upscale/worker_comms.py), for participants
that are NOT part of the local mesh (other hosts, heterogeneous
boxes, cloud pods):

  worker: poll job ready (warming the tile-processor compile in the
          background) → pipelined pull/sample/encode/submit stages
          (graph/tile_pipeline.py): placement grants run as vmapped
          K-tile device batches, the next grant's sampling dispatches
          while the previous grant's results ride the tunnel back,
          heartbeats flow from the I/O stage → final flush
  master: init queue → pull speed-sized grants, batch-sample, blend
          locally while draining worker results → on drain, collection
          phase with heartbeat-timeout requeue (busy-probe grace) →
          local fallback for requeued tiles → blend

Because per-tile noise keys fold the global tile index
(ops/upscale.py), a tile re-run after requeue is bit-identical — no
seam drift from fault recovery; batching/pipelining change WHO and
WHEN, never the per-tile inputs.

The worker side talks through a WorkClient so hermetic tests can
script the exchange without sockets (the reference's fake-comms test
pattern, reference tests/test_static_mode.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models import pipeline as pl
from ..ops import samplers as smp
from ..ops import tiles as tile_ops
from ..ops import upscale as upscale_ops
from ..utils import image as img_utils
from ..utils.async_helpers import run_async_in_server_loop
from ..utils.constants import (
    FLEET_SNAPSHOT_SECONDS,
    MAX_PAYLOAD_SIZE,
    MAX_TILE_BATCH,
    PAYLOAD_HEADROOM,
    PIPELINE_ENABLED,
    PUSH_GRANTS_ENABLED,
    PUSH_WAIT_SECONDS,
    QUEUE_POLL_INTERVAL_SECONDS,
    SCHED_MAX_PULL_BATCH,
    WARM_COMPILE,
    tile_scan_batch,
)
from ..resilience.policy import (
    http_policy,
    poll_ready_policy,
    retry_async,
    transport_errors,
    work_pull_policy,
)
from ..telemetry import TRACE_HEADER, current_trace_id
from ..telemetry.instruments import tiles_processed_total
from ..utils.exceptions import TransientServerError, WorkerError
from ..utils.logging import debug_log, log
from ..utils.network import (
    build_worker_url,
    get_client_session,
    parse_master_urls,
    probe_worker,
)
from .tile_pipeline import GrantSampler, TilePipeline, stage_span as _stage


# --------------------------------------------------------------------------
# worker side
# --------------------------------------------------------------------------


# Heartbeat suppression schedule after consecutive failures (satellite
# of the failover PR): a master outage must not turn every worker into
# a 1-failure-per-tile log/request flood while the pull path is already
# doing the patient retrying.
HEARTBEAT_BACKOFF_BASE_SECONDS = 1.0
HEARTBEAT_BACKOFF_CAP_SECONDS = 30.0


class HTTPWorkClient:
    """Worker → master RPCs (reference upscale/worker_comms.py).

    Every RPC retries through the shared RetryPolicy
    (resilience/policy.py): fixed-interval for the readiness poll,
    patient capped exponential for the work pull, and the default HTTP
    policy for submissions (safe — the master drops duplicate results,
    so a retried submit whose first attempt actually landed is a no-op).

    High availability:

    - `master_url` may be a comma-separated address list (active first,
      standbys after). `CDT_FAILOVER_AFTER` consecutive transport/5xx
      failures against the current address re-point to another — the
      re-pointed worker's next pull/heartbeat re-advertises its
      capacity, so the promoted master's placement policy re-learns the
      fleet with no extra registration RPC. Address health is tracked
      PER URL (scheduler/router.EndpointRotation): a failed address
      sits out an exponential backoff window and re-pointing prefers
      the address that last reported the highest fencing epoch, so a
      dead/lagging shard address can't throttle pulls against healthy
      ones — the old single rotation cursor punished the whole list
      for one address's outage;
    - every RPC response carries the master's fencing `epoch`; the
      client remembers the highest seen and stamps it on every mutating
      RPC. A 409 `stale_epoch` rejection (our authority predates a
      takeover) refreshes the epoch from the rejection body and lets
      the retry policy re-send — live workers heal in one round-trip,
      while a zombie master that REFUSES to adopt the new epoch stays
      rejected (jobs/store.py `_check_epoch`).
    """

    def __init__(
        self, master_url: str, job_id: str, worker_id: str, devices: int = 1
    ):
        from ..scheduler.router import EndpointRotation, ShardRouter

        self.urls = parse_master_urls(master_url) or [str(master_url)]
        # Region mode (CDT_SHARDS on the worker): this job's shard is a
        # pure function of its id, so the client re-binds to the shard's
        # own address list (active + standby) — a worker running jobs
        # from different shards multiplexes pulls across masters, and
        # one shard's outage backs off only that shard's endpoints.
        shard_router = ShardRouter.from_env()
        if shard_router.enabled:
            self.urls = parse_master_urls(
                shard_router.addresses_for(job_id)
            ) or self.urls
        self._endpoints = EndpointRotation(self.urls)
        self.job_id = job_id
        self.worker_id = worker_id
        # Advertised grant capacity (the worker mesh's data-axis width):
        # rides every pull and heartbeat so the master's placement
        # policy scales this worker's grants by its chip count.
        self.devices = max(1, int(devices))
        # Captured at construction (on the executor thread, where the
        # dispatched prompt's trace is active); RPCs run on the server
        # loop where that context is NOT set.
        self.trace_id = current_trace_id()
        # Fencing epoch: learned from responses, monotonic, attached to
        # every mutating RPC. None until the master reports one.
        self.epoch: Optional[int] = None
        # Lifecycle armor: flipped when the master reports the job
        # cancelled (a pull response with `cancelled: true`); the
        # worker loop's interrupt check reads it so an in-flight
        # pipeline aborts between batches instead of draining grants.
        self.job_cancelled = False
        self.cancel_reason = ""
        # Step-level preemption (xjob tier): flipped when a pull or
        # heartbeat response carries `preempt: true` — the executor
        # checkpoints + releases this job's in-flight tiles at the next
        # step boundary; cleared when a response stops carrying it.
        self.preempt_requested = False
        self.preempt_reason = ""
        # Remaining end-to-end deadline (seconds) as of the last pull
        # response; None = no deadline on this job.
        self.deadline_remaining: Optional[float] = None
        # Adapter plane: the job's resolved wire plan ([{name, strength,
        # content_hash}]) captured from the readiness poll. The worker
        # re-resolves it against its local catalog (hash-verified) and
        # samples with the segmented/patched params. [] = base model.
        self.adapters: list = []
        self.failovers = 0
        # Heartbeat backoff state (consecutive failures → suppression
        # window); guarded by nothing — heartbeats run on one thread
        # (the pipeline's I/O stage).
        self._hb_failures = 0
        self._hb_suppressed_until = 0.0
        # Fleet telemetry piggyback: a compact versioned snapshot of
        # this process's metrics rides at most one pull/heartbeat per
        # CDT_FLEET_SNAPSHOT_SECONDS (telemetry/fleet.local_snapshot).
        # <= 0 disables the piggyback entirely.
        self._telemetry_interval = FLEET_SNAPSHOT_SECONDS
        self._telemetry_last = 0.0

    @property
    def master_url(self) -> str:
        return self._endpoints.current

    def _maybe_telemetry(self) -> Optional[dict]:
        """The fleet snapshot to piggyback on this RPC, or None when
        one rode recently (or the piggyback is disabled). Runs on the
        single RPC-issuing thread; building the snapshot is a pure
        metrics-registry read. Never raises — telemetry must not break
        the work protocol."""
        if self._telemetry_interval <= 0:
            return None
        now = time.monotonic()
        if now - self._telemetry_last < self._telemetry_interval:
            return None
        self._telemetry_last = now
        try:
            from ..telemetry.fleet import local_snapshot

            return local_snapshot(role="worker")
        except Exception as exc:  # noqa: BLE001 - advisory payload only
            debug_log(f"fleet snapshot build failed: {exc}")
            return None

    def _learn_epoch(self, value) -> None:
        try:
            epoch = int(value)
        except (TypeError, ValueError):
            return
        if epoch <= 0:
            return
        # per-URL: the rotation remembers which address reported which
        # epoch, so re-pointing prefers the freshest (promoted) master
        self._endpoints.learn_epoch(epoch)
        if self.epoch is None or epoch > self.epoch:
            self.epoch = epoch

    def _learn_preempt(self, out: dict) -> None:
        """Track the master's per-job preemption flag from any RPC
        response that carries it (pull + heartbeat); absence clears —
        the flag is live scheduling pressure, not a latch."""
        self.preempt_requested = bool(out.get("preempt"))
        self.preempt_reason = str(out.get("preempt_reason", ""))

    def _count_error(self, op: str) -> None:
        """One master-RPC failure: counted per operation, and after
        CDT_FAILOVER_AFTER consecutive failures against the current
        address the rotation re-points (no-op with a single address).
        The failed address enters its per-URL backoff window, so the
        rotation won't land back on it while a healthy address exists."""
        from ..telemetry.instruments import (
            failover_total,
            worker_master_errors_total,
        )

        worker_master_errors_total().inc(op=op)
        previous = self.master_url
        if self._endpoints.note_failure():
            self.failovers += 1
            failover_total().inc(role="worker")
            log(
                f"worker {self.worker_id}: master {previous} unreachable "
                f"({op}); re-pointing to {self.master_url}"
            )

    async def _post(self, path: str, payload: dict, op: str = "transport") -> dict:
        session = await get_client_session()
        headers = {TRACE_HEADER: self.trace_id} if self.trace_id else {}
        if self.epoch is not None:
            payload = {**payload, "epoch": self.epoch}
        try:
            async with session.post(
                f"{self.master_url}{path}", json=payload, headers=headers
            ) as resp:
                if resp.status == 409:
                    # stale fencing epoch: a takeover happened. Refresh
                    # from the rejection and let the retry policy
                    # re-send with the new epoch (one extra round-trip).
                    try:
                        body = await resp.json()
                    except Exception:  # noqa: BLE001 - non-JSON 409
                        body = {}
                    if body.get("error") == "stale_epoch":
                        # the address answered: healthy, just ahead of us
                        self._endpoints.note_success()
                        self._learn_epoch(body.get("current_epoch"))
                        raise TransientServerError(
                            f"{path} -> stale epoch (refreshed to "
                            f"{self.epoch})", self.worker_id,
                        )
                    raise WorkerError(
                        f"{path} -> HTTP {resp.status}", self.worker_id
                    )
                if resp.status >= 500:
                    self._count_error(op)
                    raise TransientServerError(
                        f"{path} -> HTTP {resp.status}", self.worker_id
                    )
                if resp.status != 200:
                    raise WorkerError(f"{path} -> HTTP {resp.status}", self.worker_id)
                out = await resp.json()
        except transport_errors() as exc:
            self._count_error(op)
            raise exc
        self._endpoints.note_success()
        if isinstance(out, dict):
            self._learn_epoch(out.get("epoch"))
        return out

    def poll_ready(self) -> bool:
        async def attempt():
            out = await self._post(
                "/distributed/job_status",
                {"job_id": self.job_id, "worker_id": self.worker_id},
                op="status",
            )
            if not out.get("ready"):
                raise WorkerError(f"job {self.job_id} not ready", self.worker_id)
            self.adapters = list(out.get("adapters") or [])
            return True

        async def poll():
            try:
                return await retry_async(
                    attempt, poll_ready_policy(),
                    label=f"poll_ready:{self.job_id}",
                )
            except Exception:  # noqa: BLE001 - not-ready maps to False
                return False

        return run_async_in_server_loop(poll(), timeout=None)

    def request_tile(self, batch_max: int = 1) -> Optional[dict]:
        """Pull next work item; None when drained (or the master stayed
        unreachable through the whole pull policy). `batch_max` > 1
        opts into the master's speed-weighted batch pulls — the
        response then carries `tile_idxs` (placement-sized, ≤
        batch_max) alongside the compatible single `tile_idx`."""

        async def pull():
            payload = {
                "job_id": self.job_id,
                "worker_id": self.worker_id,
                "devices": self.devices,
            }
            if batch_max > 1:
                payload["batch_max"] = int(batch_max)
            snapshot = self._maybe_telemetry()
            if snapshot is not None:
                payload["telemetry"] = snapshot
            try:
                return await retry_async(
                    lambda: self._post(
                        "/distributed/request_image", payload, op="pull"
                    ),
                    work_pull_policy(),
                    label=f"request_tile:{self.worker_id}",
                )
            except Exception as exc:  # noqa: BLE001 - exhausted retries
                debug_log(f"request_tile gave up: {exc}")
                return None

        out = run_async_in_server_loop(pull(), timeout=None)
        if out is None:
            return None
        if out.get("cancelled"):
            self.job_cancelled = True
            self.cancel_reason = str(out.get("cancel_reason", ""))
            return None
        self._learn_preempt(out)
        if "deadline_remaining" in out:
            try:
                self.deadline_remaining = float(out["deadline_remaining"])
            except (TypeError, ValueError):
                pass
        if out.get("tile_idx") is None and out.get("image_idx") is None:
            return None
        return out

    # Submits retry transport failures and 5xx answers only — a 4xx is
    # the master's verdict (bad job id, malformed entry) and re-sending
    # the same payload can't change it.
    def _submit_retryable(self):
        return transport_errors() + (TransientServerError,)

    def submit_tiles(self, entries: list[dict], is_final: bool) -> None:
        async def send():
            await retry_async(
                lambda: self._post(
                    "/distributed/submit_tiles",
                    {
                        "job_id": self.job_id,
                        "worker_id": self.worker_id,
                        "tiles": entries,
                        "is_final_flush": is_final,
                    },
                    op="submit",
                ),
                http_policy(),
                retryable=self._submit_retryable(),
                label=f"submit_tiles:{self.worker_id}",
            )

        run_async_in_server_loop(send(), timeout=300)

    def submit_image(self, image_idx: int, data_url: str, is_last: bool) -> None:
        """Dynamic mode: push one whole processed frame."""

        async def send():
            await retry_async(
                lambda: self._post(
                    "/distributed/submit_image",
                    {
                        "job_id": self.job_id,
                        "worker_id": self.worker_id,
                        "image_idx": image_idx,
                        "image": data_url,
                        "is_last": is_last,
                    },
                    op="submit",
                ),
                http_policy(),
                retryable=self._submit_retryable(),
                label=f"submit_image:{self.worker_id}",
            )

        run_async_in_server_loop(send(), timeout=300)

    def heartbeat(self) -> None:
        """Best-effort liveness beat — with exponential suppression on
        consecutive failures: the pipeline heartbeats once per tile
        plus idle beats, so during a master outage an unsuppressed
        worker fleet is a log/request flood on top of the pull path's
        own (already patient) retrying. After k consecutive failures
        beats are skipped for min(base*2^(k-1), cap) seconds; the first
        success resets the schedule. Failures count into
        cdt_worker_master_errors_total and into the failover rotation
        like any other master RPC error."""
        now = time.monotonic()
        if now < self._hb_suppressed_until:
            return

        async def beat():
            payload = {
                "job_id": self.job_id,
                "worker_id": self.worker_id,
                "devices": self.devices,
            }
            snapshot = self._maybe_telemetry()
            if snapshot is not None:
                payload["telemetry"] = snapshot
            try:
                out = await self._post(
                    "/distributed/heartbeat", payload, op="heartbeat",
                )
                if isinstance(out, dict):
                    # the eviction side-channel: a worker mid-batch may
                    # be many steps from its next pull
                    self._learn_preempt(out)
            except Exception as exc:  # noqa: BLE001 - heartbeats best-effort
                self._hb_failures += 1
                backoff = min(
                    HEARTBEAT_BACKOFF_BASE_SECONDS
                    * (2.0 ** (self._hb_failures - 1)),
                    HEARTBEAT_BACKOFF_CAP_SECONDS,
                )
                self._hb_suppressed_until = time.monotonic() + backoff
                debug_log(
                    f"heartbeat failed ({self._hb_failures} consecutive; "
                    f"suppressing {backoff:.1f}s): {exc}"
                )
            else:
                self._hb_failures = 0
                self._hb_suppressed_until = 0.0

        run_async_in_server_loop(beat(), timeout=30)

    def return_tiles(
        self, tile_idxs: list[int], checkpoints: Optional[dict] = None
    ) -> None:
        """Hand claimed-but-unprocessed tiles back to the master (an
        interrupted in-flight grant, or a preemption eviction) so they
        requeue immediately instead of waiting out the heartbeat
        timeout. ``checkpoints`` (xjob tier) attaches per-tile sampler
        state so a re-granted tile resumes mid-trajectory. Best
        effort: if the master is unreachable, its timeout requeue
        still covers these tiles (recompute-from-0 stays
        bit-identical)."""

        async def send():
            payload: dict = {
                "job_id": self.job_id,
                "worker_id": self.worker_id,
                "tile_idxs": [int(t) for t in tile_idxs],
            }
            if checkpoints:
                payload["checkpoints"] = {
                    str(t): c for t, c in sorted(checkpoints.items())
                }
            try:
                await self._post(
                    "/distributed/return_tiles", payload, op="release",
                )
            except Exception as exc:  # noqa: BLE001 - best effort
                debug_log(f"return_tiles failed: {exc}")

        run_async_in_server_loop(send(), timeout=30)


class GrantSignal:
    """Push-mode grant wakeups (CDT_PUSH_GRANTS): the worker holds the
    master's `/distributed/events` WebSocket (filtered to
    `grant_available`/`job_ready`/`job_complete`) and flips a thread
    Event whenever grants land, so the pull loop wakes the instant work
    exists instead of discovering it on a poll boundary — that is the
    grant-RTT cut — and parks while the queue is dry instead of burning
    empty request_image round-trips — that is the idle-poll cut.

    Strictly an ACCELERATOR over the pull protocol: grants still
    transfer via request_image (push carries availability, never
    assignment, so placement sizing/fencing/first-result-wins are
    untouched), and every failure mode — WS refused, stream dropped,
    master failed over — degrades to exactly the pull behavior. The
    socket follows the client's failover rotation via `url_provider`.
    """

    def __init__(self, url_provider, job_id: str):
        self.url_provider = url_provider
        self.job_id = job_id
        self._event = threading.Event()
        self._stopped = threading.Event()
        self.connected = False
        self._complete = False
        self._cancelled = False
        self._future = None

    # --- worker-thread side ------------------------------------------------

    def wait_for_grant(self, timeout: float) -> bool:
        """Park until a grant_available lands (True) or `timeout`
        passes (False); clears the flag so the next wait needs a new
        push. Never blocks when the stream is down — pull fallback."""
        if not self.connected:
            return False
        fired = self._event.wait(timeout)
        self._event.clear()
        return fired

    @property
    def job_complete(self) -> bool:
        return self._complete

    @property
    def job_cancelled(self) -> bool:
        """A pushed ``job_cancelled`` frame arrived: the worker's
        interrupt check aborts the pipeline between batches (flush
        what's encoded, hand the rest back) without waiting for the
        next pull round-trip."""
        return self._cancelled

    def start(self) -> None:
        from ..utils.async_helpers import get_server_loop

        loop = get_server_loop()
        if loop is None or not loop.is_running():
            return  # no loop, no stream: pure pull mode
        import asyncio as _asyncio

        self._future = _asyncio.run_coroutine_threadsafe(self._run(), loop)

    def stop(self) -> None:
        self._stopped.set()
        future = self._future
        if future is not None:
            future.cancel()
            self._future = None

    # --- server-loop side --------------------------------------------------

    async def _run(self) -> None:
        import asyncio as _asyncio
        import json as _json

        from aiohttp import WSMsgType

        while not self._stopped.is_set():
            url = self.url_provider()
            try:
                session = await get_client_session()
                async with session.ws_connect(
                    f"{url}/distributed/events"
                    "?types=grant_available,job_ready,job_complete,"
                    "job_cancelled",
                    heartbeat=30,
                ) as ws:
                    self.connected = True
                    async for msg in ws:
                        if self._stopped.is_set():
                            return
                        if msg.type != WSMsgType.TEXT:
                            break
                        try:
                            frame = _json.loads(msg.data)
                        except (TypeError, ValueError):
                            continue
                        data = frame.get("data") or {}
                        if data.get("job_id") not in (None, self.job_id):
                            continue
                        kind = frame.get("type")
                        if kind in ("grant_available", "job_ready"):
                            self._event.set()
                        elif kind == "job_cancelled":
                            self._cancelled = True
                            self._complete = True
                            self._event.set()
                            return
                        elif kind == "job_complete":
                            self._complete = True
                            self._event.set()
                            return
            except _asyncio.CancelledError:
                return
            except Exception as exc:  # noqa: BLE001 - degrade to pull
                debug_log(f"grant signal stream to {url} failed: {exc}")
            finally:
                self.connected = False
            if self._stopped.is_set():
                return
            # the pull path keeps working meanwhile; reconnect follows
            # the client's (possibly rotated) master address
            await _asyncio.sleep(1.0)


def _flush_threshold_bytes() -> int:
    return MAX_PAYLOAD_SIZE - PAYLOAD_HEADROOM


def _make_pull(client: Any):
    """Zero-arg pull callable for the worker loop, resolved ONCE per
    client: batched grants when the client's request_tile accepts
    batch_max, plain otherwise (scripted test clients predate it). The
    capability check reads the signature — catching TypeError from the
    call itself would mask a real client bug AND double-pull work the
    master already assigned."""
    import inspect

    try:
        params = inspect.signature(client.request_tile).parameters
        supports_batch = "batch_max" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
        )
    except (TypeError, ValueError):
        supports_batch = True  # unintrospectable callable: assume current API
    if supports_batch:
        # the pull ceiling scales with advertised capacity: a D-chip
        # worker may claim D x the max grant (the master's placement
        # policy sizes the actual batch; this is just the client cap)
        cap = max(1, int(getattr(client, "devices", 1)))
        return lambda: client.request_tile(batch_max=SCHED_MAX_PULL_BATCH * cap)
    return client.request_tile


def run_worker_loop(
    bundle: pl.PipelineBundle,
    image,
    pos,
    neg,
    job_id: str,
    worker_id: str,
    master_url: str,
    upscale_by: float,
    tile: int,
    padding: int,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg: float,
    denoise: float,
    seed: int,
    upscale_method: str = "bicubic",
    mask_blur: int = 0,
    uniform: bool = True,
    tiled_decode: bool = False,
    tile_h: int | None = None,
    context=None,
    client: Any = None,
    mesh: Any = None,
) -> None:
    """Pull grants until the master's queue drains, through the staged
    tile pipeline (graph/tile_pipeline.py): placement grants execute as
    vmapped K-tile device batches (shape-bucketed so ragged tails never
    recompile), readback/encode/submit overlap the next batch's
    sampling, and results flush in size-aware batches with a heartbeat
    per processed tile (plus idle heartbeats while a device batch is in
    flight). CDT_PIPELINE=0 falls back to fully synchronous staging
    (same callbacks, no prefetch/overlap threads).

    Multi-chip: the worker builds a local device mesh (CDT_MESH_SHAPE /
    CDT_TP_SIZE; default = all local chips on the data axis on
    accelerators) and scales its tile batch by the data-axis width — a
    4-chip worker dispatches K x 4 tiles per sharded batch and
    advertises 4x grant capacity to the master's placement policy.
    Checkpoints over the CDT_MESH_HBM_GB per-chip budget shard their
    parameters along the model axis instead of failing to load."""
    from ..utils.constants import xjob_batch_enabled

    if xjob_batch_enabled():
        from ..ops.stepwise import stepwise_supported

        if stepwise_supported(sampler):
            # cross-job continuous batching (CDT_XJOB_BATCH=1): this
            # job registers with the process-shared executor and its
            # tiles share device batches with every other registered
            # job; unsupported samplers fall through to the scan tier
            from ..ops.stepwise import StepwiseUnsupported
            from .batch_executor import run_worker_xjob

            try:
                return run_worker_xjob(
                    bundle, image, pos, neg, job_id, worker_id, master_url,
                    upscale_by, tile, padding, steps, sampler, scheduler,
                    cfg, denoise, seed, upscale_method=upscale_method,
                    mask_blur=mask_blur, uniform=uniform,
                    tiled_decode=tiled_decode, tile_h=tile_h,
                    context=context, client=client, mesh=mesh,
                )
            except StepwiseUnsupported as exc:
                # the stepwise factory refused (e.g. flow model +
                # ancestral sampler) BEFORE any job state was touched:
                # the scan tier serves the job. Any other error from a
                # RUNNING xjob job propagates — re-running the whole
                # job here would double-compute it.
                debug_log(f"xjob tier unavailable for {job_id}: {exc}")

    from ..parallel.mesh import (
        advertised_capacity,
        data_axis_size,
        note_serving_mesh,
        worker_mesh,
    )
    from ..parallel.sharding import maybe_shard_params, params_byte_size

    params = bundle.params
    if mesh is None:
        mesh = worker_mesh(params_bytes=params_byte_size(params))
    note_serving_mesh(mesh)
    capacity = advertised_capacity(mesh)
    client = client or HTTPWorkClient(
        master_url, job_id, worker_id, devices=capacity
    )
    params = maybe_shard_params(params, mesh)

    _, grid, extracted = upscale_ops.prepare_upscaled_tiles(
        image, upscale_by, tile, padding, upscale_method, tile_h,
        mask_blur=mask_blur, uniform=uniform,
    )
    pos = upscale_ops.prep_cond_for_tiles(pos, grid)
    neg = upscale_ops.prep_cond_for_tiles(neg, grid)
    process = _jit_tile_processor(
        bundle, grid, steps, sampler, scheduler, cfg, denoise, tiled_decode
    )
    key = jax.random.key(seed)
    positions = grid.positions_array()
    data_width = data_axis_size(mesh) if mesh is not None else 1
    grant_sampler = GrantSampler(
        process, params, extracted, key, positions, pos, neg,
        k_max=tile_scan_batch() * data_width, role="worker", mesh=mesh,
        job_id=job_id,
    )

    # Warm the tile-processor compile while the ready poll waits on the
    # master: with the persistent compilation cache hot this turns the
    # 14-40 s first compile (BENCH_NOTES r5) into a cache load that
    # finishes before the first grant arrives.
    warm = None
    if WARM_COMPILE:
        warm = threading.Thread(
            target=grant_sampler.warmup, name="cdt-usdu-warmup", daemon=True
        )
        warm.start()
    if not client.poll_ready():
        raise WorkerError(f"job {job_id} never became ready", worker_id)
    if warm is not None:
        warm.join()

    # Adapter plane (whole-grant variant): the readiness poll carried
    # the job's resolved wire plan. Re-resolve against the LOCAL
    # catalog — resolve() hash-verifies master-stamped hashes against
    # local bytes, failing loudly on divergence — then patch the
    # weights once and rebuild the sampler around them. Shapes/dtypes
    # are unchanged, so the warmup's compiled processor is reused.
    adapter_wire = getattr(client, "adapters", None) or []
    if adapter_wire:
        from ..adapters import (
            bundle_target_map,
            get_adapter_catalog,
            operands_for_plan,
            patch_params as _adapter_patch,
            specs_from_wire,
        )
        from ..telemetry.instruments import adapter_jobs_total

        adapter_specs = get_adapter_catalog().resolve(
            specs_from_wire(adapter_wire)
        )
        adapter_ops = operands_for_plan(
            adapter_specs, bundle_target_map(bundle)
        )
        params = _adapter_patch(params, adapter_ops)
        grant_sampler = GrantSampler(
            process, params, extracted, key, positions, pos, neg,
            k_max=tile_scan_batch() * data_width, role="worker", mesh=mesh,
            job_id=job_id,
        )
        adapter_jobs_total().inc(tier="elastic")

    pending: list[dict] = []
    pending_bytes = 0

    def emit(tile_idx: int, arr) -> None:
        """One processed tile (host-side [B, h, w, C]) → pending
        entries. Runs on the pipeline's I/O stage."""
        nonlocal pending_bytes
        for batch_idx in range(arr.shape[0]):
            encoded = img_utils.encode_image_data_url(arr[batch_idx])
            y, x = grid.positions[tile_idx]
            pending.append(
                {
                    "tile_idx": tile_idx,
                    "batch_idx": batch_idx,
                    "global_idx": tile_idx * arr.shape[0] + batch_idx,
                    "x": int(x),
                    "y": int(y),
                    "extracted_w": grid.padded_w,
                    "extracted_h": grid.padded_h,
                    "image": encoded,
                }
            )
            pending_bytes += len(encoded)
        tiles_processed_total().inc(role="worker")

    def flush(is_final: bool) -> None:
        """Size-aware flush: ships when the payload budget or tile
        batch fills, or unconditionally on the final flush (an empty
        final flush still signals this worker done)."""
        nonlocal pending, pending_bytes
        if not is_final and (
            len(pending) < MAX_TILE_BATCH
            and pending_bytes < _flush_threshold_bytes()
        ):
            return
        if pending or is_final:
            # worker_id keys this span to the same (role, worker_id)
            # group as the sample/readback/encode spans — perf_report's
            # overlap column intersects per pipeline, and submit is the
            # I/O stage the overlap mostly consists of
            with _stage("submit", "worker", worker_id=worker_id):
                client.submit_tiles(pending, is_final)
        pending, pending_bytes = [], 0

    # Adaptive pull batches: the master's placement policy sizes each
    # grant by this worker's measured speed (scheduler/placement.py),
    # replacing the fixed per-pull split — a fast worker amortizes the
    # pull RPC over several tiles, a slow one stays at one so a requeue
    # never orphans a big claim. A master without the batch field
    # answers with a single tile_idx and the loop degrades to the
    # historical one-at-a-time pull.
    pull_work = _make_pull(client)

    # Push-mode grants (CDT_PUSH_GRANTS): hold the master's event
    # stream and, after an empty pull, park one PUSH_WAIT on the grant
    # signal before concluding the queue is drained — requeued/
    # speculated tiles reach this worker instead of defaulting to the
    # master's local fallback, and no empty poll requests burn while
    # the queue is dry. Scripted test clients (no master_url) and
    # CDT_PUSH_GRANTS=0 keep the pure pull protocol.
    push: Optional[GrantSignal] = None
    if PUSH_GRANTS_ENABLED and getattr(client, "master_url", None):
        push = GrantSignal(lambda: client.master_url, job_id)
        push.start()

    def _grant_ids(work: dict) -> list[int]:
        return [int(t) for t in (work.get("tile_idxs") or [work["tile_idx"]])]

    def _cancelled() -> bool:
        return bool(
            getattr(client, "job_cancelled", False)
            or (push is not None and push.job_cancelled)
        )

    def pull() -> Optional[list[int]]:
        if _cancelled():
            return None  # cancelled: no push-park, no further claims
        work = pull_work()
        if work is not None:
            return _grant_ids(work)
        if push is not None and not push.job_complete:
            if push.wait_for_grant(PUSH_WAIT_SECONDS):
                work = pull_work()
                if work is not None:
                    return _grant_ids(work)
        return None

    def check_abort() -> None:
        """Interrupt seam between batches: the dispatched prompt's
        interrupt, OR a cooperative job cancellation (pushed over the
        events stream or learned from a pull response). Raising
        InterruptedError routes through the pipeline's graceful path —
        flush what's encoded, hand the claimed remainder back via
        return_tiles — exactly the PR 5 interrupt semantics."""
        if context is not None:
            context.check_interrupted()
        if _cancelled():
            reason = getattr(client, "cancel_reason", "") or "cancelled"
            raise InterruptedError(
                f"job {job_id} cancelled by master ({reason})"
            )

    pipeline = TilePipeline(
        pull=pull,
        sample=grant_sampler.sample,
        chunks=grant_sampler.chunks,
        # sharded batches gather host-side via host_collect; unsharded
        # ones take the plain numpy path (identical to the default)
        to_host=grant_sampler.collect,
        emit=emit,
        flush=flush,
        heartbeat=client.heartbeat,
        check_interrupted=check_abort,
        release=getattr(client, "return_tiles", None),
        role="worker",
        # per-pipeline span grouping: perf_report's overlap column
        # intersects sample/I-O spans per (role, worker_id) so fleet
        # parallelism never reads as pipelining in merged traces
        span_attrs={"worker_id": worker_id} if worker_id else None,
        threaded=PIPELINE_ENABLED,
    )
    try:
        pipeline.run()
    except InterruptedError:
        if not _cancelled():
            raise  # a real interrupt (SIGTERM drain / client abort)
        # cooperative cancellation is a CLEAN exit for the worker: the
        # pipeline already flushed what was encoded and returned the
        # claimed remainder via return_tiles
        log(f"worker {worker_id}: job {job_id} cancelled; aborted cleanly")
    finally:
        if push is not None:
            push.stop()


def _jit_tile_processor(bundle, grid, steps, sampler, scheduler, cfg, denoise,
                        tiled_decode=False):
    """fn(params, tile, key, pos, neg, yx): pos/neg must be prepped via
    ops.upscale.prep_cond_for_tiles (per-tile hint/mask windows are
    sliced at yx inside)."""
    param, shift = pl.model_schedule_info(bundle)
    sigmas = smp.get_model_sigmas(
        param, scheduler, int(steps), denoise=float(denoise), flow_shift=shift
    )

    @jax.jit
    def process(params, tile, key, pos, neg, yx):
        pos_t = upscale_ops.tile_cond(pos, yx[0], yx[1], grid)
        neg_t = upscale_ops.tile_cond(neg, yx[0], yx[1], grid)
        z = bundle.vae.apply(params["vae"], tile, method="encode")
        noise_key, anc_key = jax.random.split(key)
        x = smp.noise_latents(
            param, z, jax.random.normal(noise_key, z.shape), sigmas[0]
        )
        model_fn = pl.guided_model(bundle, params, float(cfg))
        z_out = smp.sample(
            model_fn, x, sigmas, (pos_t, neg_t), sampler, anc_key,
            flow=(param == "flow"),
        )
        if tiled_decode:
            from ..ops.tiled_vae import decode_tiled

            return decode_tiled(pl._Static(bundle), params["vae"], z_out)
        return bundle.vae.apply(params["vae"], z_out, method="decode")

    return process


# --------------------------------------------------------------------------
# master side
# --------------------------------------------------------------------------


def run_master_elastic(
    bundle: pl.PipelineBundle,
    image,
    pos,
    neg,
    job_id: str,
    enabled_worker_ids: list[str],
    mesh=None,
    upscale_by: float = 2.0,
    tile: int = 512,
    padding: int = 32,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg: float = 7.0,
    denoise: float = 0.35,
    seed: int = 0,
    upscale_method: str = "bicubic",
    mask_blur: int = 0,
    uniform: bool = True,
    tiled_decode: bool = False,
    tile_h: int | None = None,
    context=None,
):
    """Master participates in the tile queue and collects worker tiles.

    Returns the blended [B, H, W, C] image. Fault tolerance: stale
    workers' tiles are requeued (busy-probe grace) and re-run locally.
    """
    from ..utils.constants import xjob_batch_enabled

    if xjob_batch_enabled():
        from ..ops.stepwise import stepwise_supported

        if stepwise_supported(sampler):
            # cross-job continuous batching (CDT_XJOB_BATCH=1): the
            # master's own participation rides the shared executor so
            # its tiles batch with every other registered job's
            from ..ops.stepwise import StepwiseUnsupported
            from .batch_executor import run_master_xjob

            try:
                return run_master_xjob(
                    bundle, image, pos, neg, job_id, enabled_worker_ids,
                    mesh=mesh, upscale_by=upscale_by, tile=tile,
                    padding=padding, steps=steps, sampler=sampler,
                    scheduler=scheduler, cfg=cfg, denoise=denoise,
                    seed=seed, upscale_method=upscale_method,
                    mask_blur=mask_blur, uniform=uniform,
                    tiled_decode=tiled_decode, tile_h=tile_h,
                    context=context,
                )
            except StepwiseUnsupported as exc:
                # raised by _prep_xjob before the job inits; any error
                # from a RUNNING xjob master propagates (the job was
                # already initialized/cleaned — re-running would
                # double-compute it against exited workers)
                debug_log(f"xjob tier unavailable for {job_id}: {exc}")

    from ..utils.config import get_worker_timeout_seconds

    server = context.server
    store = server.job_store
    upscaled, grid, extracted = upscale_ops.prepare_upscaled_tiles(
        image, upscale_by, tile, padding, upscale_method, tile_h,
        mask_blur=mask_blur, uniform=uniform,
    )
    pos = upscale_ops.prep_cond_for_tiles(pos, grid)
    neg = upscale_ops.prep_cond_for_tiles(neg, grid)
    process = _jit_tile_processor(
        bundle, grid, steps, sampler, scheduler, cfg, denoise, tiled_decode
    )
    key = jax.random.key(seed)
    positions = grid.positions_array()

    # HTTP-tier tiles arrive host-side; the native feathered-blend
    # canvas avoids a device round-trip per tile. CDT_DETERMINISTIC_BLEND
    # defers compositing to sorted tile order so the blended output is
    # bit-identical regardless of which participant finished first
    # (chaos tests assert fault-free vs fault-recovered runs equal).
    # Routing rule (CDT_DEVICE_CANVAS=1): master-local grants skip the
    # per-tile readback entirely and composite on-device — one d2h for
    # the whole composited canvas at the end of the run. Remote worker
    # tiles keep the PNG path and upload once into the device canvas.
    # Cache population needs host tile bytes at blend time, so the
    # device canvas only engages while the tile cache is off.
    import os as _os

    from ..cache import get_tile_cache as _get_tile_cache
    from ..utils.constants import device_canvas_enabled as _device_canvas_enabled

    # get_tile_cache (not the env knob alone) so a run-locally
    # installed cache — the chaos harness's swap — also disables it
    device_canvas = _device_canvas_enabled() and _get_tile_cache() is None
    if device_canvas:
        canvas = tile_ops.DeviceCanvas(upscaled, grid)
    elif _os.environ.get("CDT_DETERMINISTIC_BLEND") == "1":
        canvas = tile_ops.DeterministicHostCanvas(upscaled, grid)
    else:
        canvas = tile_ops.HostIncrementalCanvas(upscaled, grid)
    done_tiles: set[int] = set()
    timeout = get_worker_timeout_seconds()

    # Adapter plane: the orchestration parked the resolved wire plan in
    # the store — peek it (non-destructive; init_tile_job pops +
    # journals it) and build the whole-grant operands for this master's
    # own sampling. The plan key joins the cache key below; the PATCHED
    # params feed only the GrantSampler.
    adapter_ops = None
    adapter_key = None
    adapter_wire = run_async_in_server_loop(
        store.peek_job_adapters(job_id), timeout=30
    )
    if adapter_wire:
        from ..adapters import (
            adapter_plan_key,
            bundle_target_map,
            get_adapter_catalog,
            operands_for_plan,
            specs_from_wire,
        )
        from ..telemetry.instruments import adapter_jobs_total

        adapter_specs = get_adapter_catalog().resolve(
            specs_from_wire(adapter_wire)
        )
        adapter_key = adapter_plan_key(adapter_specs)
        adapter_ops = operands_for_plan(
            adapter_specs, bundle_target_map(bundle)
        )
        adapter_jobs_total().inc(tier="elastic")

    # --- content-addressed tile cache (cache/), CDT_CACHE=1 ----------
    # The elastic tier keys on the UNFOLDED base key jax.random.key(seed):
    # per-tile keys fold only the global tile index, so two jobs (any
    # tenant) with identical sampler inputs dedup against each other.
    # UNPATCHED params on purpose: the adapter's identity enters
    # through `adapter=` (the plan key), keeping one params fingerprint
    # per checkpoint while flipping every tile key per plan.
    from ..cache import bind_job_cache, job_key_context, tile_keys_for
    from ..utils.constants import USAGE_ENABLED

    cache_binding = bind_job_cache(
        lambda: tile_keys_for(
            job_key_context(
                bundle.params, pos, neg, key, grid,
                steps=steps, sampler=sampler, scheduler=scheduler,
                cfg=cfg, denoise=denoise, upscale_by=upscale_by,
                upscale_method=upscale_method, mask_blur=mask_blur,
                uniform=uniform, tiled_decode=tiled_decode,
                adapter=adapter_key,
            ),
            extracted, grid,
        )
    )

    def blend_local(tile_idx: int, result) -> None:
        with _stage("blend", "master", tile_idx):
            y, x = grid.positions[tile_idx]
            if cache_binding is not None:
                # one host materialisation serves both the write-back
                # and the host canvas blend below
                result = np.asarray(result)
                cache_binding.populate(tile_idx, result)
            canvas.blend(result, y, x)
            done_tiles.add(tile_idx)

    # Probe BEFORE the job exists, settle ATOMICALLY with its creation
    # (init_tile_job's cache_settled): hits complete in the store
    # (journaled `cache_settle`, pending queue shrunken under the same
    # lock hold) before any puller can observe the job — a warm run's
    # settled count is deterministic, never a race the master usually
    # wins. Hits blend from cached pixels at ~zero chip-time. On a
    # pre-existing job (recovery re-entry) creation ignored the list,
    # so fall back to the standalone op, which excludes tiles workers
    # already completed — those must NOT be blended again (the canvas
    # accumulates weight).
    cached_hits: dict[int, Any] = {}
    if cache_binding is not None:
        with _stage("cache.probe", "master") as probe_span:
            cached_hits = cache_binding.probe()
            probe_span.attrs["hits"] = len(cached_hits)
    job = run_async_in_server_loop(
        store.init_tile_job(
            job_id, list(range(grid.num_tiles)),
            cache_settled=sorted(cached_hits) if cached_hits else None,
        ),
        timeout=30,
    )
    if cached_hits:
        settled = [t for t in sorted(cached_hits) if t in job.cached_tiles]
        if not settled:
            settled = run_async_in_server_loop(
                store.settle_cached(job_id, sorted(cached_hits)), timeout=30
            )
        for tile_idx in settled:
            with _stage("cache.hit", "master", tile_idx):
                y, x = grid.positions[tile_idx]
                canvas.blend(cached_hits[tile_idx], y, x)
                done_tiles.add(tile_idx)
        if settled:
            cache_binding.cache.note_settled(len(settled))
            if USAGE_ENABLED:
                from ..telemetry.usage import get_usage_meter

                get_usage_meter().note_cached(
                    "master", job_id, len(settled)
                )

    def drain_results() -> None:
        async def drain():
            job = await store.get_tile_job(job_id)
            items = []
            while job is not None and not job.results.empty():
                items.append(job.results.get_nowait())
            return items

        for tile_idx, payload in run_async_in_server_loop(drain(), timeout=30):
            if tile_idx in done_tiles:
                continue
            with _stage("decode", "master", tile_idx):
                batch = [
                    img_utils.decode_image_data_url(e["image"])
                    for e in sorted(payload, key=lambda e: e["batch_idx"])
                ]
            blend_local(tile_idx, jnp.asarray(np.stack(batch, axis=0)))

    async def probe_busy(worker_id: str) -> bool:
        config = getattr(context, "config", None) or {}
        worker = next(
            (w for w in config.get("workers", []) if str(w.get("id")) == worker_id),
            None,
        )
        if worker is None:
            return False
        result = await probe_worker(build_worker_url(worker))
        return bool(result["online"] and (result["queue_remaining"] or 0) > 0)

    # --- main pull/process loop ---
    # The master pulls speed-sized grants through the same placement-
    # hooked path workers use (scheduler/placement sizes them; without
    # a policy the batch is 1 — the historical single pull) and runs
    # each grant through the bucketed vmapped K-tile processor. Tiles
    # are recorded via submit_flush so the latency sink sees per-tile
    # AMORTIZED service times, not one per-batch lump followed by
    # near-zero gaps (the watchdog's straggler median and the placement
    # speed EWMA both consume that stream).
    from ..parallel.mesh import data_axis_size as _data_axis_size
    from ..parallel.mesh import note_serving_mesh as _note_serving_mesh

    _note_serving_mesh(mesh)
    master_data_width = _data_axis_size(mesh) if mesh is not None else 1
    # the master's own chip count must reach the placement policy the
    # same way workers' does: its submit_flush latencies are amortized
    # D x lower, so without this per_chip_ratio("master") reads ~D x
    # inflated and batch sizing favors a wide-but-mediocre master.
    # worker_capacity is written only from the server loop (store.py),
    # so hop there like every other store call in this function.
    async def _note_master_capacity() -> None:
        store.note_worker_capacity("master", master_data_width)

    run_async_in_server_loop(_note_master_capacity())
    # Whole-grant adapter application (the scan tier's simpler variant):
    # every tile of every grant wears the same plan, so patch the
    # weights ONCE — same shapes/dtypes, so the compiled tile processor
    # is reused — and sample with the unchanged program.
    master_params = bundle.params
    if adapter_ops is not None:
        from ..adapters import patch_params as _adapter_patch

        master_params = _adapter_patch(master_params, adapter_ops)
    grant_sampler = GrantSampler(
        process, master_params, extracted, key, positions, pos, neg,
        k_max=tile_scan_batch() * master_data_width, role="master",
        mesh=mesh, job_id=job_id,
    )
    empty_pulls = 0
    while empty_pulls < 2:
        if context is not None:
            context.check_interrupted()
        with _stage("pull", "master") as pull_span:
            grant = run_async_in_server_loop(
                store.pull_tasks(
                    job_id, "master", timeout=QUEUE_POLL_INTERVAL_SECONDS
                ),
                timeout=30,
            )
            if not grant:
                pull_span.attrs["outcome"] = "empty"
            else:
                pull_span.attrs["tile_idx"] = int(grant[0])
                if len(grant) > 1:
                    pull_span.attrs["batch"] = [int(t) for t in grant]
        if not grant:
            empty_pulls += 1
            drain_results()
            continue
        empty_pulls = 0
        for chunk in grant_sampler.chunks(grant):
            if context is not None:
                context.check_interrupted()
            with _stage("sample", "master", chunk[0], batch=list(chunk)):
                result = grant_sampler.sample(chunk)
            with _stage("readback", "master", chunk[0], batch=list(chunk)):
                # materialise host-side before blending — sharded
                # results gather across the mesh, single-device ones
                # take the numpy path; either way the d2h transfer is
                # attributed (ledger gather bucket) instead of hiding
                # inside the first blend's implicit conversion. With
                # the device canvas on, unsharded master-local grants
                # stay device-resident (keep_device) and the span reads
                # ~0 — honestly: no readback happened.
                result = grant_sampler.collect(
                    result, keep_device=device_canvas
                )
            run_async_in_server_loop(
                store.submit_flush(
                    job_id, "master",
                    # master blends directly; no payload retained
                    {int(t): None for t in chunk},
                ),
                timeout=30,
            )
            tiles_processed_total().inc(len(chunk), role="master")
            for i, tile_idx in enumerate(chunk):
                blend_local(int(tile_idx), result[i])
            drain_results()

    # --- collection phase ---
    # Lifecycle-aware accounting: poison-quarantined tiles count as
    # SETTLED (the job completes degraded, their region blended from
    # the base image), and a terminal cancellation — client cancel or
    # the deadline sweep — unwinds the loop instead of waiting for
    # tiles that will never arrive.
    from ..utils.exceptions import JobCancelled, JobPoisoned

    def _lifecycle() -> dict:
        state = run_async_in_server_loop(
            store.job_lifecycle(job_id), timeout=30
        )
        return state or {
            "cancelled": False, "cancel_reason": "", "quarantined": [],
        }

    deadline = time.monotonic() + timeout * max(1, len(enabled_worker_ids))
    while True:
        # ONE lifecycle snapshot per iteration: termination reads may
        # be up to a poll interval stale, which only delays exit by
        # that interval — never changes the terminal outcome
        lifecycle = _lifecycle()
        quarantined = set(lifecycle["quarantined"])
        if lifecycle["cancelled"] or (
            len(done_tiles | quarantined) >= grid.num_tiles
        ):
            break
        if context is not None:
            context.check_interrupted()
        # store-side sweep: an overdue deadline cancels the job even
        # with no pull traffic left to trigger the lazy path
        run_async_in_server_loop(store.sweep_deadlines(), timeout=30)
        drain_results()
        if len(done_tiles | quarantined) >= grid.num_tiles:
            break
        requeued = run_async_in_server_loop(
            store.requeue_timed_out(job_id, timeout, probe_busy), timeout=60
        )
        # The pending queue can refill behind our back: heartbeat
        # requeues (above) AND the watchdog's speculative re-dispatch
        # of stalled in-flight tiles both route recovery through it.
        pending_now = run_async_in_server_loop(store.remaining(job_id), timeout=30)
        if requeued or pending_now:
            # Requeued/speculated ids are back in the pending queue;
            # claim them through the same pull path workers use so a
            # surviving worker may still grab some before we do
            # (first result wins; duplicates drop in the store).
            while True:
                with _stage("pull", "master") as pull_span:
                    tile_idx = run_async_in_server_loop(
                        store.pull_task(
                            job_id, "master", timeout=QUEUE_POLL_INTERVAL_SECONDS
                        ),
                        timeout=30,
                    )
                    if tile_idx is None:
                        pull_span.attrs["outcome"] = "empty"
                    else:
                        pull_span.attrs["tile_idx"] = int(tile_idx)
                if tile_idx is None:
                    break
                if tile_idx in done_tiles:
                    continue
                tkey = jax.random.fold_in(key, tile_idx)
                with _stage("sample", "master", tile_idx):
                    result = process(
                        bundle.params, extracted[tile_idx], tkey, pos, neg,
                        positions[tile_idx],
                    )
                run_async_in_server_loop(
                    store.submit_result(job_id, "master", tile_idx, None), timeout=30
                )
                tiles_processed_total().inc(role="master")
                blend_local(tile_idx, result)
        if len(done_tiles | quarantined) >= grid.num_tiles:
            break
        if time.monotonic() > deadline:
            # quarantined tiles are NOT reprocessed locally: a payload
            # that crashed every worker that touched it stays settled
            # degraded rather than taking the master down with it
            missing = sorted(
                set(range(grid.num_tiles)) - done_tiles - quarantined
            )
            log(f"USDU: deadline hit; locally processing {len(missing)} tile(s)")
            for tile_idx in missing:
                tkey = jax.random.fold_in(key, tile_idx)
                with _stage("sample", "master", tile_idx):
                    result = process(
                        bundle.params, extracted[tile_idx], tkey, pos, neg,
                        positions[tile_idx],
                    )
                tiles_processed_total().inc(role="master")
                blend_local(tile_idx, result)
            break
        time.sleep(QUEUE_POLL_INTERVAL_SECONDS)

    lifecycle = _lifecycle()
    run_async_in_server_loop(store.cleanup_tile_job(job_id), timeout=30)
    if lifecycle["cancelled"]:
        # terminal: every pending/in-flight tile was refunded by the
        # cancel; the collector settles with a cancelled status instead
        # of a partial canvas
        raise JobCancelled(job_id, lifecycle["cancel_reason"] or "cancel")
    poisoned = sorted(set(lifecycle["quarantined"]) - done_tiles)
    if poisoned:
        policy = getattr(store, "poison_policy", "degrade")
        if policy == "fail":
            raise JobPoisoned(job_id, poisoned)
        log(
            f"USDU: job {job_id} completes DEGRADED: tile(s) {poisoned} "
            "quarantined (region blended from the base image)"
        )
    if device_canvas:
        # the job's entire master-side pixel traffic rides this ONE
        # composited readback (ledger-attributed); bit-identical to
        # DeterministicHostCanvas by the sorted-compositing guarantee
        from ..telemetry.profiling import D2H as _D2H
        from ..telemetry.profiling import ledger_if_enabled as _ledger_if

        with _stage("readback", "master", tiles=canvas.tile_count):
            started = time.monotonic()
            composited = canvas.result()
            host = np.asarray(composited)  # cdt: noqa[CDT007] - the single composited flush
            ledger = _ledger_if()
            if ledger is not None:
                ledger.note_transfer(
                    _D2H, int(host.nbytes), time.monotonic() - started
                )
        return jnp.asarray(host)
    return canvas.result()


# --------------------------------------------------------------------------
# dynamic (image-queue) mode — large video batches
# --------------------------------------------------------------------------


def _process_whole_image(
    bundle, image_1, pos, neg, grid, process, key, batch_index: int
):  # pos/neg prepped via prep_cond_for_tiles
    """Upscale one [1, H, W, C] frame through all its tiles locally.

    Per-tile keys fold (batch_index, tile_idx) so dynamic mode is
    deterministic per frame regardless of which participant claims it
    (reference upscale/modes/dynamic.py processes a whole image's tiles
    on whichever participant pulled its index).
    """
    extracted = tile_ops.extract_tiles(image_1, grid)
    canvas = tile_ops.IncrementalCanvas(image_1, grid)
    frame_key = jax.random.fold_in(key, batch_index)
    positions = grid.positions_array()
    for tile_idx in range(grid.num_tiles):
        tkey = jax.random.fold_in(frame_key, tile_idx)
        result = process(
            bundle.params, extracted[tile_idx], tkey, pos, neg, positions[tile_idx]
        )
        y, x = grid.positions[tile_idx]
        canvas.blend(result, y, x)
    return canvas.result()


def run_worker_dynamic(
    bundle: pl.PipelineBundle,
    image,
    pos,
    neg,
    job_id: str,
    worker_id: str,
    master_url: str,
    upscale_by: float,
    tile: int,
    padding: int,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg: float,
    denoise: float,
    seed: int,
    upscale_method: str = "bicubic",
    mask_blur: int = 0,
    uniform: bool = True,
    tiled_decode: bool = False,
    tile_h: int | None = None,
    context=None,
    client: Any = None,
) -> None:
    """Pull whole-image indices; process all tiles locally; submit the
    finished frame (reference upscale/modes/dynamic.py:213-313)."""
    client = client or HTTPWorkClient(master_url, job_id, worker_id)
    if not client.poll_ready():
        raise WorkerError(f"job {job_id} never became ready", worker_id)
    upscaled, grid, _ = upscale_ops.prepare_upscaled_tiles(
        image, upscale_by, tile, padding, upscale_method, tile_h,
        mask_blur=mask_blur, uniform=uniform,
    )
    pos = upscale_ops.prep_cond_for_tiles(pos, grid)
    neg = upscale_ops.prep_cond_for_tiles(neg, grid)
    process = _jit_tile_processor(
        bundle, grid, steps, sampler, scheduler, cfg, denoise, tiled_decode
    )
    key = jax.random.key(seed)

    while True:
        if context is not None:
            context.check_interrupted()
        work = client.request_tile()
        if work is None:
            break
        # dynamic jobs return image_idx; HTTPWorkClient.request_tile
        # normalizes on 'tile_idx' absence, so re-read the raw field
        image_idx = int(work.get("image_idx", work.get("tile_idx")))
        frame = upscaled[image_idx : image_idx + 1]
        out = _process_whole_image(
            bundle, frame, pos, neg, grid, process, key, image_idx
        )
        arr = img_utils.ensure_numpy(out)[0]
        client.submit_image(
            image_idx,
            img_utils.encode_image_data_url(arr),
            is_last=int(work.get("estimated_remaining", 0)) == 0,
        )
        client.heartbeat()


def run_master_dynamic(
    bundle: pl.PipelineBundle,
    image,
    pos,
    neg,
    job_id: str,
    enabled_worker_ids: list[str],
    upscale_by: float = 2.0,
    tile: int = 512,
    padding: int = 32,
    steps: int = 20,
    sampler: str = "euler",
    scheduler: str = "karras",
    cfg: float = 7.0,
    denoise: float = 0.35,
    seed: int = 0,
    upscale_method: str = "bicubic",
    mask_blur: int = 0,
    uniform: bool = True,
    tiled_decode: bool = False,
    tile_h: int | None = None,
    context=None,
):
    """Image-queue master loop: master participates in pulls, drains
    worker frames between images, requeues timed-out workers, and
    assembles the output batch in frame order (reference
    upscale/modes/dynamic.py:22-211)."""
    from ..utils.config import get_worker_timeout_seconds

    store = context.server.job_store
    batch = int(image.shape[0])
    upscaled, grid, _ = upscale_ops.prepare_upscaled_tiles(
        image, upscale_by, tile, padding, upscale_method, tile_h,
        mask_blur=mask_blur, uniform=uniform,
    )
    pos = upscale_ops.prep_cond_for_tiles(pos, grid)
    neg = upscale_ops.prep_cond_for_tiles(neg, grid)
    process = _jit_tile_processor(
        bundle, grid, steps, sampler, scheduler, cfg, denoise, tiled_decode
    )
    key = jax.random.key(seed)
    timeout = get_worker_timeout_seconds()

    run_async_in_server_loop(
        store.init_tile_job(job_id, list(range(batch)), batched=False, kind="image"),
        timeout=30,
    )
    frames: dict[int, np.ndarray] = {}

    def drain() -> None:
        async def pop_all():
            job = await store.get_tile_job(job_id)
            items = []
            while job is not None and not job.results.empty():
                items.append(job.results.get_nowait())
            return items

        for image_idx, payload in run_async_in_server_loop(pop_all(), timeout=30):
            if image_idx in frames:
                continue
            frames[image_idx] = img_utils.decode_image_data_url(payload[0]["image"])

    async def probe_busy(worker_id: str) -> bool:
        config = getattr(context, "config", None) or {}
        worker = next(
            (w for w in config.get("workers", []) if str(w.get("id")) == worker_id),
            None,
        )
        if worker is None:
            return False
        result = await probe_worker(build_worker_url(worker))
        return bool(result["online"] and (result["queue_remaining"] or 0) > 0)

    def claim_and_process() -> bool:
        image_idx = run_async_in_server_loop(
            store.pull_task(job_id, "master", timeout=QUEUE_POLL_INTERVAL_SECONDS),
            timeout=30,
        )
        if image_idx is None:
            return False
        out = _process_whole_image(
            bundle, upscaled[image_idx : image_idx + 1], pos, neg, grid,
            process, key, image_idx,
        )
        frames[image_idx] = img_utils.ensure_numpy(out)[0]
        run_async_in_server_loop(
            store.submit_result(job_id, "master", image_idx, None), timeout=30
        )
        drain()
        return True

    while claim_and_process():
        if context is not None:
            context.check_interrupted()

    from ..utils.exceptions import JobCancelled

    deadline = time.monotonic() + timeout * max(1, len(enabled_worker_ids))
    while len(frames) < batch:
        if context is not None:
            context.check_interrupted()
        run_async_in_server_loop(store.sweep_deadlines(), timeout=30)
        state = run_async_in_server_loop(
            store.job_lifecycle(job_id), timeout=30
        )
        if state is not None and state["cancelled"]:
            run_async_in_server_loop(store.cleanup_tile_job(job_id), timeout=30)
            raise JobCancelled(job_id, state["cancel_reason"] or "cancel")
        drain()
        if len(frames) >= batch:
            break
        requeued = run_async_in_server_loop(
            store.requeue_timed_out(job_id, timeout, probe_busy), timeout=60
        )
        # heartbeat requeues or watchdog speculation may have refilled
        # the pending queue; claim through the shared pull path
        pending_now = run_async_in_server_loop(store.remaining(job_id), timeout=30)
        if requeued or pending_now:
            while claim_and_process():
                pass
        if len(frames) >= batch:
            break
        if time.monotonic() > deadline:
            missing = sorted(set(range(batch)) - set(frames))
            log(f"USDU dynamic: deadline hit; processing {len(missing)} frame(s) locally")
            for image_idx in missing:
                out = _process_whole_image(
                    bundle, upscaled[image_idx : image_idx + 1], pos, neg,
                    grid, process, key, image_idx,
                )
                frames[image_idx] = img_utils.ensure_numpy(out)[0]
            break
        time.sleep(QUEUE_POLL_INTERVAL_SECONDS)

    run_async_in_server_loop(store.cleanup_tile_job(job_id), timeout=30)
    stacked = np.stack([frames[i] for i in range(batch)], axis=0)
    return jnp.asarray(stacked)
