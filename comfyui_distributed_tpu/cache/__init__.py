"""Content-addressed tile result cache.

Tile work in this repo is a pure function of its sampler inputs — the
bit-identical-canvas invariant makes every result perfectly cacheable.
`keys.py` canonicalizes the exact sampler inputs into a content hash;
`store.py` holds the two-tier (host-RAM LRU + CRC-checked disk) store
and the process-global accessor the master consults at grant time.
"""

from .keys import (
    KEY_VERSION,
    JobKeyContext,
    adapter_fingerprint,
    base_key_hex,
    cond_fingerprint,
    params_fingerprint,
    tile_key,
)
from .store import (
    TileResultCache,
    get_tile_cache,
    set_tile_cache,
    _reset_tile_cache_for_tests,
)
from .integration import (
    JobCacheBinding,
    bind_job_cache,
    job_key_context,
    tile_keys_for,
)

__all__ = [
    "KEY_VERSION",
    "JobKeyContext",
    "adapter_fingerprint",
    "base_key_hex",
    "cond_fingerprint",
    "params_fingerprint",
    "tile_key",
    "JobCacheBinding",
    "bind_job_cache",
    "job_key_context",
    "tile_keys_for",
    "TileResultCache",
    "get_tile_cache",
    "set_tile_cache",
    "_reset_tile_cache_for_tests",
]
