"""Canonical cache keys for tile results.

A tile result is a pure function of (model weights, tile pixels, folded
RNG key, sampler config, adapter, geometry). The cache key must change
whenever ANY input that can change one output bit changes, and must NOT
change on inputs that cannot (job id on the elastic tier, tenant,
worker placement, pipeline depth, ...). The golden suite in
tests/test_cache_keys.py enforces both directions.

Canonicalization rules (the consistency argument in docs/caching.md):

- Every field is serialized as ``name=value\\n`` into one blake2b-256
  stream — named fields mean two adjacent values can never collide by
  concatenation ambiguity.
- Arrays contribute dtype + shape + raw bytes (C-order). A dtype or
  shape change with identical bytes changes the key.
- Floats are serialized via ``float.hex()`` — exact, no repr rounding.
- The RNG enters as the *folded base key's* raw key-data bits, not the
  integer seed: on the elastic tier the base key is
  ``jax.random.key(seed)`` (same seed across jobs/tenants → same key →
  cross-job dedup), while the xjob tier folds the job id into the base
  key (``parallel.seeds.fold_job_key``) — its outputs genuinely depend
  on the job id, so its cache keys do too. Hashing the folded bits
  makes both behaviors fall out of one rule.
- ``KEY_VERSION`` is the first field: any semantic change to sampler
  numerics or serialization bumps it and cleanly cold-starts the cache.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any

import numpy as np

KEY_VERSION = 1

_DIGEST_BYTES = 32


def _hasher() -> "hashlib._Hash":
    return hashlib.blake2b(digest_size=_DIGEST_BYTES)


def _feed(h: "hashlib._Hash", name: str, value: Any) -> None:
    """Append one named field to the hash stream canonically."""
    h.update(name.encode("utf-8"))
    h.update(b"=")
    if isinstance(value, bool):
        h.update(b"true" if value else b"false")
    elif isinstance(value, int):
        h.update(str(value).encode("ascii"))
    elif isinstance(value, float):
        h.update(value.hex().encode("ascii"))
    elif isinstance(value, str):
        h.update(value.encode("utf-8"))
    elif isinstance(value, bytes):
        h.update(value)
    elif value is None:
        h.update(b"none")
    else:
        raise TypeError(f"unsupported key field type for {name}: {type(value)}")
    h.update(b"\n")


def _feed_array(h: "hashlib._Hash", name: str, arr: Any) -> None:
    """Arrays hash as dtype + shape + C-order bytes (host-materialized)."""
    host = np.asarray(arr)
    _feed(h, name + ".dtype", str(host.dtype))
    _feed(h, name + ".shape", ",".join(str(d) for d in host.shape))
    _feed(h, name + ".data", np.ascontiguousarray(host).tobytes())


def _pytree_fingerprint(tree: Any) -> str:
    """Hex digest over a pytree: structure paths + every leaf array.

    Uses key-paths so a structural rename (a different param name with
    the same bytes) changes the fingerprint — weights drift of any kind
    must never alias.
    """
    import jax

    h = _hasher()
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    _feed(h, "leaves", len(leaves))
    for path, leaf in leaves:
        label = jax.tree_util.keystr(path)
        if hasattr(leaf, "dtype") or isinstance(leaf, (np.ndarray, np.generic)):
            _feed_array(h, "leaf:" + label, leaf)
        elif isinstance(leaf, (bool, int, float, str, bytes)) or leaf is None:
            _feed(h, "leaf:" + label, leaf)
        else:
            _feed(h, "leaf:" + label, repr(leaf))
    return h.hexdigest()


def params_fingerprint(params: Any) -> str:
    """Fingerprint of the model weights pytree (compute once per job)."""
    return _pytree_fingerprint(params)


def cond_fingerprint(pos: Any, neg: Any) -> str:
    """Fingerprint of the positive/negative conditioning pytrees."""
    h = _hasher()
    _feed(h, "pos", _pytree_fingerprint(pos))
    _feed(h, "neg", _pytree_fingerprint(neg))
    return h.hexdigest()


def adapter_fingerprint(adapter: Any = None) -> str:
    """Fingerprint of per-job adapter deltas ("" base model = no adapter).

    LoRA merging happens at load time today so merged weights already
    show up in params_fingerprint; this field exists so the per-tile
    adapter work (ROADMAP) joins the key without a version bump.
    """
    if adapter is None:
        return ""
    return _pytree_fingerprint(adapter)


def base_key_hex(key: Any) -> str:
    """Raw key-data bits of a (possibly folded) jax PRNG key, as hex."""
    import jax

    data = np.asarray(jax.random.key_data(key))
    return data.tobytes().hex()


@dataclass(frozen=True)
class JobKeyContext:
    """Per-job invariants of the cache key, computed once at job start.

    The expensive fingerprints (weights, conditioning) and the sampler/
    geometry scalars live here; `tile_key` adds only the per-tile
    variables (index, pixels, position).
    """

    weights_fp: str
    cond_fp: str
    base_key: str  # hex of the base (elastic) / job-folded (xjob) key bits
    steps: int
    sampler: str
    scheduler: str
    cfg: float
    denoise: float
    adapter_fp: str = ""
    # geometry: everything about the grid that shapes extraction/blend
    upscale_by: float = 1.0
    upscale_method: str = ""
    mask_blur: int = 0
    uniform: bool = False
    tiled_decode: bool = False
    tile_w: int = 0
    tile_h: int = 0
    padding: int = 0
    grid_w: int = 0
    grid_h: int = 0
    num_tiles: int = 0


def tile_key(ctx: JobKeyContext, tile_idx: int, tile: Any, y: int, x: int) -> str:
    """Canonical content key for one tile's result.

    ``tile`` is the extracted (pre-sampling) tile pixels exactly as fed
    to the processor; ``y``/``x`` are the tile's canvas position (they
    reach the sampler through positional conditioning, so they are
    output-affecting).
    """
    h = _hasher()
    _feed(h, "v", KEY_VERSION)
    _feed(h, "weights", ctx.weights_fp)
    _feed(h, "cond", ctx.cond_fp)
    _feed(h, "base_key", ctx.base_key)
    _feed(h, "steps", ctx.steps)
    _feed(h, "sampler", ctx.sampler)
    _feed(h, "scheduler", ctx.scheduler)
    _feed(h, "cfg", float(ctx.cfg))
    _feed(h, "denoise", float(ctx.denoise))
    _feed(h, "adapter", ctx.adapter_fp)
    _feed(h, "upscale_by", float(ctx.upscale_by))
    _feed(h, "upscale_method", ctx.upscale_method)
    _feed(h, "mask_blur", int(ctx.mask_blur))
    _feed(h, "uniform", bool(ctx.uniform))
    _feed(h, "tiled_decode", bool(ctx.tiled_decode))
    _feed(h, "tile_w", int(ctx.tile_w))
    _feed(h, "tile_h", int(ctx.tile_h))
    _feed(h, "padding", int(ctx.padding))
    _feed(h, "grid_w", int(ctx.grid_w))
    _feed(h, "grid_h", int(ctx.grid_h))
    _feed(h, "num_tiles", int(ctx.num_tiles))
    _feed(h, "tile_idx", int(tile_idx))
    _feed(h, "y", int(y))
    _feed(h, "x", int(x))
    _feed_array(h, "pixels", tile)
    return h.hexdigest()
