"""Two-tier content-addressed store for tile results.

Tier 1 is a bounded host-RAM LRU of decoded float arrays (the exact
host array the master blends). Tier 2 is an optional disk tier reusing
the ``utils/fsio.py`` atomic-write recipe, with a CRC32 over the pixel
bytes checked on every read: a corrupt/truncated/alien file is deleted
and reported as a miss — the cache can degrade to recompute but can
never place a wrong pixel on a canvas.

The store is master-side only and thread-safe (the elastic master, the
xjob executor thread, and the API routes all touch it). Entries are
immutable: ``put`` copies, ``get`` returns a read-only array.
"""

from __future__ import annotations

import binascii
import contextlib
import json
import os
import struct
import threading
from collections import OrderedDict

import numpy as np

from ..utils import constants
from ..utils.fsio import atomic_write_bytes

_MAGIC = b"CDTC"
_HEADER_STRUCT = struct.Struct("<4sI")  # magic, header-json length


class TileResultCache:
    """Bounded RAM LRU + CRC-checked disk tier, keyed by content hash."""

    def __init__(
        self,
        ram_mb: float | None = None,
        disk_dir: str | None = None,
        disk_mb: float | None = None,
    ) -> None:
        if ram_mb is None:
            ram_mb = constants.CACHE_RAM_MB
        if disk_mb is None:
            disk_mb = constants.CACHE_DISK_MB
        self._lock = threading.Lock()
        self._ram: OrderedDict[str, np.ndarray] = OrderedDict()
        self._ram_bytes = 0
        self._ram_budget = max(0, int(ram_mb * 1024 * 1024))
        self._disk_dir = disk_dir
        self._disk_budget = max(0, int(disk_mb * 1024 * 1024))
        self._disk_bytes = 0
        self._hits_ram = 0
        self._hits_disk = 0
        self._misses = 0
        self._puts = 0
        self._evictions = 0
        self._corrupt = 0
        self._settled = 0
        # scrape-time delta marks for the mirrored counters (the
        # flight-recorder idiom — see instruments.bind_server_collectors)
        self.scrape_mirrored: dict[str, int] = {}
        if self._disk_dir:
            os.makedirs(self._disk_dir, exist_ok=True)
            self._disk_bytes = self._scan_disk_bytes()

    # -- lookup / populate -------------------------------------------------

    def get(self, key: str) -> np.ndarray | None:
        """The cached result array, or None. RAM first, then disk (a
        disk hit is promoted into RAM)."""
        with self._lock:
            arr = self._ram.get(key)
            if arr is not None:
                self._ram.move_to_end(key)
                self._hits_ram += 1
                return arr
        arr = self._disk_read(key)
        with self._lock:
            if arr is not None:
                self._hits_disk += 1
                self._ram_insert(key, arr)
            else:
                self._misses += 1
        return arr

    def put(self, key: str, arr) -> None:
        """Populate both tiers. The stored copy is frozen so a hit can
        be blended without defensive copying."""
        host = np.ascontiguousarray(np.asarray(arr)).copy()
        host.setflags(write=False)
        with self._lock:
            self._puts += 1
            self._ram_insert(key, host)
        self._disk_write(key, host)

    def note_settled(self, n: int = 1) -> None:
        """Count tiles settled into a job straight from the cache."""
        with self._lock:
            self._settled += int(n)

    # -- RAM tier (call under self._lock) ----------------------------------

    def _ram_insert(self, key: str, arr: np.ndarray) -> None:
        if key in self._ram:
            self._ram.move_to_end(key)
            return
        size = arr.nbytes
        if size > self._ram_budget:
            return  # larger than the whole budget: disk-only
        self._ram[key] = arr
        self._ram_bytes += size
        while self._ram_bytes > self._ram_budget and self._ram:
            _, evicted = self._ram.popitem(last=False)
            self._ram_bytes -= evicted.nbytes
            self._evictions += 1

    # -- disk tier ---------------------------------------------------------

    def _disk_path(self, key: str) -> str:
        return os.path.join(self._disk_dir, key[:2], key + ".tile")

    def _disk_write(self, key: str, arr: np.ndarray) -> None:
        if not self._disk_dir:
            return
        body = arr.tobytes()
        header = json.dumps(
            {
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
                "crc": binascii.crc32(body) & 0xFFFFFFFF,
            }
        ).encode("utf-8")
        blob = _HEADER_STRUCT.pack(_MAGIC, len(header)) + header + body
        path = self._disk_path(key)
        try:
            existed = os.path.exists(path)
            atomic_write_bytes(path, blob)
        except OSError:
            return  # disk tier is best-effort; RAM tier already has it
        with self._lock:
            if not existed:
                self._disk_bytes += len(blob)
        self._disk_prune()

    def _disk_read(self, key: str) -> np.ndarray | None:
        if not self._disk_dir:
            return None
        path = self._disk_path(key)
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError:
            return None
        try:
            magic, header_len = _HEADER_STRUCT.unpack_from(blob, 0)
            if magic != _MAGIC:
                raise ValueError("bad magic")
            header_end = _HEADER_STRUCT.size + header_len
            header = json.loads(blob[_HEADER_STRUCT.size:header_end])
            body = blob[header_end:]
            if (binascii.crc32(body) & 0xFFFFFFFF) != int(header["crc"]):
                raise ValueError("crc mismatch")
            arr = np.frombuffer(body, dtype=np.dtype(header["dtype"]))
            arr = arr.reshape([int(d) for d in header["shape"]])
        except (ValueError, KeyError, TypeError, struct.error, json.JSONDecodeError):
            # Corrupt entry: delete it (a retry must not re-read the
            # same bad bytes) and report a miss — never a wrong canvas.
            with self._lock:
                self._corrupt += 1
                self._disk_bytes = max(0, self._disk_bytes - len(blob))
            with contextlib.suppress(OSError):
                os.unlink(path)
            return None
        arr.setflags(write=False)
        return arr

    def _disk_prune(self) -> None:
        """Prune oldest disk entries past the byte budget (0 = unbounded)."""
        if not self._disk_dir or not self._disk_budget:
            return
        with self._lock:
            over = self._disk_bytes > self._disk_budget
        if not over:
            return
        entries = []
        for sub in os.scandir(self._disk_dir):
            if not sub.is_dir():
                continue
            for ent in os.scandir(sub.path):
                if ent.is_file() and ent.name.endswith(".tile"):
                    st = ent.stat()
                    entries.append((st.st_mtime, st.st_size, ent.path))
        entries.sort()
        total = sum(size for _, size, _ in entries)
        for _, size, path in entries:
            if total <= self._disk_budget:
                break
            with contextlib.suppress(OSError):
                os.unlink(path)
            total -= size
            with self._lock:
                self._evictions += 1
        with self._lock:
            self._disk_bytes = total

    def _scan_disk_bytes(self) -> int:
        total = 0
        try:
            for sub in os.scandir(self._disk_dir):
                if not sub.is_dir():
                    continue
                for ent in os.scandir(sub.path):
                    if ent.is_file() and ent.name.endswith(".tile"):
                        total += ent.stat().st_size
        except OSError:
            return 0
        return total

    # -- management --------------------------------------------------------

    def clear(self) -> dict:
        """Drop both tiers; returns what was dropped (the API response)."""
        with self._lock:
            dropped_entries = len(self._ram)
            dropped_bytes = self._ram_bytes
            self._ram.clear()
            self._ram_bytes = 0
        disk_entries = 0
        if self._disk_dir:
            for sub in list(os.scandir(self._disk_dir)):
                if not sub.is_dir():
                    continue
                for ent in list(os.scandir(sub.path)):
                    if ent.is_file() and ent.name.endswith(".tile"):
                        with contextlib.suppress(OSError):
                            dropped_bytes += ent.stat().st_size
                            os.unlink(ent.path)
                            disk_entries += 1
            with self._lock:
                self._disk_bytes = 0
        return {
            "dropped_entries": dropped_entries + disk_entries,
            "dropped_bytes": dropped_bytes,
        }

    def stats(self) -> dict:
        with self._lock:
            hits = self._hits_ram + self._hits_disk
            lookups = hits + self._misses
            return {
                "hits": hits,
                "hits_ram": self._hits_ram,
                "hits_disk": self._hits_disk,
                "misses": self._misses,
                "hit_rate": (hits / lookups) if lookups else 0.0,
                "puts": self._puts,
                "evictions": self._evictions,
                "corrupt": self._corrupt,
                "settled": self._settled,
                "ram_entries": len(self._ram),
                "ram_bytes": self._ram_bytes,
                "disk_bytes": self._disk_bytes if self._disk_dir else 0,
                "disk_tier": bool(self._disk_dir),
            }


# -- process-global accessor (mirrors telemetry/usage.py's meter) ----------

_tile_cache: TileResultCache | None = None
_cache_lock = threading.Lock()


def get_tile_cache() -> TileResultCache | None:
    """The process-global cache, or None when CDT_CACHE is off.

    Constructed lazily from the CDT_CACHE_* knobs on first enabled
    call; while disabled nothing is memoized, so tests can flip the
    env and reset freely.
    """
    global _tile_cache
    with _cache_lock:
        if _tile_cache is not None:
            return _tile_cache
        if not constants.cache_enabled():
            return None
        _tile_cache = TileResultCache(disk_dir=constants.cache_dir())
        return _tile_cache


def set_tile_cache(cache: TileResultCache | None) -> TileResultCache | None:
    """Install a specific cache instance (chaos/bench harnesses); returns
    the previous one so callers can restore it."""
    global _tile_cache
    with _cache_lock:
        prev = _tile_cache
        _tile_cache = cache
        return prev


def _reset_tile_cache_for_tests() -> None:
    set_tile_cache(None)
