"""Master-side cache wiring shared by both execution tiers.

The elastic master (graph/usdu_elastic.py) and the xjob executor
master (graph/batch_executor.py) consume the cache identically: build
the job's :class:`~.keys.JobKeyContext` once, derive one key per tile,
probe, settle the hits into the job store (so workers never pull
them), and blend the cached pixels locally. The only tier difference
is the base RNG key handed in — ``jax.random.key(seed)`` for the
elastic tier (cross-job dedup: two jobs with identical inputs share
results) versus ``fold_job_key(key, job_id)`` for the xjob tier
(whose tile outputs fold the job id and so can only dedup within the
same job's retries).

Everything here is best-effort around the cache only: key derivation
runs exactly once per job, and a disabled cache costs one ``None``
check.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from .keys import (
    JobKeyContext,
    adapter_fingerprint,
    base_key_hex,
    cond_fingerprint,
    params_fingerprint,
    tile_key,
)
from .store import TileResultCache, get_tile_cache


def job_key_context(
    params: Any,
    pos: Any,
    neg: Any,
    base_key: Any,
    grid: Any,
    *,
    steps: int,
    sampler: str,
    scheduler: str,
    cfg: float,
    denoise: float,
    upscale_by: float = 1.0,
    upscale_method: str = "",
    mask_blur: int = 0,
    uniform: bool = False,
    tiled_decode: bool = False,
    adapter: Any = None,
) -> JobKeyContext:
    """The canonical per-job key context for a prepared tile run.

    ``pos``/``neg`` must be the PREPPED conds (the exact sampler
    inputs, post ``prep_cond_for_tiles``); ``params`` the exact bundle
    params the processor closes over (LoRA-merged weights hash
    differently than base weights by construction).
    """
    return JobKeyContext(
        weights_fp=params_fingerprint(params),
        cond_fp=cond_fingerprint(pos, neg),
        base_key=base_key_hex(base_key),
        steps=int(steps),
        sampler=str(sampler),
        scheduler=str(scheduler),
        cfg=float(cfg),
        denoise=float(denoise),
        adapter_fp=adapter_fingerprint(adapter),
        upscale_by=float(upscale_by),
        upscale_method=str(upscale_method),
        mask_blur=int(mask_blur),
        uniform=bool(uniform),
        tiled_decode=bool(tiled_decode),
        tile_w=int(grid.tile_w),
        tile_h=int(grid.tile_h),
        padding=int(grid.padding),
        grid_w=int(grid.cols),
        grid_h=int(grid.rows),
        num_tiles=int(grid.num_tiles),
    )


def tile_keys_for(ctx: JobKeyContext, extracted: Any, grid: Any) -> list[str]:
    """One content key per tile index. ``extracted`` is the full
    prepared tile stack ``[T, B, th, tw, C]`` (device or host); it is
    materialised host-side ONCE here — the same transfer the blend
    path pays anyway."""
    host = np.asarray(extracted)
    return [
        tile_key(ctx, idx, host[idx], *grid.positions[idx])
        for idx in range(grid.num_tiles)
    ]


class JobCacheBinding:
    """Per-job view over the global cache for one master run.

    ``probe()`` collects hits; the caller settles them in the store
    and blends via ``hits`` (tile_idx -> frozen host array).
    ``populate(tile_idx, arr)`` writes back a computed tile unless the
    tile was itself served from the cache (re-putting a hit would just
    churn the LRU order with identical bytes).
    """

    def __init__(self, cache: TileResultCache, keys: list[str]) -> None:
        self.cache = cache
        self.keys = keys
        self.hits: dict[int, np.ndarray] = {}

    def probe(self) -> dict[int, np.ndarray]:
        for idx, key in enumerate(self.keys):
            arr = self.cache.get(key)
            if arr is not None:
                self.hits[idx] = arr
        return self.hits

    def populate(self, tile_idx: int, arr: Any) -> None:
        if tile_idx in self.hits:
            return
        if 0 <= tile_idx < len(self.keys):
            self.cache.put(self.keys[tile_idx], arr)


def bind_job_cache(
    build_keys: Callable[[], list[str]],
) -> JobCacheBinding | None:
    """A :class:`JobCacheBinding` when CDT_CACHE=1, else None.

    ``build_keys`` is deferred so a disabled cache never pays the
    params-fingerprint/host-transfer cost of key derivation.
    """
    cache = get_tile_cache()
    if cache is None:
        return None
    return JobCacheBinding(cache, build_keys())
