"""Step-level preemption coordination: lanes get real teeth.

Before this module the admission lanes (scheduler/queue.py) only
ordered work at GRANT time, and brownout could only *shed* cheap
lanes' new admissions — a premium job admitted mid-flight still sat
behind a batch job's running grant for the grant's full duration. The
coordinator closes that gap:

- **premium arrival** — when a job inits on a lane that outranks
  running work, every active lower-lane job with outstanding tiles is
  flagged for preemption (``JobStore.request_preemption``): its pulls
  read as drained, pull/heartbeat responses carry ``preempt: true``,
  and the continuous-batching executor (graph/batch_executor.py)
  checkpoints + releases its in-flight tiles at the next step
  boundary. The premium job's tiles take the freed batch slots at the
  very next scheduling round — a step-boundary wait, not a grant wait.
- **settle** — when the premium job completes or cancels, the flags it
  raised lift (unless another outstanding premium still claims the
  victim) and the evicted work resumes from its checkpoints (or
  recomputes from step 0 when a checkpoint was lost — bit-identical
  either way).
- **brownout eviction** (CDT_PREEMPT_BROWNOUT_LEVEL) — at or above the
  configured shed level the brownout controller's hook also evicts
  RUNNING work from shed lanes instead of only rejecting their new
  admissions.

The coordinator owns lane ranking (the admission queue's strict
priority order); the store owns flags/state. Everything is advisory:
a coordinator failure degrades to today's no-preemption behavior,
never to a stuck queue. All methods run on the server loop.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from ..telemetry import instruments  # noqa: F401 - counted in the store
from ..utils import constants
from ..utils.logging import debug_log, log

# Rank assigned to jobs with no / unknown lane: below every declared
# lane, so legacy jobs never outrank an explicit premium lane and are
# always eligible victims.
UNRANKED = 1 << 20


class PreemptionCoordinator:
    """Maps lane order onto preemption decisions over one JobStore.

    ``lane_order`` is the admission queue's priority order (highest
    first — ``AdmissionQueue.lane_order``). ``preempt_rank_limit``
    restricts which arrivals may preempt at all: only jobs whose lane
    rank is strictly below it (default 1: only the TOP lane preempts,
    so mid-tier lanes cannot churn the fleet with evictions).
    """

    def __init__(
        self,
        lane_order: Sequence[str],
        store: Any,
        enabled: Optional[bool] = None,
        preempt_rank_limit: int = 1,
    ) -> None:
        self.lane_order = [str(lane) for lane in lane_order]
        self._rank = {lane: i for i, lane in enumerate(self.lane_order)}
        self.store = store
        self.enabled = (
            bool(enabled)
            if enabled is not None
            else constants.PREEMPT_ENABLED == 1
        )
        self.preempt_rank_limit = max(1, int(preempt_rank_limit))
        # premium job id -> victims it flagged (for settle-time lifts)
        self._claims: dict[str, list[str]] = {}
        self.preemptions = 0

    # --- ranking ----------------------------------------------------------

    def lane_rank(self, lane: str) -> int:
        """Lower = more urgent; unknown/blank lanes rank UNRANKED (the
        JobStore delegates its ordering and victim selection here)."""
        return self._rank.get(str(lane or ""), UNRANKED)

    # --- store seams ------------------------------------------------------

    async def on_job_init(self, job_id: str) -> list[str]:
        """A job just initialized: if its lane outranks running work
        (and sits inside the preempting rank band), flag the victims.
        Returns the victim job ids (empty = no preemption)."""
        if not self.enabled:
            return []
        job = await self.store.get_tile_job(job_id)
        if job is None:
            return []
        rank = self.lane_rank(job.lane)
        if rank >= self.preempt_rank_limit:
            return []
        # claim EVERY lower-ranked job — including ones an earlier
        # premium already flagged — so that premium's settle cannot
        # lift flags this one still depends on; only the unflagged
        # subset is newly requested
        claims = [
            v
            for v in await self.store.preempt_victims(
                rank, include_flagged=True
            )
            if v != job_id
        ]
        if not claims:
            return []
        flagged = await self.store.request_preemption(
            claims, reason="premium_arrival"
        )
        self._claims[job_id] = claims
        if flagged:
            self.preemptions += len(flagged)
            log(
                f"premium job {job_id} (lane {job.lane!r}) preempts "
                f"{len(flagged)} running job(s): {', '.join(flagged)}"
            )
        return flagged

    async def on_job_settled(self, job_id: str) -> list[str]:
        """A job completed/cancelled: lift the flags it raised, except
        for victims another OUTSTANDING premium still claims."""
        claimed = self._claims.pop(job_id, None)
        if not claimed:
            return []
        still_claimed = {
            victim
            for premium, victims in sorted(self._claims.items())
            for victim in victims
        }
        release = [v for v in claimed if v not in still_claimed]
        if not release:
            return []
        # a flag brownout currently owns is not this premium's to
        # lift — brownout's own de-escalation hook clears those
        async with self.store.lock:
            release = [
                v
                for v in release
                if (job := self.store.tile_jobs.get(v)) is not None
                and job.preempt_reason != "brownout"
            ]
        if not release:
            return []
        cleared = await self.store.clear_preemption(release)
        if cleared:
            debug_log(
                f"preemption lifted after {job_id} settled: "
                f"{', '.join(cleared)}"
            )
        return cleared

    # --- brownout seam ----------------------------------------------------

    async def on_brownout(self, level: int, shed_lanes: Sequence[str]) -> list[str]:
        """Brownout level changed: at or above
        CDT_PREEMPT_BROWNOUT_LEVEL, evict RUNNING work from the shed
        lanes too (reason="brownout"); below it — including every
        de-escalation step — LIFT any brownout flags on jobs whose
        lane is no longer shed, so evicted work resumes the moment
        pressure recedes (a brownout flag must never outlive the
        brownout). With the knob at its 0 default brownout stays
        admission-only, exactly as before."""
        threshold = constants.PREEMPT_BROWNOUT_LEVEL
        if not self.enabled or threshold <= 0:
            return []
        shed = (
            {str(lane) for lane in shed_lanes} if level >= threshold else set()
        )
        async with self.store.lock:
            jobs = sorted(
                self.store.tile_jobs.values(),
                key=lambda j: (j.created_at, j.job_id),
            )
            victims = [
                job.job_id
                for job in jobs
                if not job.cancelled
                and not job.preempt_requested
                and job.lane in shed
            ]
            stale = [
                job.job_id
                for job in jobs
                if job.preempt_requested
                and job.preempt_reason == "brownout"
                and job.lane not in shed
            ]
        if stale:
            await self.store.clear_preemption(stale)
        if not victims:
            return []
        return await self.store.request_preemption(victims, reason="brownout")

    # --- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "enabled": self.enabled,
            "lane_order": list(self.lane_order),
            "preempt_rank_limit": self.preempt_rank_limit,
            "preemptions": self.preemptions,
            "active_claims": {
                premium: list(victims)
                for premium, victims in sorted(self._claims.items())
            },
        }
