"""Brownout: progressive load shedding above the admission lanes.

The failure the controller prevents: under sustained overload the
admission queue keeps accepting work into every lane, queue waits
climb unboundedly, the journal fsync path saturates, and the master
tips over for EVERYONE — premium tenants included. The brownout
controller watches two leading indicators —

- **queue-wait p95**: seconds recently-granted requests spent queued
  (fed by ``AdmissionQueue`` on every grant);
- **journal-append p95**: seconds recent write-ahead appends took
  (fed by the ``DurabilityManager`` when journaling is enabled);

— and, when either crosses its threshold, sheds one more
lowest-priority lane: requests for shed lanes are rejected at
admission with HTTP 429 + Retry-After (``cdt_shed_total``), *before*
they consume queue depth, grant slots, or journal bandwidth. The top
(premium) lane is never shed — brownout degrades the cheap lanes to
keep the premium lane's grant latency bounded. Levels step at most
once per ``CDT_SHED_COOLDOWN`` and step back down once BOTH signals
fall under half their thresholds (hysteresis, so a noisy boundary
doesn't flap admission).

Everything is injectable (clock, thresholds, window) so tier-1 tests
drive the whole ladder on a fake timeline; see
tests/scheduler/test_brownout.py and docs/scheduler.md §brownout.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional, Sequence

from ..telemetry import instruments
from ..telemetry.events import get_event_bus
from ..utils import constants
from ..utils.logging import log


def _p95(samples: Sequence[float]) -> float:
    if not samples:
        return 0.0
    ordered = sorted(samples)
    index = min(len(ordered) - 1, max(0, int(0.95 * len(ordered))))
    return ordered[index]


class BrownoutController:
    """Progressive lane shedding driven by wait/journal p95 windows.

    ``lane_order`` is the admission queue's strict priority order
    (highest first); level k sheds the k LOWEST-priority lanes. The
    controller is called from the server loop (admission path) and fed
    from the loop (grants) plus the journal seam — a lock keeps the
    windows coherent for the occasional off-loop feeder.
    """

    def __init__(
        self,
        lane_order: Sequence[str],
        wait_p95_threshold: Optional[float] = None,
        journal_p95_threshold: Optional[float] = None,
        window: Optional[int] = None,
        cooldown: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.lane_order = list(lane_order)
        self.wait_p95_threshold = (
            wait_p95_threshold
            if wait_p95_threshold is not None
            else constants.SHED_WAIT_P95_SECONDS
        )
        self.journal_p95_threshold = (
            journal_p95_threshold
            if journal_p95_threshold is not None
            else constants.SHED_JOURNAL_P95_SECONDS
        )
        window = window if window is not None else constants.SHED_WINDOW_SAMPLES
        self.cooldown = (
            cooldown if cooldown is not None else constants.SHED_COOLDOWN_SECONDS
        )
        self.clock = clock
        self._lock = threading.Lock()
        self._waits: collections.deque = collections.deque(maxlen=max(1, window))
        self._journal: collections.deque = collections.deque(
            maxlen=max(1, window)
        )
        self.level = 0
        self._last_step = -float("inf")
        # monotonic time of the newest sample on either window: when a
        # shed system goes quiet (shedding IS why no samples arrive),
        # the stale p95 must not latch the level forever
        self._last_signal: Optional[float] = None
        self.shed_counts: dict[str, int] = {}
        # Step-level eviction seam (CDT_PREEMPT_BROWNOUT_LEVEL): fired
        # with (level, shed_lanes) on EVERY level change — a rise lets
        # the preemption coordinator evict RUNNING work from shed
        # lanes, and a drop lets it LIFT the brownout flags it raised
        # so the evicted work resumes. Advisory; must never raise into
        # the admission path.
        self.preempt_hook: Optional[Callable[[int, list[str]], None]] = None

    # --- signal feeds -----------------------------------------------------

    def note_queue_wait(self, seconds: float) -> None:
        """One granted request's queue wait (AdmissionQueue.wait_sink)."""
        with self._lock:
            self._waits.append(float(seconds))
            self._last_signal = self.clock()

    def note_journal_append(self, seconds: float) -> None:
        """One write-ahead append's latency (DurabilityManager sink)."""
        with self._lock:
            self._journal.append(float(seconds))
            self._last_signal = self.clock()

    # --- the ladder -------------------------------------------------------

    def signals(self) -> dict:
        with self._lock:
            wait_p95 = _p95(self._waits)
            journal_p95 = _p95(self._journal)
        return {"wait_p95": wait_p95, "journal_p95": journal_p95}

    def evaluate(self) -> int:
        """Recompute the shed level (hysteresis + cooldown); returns
        the current level. Cheap enough to run on every admission."""
        sig = self.signals()
        now = self.clock()
        overloaded = (
            sig["wait_p95"] > self.wait_p95_threshold
            or sig["journal_p95"] > self.journal_p95_threshold
        )
        recovered = (
            sig["wait_p95"] < self.wait_p95_threshold / 2.0
            and sig["journal_p95"] < self.journal_p95_threshold / 2.0
        )
        # Signal starvation while shedding: the windows only refresh on
        # grants/appends, and shedding is exactly what stops those. If
        # nothing has fed the controller for 2x the cooldown, the stale
        # overload reading must decay (and its samples drop) so shed
        # clients get a probe chance — persistent overload will simply
        # re-shed on the next real samples.
        with self._lock:
            starved = (
                self.level > 0
                and self._last_signal is not None
                and now - self._last_signal > 2.0 * self.cooldown
            )
            if starved:
                self._waits.clear()
                self._journal.clear()
                self._last_signal = now
        if starved:
            overloaded = False
            recovered = True
            sig = {"wait_p95": 0.0, "journal_p95": 0.0}
        max_level = max(0, len(self.lane_order) - 1)
        step = 0
        if overloaded and self.level < max_level:
            step = 1
        elif recovered and self.level > 0:
            step = -1
        if step and now - self._last_step >= self.cooldown:
            self.level += step
            self._last_step = now
            instruments.brownout_level().set(self.level)
            get_event_bus().publish(
                "brownout_level",
                level=self.level,
                direction="up" if step > 0 else "down",
                wait_p95=round(sig["wait_p95"], 4),
                journal_p95=round(sig["journal_p95"], 4),
                shed_lanes=self.shed_lanes(),
            )
            log(
                f"brownout level {'raised' if step > 0 else 'lowered'} to "
                f"{self.level} (wait p95 {sig['wait_p95']:.2f}s, journal "
                f"p95 {sig['journal_p95']:.3f}s); shedding "
                f"{self.shed_lanes() or 'nothing'}"
            )
            if self.preempt_hook is not None:
                try:
                    self.preempt_hook(self.level, self.shed_lanes())
                except Exception:  # noqa: BLE001 - eviction is advisory
                    pass
        return self.level

    def shed_lanes(self) -> list[str]:
        if self.level <= 0:
            return []
        return self.lane_order[-self.level:]

    def should_shed(self, lane: str) -> bool:
        """Admission-path gate: evaluate the ladder, then answer
        whether this lane is currently shed. The premium (first) lane
        never sheds, whatever the level."""
        level = self.evaluate()
        if level <= 0 or lane == self.lane_order[0]:
            return False
        return lane in self.lane_order[-level:]

    def record_shed(self, lane: str) -> None:
        """One rejected admission (the caller answered 429)."""
        self.shed_counts[lane] = self.shed_counts.get(lane, 0) + 1
        instruments.shed_total().inc(lane=lane)
        get_event_bus().publish("shed", lane=lane, level=self.level)

    # --- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        sig = self.signals()
        return {
            "level": self.level,
            "shed_lanes": self.shed_lanes(),
            "shed_counts": dict(self.shed_counts),
            "wait_p95_seconds": round(sig["wait_p95"], 4),
            "journal_p95_seconds": round(sig["journal_p95"], 4),
            "wait_p95_threshold": self.wait_p95_threshold,
            "journal_p95_threshold": self.journal_p95_threshold,
            "cooldown_seconds": self.cooldown,
        }
