"""Scheduler control plane: admission, fair-share queueing, placement.

The layer between the HTTP API and the job store that turns the
passive first-come-first-served pipeline into an actively managed one:

- `queue.AdmissionQueue` — multi-lane admission with priority classes,
  per-tenant deficit-round-robin fair share, bounded depth with
  explicit backpressure, and pause/resume/drain controls;
- `placement.PlacementPolicy` — cost-aware work assignment: per-worker
  throughput weights (EWMA over the store's pull→submit latencies)
  plus analytic tile-FLOP estimates size each worker's pull batch and
  trim the job tail away from suspect/slow workers;
- `control.SchedulerControl` — the state machine the
  `/distributed/scheduler/*` routes drive, and the single object a
  `DistributedServer` owns.

Determinism invariant: placement may change WHO computes a tile, never
the blended result (per-tile noise keys + the deterministic canvas);
the chaos suite asserts bit-identical canvases under weighted
placement.
"""

from .brownout import BrownoutController
from .control import SchedulerControl, SchedulerState
from .placement import PlacementPolicy
from .queue import (
    AdmissionClosed,
    AdmissionQueue,
    DeadlineUnmeetable,
    SchedulerOverloaded,
    SchedulerSaturated,
    Ticket,
)
from .router import EndpointRotation, ShardRing, ShardRouter

__all__ = [
    "AdmissionClosed",
    "AdmissionQueue",
    "BrownoutController",
    "DeadlineUnmeetable",
    "EndpointRotation",
    "PlacementPolicy",
    "SchedulerControl",
    "SchedulerOverloaded",
    "SchedulerSaturated",
    "SchedulerState",
    "ShardRing",
    "ShardRouter",
    "Ticket",
]
