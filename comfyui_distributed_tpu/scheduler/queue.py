"""Multi-lane admission queue with per-tenant fair share.

The Borg/vLLM lesson applied to the distributed queue route: requests
are *admitted* into a priority lane, wait their turn under
deficit-round-robin (DRR) across tenants, and only then *granted* one
of `max_active` orchestration slots. A full lane rejects with explicit
backpressure (the route maps `SchedulerSaturated` to HTTP 429 +
``Retry-After``); drain mode closes admission while everything already
admitted completes.

Fairness is classic DRR (Shreedhar & Varghese): each lane keeps one
FIFO per tenant plus a deficit counter; a tenant at the head of the
rotation is replenished ``quantum x weight`` once per visit and serves
requests while its deficit covers their cost (cost = the request's
estimated tile count, so fair share is over *tile work*, not request
count). Two backlogged tenants with weights 3:1 therefore receive tile
work 3:1 regardless of arrival order or request sizes.

Single-loop discipline: every method is expected on the server loop
(route handlers, pump, and control routes all live there); the clock
is injectable so tier-1 tests drive fairness over a fake timeline.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import math
import time
from typing import Callable, Iterable, Optional

from ..telemetry import instruments
from ..telemetry.events import get_event_bus
from ..utils import constants
from ..utils.exceptions import DistributedError
from ..utils.logging import log

# Scheduler admission states (mirrored by control.SchedulerState).
RUNNING = "running"
PAUSED = "paused"
DRAINING = "draining"


class SchedulerSaturated(DistributedError):
    """Lane at capacity (or grant wait expired): back off and retry."""

    def __init__(self, message: str, lane: str, retry_after: float):
        super().__init__(message)
        self.lane = lane
        self.retry_after = retry_after


class AdmissionClosed(DistributedError):
    """Drain mode: no new work is admitted until resume."""

    def __init__(self, message: str, retry_after: float):
        super().__init__(message)
        self.retry_after = retry_after


class SchedulerOverloaded(SchedulerSaturated):
    """Brownout: this lane is currently SHED by the load-shed
    controller (scheduler/brownout.py) — rejected before consuming
    queue depth or a grant slot. Same 429 + Retry-After contract as a
    full lane."""


class DeadlineUnmeetable(SchedulerSaturated):
    """The request carried an end-to-end deadline the scheduler cannot
    meet at admission time (estimated queue wait already exceeds it):
    rejected with 429 instead of admitting doomed work."""

    def __init__(
        self, message: str, lane: str, retry_after: float,
        deadline_s: float, estimated_wait: float,
    ):
        super().__init__(message, lane, retry_after)
        self.deadline_s = deadline_s
        self.estimated_wait = estimated_wait


def parse_lane_spec(spec: str) -> list[tuple[str, int]]:
    """"interactive:64,batch:256" → [(name, depth), ...] in priority
    order; malformed entries raise so a typo'd deployment fails loud."""
    lanes: list[tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, depth_s = part.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"bad lane entry {part!r} in {spec!r}")
        try:
            depth = int(depth_s) if depth_s else 64
        except ValueError as exc:
            raise ValueError(f"bad lane depth in {part!r}") from exc
        if depth <= 0:
            raise ValueError(f"lane depth must be > 0 in {part!r}")
        lanes.append((name, depth))
    if not lanes:
        raise ValueError(f"no lanes in spec {spec!r}")
    return lanes


def parse_tenant_weights(spec: str) -> dict[str, float]:
    """"a=3,b=1" → {"a": 3.0, "b": 1.0}; unlisted tenants weigh 1."""
    weights: dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        tenant, _, value = part.partition("=")
        try:
            weight = float(value)
        except ValueError as exc:
            raise ValueError(f"bad tenant weight {part!r}") from exc
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0 in {part!r}")
        weights[tenant.strip()] = weight
    return weights


@dataclasses.dataclass
class Ticket:
    """One admitted request's place in the control plane."""

    ticket_id: str
    tenant: str
    lane: str
    cost: float
    trace_id: Optional[str]
    submitted_at: float
    granted_at: Optional[float] = None
    released_at: Optional[float] = None
    state: str = "queued"  # queued | granted | cancelled | released
    _granted: asyncio.Event = dataclasses.field(
        default_factory=asyncio.Event, repr=False
    )

    async def granted(self) -> None:
        await self._granted.wait()

    @property
    def queue_wait_seconds(self) -> Optional[float]:
        if self.granted_at is None:
            return None
        return self.granted_at - self.submitted_at


class _Lane:
    """One priority class: per-tenant FIFOs + DRR bookkeeping."""

    def __init__(self, name: str, max_depth: int):
        self.name = name
        self.max_depth = max_depth
        self.queues: dict[str, collections.deque[Ticket]] = {}
        self.rotation: collections.deque[str] = collections.deque()
        self.deficit: dict[str, float] = {}

    def depth(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def push(self, ticket: Ticket) -> None:
        queue = self.queues.get(ticket.tenant)
        if queue is None:
            queue = collections.deque()
            self.queues[ticket.tenant] = queue
        if not queue and ticket.tenant not in self.rotation:
            self.rotation.append(ticket.tenant)
            self.deficit.setdefault(ticket.tenant, 0.0)
        queue.append(ticket)

    def _drop_tenant(self, tenant: str) -> None:
        """A tenant's queue drained: leave the rotation and forfeit any
        leftover deficit (an idle tenant must not bank credit)."""
        if tenant in self.rotation:
            self.rotation.remove(tenant)
        self.deficit[tenant] = 0.0
        self.queues.pop(tenant, None)

    def _serve(self, tenant: str) -> Ticket:
        queue = self.queues[tenant]
        ticket = queue.popleft()
        self.deficit[tenant] -= ticket.cost
        if not queue:
            self._drop_tenant(tenant)
        return ticket

    def pop_next(
        self, quantum: float, weight_of: Callable[[str], float]
    ) -> Optional[Ticket]:
        """Deficit-round-robin pop of the next ticket; None when empty.

        The rotation head keeps serving while its banked deficit covers
        its head request (the classic DRR burst). When it can't, it
        moves to the back and — instead of looping rotations one at a
        time, which would strand large-cost requests behind a small
        quantum — the number of whole rotations until SOME tenant's
        deficit covers its head cost is computed in closed form; every
        deficit advances by exactly that many rotations' replenishment
        (quantum x weight each), which is bit-for-bit the state classic
        DRR would reach, just without the walk."""
        if not self.rotation:
            return None
        head = self.rotation[0]
        if self.deficit[head] >= self.queues[head][0].cost - 1e-12:
            return self._serve(head)
        # head's burst is over: to the back, as DRR's visit order does
        self.rotation.rotate(-1)
        # Visit k of tenant t replenishes it for the k-th time; t can
        # first serve on visit ceil(need / (quantum x weight)) — at
        # least 1, since every visit replenishes even a tenant whose
        # bank already covers its head. The winner is the earliest
        # (visit, position) pair; at serve time classic DRR has
        # replenished positions ≤ winner `k` times and positions after
        # it `k - 1` times. Advancing deficits by exactly those counts
        # reaches the same state without walking the rotations.
        best: Optional[tuple[int, int, str]] = None
        for pos, tenant in enumerate(self.rotation):
            need = self.queues[tenant][0].cost - self.deficit[tenant]
            per_round = quantum * max(weight_of(tenant), 1e-9)
            rounds = max(1, math.ceil(need / per_round - 1e-12))
            if best is None or (rounds, pos) < best[:2]:
                best = (rounds, pos, tenant)
        rounds, pos, winner = best
        for p, tenant in enumerate(self.rotation):
            visits = rounds if p <= pos else rounds - 1
            if visits:
                self.deficit[tenant] += visits * quantum * weight_of(tenant)
        self.rotation.rotate(-pos)  # winner to the head; burst continues
        return self._serve(winner)

    def remove(self, ticket: Ticket) -> bool:
        queue = self.queues.get(ticket.tenant)
        if queue is None or ticket not in queue:
            return False
        queue.remove(ticket)
        if not queue:
            self._drop_tenant(ticket.tenant)
        return True

    def tenants_snapshot(self) -> dict[str, dict[str, float]]:
        return {
            tenant: {
                "queued": len(queue),
                "deficit": round(self.deficit.get(tenant, 0.0), 6),
            }
            for tenant, queue in self.queues.items()
            if queue
        }


class AdmissionQueue:
    def __init__(
        self,
        lanes: Optional[Iterable[tuple[str, int]]] = None,
        max_active: Optional[int] = None,
        tenant_weights: Optional[dict[str, float]] = None,
        quantum: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        lane_spec = (
            list(lanes)
            if lanes is not None
            else parse_lane_spec(constants.SCHED_LANES)
        )
        self.lanes: dict[str, _Lane] = {
            name: _Lane(name, depth) for name, depth in lane_spec
        }
        self.lane_order = [name for name, _ in lane_spec]
        self.max_active = (
            max_active if max_active is not None else constants.SCHED_MAX_ACTIVE
        )
        self.quantum = quantum if quantum is not None else constants.SCHED_QUANTUM
        self.tenant_weights = dict(
            tenant_weights
            if tenant_weights is not None
            else parse_tenant_weights(constants.SCHED_TENANT_WEIGHTS)
        )
        self.clock = clock
        self.state = RUNNING
        self.active: dict[str, Ticket] = {}
        # Optional per-grant queue-wait feed (the brownout controller's
        # leading overload indicator); must never raise into _pump.
        self.wait_sink: Optional[Callable[[float], None]] = None
        self._seq = 0
        # EWMAs feeding the Retry-After estimate and the status view.
        self._service_ewma: Optional[float] = None
        self._wait_ewma: Optional[float] = None
        self.totals = {
            "admitted": 0,
            "granted": 0,
            "released": 0,
            "rejected_full": 0,
            "rejected_draining": 0,
            "cancelled": 0,
        }

    # --- weights ----------------------------------------------------------

    def weight(self, tenant: str) -> float:
        return self.tenant_weights.get(tenant, 1.0)

    def set_weight(self, tenant: str, weight: float) -> None:
        if weight <= 0:
            raise ValueError("tenant weight must be > 0")
        self.tenant_weights[tenant] = float(weight)

    # --- admission --------------------------------------------------------

    def submit(
        self,
        tenant: str,
        lane: Optional[str] = None,
        cost: float = 1.0,
        trace_id: Optional[str] = None,
    ) -> Ticket:
        """Admit one request; raises AdmissionClosed while draining and
        SchedulerSaturated when the lane is full. The returned ticket's
        `granted()` resolves once a slot is assigned."""
        lane_name = lane or constants.SCHED_DEFAULT_LANE
        lane_state = self.lanes.get(lane_name)
        if lane_state is None:
            # unknown lane → lowest-priority lane, never a hard error —
            # but say so: a typo'd lane silently waiting behind every
            # other class is otherwise undiagnosable (the effective
            # lane is also echoed in the queue response and the ticket)
            log(
                f"scheduler: unknown lane {lane_name!r} from tenant "
                f"{tenant!r}; routed to {self.lane_order[-1]!r}"
            )
            lane_name = self.lane_order[-1]
            lane_state = self.lanes[lane_name]
        if self.state == DRAINING:
            self.totals["rejected_draining"] += 1
            instruments.sched_admissions_total().inc(
                lane=lane_name, tenant=tenant, outcome="rejected_draining"
            )
            raise AdmissionClosed(
                "scheduler is draining; admission closed",
                retry_after=self.estimate_retry_after(lane_name),
            )
        if lane_state.depth() >= lane_state.max_depth:
            self.totals["rejected_full"] += 1
            instruments.sched_admissions_total().inc(
                lane=lane_name, tenant=tenant, outcome="rejected_full"
            )
            raise SchedulerSaturated(
                f"lane {lane_name!r} is full "
                f"({lane_state.max_depth} queued); retry later",
                lane=lane_name,
                retry_after=self.estimate_retry_after(lane_name),
            )
        self._seq += 1
        ticket = Ticket(
            ticket_id=f"t{self._seq}",
            tenant=tenant,
            lane=lane_name,
            cost=max(float(cost), 1e-9),
            trace_id=trace_id,
            submitted_at=self.clock(),
        )
        lane_state.push(ticket)
        self.totals["admitted"] += 1
        instruments.sched_admissions_total().inc(
            lane=lane_name, tenant=tenant, outcome="admitted"
        )
        get_event_bus().publish(
            "sched_admitted",
            ticket_id=ticket.ticket_id,
            tenant=tenant,
            lane=lane_name,
            cost=ticket.cost,
            depth=lane_state.depth(),
        )
        self._pump()
        return ticket

    def cancel(self, ticket: Ticket) -> bool:
        """Withdraw a queued ticket (grant-wait timeout / client gone /
        the DELETE ticket route). A ticket already granted cannot be
        cancelled — release it."""
        if ticket.state != "queued":
            return False
        lane_state = self.lanes.get(ticket.lane)
        if lane_state is None or not lane_state.remove(ticket):
            return False
        ticket.state = "cancelled"
        self.totals["cancelled"] += 1
        instruments.sched_admissions_total().inc(
            lane=ticket.lane, tenant=ticket.tenant, outcome="cancelled"
        )
        # wake a request parked on granted(): it re-checks the state
        # and unwinds as cancelled instead of waiting out the grant
        # timeout (the DELETE route's whole point)
        ticket._granted.set()
        return True

    def find_ticket(self, ticket_id: str) -> Optional[Ticket]:
        """Locate a QUEUED ticket by id (granted/released tickets are
        not findable here — cancellation of granted work goes through
        the job-level cancel seam)."""
        for lane_state in self.lanes.values():
            for queue in lane_state.queues.values():
                for ticket in queue:
                    if ticket.ticket_id == ticket_id:
                        return ticket
        return None

    def cancel_ticket(self, ticket_id: str) -> bool:
        """Pre-admission abandon over HTTP: withdraw one queued ticket
        by id (DELETE /distributed/queue/{ticket_id})."""
        ticket = self.find_ticket(ticket_id)
        if ticket is None:
            return False
        return self.cancel(ticket)

    # --- granting ---------------------------------------------------------

    def _pump(self) -> None:
        """Grant queued tickets into free slots: strict lane priority,
        DRR across tenants within a lane. PAUSED stops granting;
        DRAINING only stops admission, so queued work keeps granting."""
        if self.state == PAUSED:
            return
        while len(self.active) < self.max_active:
            ticket = None
            for lane_name in self.lane_order:
                ticket = self.lanes[lane_name].pop_next(self.quantum, self.weight)
                if ticket is not None:
                    break
            if ticket is None:
                return
            now = self.clock()
            ticket.granted_at = now
            ticket.state = "granted"
            self.active[ticket.ticket_id] = ticket
            self.totals["granted"] += 1
            wait = max(now - ticket.submitted_at, 0.0)
            self._wait_ewma = (
                wait
                if self._wait_ewma is None
                else 0.8 * self._wait_ewma + 0.2 * wait
            )
            if self.wait_sink is not None:
                try:
                    self.wait_sink(wait)
                except Exception:  # noqa: BLE001 - observability only
                    pass
            instruments.sched_grants_total().inc(
                lane=ticket.lane, tenant=ticket.tenant
            )
            instruments.sched_wait_seconds().observe(
                wait, lane=ticket.lane, tenant=ticket.tenant
            )
            get_event_bus().publish(
                "sched_granted",
                ticket_id=ticket.ticket_id,
                tenant=ticket.tenant,
                lane=ticket.lane,
                queue_wait_seconds=wait,
            )
            ticket._granted.set()

    def release(self, ticket: Ticket) -> None:
        """The granted request finished (or failed): free its slot."""
        if self.active.pop(ticket.ticket_id, None) is None:
            return
        ticket.state = "released"
        ticket.released_at = self.clock()
        if ticket.granted_at is not None:
            service = max(ticket.released_at - ticket.granted_at, 0.0)
            self._service_ewma = (
                service
                if self._service_ewma is None
                else 0.8 * self._service_ewma + 0.2 * service
            )
        self.totals["released"] += 1
        self._pump()

    # --- control ----------------------------------------------------------

    def pause(self) -> None:
        if self.state != PAUSED:
            log("scheduler paused: grants withheld, admission open")
        self.state = PAUSED

    def resume(self) -> None:
        if self.state != RUNNING:
            log("scheduler resumed")
        self.state = RUNNING
        self._pump()

    def drain(self) -> None:
        if self.state != DRAINING:
            log("scheduler draining: admission closed, queued work completing")
        self.state = DRAINING
        self._pump()

    def reprioritize(self, ticket_id: str, lane: str) -> bool:
        """Move one queued ticket to another lane (front-of-class
        escalation or demotion); False when not found / not queued."""
        if lane not in self.lanes:
            raise ValueError(f"unknown lane {lane!r}")
        for lane_state in self.lanes.values():
            for queue in lane_state.queues.values():
                for ticket in queue:
                    if ticket.ticket_id == ticket_id:
                        lane_state.remove(ticket)
                        ticket.lane = lane
                        self.lanes[lane].push(ticket)
                        self._pump()
                        return True
        return False

    # --- durability hooks (durability/snapshot.py) ------------------------

    def export_state(self) -> dict:
        """The aggregates worth surviving a master restart: tenant DRR
        deficits (fair-share position), live tenant weights (operator
        retunes via /distributed/scheduler/reprioritize), and the
        admission totals. Queued TICKETS are deliberately absent — they
        wrap asyncio futures of HTTP requests that died with the old
        process; their clients retry against the restarted master."""
        return {
            "tenant_weights": dict(self.tenant_weights),
            "deficits": {
                name: {t: round(d, 9) for t, d in lane.deficit.items()}
                for name, lane in self.lanes.items()
            },
            "totals": dict(self.totals),
        }

    def restore_state(self, state: dict) -> None:
        """Best-effort inverse of export_state onto a fresh queue:
        unknown lanes/keys are skipped (lane specs may change across
        restarts), bad values are ignored — restoring advisory
        aggregates must never be able to wedge admission."""
        for tenant, weight in (state.get("tenant_weights") or {}).items():
            try:
                if float(weight) > 0:
                    self.tenant_weights[str(tenant)] = float(weight)
            except (TypeError, ValueError):
                continue
        for lane_name, deficits in (state.get("deficits") or {}).items():
            lane = self.lanes.get(str(lane_name))
            if lane is None or not isinstance(deficits, dict):
                continue
            for tenant, deficit in deficits.items():
                try:
                    lane.deficit[str(tenant)] = float(deficit)
                except (TypeError, ValueError):
                    continue
        for key, value in (state.get("totals") or {}).items():
            if key in self.totals:
                try:
                    self.totals[key] = int(value)
                except (TypeError, ValueError):
                    continue

    # --- observability ----------------------------------------------------

    def estimate_retry_after(self, lane: str) -> float:
        """Seconds a rejected client should wait: the lane's queued
        cost over the grant rate, bounded to something polite."""
        service = self._service_ewma if self._service_ewma else 1.0
        depth = self.lanes[lane].depth() if lane in self.lanes else 0
        estimate = service * (depth + 1) / max(self.max_active, 1)
        return float(min(max(round(estimate), 1), 60))

    def estimate_wait(self, lane: str) -> float:
        """Estimated queue wait for a request admitted to `lane` NOW —
        the deadline-admission gate's input. Unlike estimate_retry_after
        this is unclamped and may be 0 (empty queue, free slot: no
        wait), so short deadlines pass on an idle scheduler."""
        if len(self.active) < self.max_active and self.queued() == 0:
            return 0.0
        service = self._service_ewma if self._service_ewma else 1.0
        depth = self.lanes[lane].depth() if lane in self.lanes else 0
        backlog = depth + max(0, len(self.active) - self.max_active + 1)
        estimate = service * backlog / max(self.max_active, 1)
        if self._wait_ewma is not None:
            estimate = max(estimate, self._wait_ewma)
        return float(estimate)

    def queued(self) -> int:
        return sum(lane.depth() for lane in self.lanes.values())

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "max_active": self.max_active,
            "active": len(self.active),
            "active_tickets": [
                {
                    "ticket_id": t.ticket_id,
                    "tenant": t.tenant,
                    "lane": t.lane,
                    "cost": t.cost,
                }
                for t in self.active.values()
            ],
            "queued": self.queued(),
            "lanes": [
                {
                    "name": name,
                    "priority": idx,
                    "depth": self.lanes[name].depth(),
                    "max_depth": self.lanes[name].max_depth,
                    "tenants": self.lanes[name].tenants_snapshot(),
                }
                for idx, name in enumerate(self.lane_order)
            ],
            "tenant_weights": dict(self.tenant_weights),
            "quantum": self.quantum,
            "wait_ewma_seconds": self._wait_ewma,
            "service_ewma_seconds": self._service_ewma,
            "totals": dict(self.totals),
        }
