"""Usage-driven autoscaler: SLO burn + chip-second demand → fleet size.

The controller closes the loop between the two measurement planes this
repo already runs and the one actuator it already has:

- **signals** — SLO burn-rate alerts (telemetry/slo.SLOEngine, PR 12):
  an active burn on availability / tile_latency / deadline_miss means
  the fleet is failing users NOW; and measured chip-second demand
  (telemetry/usage.UsageAggregator, PR 15): the delta of attributed
  chip-seconds per evaluation window is the fleet's *actual* load in
  the only unit that survives heterogeneous chips;
- **actuation** — launch one managed local worker through
  workers/process_manager (the workers/startup.py launch path), or
  drain one via its SIGTERM graceful-drain path (PR 10: the in-flight
  grant returns to the master before the process dies).

Policy (deliberately boring — a thermostat, not an optimizer):

- utilization = demand chip-seconds / capacity chip-seconds over the
  window. Above ``CDT_AUTOSCALE_TARGET_UTIL`` (or any burn alert
  active) and below the max: **scale up** immediately.
- Below half the target for ``CDT_AUTOSCALE_DOWN_HOLD`` seconds and
  above the min: **scale down** one worker. Up is twitchy, down is
  patient — the asymmetry is the thrash guard.

Every decision is recorded with its **measured chip-second
cost/benefit**: the demand and capacity chip-seconds of the window
that justified it, and — settled on the NEXT evaluation — the
capacity and demand deltas the action actually bought. An operator
reading ``GET /distributed/autoscale`` sees what each decision cost
and returned in the same unit the tenants are billed in
(docs/operator-runbook.md §autoscaler triage).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ..utils.constants import (
    AUTOSCALE_DOWN_HOLD_SECONDS,
    AUTOSCALE_INTERVAL_SECONDS,
    AUTOSCALE_MAX_WORKERS,
    AUTOSCALE_MIN_WORKERS,
    AUTOSCALE_TARGET_UTILIZATION,
)
from ..utils.logging import debug_log, log

# burn alerts that indicate capacity pressure (journal_latency burns
# point at the disk, not the fleet — more workers would make it worse)
SCALE_UP_ALERTS = ("availability", "tile_latency", "deadline_miss")
DECISION_HISTORY = 256


class AutoscaleController:
    """One master's scale-up/down loop.

    ``launcher()`` brings up one worker and returns its id (None when
    nothing launchable remains); ``drainer()`` drains one worker and
    returns its id (None when nothing drainable). ``capacity_fn()``
    returns (worker_count, chip_count) — the denominator of
    utilization in chips. All three are injected so the chaos suite
    and unit tests can run the policy against fakes with a fake
    clock."""

    def __init__(
        self,
        *,
        slo: Any = None,
        usage: Any = None,
        launcher: Optional[Callable[[], Optional[str]]] = None,
        drainer: Optional[Callable[[], Optional[str]]] = None,
        capacity_fn: Optional[Callable[[], tuple[int, float]]] = None,
        clock: Callable[[], float] = time.time,
        interval: Optional[float] = None,
        min_workers: Optional[int] = None,
        max_workers: Optional[int] = None,
        target_util: Optional[float] = None,
        down_hold: Optional[float] = None,
    ) -> None:
        self.slo = slo
        self.usage = usage
        self.launcher = launcher
        self.drainer = drainer
        self.capacity_fn = capacity_fn
        self.clock = clock
        self.interval = (
            float(interval) if interval is not None
            else AUTOSCALE_INTERVAL_SECONDS
        )
        self.min_workers = (
            int(min_workers) if min_workers is not None
            else AUTOSCALE_MIN_WORKERS
        )
        self.max_workers = (
            int(max_workers) if max_workers is not None
            else AUTOSCALE_MAX_WORKERS
        )
        self.target_util = (
            float(target_util) if target_util is not None
            else AUTOSCALE_TARGET_UTILIZATION
        )
        self.down_hold = (
            float(down_hold) if down_hold is not None
            else AUTOSCALE_DOWN_HOLD_SECONDS
        )
        self._lock = threading.Lock()
        self.decisions: deque[dict[str, Any]] = deque(maxlen=DECISION_HISTORY)
        self._prev_demand_total: Optional[float] = None
        self._prev_step_at: Optional[float] = None
        self._low_util_since: Optional[float] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # --- signal reads -----------------------------------------------------

    def _demand_total_chip_s(self) -> float:
        """Cumulative attributed chip-seconds, fleet-wide (monotonic:
        deltas between evaluations are the window's demand)."""
        if self.usage is None:
            return 0.0
        try:
            return float(self.usage.rollup()["totals"]["chip_s"])
        except Exception as exc:  # noqa: BLE001 - a signal, not a fault
            debug_log(f"autoscale: usage read failed: {exc}")
            return 0.0

    def _burn_alerts(self) -> list[str]:
        if self.slo is None:
            return []
        try:
            return [
                name for name in SCALE_UP_ALERTS if self.slo.is_active(name)
            ]
        except Exception as exc:  # noqa: BLE001
            debug_log(f"autoscale: slo read failed: {exc}")
            return []

    def _capacity(self) -> tuple[int, float]:
        if self.capacity_fn is not None:
            try:
                workers, chips = self.capacity_fn()
                return int(workers), float(chips)
            except Exception as exc:  # noqa: BLE001
                debug_log(f"autoscale: capacity read failed: {exc}")
        return 0, 0.0

    # --- the evaluation ----------------------------------------------------

    def step(self) -> dict[str, Any]:
        """One evaluation: read signals, decide, actuate, record. The
        record's ``measured`` block for the PREVIOUS decision is
        settled here — cost/benefit in chip-seconds is only knowable
        one window later."""
        now = self.clock()
        demand_total = self._demand_total_chip_s()
        workers, chips = self._capacity()
        elapsed = (
            now - self._prev_step_at
            if self._prev_step_at is not None
            else self.interval
        )
        elapsed = max(elapsed, 1e-9)
        demand_chip_s = (
            max(0.0, demand_total - self._prev_demand_total)
            if self._prev_demand_total is not None
            else 0.0
        )
        capacity_chip_s = max(chips, 0.0) * elapsed
        utilization = (
            demand_chip_s / capacity_chip_s if capacity_chip_s > 0 else 0.0
        )
        burn = self._burn_alerts()

        action, reason, target = self._decide(
            now, workers, utilization, burn
        )
        record: dict[str, Any] = {
            "ts": now,
            "action": action,
            "reason": reason,
            "worker": target,
            "workers": workers,
            "chips": chips,
            "window_s": round(elapsed, 3),
            "demand_chip_s": round(demand_chip_s, 6),
            "capacity_chip_s": round(capacity_chip_s, 6),
            "utilization": round(utilization, 4),
            "burn_alerts": burn,
            # settled by the NEXT step: what the action actually bought
            "measured": None,
        }
        with self._lock:
            if self.decisions:
                prev = self.decisions[-1]
                prev["measured"] = {
                    "capacity_delta_chip_s": round(
                        capacity_chip_s - prev["capacity_chip_s"], 6
                    ),
                    "demand_delta_chip_s": round(
                        demand_chip_s - prev["demand_chip_s"], 6
                    ),
                    "utilization_after": round(utilization, 4),
                }
            self.decisions.append(record)
        self._prev_demand_total = demand_total
        self._prev_step_at = now
        if action != "hold":
            log(
                f"autoscale: {action} ({reason}) — util "
                f"{utilization:.2f}, demand {demand_chip_s:.2f} chip-s / "
                f"capacity {capacity_chip_s:.2f} chip-s, "
                f"burn={burn or 'none'}"
            )
        return record

    def _decide(
        self,
        now: float,
        workers: int,
        utilization: float,
        burn: list[str],
    ) -> tuple[str, str, Optional[str]]:
        pressured = bool(burn) or utilization > self.target_util
        if pressured:
            self._low_util_since = None
            if workers >= self.max_workers:
                return "hold", "pressure at max_workers", None
            if self.launcher is None:
                return "hold", "pressure but no launcher", None
            target = self._actuate(self.launcher, "launch")
            if target is None:
                return "hold", "pressure but nothing launchable", None
            reason = (
                f"burn:{','.join(burn)}" if burn
                else f"utilization {utilization:.2f} > {self.target_util:.2f}"
            )
            return "scale_up", reason, target
        if utilization < self.target_util / 2.0 and workers > self.min_workers:
            if self._low_util_since is None:
                self._low_util_since = now
            held = now - self._low_util_since
            if held < self.down_hold:
                return (
                    "hold",
                    f"low utilization held {held:.0f}s/"
                    f"{self.down_hold:.0f}s",
                    None,
                )
            if self.drainer is None:
                return "hold", "idle but no drainer", None
            target = self._actuate(self.drainer, "drain")
            if target is None:
                return "hold", "idle but nothing drainable", None
            self._low_util_since = None
            return (
                "scale_down",
                f"utilization {utilization:.2f} < "
                f"{self.target_util / 2.0:.2f} for {self.down_hold:.0f}s",
                target,
            )
        self._low_util_since = None
        return "hold", "within band", None

    @staticmethod
    def _actuate(fn: Callable[[], Optional[str]], what: str) -> Optional[str]:
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 - actuation is best effort
            log(f"autoscale: {what} failed: {exc}")
            return None

    # --- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.step()
                except Exception as exc:  # noqa: BLE001 - keep looping
                    debug_log(f"autoscale step failed: {exc}")

        self._thread = threading.Thread(
            target=run, name="cdt-autoscale", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # --- introspection -----------------------------------------------------

    def status(self, limit: int = 32) -> dict[str, Any]:
        with self._lock:
            recent = list(self.decisions)[-max(1, int(limit)):]
        workers, chips = self._capacity()
        return {
            "enabled": True,
            "interval_s": self.interval,
            "bounds": {"min": self.min_workers, "max": self.max_workers},
            "target_utilization": self.target_util,
            "down_hold_s": self.down_hold,
            "workers": workers,
            "chips": chips,
            "decisions": recent,
        }


def managed_worker_actuators(
    config_path: Optional[str] = None,
) -> tuple[Callable[[], Optional[str]], Callable[[], Optional[str]],
           Callable[[], tuple[int, float]]]:
    """(launcher, drainer, capacity_fn) over the managed local-worker
    pool: launch the first enabled-but-not-running local config entry,
    drain (SIGTERM → graceful drain → stop) the most recently launched
    one, count capacity as running workers × their configured chips."""
    from ..utils import config as config_mod
    from .. import workers as _workers  # noqa: F401 - package anchor
    from ..workers.process_manager import get_worker_manager

    def _entries() -> list[dict[str, Any]]:
        config = config_mod.load_config(config_path)
        return [
            w for w in config.get("workers", [])
            if w.get("type") in ("local",)
        ]

    def _running() -> dict[str, Any]:
        manager = get_worker_manager()
        return manager.managed_processes(config_path)

    def launcher() -> Optional[str]:
        manager = get_worker_manager()
        running = _running()
        for worker in _entries():
            worker_id = str(worker.get("id") or worker.get("name") or "")
            if not worker_id or worker_id in running:
                continue
            if not worker.get("enabled"):
                continue
            manager.launch_worker(worker, config_path)
            return worker_id
        return None

    def drainer() -> Optional[str]:
        manager = get_worker_manager()
        running = _running()
        if not running:
            return None
        worker_id = sorted(running)[-1]
        # stop_worker's kill tree leads with SIGTERM: the worker's
        # registered drain handler (workers/startup.py) finishes the
        # in-flight device batch and returns unprocessed tiles first
        manager.stop_worker(worker_id, config_path)
        return worker_id

    def capacity_fn() -> tuple[int, float]:
        running = _running()
        chips_by_id = {
            str(w.get("id") or w.get("name") or ""):
                max(1, len(w.get("tpu_chips") or [0]))
            for w in _entries()
        }
        chips = sum(chips_by_id.get(wid, 1) for wid in running)
        return len(running), float(chips)

    return launcher, drainer, capacity_fn


__all__ = ["AutoscaleController", "managed_worker_actuators"]
