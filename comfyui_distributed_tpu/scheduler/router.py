"""Lease-aware shard router: jobs → masters, addresses → health.

Region mode runs M master shards (each with its own WAL + standby pair
and its own lease), and this module is the thin layer that decides,
for every job and every RPC, which address to talk to:

- ``ShardRing`` — consistent hashing with virtual nodes: a job id maps
  to one shard, the mapping is stable across processes (md5, not
  Python's salted ``hash``), and adding/removing a shard reshuffles
  only ~1/M of the keys;
- ``EndpointRotation`` — per-URL failure backoff + epoch tracking for
  one shard's address list (active first, standbys after). This
  replaces the worker client's old single rotation cursor: a dead or
  lagging address sits out an exponential backoff window while pulls
  continue against healthy addresses, and re-pointing prefers the
  address that last reported the highest fencing epoch (the promoted
  master, not a random next-in-list);
- ``ShardRouter`` — the map from job ids to shards plus the per-shard
  health/epoch view the ``/distributed/region`` route serves.

One shard's failover or brownout never stalls the others: rotation
state is per shard per address, and the ring never consults health —
placement of a job on a shard is a pure function of its id, so every
participant (workers, the soak harness, a restarted master) computes
the same answer without coordination.
"""

from __future__ import annotations

import hashlib
import time
from bisect import bisect_right
from typing import Any, Callable, Optional

from ..utils.constants import (
    ROUTER_BACKOFF_BASE_SECONDS,
    ROUTER_BACKOFF_CAP_SECONDS,
    SHARD_VNODES,
)
from ..utils.logging import log


class EndpointState:
    """One master address's health ledger."""

    __slots__ = ("url", "fails", "bursts", "backoff_until", "epoch", "last_ok")

    def __init__(self, url: str) -> None:
        self.url = url
        self.fails = 0          # consecutive failures while current
        self.bursts = 0         # threshold crossings (backoff exponent)
        self.backoff_until = 0.0
        self.epoch: Optional[int] = None  # highest epoch it reported
        self.last_ok = 0.0

    def as_dict(self, now: float) -> dict[str, Any]:
        return {
            "url": self.url,
            "fails": self.fails,
            "backoff_remaining_s": round(max(0.0, self.backoff_until - now), 3),
            "epoch": self.epoch,
        }


class EndpointRotation:
    """Per-URL backoff + epoch tracking over one address list.

    The contract the old global cursor provided is preserved —
    ``CDT_FAILOVER_AFTER`` consecutive failures against the current
    address re-point to another — but failure history is now per
    address: a re-pointed-away-from address carries an exponential
    backoff window (``CDT_ROUTER_BACKOFF_BASE`` · 2^bursts, capped at
    ``CDT_ROUTER_BACKOFF_CAP``) so rotation never lands back on a
    known-dead address while a healthy one exists, and any successful
    response resets that address's schedule. Selection prefers
    non-backed-off addresses reporting the highest fencing epoch (the
    freshest master); when everything is backing off it takes the
    address whose window expires soonest.
    """

    def __init__(
        self,
        urls: list[str],
        threshold: Optional[int] = None,
        backoff_base: Optional[float] = None,
        backoff_cap: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.urls = [str(u) for u in urls] or ["http://127.0.0.1:8188"]
        self._threshold = threshold
        self.backoff_base = (
            backoff_base if backoff_base is not None
            else ROUTER_BACKOFF_BASE_SECONDS
        )
        self.backoff_cap = (
            backoff_cap if backoff_cap is not None
            else ROUTER_BACKOFF_CAP_SECONDS
        )
        self.clock = clock
        self._states = {u: EndpointState(u) for u in self.urls}
        self._idx = 0

    @property
    def threshold(self) -> int:
        # resolved per call so tests can monkeypatch the constants module
        if self._threshold is not None:
            return max(1, self._threshold)
        from ..utils import constants

        return max(1, constants.FAILOVER_AFTER_ERRORS)

    @property
    def current(self) -> str:
        return self.urls[self._idx % len(self.urls)]

    @property
    def current_state(self) -> EndpointState:
        return self._states[self.current]

    def note_success(self) -> None:
        state = self.current_state
        state.fails = 0
        state.bursts = 0
        state.backoff_until = 0.0
        state.last_ok = self.clock()

    def learn_epoch(self, epoch: int) -> None:
        state = self.current_state
        if state.epoch is None or epoch > state.epoch:
            state.epoch = epoch

    def note_failure(self) -> bool:
        """One failure against the current address. Returns True when
        the threshold tripped and the rotation re-pointed (the caller
        logs/meters the failover); always False with one address."""
        state = self.current_state
        state.fails += 1
        if len(self.urls) < 2 or state.fails < self.threshold:
            return False
        now = self.clock()
        window = min(
            self.backoff_cap, self.backoff_base * (2.0 ** state.bursts)
        )
        state.bursts += 1
        state.fails = 0
        state.backoff_until = now + window
        self._idx = self.urls.index(self._select_next(now))
        return True

    def _select_next(self, now: float) -> str:
        """The re-point target: rotation order from the current
        address, healthy (not backing off) first, highest known epoch
        among the healthy; all-backing-off falls back to the earliest
        window expiry — never a hard stall."""
        start = self._idx % len(self.urls)
        order = [
            self.urls[(start + offset) % len(self.urls)]
            for offset in range(1, len(self.urls) + 1)
        ][:-1]  # every address except the current one
        healthy = [u for u in order if self._states[u].backoff_until <= now]
        if healthy:
            best = max(self._states[u].epoch or 0 for u in healthy)
            for url in healthy:
                if (self._states[url].epoch or 0) == best:
                    return url
        return min(order, key=lambda u: self._states[u].backoff_until)

    def snapshot(self) -> list[dict[str, Any]]:
        now = self.clock()
        out = []
        for url in self.urls:
            entry = self._states[url].as_dict(now)
            entry["current"] = url == self.current
            out.append(entry)
        return out


class ShardRing:
    """Consistent-hash ring: stable job→shard placement with bounded
    reshuffle on membership change. md5 keeps the mapping identical
    across processes and restarts (Python's ``hash`` is salted)."""

    def __init__(
        self, shards: list[str], vnodes: Optional[int] = None
    ) -> None:
        self.vnodes = max(1, vnodes if vnodes is not None else SHARD_VNODES)
        self._points: list[tuple[int, str]] = []
        self.shards: list[str] = []
        for shard in shards:
            self.add(shard)

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.md5(key.encode("utf-8")).digest()[:8], "big"
        )

    def add(self, shard: str) -> None:
        if shard in self.shards:
            return
        self.shards.append(shard)
        for v in range(self.vnodes):
            self._points.append((self._hash(f"{shard}#{v}"), shard))
        self._points.sort()

    def remove(self, shard: str) -> None:
        if shard not in self.shards:
            return
        self.shards.remove(shard)
        self._points = [(h, s) for h, s in self._points if s != shard]

    def shard_for(self, key: str) -> str:
        if not self._points:
            raise ValueError("shard ring is empty")
        h = self._hash(str(key))
        idx = bisect_right([p[0] for p in self._points], h)
        return self._points[idx % len(self._points)][1]


class ShardInfo:
    """One shard's addresses + rotation + lease view."""

    def __init__(self, name: str, urls: list[str]) -> None:
        self.name = name
        self.urls = list(urls)
        self.rotation = EndpointRotation(self.urls)
        self.epoch: Optional[int] = None  # highest fencing epoch seen

    def note_epoch(self, epoch) -> None:
        try:
            value = int(epoch)
        except (TypeError, ValueError):
            return
        if value > 0 and (self.epoch is None or value > self.epoch):
            self.epoch = value
            self.rotation.learn_epoch(value)

    def as_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "urls": list(self.urls),
            "epoch": self.epoch,
            "endpoints": self.rotation.snapshot(),
        }


class ShardRouter:
    """job id → shard → address list, with the per-shard epoch/health
    view the region routes serve. Construction from the CDT_SHARDS
    spec (shards ';'-separated, each a comma list) or an explicit
    ``{name: [urls]}`` map; an empty spec is the unsharded topology
    (``enabled`` False, every job routes to the single master)."""

    def __init__(
        self,
        shard_map: Optional[dict[str, list[str]]] = None,
        vnodes: Optional[int] = None,
    ) -> None:
        self.shards: dict[str, ShardInfo] = {
            name: ShardInfo(name, urls)
            for name, urls in (shard_map or {}).items()
        }
        self.ring = ShardRing(sorted(self.shards), vnodes=vnodes)

    @classmethod
    def from_spec(
        cls, spec: str, vnodes: Optional[int] = None
    ) -> "ShardRouter":
        from ..utils.network import parse_master_urls

        shard_map: dict[str, list[str]] = {}
        for i, group in enumerate(g for g in spec.split(";") if g.strip()):
            urls = parse_master_urls(group)
            if urls:
                shard_map[f"shard{i}"] = urls
        return cls(shard_map, vnodes=vnodes)

    @classmethod
    def from_env(cls) -> "ShardRouter":
        # resolved per call so tests (and workers spawned with a
        # different CDT_SHARDS) see the current knob, not import-time
        from ..utils import constants

        return cls.from_spec(constants.SHARDS_SPEC)

    @property
    def enabled(self) -> bool:
        return bool(self.shards)

    def shard_for(self, job_id: str) -> str:
        return self.ring.shard_for(job_id)

    def route(self, job_id: str) -> ShardInfo:
        return self.shards[self.shard_for(job_id)]

    def addresses_for(self, job_id: str) -> str:
        """The comma list the worker client consumes for this job —
        the multiplexing seam: each of a worker's jobs pulls from its
        own shard's addresses, so one shard's outage backs off only
        that shard's endpoints."""
        return ",".join(self.route(job_id).urls)

    def client_for(self, job_id: str, worker_id: str, devices: int = 1):
        """An HTTPWorkClient bound to the job's shard."""
        from ..graph.usdu_elastic import HTTPWorkClient

        return HTTPWorkClient(
            self.addresses_for(job_id), job_id, worker_id, devices=devices
        )

    def note_epoch(self, shard_name: str, epoch) -> None:
        info = self.shards.get(shard_name)
        if info is not None:
            info.note_epoch(epoch)

    def rebalance(self, name: str, urls: Optional[list[str]]) -> None:
        """Add (urls given) or remove (None) one shard. Logged: a
        membership change reshuffles ~1/M of the job space."""
        if urls is None:
            self.shards.pop(name, None)
            self.ring.remove(name)
            log(f"shard router: removed shard {name}")
            return
        self.shards[name] = ShardInfo(name, urls)
        self.ring.add(name)
        log(f"shard router: added shard {name} -> {urls}")

    def status(self) -> dict[str, Any]:
        return {
            "enabled": self.enabled,
            "vnodes": self.ring.vnodes,
            "shards": {
                name: info.as_dict()
                for name, info in sorted(self.shards.items())
            },
        }


__all__ = [
    "EndpointRotation",
    "EndpointState",
    "ShardInfo",
    "ShardRing",
    "ShardRouter",
]
