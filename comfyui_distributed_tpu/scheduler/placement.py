"""Cost-aware work placement: who pulls how much, and who sits out.

The elastic tile queue is pull-based — workers claim work at their own
pace — which self-balances in the mean but wastes the tail: a slow or
suspect worker that claims one of the last tiles holds the whole job's
latency hostage (the straggler problem the watchdog *detects* after
the fact). This policy closes the loop *before* assignment:

- **throughput weights** — an EWMA over each worker's pull→submit tile
  latencies (the same stream the watchdog consumes; the JobStore's
  ``latency_sink`` fans out to both). A worker's *speed* is 1/EWMA,
  normalized against the fleet mean, so weights are self-calibrating
  across models and tile sizes;
- **size-aware batches** — ``batch_size`` scales a worker's pull batch
  with its relative speed (base x speed, clamped to
  [1, CDT_SCHED_MAX_PULL_BATCH]), replacing the fixed per-pull split:
  fast workers amortize RPC overhead over more tiles, slow workers
  stay at 1 so a requeue never orphans a big batch. Analytic tile-FLOP
  estimates (ops/costs.py) convert heterogeneous tile sizes into one
  cost currency when a job carries per-task costs;
- **tail trimming** — inside the last ``CDT_SCHED_TAIL_TILES`` pending
  tiles, workers that are SUSPECT/QUARANTINED in the health registry
  or slower than ``CDT_SCHED_TRIM_RATIO`` x the mean speed are denied
  pulls (their pull reads as drained), steering the job's tail to fast
  healthy participants. Exempt ids (the master) are never denied —
  someone must always be able to finish the job.

Thread-safe: ``record_latency`` arrives from the store's sink on
arbitrary threads; decisions run on the server loop.

Determinism: placement changes WHO computes a tile, never the result —
per-tile noise keys and the deterministic blend canvas make the output
independent of assignment (asserted by tests/test_chaos_usdu.py).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ..utils import constants

# Sanity ceiling on a worker's advertised chip count. The field rides
# an untrusted client RPC and multiplies the server-side grant cap
# (batch_size clamps to max_batch x capacity), so without a bound one
# bogus worker could be granted an entire job's queue in one pull.
# Real TPU hosts top out well below this.
MAX_WORKER_DEVICES = 64

# Bound on distinct worker ids whose capacity is tracked (and persisted
# via export_state): capacity arrives on unauthenticated heartbeats, so
# a client cycling worker ids must not grow master memory or durability
# snapshots without limit. Far above any real fleet.
MAX_TRACKED_WORKERS = 1024


class PlacementPolicy:
    def __init__(
        self,
        health: Any = None,
        alpha: float | None = None,
        min_samples: int | None = None,
        base_batch: int | None = None,
        max_batch: int | None = None,
        tail_tiles: int | None = None,
        trim_ratio: float | None = None,
        exempt: tuple[str, ...] = ("master",),
        task_cost_flops: float | None = None,
    ) -> None:
        self.health = health
        self.alpha = alpha if alpha is not None else constants.SCHED_EWMA_ALPHA
        self.min_samples = (
            min_samples if min_samples is not None else constants.SCHED_MIN_SAMPLES
        )
        self.base_batch = (
            base_batch if base_batch is not None else constants.SCHED_BASE_PULL_BATCH
        )
        self.max_batch = (
            max_batch if max_batch is not None else constants.SCHED_MAX_PULL_BATCH
        )
        self.tail_tiles = (
            tail_tiles if tail_tiles is not None else constants.SCHED_TAIL_TILES
        )
        self.trim_ratio = (
            trim_ratio if trim_ratio is not None else constants.SCHED_TRIM_RATIO
        )
        self.exempt = frozenset(exempt)
        # One task's estimated FLOPs (ops/costs.analytic_tile_flops);
        # informational in the snapshot and the currency batch sizing
        # would use for heterogeneous tasks.
        self.task_cost_flops = task_cost_flops
        self._lock = threading.Lock()
        self._ewma: dict[str, float] = {}
        self._samples: dict[str, int] = {}
        self._trimmed: dict[str, int] = {}
        # advertised chip counts (worker mesh data-axis width), fed by
        # the pull/heartbeat RPCs through JobStore.note_worker_capacity
        self._capacity: dict[str, int] = {}
        # Departed-worker seam: called (outside the lock) with every
        # worker id this policy forgets or evicts, so downstream
        # consumers keyed by worker id (the fleet registry's per-worker
        # series) drop their state in the same breath.
        self.on_forget: Optional[Any] = None

    # --- inputs -----------------------------------------------------------

    def record_latency(self, worker_id: str, seconds: float) -> None:
        """One completed task's pull→submit latency (JobStore sink)."""
        seconds = max(float(seconds), 1e-6)
        with self._lock:
            prev = self._ewma.get(worker_id)
            self._ewma[worker_id] = (
                seconds
                if prev is None
                else (1.0 - self.alpha) * prev + self.alpha * seconds
            )
            self._samples[worker_id] = self._samples.get(worker_id, 0) + 1

    def set_capacity(self, worker_id: str, devices: int) -> None:
        """Advertised grant capacity (chip count) for a worker — the
        data-axis width of its local mesh, carried on every pull and
        heartbeat. Scales the pull-batch ceiling and the cold-start
        grant size so a 4-chip worker pulls ~4x the tiles of a 1-chip
        worker at equal per-chip speed. Clamped to MAX_WORKER_DEVICES:
        the value originates in a client RPC and multiplies server-side
        grant caps, so it must never be unbounded."""
        devices = max(1, min(int(devices), MAX_WORKER_DEVICES))
        stale = None
        with self._lock:
            if (
                worker_id not in self._capacity
                and len(self._capacity) >= MAX_TRACKED_WORKERS
            ):
                # evict a worker with no latency history first (likely
                # garbage ids), else the oldest-tracked one
                stale = next(
                    (w for w in self._capacity if w not in self._ewma),
                    next(iter(self._capacity)),
                )
                self._capacity.pop(stale)
            self._capacity[worker_id] = devices
        if stale is not None:
            self._notify_forget(stale)

    def capacity(self, worker_id: str) -> int:
        with self._lock:
            return self._capacity.get(worker_id, 1)

    def forget(self, worker_id: str) -> None:
        with self._lock:
            self._ewma.pop(worker_id, None)
            self._samples.pop(worker_id, None)
            self._trimmed.pop(worker_id, None)
            self._capacity.pop(worker_id, None)
        self._notify_forget(worker_id)

    def _notify_forget(self, worker_id: str) -> None:
        hook = self.on_forget
        if hook is None:
            return
        try:
            hook(worker_id)
        except Exception:  # noqa: BLE001 - advisory fan-out only
            pass

    # --- model ------------------------------------------------------------

    def _speeds_locked(self) -> dict[str, float]:
        """worker → tiles/sec for workers with enough samples."""
        return {
            wid: 1.0 / ewma
            for wid, ewma in self._ewma.items()
            if self._samples.get(wid, 0) >= self.min_samples and ewma > 0
        }

    @staticmethod
    def _fleet_ratio(speeds: dict[str, float], worker_id: str) -> float:
        """``speeds[worker_id]`` relative to the fleet mean; 1.0 while
        this worker (or the fleet) lacks samples — unknown workers are
        assumed average, so cold-start behavior is exactly the old
        uniform pull."""
        mine = speeds.get(worker_id)
        if mine is None or not speeds:
            return 1.0
        mean = sum(speeds.values()) / len(speeds)
        if mean <= 0:
            return 1.0
        return mine / mean

    def speed_ratio(self, worker_id: str) -> float:
        """This worker's throughput relative to the fleet mean."""
        with self._lock:
            speeds = self._speeds_locked()
        return self._fleet_ratio(speeds, worker_id)

    def per_chip_ratio(self, worker_id: str) -> float:
        """Measured speed per advertised chip, normalized against the
        fleet's per-chip mean. This is the capacity-neutral quality
        signal: a 4-chip worker's amortized per-tile latency is ~4x
        smaller than an equal-chip 1-chip worker's, so raw throughput
        ratios would double-count capacity once `batch_size` multiplies
        by it — and the job tail (grants of one tile) runs on ONE chip,
        so tail trimming must compare chips, not fleets."""
        with self._lock:
            speeds = self._speeds_locked()
            caps = dict(self._capacity)
        per_chip = {
            wid: speed / max(1, caps.get(wid, 1))
            for wid, speed in speeds.items()
        }
        return self._fleet_ratio(per_chip, worker_id)

    # --- decisions --------------------------------------------------------

    def batch_size(self, worker_id: str, remaining: int) -> int:
        """How many tasks this worker's pull may claim at once.

        Sizes are aligned DOWN to a power of two so a speed-scaled
        grant lands exactly on a tile-processor shape bucket the worker
        has already compiled (ops/upscale.grant_buckets = powers of two
        plus the executor's K_max), instead of paying wraparound
        padding (or a fresh compile) on every oddly-sized grant. Pure
        powers of two — NOT grant_buckets(self.max_batch) — because the
        pull cap and the executor's CDT_TILE_BATCH are separate knobs
        (and may even differ per worker platform): every pow2 grant is
        a bucket under ANY K_max, either directly or after the executor
        splits it into K_max-sized chunks whose pow2 remainders are
        buckets too. The ragged job tail still produces sub-bucket
        grants; the executor pads those.

        Advertised capacity multiplies both the sized grant and its
        ceiling: a D-chip worker's per-chip speed ratio x base_batch x
        D, clamped to max_batch x D — so a 4-chip worker pulls 4x the
        tiles of an equal-per-chip-speed 1-chip worker from its very
        first grant (the capacity is advertised before any latency
        sample exists), and the measured per-chip ratio then corrects
        for actual chip quality without double-counting capacity.
        """
        if remaining <= 0:
            return 1
        if remaining <= self.tail_tiles:
            return 1  # tail tiles are precious: no batch hoarding
        cap = self.capacity(worker_id)
        ratio = self.per_chip_ratio(worker_id)
        size = max(
            1,
            min(
                int(round(ratio * self.base_batch * cap)),
                self.max_batch * cap,
            ),
        )
        aligned = 1
        while aligned * 2 <= size:
            aligned *= 2
        return min(aligned, remaining)

    def _health_state(self, worker_id: str) -> Optional[str]:
        if self.health is None:
            return None
        try:
            state = self.health.state(worker_id)
        except Exception:  # noqa: BLE001 - advisory only
            return None
        return getattr(state, "value", state)

    def may_pull(self, worker_id: str, remaining: int) -> bool:
        """False = this pull reads as drained (the worker finishes its
        in-flight work and exits). Only ever False in the job tail, and
        never for exempt participants."""
        if worker_id in self.exempt:
            return True
        if remaining <= 0 or remaining > self.tail_tiles:
            return True
        state = self._health_state(worker_id)
        if state in ("suspect", "quarantined", "probing"):
            self._note_trim(worker_id)
            return False
        # per-chip, not throughput: a tail grant is one tile on one
        # chip, so chip quality decides who should run it (a slow
        # 4-chip worker must not hide behind its aggregate throughput)
        if self.per_chip_ratio(worker_id) < self.trim_ratio:
            self._note_trim(worker_id)
            return False
        return True

    def _note_trim(self, worker_id: str) -> None:
        with self._lock:
            self._trimmed[worker_id] = self._trimmed.get(worker_id, 0) + 1

    # --- push-mode grants (CDT_PUSH_GRANTS) -------------------------------

    def notify_grants(self, job_id: str, count: int) -> None:
        """Push-mode grant dispatch: announce that `count` tasks just
        became pullable on `job_id`. Published as a `grant_available`
        event on the process bus — workers holding the
        /distributed/events WebSocket wake and pull immediately instead
        of discovering the work on their next poll, which is what cuts
        grant RTT (no poll-interval quantization) and idle poll volume
        (no empty request_image round-trips while the queue is dry).
        The JobStore fires this hook on every pending-queue refill
        (init, timeout/quarantine requeue, voluntary release,
        speculation); it must never block — the bus is lock-light and
        drops to a no-op with zero subscribers."""
        from ..telemetry import instruments
        from ..telemetry.events import get_event_bus

        count = max(0, int(count))
        if count == 0:
            return
        instruments.push_grants_total().inc(count)
        get_event_bus().publish("grant_available", job_id=job_id, tasks=count)

    # --- durability hooks (durability/snapshot.py) ------------------------

    def export_state(self) -> dict:
        """Per-worker speed model (EWMA + sample counts) for the
        control-plane snapshot: a restarted master places work with
        learned weights immediately instead of re-learning the fleet
        from uniform cold start."""
        with self._lock:
            return {
                "ewma": {w: round(v, 9) for w, v in self._ewma.items()},
                "samples": dict(self._samples),
                "capacity": dict(self._capacity),
            }

    def restore_state(self, state: dict) -> None:
        with self._lock:
            for worker_id, value in (state.get("ewma") or {}).items():
                try:
                    if float(value) > 0:
                        self._ewma[str(worker_id)] = float(value)
                except (TypeError, ValueError):
                    continue
            for worker_id, count in (state.get("samples") or {}).items():
                try:
                    self._samples[str(worker_id)] = int(count)
                except (TypeError, ValueError):
                    continue
            for worker_id, devices in (state.get("capacity") or {}).items():
                if len(self._capacity) >= MAX_TRACKED_WORKERS:
                    break
                try:
                    self._capacity[str(worker_id)] = max(
                        1, min(int(devices), MAX_WORKER_DEVICES)
                    )
                except (TypeError, ValueError):
                    continue

    # --- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            ewma = dict(self._ewma)
            samples = dict(self._samples)
            trimmed = dict(self._trimmed)
            capacity = dict(self._capacity)
            speeds = self._speeds_locked()
        mean = sum(speeds.values()) / len(speeds) if speeds else 0.0
        return {
            "workers": {
                wid: {
                    "ewma_tile_seconds": (
                        round(ewma[wid], 6) if wid in ewma else None
                    ),
                    "samples": samples.get(wid, 0),
                    "speed_ratio": (
                        round(speeds[wid] / mean, 4)
                        if wid in speeds and mean > 0
                        else None
                    ),
                    "tail_trims": trimmed.get(wid, 0),
                    "devices": capacity.get(wid, 1),
                }
                for wid in sorted(set(ewma) | set(capacity))
            },
            "base_batch": self.base_batch,
            "max_batch": self.max_batch,
            "tail_tiles": self.tail_tiles,
            "trim_ratio": self.trim_ratio,
            "task_cost_flops": self.task_cost_flops,
        }

    def weights(self) -> dict[str, float]:
        """worker → speed ratio (mean-normalized); status endpoints."""
        with self._lock:
            speeds = self._speeds_locked()
        if not speeds:
            return {}
        mean = sum(speeds.values()) / len(speeds)
        return {wid: round(s / mean, 4) for wid, s in sorted(speeds.items())}
