"""SchedulerControl: the one object a DistributedServer owns.

Couples the admission queue (fair-share grant order, backpressure,
pause/resume/drain) with the placement policy (worker speed weights,
batch sizing, tail trimming) and maps request payloads onto tenants,
lanes, and costs. The `/distributed/scheduler/*` routes
(api/scheduler_routes.py) and the queue route's admission gate
(api/job_routes.py) talk to this, never to the internals directly.
"""

from __future__ import annotations

import enum
import time
from typing import Any, Callable, Optional

from ..telemetry.events import get_event_bus
from ..utils.logging import log
from .brownout import BrownoutController
from .placement import PlacementPolicy
from .queue import AdmissionQueue, DeadlineUnmeetable, SchedulerOverloaded, Ticket


class SchedulerState(enum.Enum):
    RUNNING = "running"
    PAUSED = "paused"
    DRAINING = "draining"


class SchedulerControl:
    def __init__(
        self,
        health: Any = None,
        clock: Callable[[], float] = time.monotonic,
        queue: Optional[AdmissionQueue] = None,
        placement: Optional[PlacementPolicy] = None,
        brownout: Optional[BrownoutController] = None,
    ) -> None:
        self.queue = queue or AdmissionQueue(clock=clock)
        self.placement = placement or PlacementPolicy(health=health)
        # Load-shed controller above the lanes: fed queue waits by the
        # admission queue (and journal-append latencies by the server's
        # DurabilityManager wiring); consulted before every submit.
        self.brownout = brownout or BrownoutController(
            self.queue.lane_order, clock=clock
        )
        self.queue.wait_sink = self.brownout.note_queue_wait
        # Measured-cost seam (CDT_USAGE_COST=1): the server wires this
        # to UsageAggregator.cost_ratio — a tenant's measured
        # chip-seconds-per-tile relative to the fleet mean — so DRR
        # admission cost meters what the tenant actually burns, not
        # just the client's estimated_tiles.
        self.usage_cost: Optional[Callable[[str], float]] = None
        # Admission-gap accounting (the DRR full-cost-until-settle
        # leftover): admission charges the FULL estimated-tiles cost,
        # but tiles later settled from the content-addressed cache
        # never burn chip time — the fair-share meter over-charged the
        # tenant by (settled tiles x per-tile admitted cost). The gap
        # accumulates here (cumulative cost units) and is surfaced as
        # `cdt_cache_unsettled_admission_cost` at scrape. Per-tile cost
        # is the tenant's LAST admitted per-tile cost — bounded map,
        # oldest-admitted evicted (tenant ids arrive from the network).
        self.unsettled_admission_cost = 0.0
        self._tenant_tile_cost: dict[str, float] = {}
        self._max_tenant_tile_cost = 1024
        # Cache-hit admission discount (CDT_CACHE_COST=1): per-tenant
        # admitted-vs-settled tile counters feed a bounded multiplier —
        # a tenant whose recent tiles mostly settle from the tile cache
        # pays proportionally less at admission, floored by
        # CDT_CACHE_COST_FLOOR so a cold burst can't ride an unbounded
        # discount. Counters halve past the window so the hit share
        # tracks RECENT behavior, not all-time history; both maps are
        # bounded like _tenant_tile_cost (tenant ids are network input).
        self._tenant_admitted_tiles: dict[str, float] = {}
        self._tenant_settled_tiles: dict[str, float] = {}
        self._cache_hit_window = 4096.0

    # --- payload mapping --------------------------------------------------

    def resolve_lane(self, lane: Optional[str]) -> str:
        """The lane a payload will actually land in (unknown lanes
        route to the lowest class, exactly as queue.submit does)."""
        from ..utils import constants

        lane_name = lane or constants.SCHED_DEFAULT_LANE
        if lane_name not in self.queue.lanes:
            return self.queue.lane_order[-1]
        return lane_name

    def submit_payload(self, payload: Any) -> Ticket:
        """Admit one parsed QueueRequestPayload. Cost is the request's
        estimated tile count when the client provided one
        (`estimated_tiles` in the body), else 1 — so fair share meters
        tile WORK, and a tenant of huge upscales can't starve a tenant
        of small ones by request-count arithmetic.

        Two lifecycle gates run BEFORE the lane sees the request:

        - **brownout** — a shed lane answers 429 without consuming
          queue depth, a grant slot, or journal bandwidth;
        - **deadline admission** — a request whose end-to-end deadline
          is already unmeetable (estimated queue wait exceeds it)
          answers 429 instead of burning work that must miss.
        """
        lane_name = self.resolve_lane(payload.lane)
        if self.brownout.should_shed(lane_name):
            self.brownout.record_shed(lane_name)
            raise SchedulerOverloaded(
                f"lane {lane_name!r} is shed (brownout level "
                f"{self.brownout.level}); retry later or use a higher "
                "priority lane",
                lane=lane_name,
                retry_after=self.queue.estimate_retry_after(lane_name),
            )
        deadline_s = getattr(payload, "deadline_s", None)
        if deadline_s is not None:
            estimated = self.queue.estimate_wait(lane_name)
            if estimated >= float(deadline_s):
                raise DeadlineUnmeetable(
                    f"deadline {float(deadline_s):g}s cannot be met: "
                    f"estimated queue wait is {estimated:.1f}s",
                    lane=lane_name,
                    retry_after=self.queue.estimate_retry_after(lane_name),
                    deadline_s=float(deadline_s),
                    estimated_wait=estimated,
                )
        cost = 1.0
        tiles = 1.0
        estimated_tiles = payload.extra.get("estimated_tiles")
        try:
            if estimated_tiles is not None and float(estimated_tiles) > 0:
                cost = float(estimated_tiles)
                tiles = float(estimated_tiles)
        except (TypeError, ValueError):
            pass
        cost *= self._measured_cost_ratio(payload.tenant)
        cost *= self._adapter_cost(payload)
        cost *= self._cache_cost(payload.tenant)
        self._note_admitted_cost(payload.tenant, cost / tiles)
        self._note_admitted_tiles(payload.tenant, tiles)
        return self.queue.submit(
            tenant=payload.tenant,
            lane=payload.lane,
            cost=cost,
            trace_id=payload.trace_id,
        )

    def _note_admitted_cost(self, tenant: str, per_tile_cost: float) -> None:
        tenant = str(tenant)
        self._tenant_tile_cost.pop(tenant, None)
        while len(self._tenant_tile_cost) >= self._max_tenant_tile_cost:
            self._tenant_tile_cost.pop(next(iter(self._tenant_tile_cost)))
        self._tenant_tile_cost[tenant] = float(per_tile_cost)

    def note_cache_settled(self, tenant: str, tiles: int) -> float:
        """One cache settle's contribution to the admission gap:
        ``tiles`` of this tenant completed from the tile cache after
        admission charged their full per-tile cost. Returns the gap
        added (cost units). Fed by JobStore.settle_sink; an unknown
        tenant (admitted before this process started, or a direct
        executor call that bypassed admission) charges the static 1.0
        per-tile cost — the same fallback admission itself uses.

        With the CDT_CACHE_COST discount on, the recorded per-tile
        admitted cost already carries the discount, so each settle
        lands a strictly smaller gap on the
        `cdt_cache_unsettled_admission_cost` gauge — admission
        pre-paying the expected hits IS what drops the gauge."""
        tiles = int(tiles)
        if tiles <= 0:
            return 0.0
        per_tile = self._tenant_tile_cost.get(str(tenant), 1.0)
        gap = tiles * per_tile
        self.unsettled_admission_cost += gap
        self._note_settled_tiles(tenant, tiles)
        return gap

    def _cache_cost(self, tenant: str) -> float:
        """The CDT_CACHE_COST multiplier: 1 - (tenant's recent cache-hit
        share), floored by CDT_CACHE_COST_FLOOR. Tiles the cache index
        keeps settling never burn chip time, so charging full freight
        for them at DRR admission double-bills the tenant. 1.0 when the
        knob is off or the tenant has no settle history yet."""
        from ..utils import constants

        if not constants.cache_cost_enabled():
            return 1.0
        admitted = self._tenant_admitted_tiles.get(str(tenant), 0.0)
        settled = self._tenant_settled_tiles.get(str(tenant), 0.0)
        if admitted <= 0.0 or settled <= 0.0:
            return 1.0
        hit_share = min(1.0, settled / admitted)
        return max(constants.cache_cost_floor(), 1.0 - hit_share)

    def _note_admitted_tiles(self, tenant: str, tiles: float) -> None:
        tenant = str(tenant)
        adm = self._tenant_admitted_tiles
        prev = adm.pop(tenant, 0.0)
        while len(adm) >= self._max_tenant_tile_cost:
            adm.pop(next(iter(adm)))
        total = prev + float(tiles)
        if total > self._cache_hit_window:
            # halve BOTH counters so the hit share tracks recent
            # behavior instead of freezing on all-time history
            total *= 0.5
            settled = self._tenant_settled_tiles.get(tenant, 0.0)
            if settled:
                self._tenant_settled_tiles[tenant] = settled * 0.5
        adm[tenant] = total

    def _note_settled_tiles(self, tenant: str, tiles: float) -> None:
        tenant = str(tenant)
        st = self._tenant_settled_tiles
        prev = st.pop(tenant, 0.0)
        while len(st) >= self._max_tenant_tile_cost:
            st.pop(next(iter(st)))
        st[tenant] = prev + float(tiles)

    def _adapter_cost(self, payload: Any) -> float:
        """The CDT_ADAPTER_COLD_COST multiplier: a request whose
        adapter plan is NOT resident in the host operand cache pays a
        cold surcharge at DRR admission — the decode + operand build
        it will trigger is real work the fair-share meter should see.
        1.0 (off by default) when the knob is unset, the request wears
        no adapters, or every adapter is warm. Advisory: a broken
        cache peek must never fail admission."""
        from ..adapters import adapter_admission_cost

        specs = getattr(payload, "adapters", None) or []
        hashes = [
            getattr(s, "content_hash", "") for s in specs
            if getattr(s, "content_hash", "")
        ]
        return adapter_admission_cost(hashes)

    def _measured_cost_ratio(self, tenant: str) -> float:
        """The CDT_USAGE_COST multiplier: the tenant's measured
        chip-seconds-per-tile relative to the fleet mean (clamped by
        the aggregator). 1.0 when the knob is off, the seam is unwired,
        or the ratio is degenerate — the static cost is the fallback,
        never a failure."""
        from ..utils import constants

        if not constants.USAGE_COST_ENABLED or self.usage_cost is None:
            return 1.0
        try:
            ratio = float(self.usage_cost(tenant))
        except Exception as exc:  # noqa: BLE001 - advisory model
            log(f"scheduler: usage cost ratio for {tenant!r} failed: {exc}")
            return 1.0
        if not (ratio > 0.0) or ratio != ratio:
            return 1.0
        return ratio

    # --- state machine ----------------------------------------------------

    @property
    def state(self) -> SchedulerState:
        return SchedulerState(self.queue.state)

    def pause(self) -> SchedulerState:
        self.queue.pause()
        self._publish_state()
        return self.state

    def resume(self) -> SchedulerState:
        self.queue.resume()
        self._publish_state()
        return self.state

    def drain(self) -> SchedulerState:
        self.queue.drain()
        self._publish_state()
        return self.state

    def _publish_state(self) -> None:
        get_event_bus().publish(
            "scheduler_state",
            state=self.queue.state,
            active=len(self.queue.active),
            queued=self.queue.queued(),
        )

    # --- reprioritization -------------------------------------------------

    def reprioritize(
        self,
        ticket_id: Optional[str] = None,
        lane: Optional[str] = None,
        tenant: Optional[str] = None,
        weight: Optional[float] = None,
    ) -> dict:
        """Two shapes: {ticket_id, lane} moves one queued request to
        another priority class; {tenant, weight} retunes a tenant's
        fair share live. Both may appear in one call."""
        moved = None
        if ticket_id is not None:
            if not lane:
                raise ValueError("'lane' is required to move a ticket")
            moved = self.queue.reprioritize(ticket_id, lane)
            if moved:
                log(f"scheduler: ticket {ticket_id} moved to lane {lane!r}")
        if tenant is not None:
            if weight is None:
                raise ValueError("'weight' is required to retune a tenant")
            self.queue.set_weight(tenant, float(weight))
            log(f"scheduler: tenant {tenant!r} weight set to {float(weight):g}")
        return {
            "moved": moved,
            "tenant_weights": dict(self.queue.tenant_weights),
        }

    # --- durability hooks (durability/manager.py) -------------------------

    def export_state(self) -> dict:
        """Sampled into every control-plane snapshot: admission
        aggregates + placement speed model (docs/durability.md)."""
        return {
            "admission": self.queue.export_state(),
            "placement": self.placement.export_state(),
        }

    def restore_state(self, state: dict) -> None:
        admission = state.get("admission")
        if isinstance(admission, dict):
            self.queue.restore_state(admission)
        placement = state.get("placement")
        if isinstance(placement, dict):
            self.placement.restore_state(placement)

    # --- observability ----------------------------------------------------

    def status(self) -> dict:
        return {
            "state": self.queue.state,
            "admission": self.queue.snapshot(),
            "placement": self.placement.snapshot(),
            "worker_weights": self.placement.weights(),
            "brownout": self.brownout.snapshot(),
            "unsettled_admission_cost": round(
                self.unsettled_admission_cost, 6
            ),
        }
