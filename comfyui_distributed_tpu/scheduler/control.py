"""SchedulerControl: the one object a DistributedServer owns.

Couples the admission queue (fair-share grant order, backpressure,
pause/resume/drain) with the placement policy (worker speed weights,
batch sizing, tail trimming) and maps request payloads onto tenants,
lanes, and costs. The `/distributed/scheduler/*` routes
(api/scheduler_routes.py) and the queue route's admission gate
(api/job_routes.py) talk to this, never to the internals directly.
"""

from __future__ import annotations

import enum
import time
from typing import Any, Callable, Optional

from ..telemetry.events import get_event_bus
from ..utils.logging import log
from .placement import PlacementPolicy
from .queue import AdmissionQueue, Ticket


class SchedulerState(enum.Enum):
    RUNNING = "running"
    PAUSED = "paused"
    DRAINING = "draining"


class SchedulerControl:
    def __init__(
        self,
        health: Any = None,
        clock: Callable[[], float] = time.monotonic,
        queue: Optional[AdmissionQueue] = None,
        placement: Optional[PlacementPolicy] = None,
    ) -> None:
        self.queue = queue or AdmissionQueue(clock=clock)
        self.placement = placement or PlacementPolicy(health=health)

    # --- payload mapping --------------------------------------------------

    def submit_payload(self, payload: Any) -> Ticket:
        """Admit one parsed QueueRequestPayload. Cost is the request's
        estimated tile count when the client provided one
        (`estimated_tiles` in the body), else 1 — so fair share meters
        tile WORK, and a tenant of huge upscales can't starve a tenant
        of small ones by request-count arithmetic."""
        cost = 1.0
        estimated = payload.extra.get("estimated_tiles")
        try:
            if estimated is not None and float(estimated) > 0:
                cost = float(estimated)
        except (TypeError, ValueError):
            pass
        return self.queue.submit(
            tenant=payload.tenant,
            lane=payload.lane,
            cost=cost,
            trace_id=payload.trace_id,
        )

    # --- state machine ----------------------------------------------------

    @property
    def state(self) -> SchedulerState:
        return SchedulerState(self.queue.state)

    def pause(self) -> SchedulerState:
        self.queue.pause()
        self._publish_state()
        return self.state

    def resume(self) -> SchedulerState:
        self.queue.resume()
        self._publish_state()
        return self.state

    def drain(self) -> SchedulerState:
        self.queue.drain()
        self._publish_state()
        return self.state

    def _publish_state(self) -> None:
        get_event_bus().publish(
            "scheduler_state",
            state=self.queue.state,
            active=len(self.queue.active),
            queued=self.queue.queued(),
        )

    # --- reprioritization -------------------------------------------------

    def reprioritize(
        self,
        ticket_id: Optional[str] = None,
        lane: Optional[str] = None,
        tenant: Optional[str] = None,
        weight: Optional[float] = None,
    ) -> dict:
        """Two shapes: {ticket_id, lane} moves one queued request to
        another priority class; {tenant, weight} retunes a tenant's
        fair share live. Both may appear in one call."""
        moved = None
        if ticket_id is not None:
            if not lane:
                raise ValueError("'lane' is required to move a ticket")
            moved = self.queue.reprioritize(ticket_id, lane)
            if moved:
                log(f"scheduler: ticket {ticket_id} moved to lane {lane!r}")
        if tenant is not None:
            if weight is None:
                raise ValueError("'weight' is required to retune a tenant")
            self.queue.set_weight(tenant, float(weight))
            log(f"scheduler: tenant {tenant!r} weight set to {float(weight):g}")
        return {
            "moved": moved,
            "tenant_weights": dict(self.queue.tenant_weights),
        }

    # --- durability hooks (durability/manager.py) -------------------------

    def export_state(self) -> dict:
        """Sampled into every control-plane snapshot: admission
        aggregates + placement speed model (docs/durability.md)."""
        return {
            "admission": self.queue.export_state(),
            "placement": self.placement.export_state(),
        }

    def restore_state(self, state: dict) -> None:
        admission = state.get("admission")
        if isinstance(admission, dict):
            self.queue.restore_state(admission)
        placement = state.get("placement")
        if isinstance(placement, dict):
            self.placement.restore_state(placement)

    # --- observability ----------------------------------------------------

    def status(self) -> dict:
        return {
            "state": self.queue.state,
            "admission": self.queue.snapshot(),
            "placement": self.placement.snapshot(),
            "worker_weights": self.placement.weights(),
        }
