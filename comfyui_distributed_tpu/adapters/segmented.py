"""Segmented batched LoRA application (S-LoRA / Punica transplanted).

``models/lora.py`` merges an adapter into the weights at load time —
correct, but it forks one params tree (and on the xjob tier one
compiled program) per adapter. This module instead carries the adapter
as per-slot *operands*: for every targetable kernel ``W`` ([I, O]
layout) a pair ``down`` [r_b, I] / ``up`` [O, r_b] with the kohya
``alpha/rank`` scale pre-folded into ``down``, so one denoise step
computes

    x @ (W + scale * down.T @ up.T)  ==  x@W + scale * (x@down.T)@up.T

— the S-LoRA identity. Operands are zero-padded to a small bounded
rank-bucket set (``CDT_ADAPTER_RANK_BUCKETS``) and cover the FULL
target map (zeros where the adapter doesn't touch), which makes the
operand pytree structure a pure function of (model config, rank
bucket): tiles wearing *different* adapters stack into one vmapped
device batch and share ONE compiled program per
(stepwise signature, rank bucket). Zero padding is exact — a padded
rank row contributes ``0·(x@0)`` — so bucketing never changes numerics.

Adapter-less jobs never enter this path at all: their signature (and
program) is the unmodified stepwise one, which is what keeps them
bit-identical to the pre-adapter repo end-to-end.

Scope: the diffusion backbone (``unet`` part) only. Text-encoder
conditioning is computed upstream of the USDU tile loop, so ``lora_te*``
components cannot act on the batched tier; they are skipped here
(callers log the skip) and remain the merged loader's job.
"""

from __future__ import annotations

import hashlib
from typing import Any, NamedTuple

import numpy as np

from .registry import AdapterError

_DEFAULT_RANK_BUCKETS = "4,8,16,32,64"


def rank_buckets() -> tuple[int, ...]:
    """The bounded rank-bucket set (CDT_ADAPTER_RANK_BUCKETS). One
    compiled program exists per (signature, bucket) — the set is the
    compile-count bound, exactly like ops/upscale.grant_buckets is for
    batch widths."""
    import os

    raw = os.environ.get("CDT_ADAPTER_RANK_BUCKETS", _DEFAULT_RANK_BUCKETS)
    try:
        vals = sorted({int(v) for v in raw.split(",") if v.strip()})
    except ValueError as exc:
        raise AdapterError(
            f"CDT_ADAPTER_RANK_BUCKETS must be comma-separated ints: {raw!r}"
        ) from exc
    if not vals or vals[0] <= 0:
        raise AdapterError(
            f"CDT_ADAPTER_RANK_BUCKETS must be positive ints: {raw!r}"
        )
    return tuple(vals)


def rank_bucket_for(rank: int, buckets: tuple[int, ...] | None = None) -> int:
    """Smallest bucket >= rank; AdapterError past the largest (an
    unsupported rank must fail at admission, not at trace time)."""
    buckets = rank_buckets() if buckets is None else buckets
    for b in buckets:
        if rank <= b:
            return b
    raise AdapterError(
        f"adapter rank {rank} exceeds the largest rank bucket "
        f"{buckets[-1]} (CDT_ADAPTER_RANK_BUCKETS)"
    )


class SegmentOperands(NamedTuple):
    """One resolved plan's device-ready operands.

    ``paths``/``downs``/``ups`` are index-aligned; paths are sorted
    full param paths (``unet/params/.../kernel``) spanning the WHOLE
    target map so the pytree structure is adapter-independent.
    ``scale`` is the strength that rides as a traced per-slot scalar
    (1.0 when strengths were folded in by ``compose_operands``)."""

    paths: tuple[str, ...]
    downs: tuple[np.ndarray, ...]  # each [rank_bucket, I], float32
    ups: tuple[np.ndarray, ...]  # each [O, rank_bucket], float32
    scale: float
    rank_bucket: int
    nbytes: int
    fingerprint: str


def bundle_target_map(bundle: Any) -> dict[str, tuple[str, tuple[int, int]]]:
    """{kohya module name: (full param path, (I, O))} for every
    backbone kernel a LoRA can target on this bundle. Derived from the
    same ``lora_target_map`` schedule the merged loader uses (one
    naming source of truth), filtered to leaves actually present in
    ``bundle.params['unet']`` with 2-D kernels."""
    from ..models import get_config
    from ..models.lora import _flatten_leaves, lora_target_map

    try:
        targets = lora_target_map(get_config(bundle.model_name))
    except ValueError as exc:
        raise AdapterError(str(exc)) from exc
    flat: dict[str, Any] = {}
    _flatten_leaves(bundle.params.get("unet", {}), flat)
    out: dict[str, tuple[str, tuple[int, int]]] = {}
    for name in sorted(targets):
        part, path = targets[name]
        if part != "unet":
            continue
        leaf = flat.get(path)
        if leaf is None or len(getattr(leaf, "shape", ())) != 2:
            continue
        out[name] = (f"unet/{path}", (int(leaf.shape[0]), int(leaf.shape[1])))
    return out


def build_operands(
    state_dict: dict[str, np.ndarray],
    target_map: dict[str, tuple[str, tuple[int, int]]],
    bucket: int | None = None,
    *,
    fingerprint: str = "",
) -> SegmentOperands:
    """Decode one kohya state dict into rank-bucketed operands.

    ``alpha/rank`` folds into ``down`` here (operand build is per
    adapter, cached) so the traced step multiplies by strength only.
    Modules outside the target map (``lora_te*``, unknown names, shape
    mismatches) are skipped — the batched tier is backbone-only; the
    count is logged by callers via the returned zero rows being absent.
    """
    from ..models.lora import parse_lora
    from ..utils.logging import debug_log

    modules = parse_lora(state_dict)
    per_path: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    skipped: list[str] = []
    max_rank = 0
    for name in sorted(modules):
        payload = modules[name]
        target = target_map.get(name)
        if target is None or "down" not in payload or "up" not in payload:
            skipped.append(name)
            continue
        path, (dim_in, dim_out) = target
        down = np.asarray(payload["down"], np.float32)
        up = np.asarray(payload["up"], np.float32)
        if down.ndim == 4:  # conv1x1-style LoRA on projection layers
            down = down[:, :, 0, 0]
            up = up[:, :, 0, 0]
        rank = int(down.shape[0])
        if (
            down.ndim != 2
            or up.ndim != 2
            or down.shape[1] != dim_in
            or up.shape != (dim_out, rank)
        ):
            skipped.append(name)
            continue
        alpha = float(payload.get("alpha", rank))
        per_path[path] = ((alpha / rank) * down, up)
        max_rank = max(max_rank, rank)
    if skipped:
        debug_log(
            f"adapter operands: skipped {len(skipped)} non-backbone/"
            f"mismatched module(s) (first: {skipped[0]})"
        )
    if bucket is None:
        bucket = rank_bucket_for(max(1, max_rank))
    elif max_rank > bucket:
        raise AdapterError(
            f"adapter rank {max_rank} exceeds requested bucket {bucket}"
        )
    paths = tuple(sorted(path for path, _ in target_map.values()))
    shapes = {path: shape for path, shape in target_map.values()}
    downs: list[np.ndarray] = []
    ups: list[np.ndarray] = []
    for path in paths:
        dim_in, dim_out = shapes[path]
        pair = per_path.get(path)
        down = np.zeros((bucket, dim_in), np.float32)
        up = np.zeros((dim_out, bucket), np.float32)
        if pair is not None:
            down[: pair[0].shape[0]] = pair[0]
            up[:, : pair[1].shape[1]] = pair[1]
        downs.append(down)
        ups.append(up)
    nbytes = sum(a.nbytes for a in downs) + sum(a.nbytes for a in ups)
    return SegmentOperands(
        paths=paths,
        downs=tuple(downs),
        ups=tuple(ups),
        scale=1.0,
        rank_bucket=int(bucket),
        nbytes=int(nbytes),
        fingerprint=str(fingerprint),
    )


def compose_operands(
    parts: list[SegmentOperands], strengths: list[float]
) -> SegmentOperands:
    """Stack multiple adapters into ONE operand pair per path by
    concatenating along the rank axis with each adapter's strength
    folded into its ``down`` slice:

        up_cat @ diag-free concat(down_i * s_i)  ==  Σ s_i · up_i @ down_i

    so the traced step stays the single-pair program (scale rides 1.0).
    The concat re-buckets to cover the summed rank."""
    if not parts:
        raise AdapterError("compose_operands needs at least one adapter")
    if len(parts) != len(strengths):
        raise AdapterError("compose_operands: strengths/parts length mismatch")
    paths = parts[0].paths
    for ops in parts[1:]:
        if ops.paths != paths:
            raise AdapterError(
                "compose_operands: adapters were built against different "
                "target maps"
            )
    total = sum(ops.rank_bucket for ops in parts)
    bucket = rank_bucket_for(total)
    downs: list[np.ndarray] = []
    ups: list[np.ndarray] = []
    for i, path in enumerate(paths):
        down = np.concatenate(
            [float(s) * ops.downs[i] for ops, s in zip(parts, strengths)],
            axis=0,
        )
        up = np.concatenate([ops.ups[i] for ops in parts], axis=1)
        pad = bucket - down.shape[0]
        if pad:
            down = np.concatenate(
                [down, np.zeros((pad, down.shape[1]), np.float32)], axis=0
            )
            up = np.concatenate(
                [up, np.zeros((up.shape[0], pad), np.float32)], axis=1
            )
        downs.append(np.ascontiguousarray(down, np.float32))
        ups.append(np.ascontiguousarray(up, np.float32))
    nbytes = sum(a.nbytes for a in downs) + sum(a.nbytes for a in ups)
    return SegmentOperands(
        paths=paths,
        downs=tuple(downs),
        ups=tuple(ups),
        scale=1.0,
        rank_bucket=int(bucket),
        nbytes=int(nbytes),
        fingerprint="+".join(ops.fingerprint for ops in parts),
    )


def _with_leaf(tree: Any, parts: tuple[str, ...], leaf: Any) -> Any:
    """Copy-on-write nested dict update (shares every untouched
    subtree — a few-leaf patch neither copies nor re-uploads the rest)."""
    if not parts:
        return leaf
    new = dict(tree)
    new[parts[0]] = _with_leaf(tree[parts[0]], parts[1:], leaf)
    return new


def apply_segment_delta(params, paths, downs, ups, scale):
    """``W ← (W_f32 + scale · down.T @ up.T).astype(W.dtype)`` on each
    targeted leaf. Pure (copy-on-write), jnp-traceable: inside the
    executor's vmapped step the operands are per-lane (in_axes=0) while
    ``params`` stays broadcast, so only the targeted leaves batch."""
    import jax.numpy as jnp

    patched = params
    for path, down, up in zip(paths, downs, ups):
        parts = tuple(path.split("/"))
        leaf = params
        for part in parts:
            leaf = leaf[part]
        delta = jnp.matmul(down.T, up.T)  # [I, O] kernel layout
        new = (leaf.astype(jnp.float32) + scale * delta).astype(leaf.dtype)
        patched = _with_leaf(patched, parts, new)
    return patched


def make_adapter_step(step_one, paths: tuple[str, ...]):
    """Adapter-aware arity of a stepwise ``step``: 3 extra traced
    operands (downs, ups, scale) patch the targeted leaves before the
    base step runs. ``paths`` is static — it is part of the extended
    batch signature, so one wrapped program per (signature, bucket)."""

    def step(params, x, key, pos, neg, yx, i, downs, ups, scale):
        return step_one(
            apply_segment_delta(params, paths, downs, ups, scale),
            x, key, pos, neg, yx, i,
        )

    return step


def patch_params(params, operands: SegmentOperands, scale: float | None = None):
    """Whole-grant eager variant (the elastic scan tier): every tile of
    the grant wears the same plan, so patch once and sample with the
    unchanged compiled process (same shapes → no recompile)."""
    s = float(operands.scale if scale is None else scale)
    return apply_segment_delta(
        params, operands.paths, operands.downs, operands.ups, s
    )


def adapter_signature(base_signature: tuple, operands: SegmentOperands) -> tuple:
    """Extend a stepwise batching signature with the adapter plane's
    compile-relevant identity: rank bucket + target-path-set digest.
    Strength and adapter CONTENT are absent by design — they are traced
    operands, which is exactly why N distinct same-rank adapters share
    one program."""
    digest = hashlib.blake2b(
        "\n".join(operands.paths).encode("utf-8"), digest_size=8
    ).hexdigest()
    return tuple(base_signature) + (
        ("adapter", int(operands.rank_bucket), digest),
    )
