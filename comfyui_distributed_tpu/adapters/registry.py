"""Adapter catalog + identity: request names → content-hashed sources.

Requests name adapters (``adapters: [{name, strength}]`` in the queue
payload); everything downstream of admission speaks the blake2b
content hash instead. The hash is the identity that joins the PR-17
tile cache key, the xjob batch signature, and usage attribution — two
files with the same *name* but different bytes must never alias, and a
renamed copy of the same bytes must (operand-cache-wise) dedup.

Resolution follows the LoraLoader convention (graph/nodes_core):
absolute path, or ``CDT_LORA_DIR/<name>[.safetensors]``. Tests, chaos
drivers and the smoke job register in-memory state dicts instead
(``register_memory``) so no real checkpoint files are needed.

Workers re-resolve names against their OWN catalog and verify the
master-stamped hash matches before sampling: a fleet with divergent
adapter files fails loudly (AdapterError) instead of producing wrong
pixels.
"""

from __future__ import annotations

import hashlib
import math
import os
import threading
from dataclasses import dataclass, replace
from typing import Any

import numpy as np

# Bound per-request adapter stacks: operand concat grows the effective
# rank additively, and MAX * largest-rank-bucket must stay inside the
# bucket set (segmented.compose_operands re-buckets the concat).
MAX_ADAPTERS_PER_REQUEST = 4

_HASH_BYTES = 16  # 32 hex chars; short enough for wire + signatures


class AdapterError(ValueError):
    """Invalid adapter request (unknown name, bad spec, hash mismatch,
    unsupported rank). Routes map it to HTTP 400 at admission; workers
    treat a mid-job instance as a hard job failure — an unresolved
    adapter must never silently sample the base model."""


@dataclass(frozen=True)
class AdapterSpec:
    """One requested adapter: the wire-level unit of the plan.

    ``content_hash`` is empty until the catalog stamps it (``resolve``);
    every surface past admission requires it stamped.
    """

    name: str
    strength: float = 1.0
    content_hash: str = ""


def parse_adapter_specs(raw: Any) -> list[AdapterSpec]:
    """Validate the request-payload ``adapters`` field → specs.

    Accepts None/[] (no adapters), a list of ``{"name": ..,
    "strength": ..}`` dicts, or bare name strings (strength 1.0).
    Raises AdapterError naming the offending field — the queue route
    surfaces it as a 400.
    """
    if raw is None:
        return []
    if not isinstance(raw, (list, tuple)):
        raise AdapterError("adapters must be a list of {name, strength}")
    if len(raw) > MAX_ADAPTERS_PER_REQUEST:
        raise AdapterError(
            f"adapters lists at most {MAX_ADAPTERS_PER_REQUEST} entries "
            f"(got {len(raw)})"
        )
    specs: list[AdapterSpec] = []
    seen: set[str] = set()
    for i, entry in enumerate(raw):
        if isinstance(entry, str):
            entry = {"name": entry}
        if not isinstance(entry, dict):
            raise AdapterError(f"adapters[{i}] must be an object or string")
        name = entry.get("name")
        if not isinstance(name, str) or not name.strip():
            raise AdapterError(f"adapters[{i}].name must be a non-empty string")
        name = name.strip()
        if name in seen:
            raise AdapterError(f"adapters[{i}].name {name!r} repeats")
        seen.add(name)
        strength = entry.get("strength", 1.0)
        if isinstance(strength, bool) or not isinstance(strength, (int, float)):
            raise AdapterError(f"adapters[{i}].strength must be a number")
        strength = float(strength)
        if not math.isfinite(strength):
            raise AdapterError(f"adapters[{i}].strength must be finite")
        content_hash = entry.get("content_hash", "")
        if not isinstance(content_hash, str):
            raise AdapterError(f"adapters[{i}].content_hash must be a string")
        specs.append(AdapterSpec(name, strength, content_hash))
    return specs


def specs_to_wire(specs: list[AdapterSpec]) -> list[dict[str, Any]]:
    """Specs → JSON-able wire form (job journal, job_status response)."""
    return [
        {
            "name": s.name,
            "strength": float(s.strength),
            "content_hash": s.content_hash,
        }
        for s in specs
    ]


def specs_from_wire(raw: Any) -> list[AdapterSpec]:
    """Wire form → specs. Same validation as the request parser (the
    journal and the master's job_status answer both replay through
    here), so a corrupt record raises instead of sampling wrong."""
    return parse_adapter_specs(raw)


def adapter_plan_key(specs: list[AdapterSpec]) -> tuple:
    """The canonical content identity of a RESOLVED plan:
    ``((content_hash, strength), ...)`` in request order. This exact
    tuple is what joins the PR-17 cache key (``adapter_fingerprint``)
    and the operand-cache key — strength is output-affecting, order is
    output-affecting (stacked adapters do not commute bit-wise), both
    are in. Empty tuple = no adapters = legacy key."""
    for s in specs:
        if not s.content_hash:
            raise AdapterError(
                f"adapter {s.name!r} has no content hash (unresolved plan)"
            )
    return tuple((s.content_hash, float(s.strength)) for s in specs)


def _hash_state_dict(state: dict[str, np.ndarray]) -> str:
    """Canonical content hash of an in-memory kohya state dict: sorted
    key order, dtype + shape + C-order bytes per tensor — the same
    identity a safetensors round-trip of the dict would produce
    byte-wise, without depending on file framing."""
    h = hashlib.blake2b(digest_size=_HASH_BYTES)
    for key in sorted(state):
        arr = np.asarray(state[key])
        h.update(key.encode("utf-8"))
        h.update(b"\x00")
        h.update(str(arr.dtype).encode("ascii"))
        h.update(b"\x00")
        h.update(",".join(str(d) for d in arr.shape).encode("ascii"))
        h.update(b"\x00")
        h.update(np.ascontiguousarray(arr).tobytes())
        h.update(b"\x01")
    return h.hexdigest()


def _hash_file(path: str) -> str:
    h = hashlib.blake2b(digest_size=_HASH_BYTES)
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


class AdapterCatalog:
    """name → source registry with cached content hashes.

    Explicit registrations (file or memory) win over the implicit
    ``CDT_LORA_DIR`` scan; the scan itself is sorted (CDT004: listing
    order must never reach behavior)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> ("file", path) | ("memory", state_dict)
        self._entries: dict[str, tuple[str, Any]] = {}
        self._hashes: dict[str, str] = {}

    # --- registration -------------------------------------------------

    def register_file(self, name: str, path: str) -> None:
        if not os.path.exists(path):
            raise AdapterError(f"adapter file not found: {path}")
        with self._lock:
            self._entries[str(name)] = ("file", str(path))
            self._hashes.pop(str(name), None)

    def register_memory(self, name: str, state: dict[str, np.ndarray]) -> None:
        with self._lock:
            self._entries[str(name)] = ("memory", dict(state))
            self._hashes.pop(str(name), None)

    def names(self) -> list[str]:
        """Sorted catalog listing: explicit registrations + the
        CDT_LORA_DIR scan (stems of *.safetensors)."""
        found = set()
        root = os.environ.get("CDT_LORA_DIR", "")
        if root and os.path.isdir(root):
            for entry in sorted(os.listdir(root)):
                if entry.endswith(".safetensors"):
                    found.add(entry[: -len(".safetensors")])
        with self._lock:
            found.update(self._entries)
        return sorted(found)

    # --- resolution ---------------------------------------------------

    def _source(self, name: str) -> tuple[str, Any]:
        with self._lock:
            entry = self._entries.get(name)
        if entry is not None:
            return entry
        # LoraLoader path convention (graph/nodes_core)
        path = name
        if not os.path.isabs(path):
            root = os.environ.get("CDT_LORA_DIR", "")
            candidate = os.path.join(root, path) if root else path
            if not os.path.exists(candidate) and not candidate.endswith(
                ".safetensors"
            ):
                candidate += ".safetensors"
            path = candidate
        if not os.path.exists(path):
            raise AdapterError(f"unknown adapter {name!r}")
        return ("file", path)

    def content_hash(self, name: str) -> str:
        with self._lock:
            cached = self._hashes.get(name)
        if cached is not None:
            return cached
        kind, source = self._source(name)
        digest = (
            _hash_file(source) if kind == "file" else _hash_state_dict(source)
        )
        with self._lock:
            self._hashes[name] = digest
        return digest

    def load_state_dict(self, name: str) -> dict[str, np.ndarray]:
        kind, source = self._source(name)
        if kind == "memory":
            return dict(source)
        from ..models.lora import read_lora

        return read_lora(source)

    def resolve(self, specs: list[AdapterSpec]) -> list[AdapterSpec]:
        """Stamp content hashes onto specs. A spec arriving WITH a hash
        (worker side: the master stamped it) is verified against the
        local resolution — a mismatch means this host's file differs
        from the master's and the job must fail, not sample wrong."""
        resolved: list[AdapterSpec] = []
        for spec in specs:
            digest = self.content_hash(spec.name)
            if spec.content_hash and spec.content_hash != digest:
                raise AdapterError(
                    f"adapter {spec.name!r} content mismatch: master has "
                    f"{spec.content_hash}, this host resolved {digest}"
                )
            resolved.append(replace(spec, content_hash=digest))
        return resolved


_CATALOG = AdapterCatalog()


def get_adapter_catalog() -> AdapterCatalog:
    return _CATALOG


def _reset_adapter_catalog_for_tests() -> None:
    global _CATALOG
    _CATALOG = AdapterCatalog()
