"""The adapter plane: per-request LoRA personalization (docs/personalization.md).

Three layers, bottom-up:

- ``registry``  — the adapter catalog: name → kohya safetensors source
  with a blake2b content-hash identity. Requests name adapters; every
  downstream surface (cache keys, batch signatures, usage attribution,
  worker-side verification) speaks the hash.
- ``segmented`` — S-LoRA/Punica-style segmented batched application:
  per-slot ``(down, up, scale)`` operands, rank-padded to a bounded
  rank-bucket set, so tiles wearing *different* adapters share ONE
  compiled program per (signature, rank bucket) inside the cross-job
  executor; plus the whole-grant params patch the scan tier uses.
- ``cache``     — the host-side LRU over decoded tensors → device-ready
  operands (byte budget, hit/miss/eviction metrics) and the
  adapter-miss cold-cost seam DRR admission consults.
"""

from .registry import (  # noqa: F401
    AdapterError,
    AdapterSpec,
    MAX_ADAPTERS_PER_REQUEST,
    adapter_plan_key,
    get_adapter_catalog,
    parse_adapter_specs,
    specs_from_wire,
    specs_to_wire,
)
from .segmented import (  # noqa: F401
    SegmentOperands,
    adapter_signature,
    apply_segment_delta,
    build_operands,
    bundle_target_map,
    compose_operands,
    make_adapter_step,
    patch_params,
    rank_bucket_for,
    rank_buckets,
)
from .cache import (  # noqa: F401
    AdapterOperandCache,
    adapter_admission_cost,
    get_adapter_cache,
    operands_for_plan,
)
