"""Host-side adapter operand cache + the DRR admission cold-cost seam.

Decoding a kohya safetensors file and laying out rank-bucketed
operands is host work worth hundreds of ms on real adapters — far too
slow to redo per job at million-user churn. This LRU holds
device-ready ``SegmentOperands`` per (content hash, target map, rank
bucket set) under a byte budget (``CDT_ADAPTER_CACHE_MB``), feeding
``cdt_adapter_cache_*`` metrics the runbook's thrashing triage reads.

Scheduler awareness: ``adapter_admission_cost`` answers "would this
plan's operands come warm?" — a miss multiplies the job's DRR
admission cost by ``CDT_ADAPTER_COLD_COST`` (the PR-15 measured-cost
seam's shape: advisory, multiplicative, default 1.0 = off), so a
tenant thrashing the adapter cache pays for its churn instead of
taxing warm tenants' fair share.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable

from .registry import AdapterError, AdapterSpec, get_adapter_catalog
from .segmented import SegmentOperands, build_operands, compose_operands


def _metrics():
    from ..telemetry.instruments import (
        adapter_cache_bytes,
        adapter_cache_evictions_total,
        adapter_cache_lookups_total,
    )

    return (
        adapter_cache_lookups_total(),
        adapter_cache_evictions_total(),
        adapter_cache_bytes(),
    )


class AdapterOperandCache:
    """Byte-budgeted LRU: plan-part key → SegmentOperands.

    Keys carry the content hash, the target-map digest, and the active
    rank-bucket set — flipping any knob or file content can never serve
    stale operands. ``contains_hash`` is the admission-time peek (no
    LRU promotion: admission must not distort eviction order)."""

    def __init__(self, budget_bytes: int | None = None) -> None:
        if budget_bytes is None:
            from ..utils.constants import adapter_cache_mb

            budget_bytes = int(adapter_cache_mb() * 1024 * 1024)
        self.budget_bytes = max(0, int(budget_bytes))
        self._lock = threading.Lock()
        # key → (operands, content hashes backing the entry)
        self._entries: "OrderedDict[tuple, tuple[SegmentOperands, tuple[str, ...]]]" = (
            OrderedDict()
        )
        # content hash → resident entry count (admission peek)
        self._hash_refs: dict[str, int] = {}
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _note_bytes(self) -> None:
        lookups, evictions, gauge = _metrics()
        del lookups, evictions
        gauge.set(float(self.bytes))

    def _evict_until_fits(self) -> None:
        while self.bytes > self.budget_bytes and len(self._entries) > 1:
            _, (ops, hashes) = self._entries.popitem(last=False)
            self.bytes -= ops.nbytes
            self.evictions += 1
            for digest in hashes:
                refs = self._hash_refs.get(digest, 0) - 1
                if refs <= 0:
                    self._hash_refs.pop(digest, None)
                else:
                    self._hash_refs[digest] = refs
            _metrics()[1].inc()

    def get_or_build(
        self,
        key: tuple,
        hashes: tuple[str, ...],
        builder: Callable[[], SegmentOperands],
    ) -> tuple[SegmentOperands, bool]:
        """Return (operands, was_hit). The builder runs OUTSIDE the
        lock (safetensors decode can take a while; concurrent jobs for
        other adapters must not serialize behind it) — a racing build
        of the same key keeps the first inserted entry."""
        with self._lock:
            cached = self._entries.get(key)
            if cached is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                _metrics()[0].inc(outcome="hit")
                return cached[0], True
        ops = builder()
        lookups, _, _ = _metrics()
        with self._lock:
            raced = self._entries.get(key)
            if raced is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                lookups.inc(outcome="hit")
                return raced[0], True
            self.misses += 1
            lookups.inc(outcome="miss")
            if ops.nbytes <= self.budget_bytes:
                self._entries[key] = (ops, tuple(hashes))
                self.bytes += ops.nbytes
                for digest in hashes:
                    self._hash_refs[digest] = self._hash_refs.get(digest, 0) + 1
                self._evict_until_fits()
            self._note_bytes()
        return ops, False

    def contains_hash(self, content_hash: str) -> bool:
        with self._lock:
            return self._hash_refs.get(content_hash, 0) > 0

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": int(self.bytes),
                "budget_bytes": int(self.budget_bytes),
                "hits": int(self.hits),
                "misses": int(self.misses),
                "evictions": int(self.evictions),
            }


_CACHE: AdapterOperandCache | None = None
_CACHE_LOCK = threading.Lock()


def get_adapter_cache() -> AdapterOperandCache:
    global _CACHE
    with _CACHE_LOCK:
        if _CACHE is None:
            _CACHE = AdapterOperandCache()
        return _CACHE


def _reset_adapter_cache_for_tests() -> None:
    global _CACHE
    with _CACHE_LOCK:
        _CACHE = None


def _paths_digest(target_map: dict) -> tuple[str, ...]:
    return tuple(sorted(path for path, _ in target_map.values()))


def operands_for_plan(
    specs: list[AdapterSpec],
    target_map: dict,
    *,
    catalog: Any = None,
    cache: AdapterOperandCache | None = None,
) -> SegmentOperands:
    """Resolved plan → device-ready operands, through the cache.

    Per-adapter operands cache under (hash, target map, bucket set) —
    strength-INDEPENDENT, so a tenant sweeping strengths reuses one
    entry. A single adapter rides its strength as the traced scale; a
    stack folds strengths at compose time (scale 1.0) — either way the
    compiled program is the same."""
    if not specs:
        raise AdapterError("operands_for_plan: empty plan")
    catalog = catalog or get_adapter_catalog()
    cache = cache or get_adapter_cache()
    from .segmented import rank_buckets

    buckets = rank_buckets()
    digest = _paths_digest(target_map)
    parts: list[SegmentOperands] = []
    for spec in specs:
        if not spec.content_hash:
            raise AdapterError(
                f"adapter {spec.name!r} has no content hash (unresolved plan)"
            )
        key = ("one", spec.content_hash, digest, buckets)
        ops, _ = cache.get_or_build(
            key,
            (spec.content_hash,),
            lambda spec=spec: build_operands(
                catalog.load_state_dict(spec.name),
                target_map,
                fingerprint=spec.content_hash,
            ),
        )
        parts.append(ops)
    if len(parts) == 1:
        return parts[0]._replace(scale=float(specs[0].strength))
    return compose_operands(parts, [float(s.strength) for s in specs])


def adapter_admission_cost(hashes: Any) -> float:
    """DRR admission multiplier for a plan's content hashes: 1.0 when
    the knob is off, the plan is empty, or every adapter's operands
    are resident; CDT_ADAPTER_COLD_COST otherwise. Advisory — errors
    here must never block admission (same contract as the PR-15
    measured-cost seam)."""
    try:
        hashes = tuple(hashes or ())
        if not hashes:
            return 1.0
        from ..utils.constants import adapter_cold_cost

        cost = float(adapter_cold_cost())
        if cost == 1.0:
            return 1.0
        cache = get_adapter_cache()
        if all(cache.contains_hash(h) for h in hashes):
            return 1.0
        return cost
    except Exception:  # noqa: BLE001 - advisory seam
        return 1.0
