"""comfyui_distributed_tpu — a TPU-native distributed diffusion framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
robertvoy/ComfyUI-Distributed (reference: /root/reference): parallel
workflow replication with seed offsetting and result collection,
distributed tile-based upscaling, worker lifecycle management, fault
tolerance (heartbeat / timeout / requeue), media sync, and a JSON
config system — but built TPU-first:

- Inside a pod slice, "workers" are mesh axis indices, not processes;
  the collector is an ICI all-gather (reference: nodes/collector.py),
  and tile distribution is a sharded array axis under shard_map
  (reference: upscale/job_store.py + api/usdu_routes.py HTTP queue).
- Across hosts / heterogeneous participants, an elastic HTTP tier with
  the reference's canonical envelopes, heartbeats, and requeue
  semantics is retained (reference: api/*, upscale/worker_comms.py).
- Compute is JAX: UNet/DiT/VAE in bfloat16 on the MXU, samplers as
  lax.scan loops, Pallas kernels for attention.

Subpackages:
    utils     — config, logging, tracing, network, async bridge, codecs
    parallel  — mesh/topology, collective collector, sharding rules
    ops       — tile math, samplers, attention kernels, conditioning
    models    — UNet / DiT / VAE / text encoder model zoo
    graph     — workflow graph (prompt) executor + node registry
    jobs      — job store, models, timeouts (elastic tier state)
    api       — aiohttp control plane (master/worker HTTP+WS API)
    workers   — host process lifecycle, detection, monitoring
"""

__version__ = "0.1.0"
