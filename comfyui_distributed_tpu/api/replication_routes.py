"""Replication route: the active master's journal as a live stream.

    GET /distributed/replicate — WebSocket, standby masters only

Wire protocol (JSON text frames, one per message):

    repl_hello     {epoch, head_lsn, state}   full snapshot state at
                                              attach time (the manager
                                              shadow, serialized under
                                              the manager lock)
    repl_record    {record}                   one journaled record, in
                                              lsn order (record carries
                                              its lsn)
    repl_heartbeat {epoch, head_lsn}          periodic head advance so
                                              the standby can measure
                                              lag while the journal is
                                              quiet
    repl_lost      {}                         the subscription buffer
                                              overflowed; the stream is
                                              closed and the standby
                                              re-syncs from a fresh
                                              hello on reconnect

The (hello, records) pair is exactly consistent: the subscription is
registered and the snapshot serialized under one manager lock hold
(DurabilityManager.subscribe_replica), and frames at or below the
snapshot's lsn are deduplicated replica-side — so no record is ever
missed or double-applied regardless of attach timing.

Only an ACTIVE journaled master serves this route: a standby (not yet
promoted) answers 409 so a misconfigured standby-of-standby chain
fails loudly instead of replicating an empty shadow.
"""

from __future__ import annotations

import asyncio
import contextlib
import json

from aiohttp import web

from ..utils.async_helpers import run_blocking
from ..utils.constants import STANDBY_POLL_SECONDS
from ..utils.logging import debug_log


def register(app: web.Application, server) -> None:
    routes = ReplicationRoutes(server)
    app.router.add_get("/distributed/replicate", routes.replicate)


class ReplicationRoutes:
    def __init__(self, server):
        self.server = server

    async def replicate(self, request: web.Request) -> web.StreamResponse:
        manager = getattr(self.server, "durability", None)
        standby = getattr(self.server, "standby", None)
        if manager is None:
            return web.json_response(
                {"error": "journaling disabled",
                 "hint": "set CDT_JOURNAL_DIR on the active master"},
                status=409,
            )
        if standby is not None and not standby.promoted:
            return web.json_response(
                {"error": "standby",
                 "hint": "this master is itself a standby; replicate "
                         "from the active master"},
                status=409,
            )
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        # registered so server.stop() can close a parked stream instead
        # of waiting out the runner's graceful-shutdown timeout
        self.server.replication_sockets.add(ws)
        sub = manager.subscribe_replica()
        debug_log(
            f"replication: standby attached at lsn {sub.head_lsn} "
            f"(epoch {sub.epoch})"
        )
        try:
            await ws.send_str(
                json.dumps(
                    {
                        "type": "repl_hello",
                        "epoch": sub.epoch,
                        "head_lsn": sub.head_lsn,
                        "state": sub.snapshot_state,
                    },
                    default=str,
                )
            )
            while not ws.closed:
                # Park off-loop on the subscription's wakeup flag; the
                # timeout doubles as the heartbeat cadence.
                await run_blocking(sub.wait, STANDBY_POLL_SECONDS)
                for record in sub.pop():
                    await ws.send_str(
                        json.dumps(
                            {"type": "repl_record", "record": record},
                            default=str,
                        )
                    )
                if sub.lost:
                    await ws.send_str(json.dumps({"type": "repl_lost"}))
                    break
                await ws.send_str(
                    json.dumps(
                        {
                            "type": "repl_heartbeat",
                            "epoch": manager.epoch,
                            "head_lsn": manager.head_lsn(),
                        }
                    )
                )
        except (ConnectionResetError, asyncio.CancelledError, RuntimeError):
            pass  # standby went away mid-send / server shutting down
        finally:
            self.server.replication_sockets.discard(ws)
            manager.unsubscribe_replica(sub)
            with contextlib.suppress(Exception):
                await ws.close()
        return ws
