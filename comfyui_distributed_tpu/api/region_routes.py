"""Region control-plane routes: shard map, lease view, autoscaler.

- ``GET  /distributed/region`` — the shard router's job→master map
  (per-shard addresses, endpoint health, highest fencing epoch) plus
  this master's lease view (file or quorum; the quorum view includes
  every peer's register, the operator's split-brain forensic);
- ``GET  /distributed/autoscale`` — the autoscaler's bounds and its
  recent decisions, each carrying the chip-second demand/capacity
  window that justified it and the measured delta the action bought;
- ``POST /distributed/autoscale/step`` — force one evaluation NOW
  (the soak harness and operators use it instead of waiting out the
  interval; answers 409 when the controller is disabled).

Registered unconditionally — on an unsharded, non-autoscaled master
the region route answers ``enabled: false`` everywhere so dashboards
can probe capability without 404 special-casing.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from aiohttp import web

if TYPE_CHECKING:  # pragma: no cover
    from .server import DistributedServer


class RegionRoutes:
    def __init__(self, server: "DistributedServer") -> None:
        self.server = server

    async def handle_region(self, request: web.Request) -> web.Response:
        server = self.server
        router = getattr(server, "router", None)
        lease_view = None
        manager = server.durability
        if manager is not None and manager.lease is not None:
            lease = manager.lease
            status_fn = getattr(lease, "status", None)
            if callable(status_fn):
                lease_view = status_fn()
            else:
                lease_view = {
                    "backend": "file",
                    "owner": lease.owner,
                    "epoch": getattr(lease, "epoch", None),
                    "ttl_seconds": getattr(lease, "ttl", None),
                }
        body = {
            "enabled": bool(router is not None and router.enabled),
            "deposed": server.deposed,
            "shards": router.status() if router is not None else {
                "enabled": False, "shards": {},
            },
            "lease": lease_view,
        }
        return web.json_response(body)

    async def handle_autoscale(self, request: web.Request) -> web.Response:
        controller = getattr(self.server, "autoscale", None)
        if controller is None:
            return web.json_response({"enabled": False, "decisions": []})
        return web.json_response(controller.status())

    async def handle_autoscale_step(
        self, request: web.Request
    ) -> web.Response:
        controller = getattr(self.server, "autoscale", None)
        if controller is None:
            return web.json_response(
                {"error": "autoscaler disabled (CDT_AUTOSCALE=0)"},
                status=409,
            )
        import asyncio

        record = await asyncio.get_running_loop().run_in_executor(
            None, controller.step
        )
        return web.json_response({"decision": record})


def register(app: web.Application, server: "DistributedServer") -> None:
    routes = RegionRoutes(server)
    app.router.add_get("/distributed/region", routes.handle_region)
    app.router.add_get("/distributed/autoscale", routes.handle_autoscale)
    app.router.add_post(
        "/distributed/autoscale/step", routes.handle_autoscale_step
    )
