"""Device-profiler capture routes + transfer-ledger surface.

    GET  /distributed/profile        — ledger totals + capture status
                                       + retained trace index
    POST /distributed/profile/start  — begin a bounded jax.profiler
                                       trace (single-flight, duration
                                       capped, auto-stops)
    POST /distributed/profile/stop   — stop the active trace early

Capture routes are enabled when ``CDT_PROFILE_DIR`` is set; otherwise
they answer ``enabled: false`` with a hint (the journal-dir idiom).
The ledger block is served regardless — it is in-memory and rides the
fleet snapshot anyway. Trace start/stop touch the filesystem and the
profiler runtime, so they run off the event loop via ``run_blocking``.
"""

from __future__ import annotations

from aiohttp import web

from ..telemetry.profiling import get_profiler_capture, peek_transfer_ledger
from ..utils.async_helpers import run_blocking

DISABLED_HINT = {
    "enabled": False,
    "hint": "set CDT_PROFILE_DIR to enable device trace capture",
}


def register(app: web.Application, server) -> None:
    routes = ProfileRoutes(server)
    app.router.add_get("/distributed/profile", routes.status)
    app.router.add_post("/distributed/profile/start", routes.start)
    app.router.add_post("/distributed/profile/stop", routes.stop)


class ProfileRoutes:
    def __init__(self, server):
        self.server = server

    async def status(self, request: web.Request) -> web.Response:
        ledger = peek_transfer_ledger()
        capture = get_profiler_capture()
        role = "worker" if getattr(self.server, "is_worker", False) else "master"
        payload: dict = {
            "ledger": ledger.totals(role) if ledger is not None else None,
        }
        if capture is None:
            payload.update(DISABLED_HINT)
        else:
            payload["enabled"] = True
            payload["capture"] = await run_blocking(capture.status)
            payload["captures"] = await run_blocking(capture.captures)
        return web.json_response(payload)

    async def start(self, request: web.Request) -> web.Response:
        """Begin a capture. Optional JSON body:
        ``{"duration_s": <float>, "tag": <str>}``; the duration is
        clamped to CDT_PROFILE_MAX_SECONDS and the trace auto-stops."""
        capture = get_profiler_capture()
        if capture is None:
            return web.json_response(DISABLED_HINT, status=400)
        duration = None
        tag = "manual"
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:  # noqa: BLE001 - empty/invalid body is fine
                body = None
            if isinstance(body, dict):
                try:
                    if body.get("duration_s") is not None:
                        duration = float(body["duration_s"])
                except (TypeError, ValueError):
                    return web.json_response(
                        {"error": "duration_s must be a number"}, status=400
                    )
                if body.get("tag"):
                    tag = str(body["tag"])
        result = await run_blocking(
            lambda: capture.start(duration_s=duration, tag=tag)
        )
        status = 200 if result.get("started") else 409
        return web.json_response(result, status=status)

    async def stop(self, request: web.Request) -> web.Response:
        capture = get_profiler_capture()
        if capture is None:
            return web.json_response(DISABLED_HINT, status=400)
        result = await run_blocking(capture.stop)
        return web.json_response(result)
