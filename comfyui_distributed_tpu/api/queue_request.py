"""Queue-request payload parsing/validation.

Contract parity with reference api/queue_request.py + api/schemas.py:
accepts {"prompt" | "workflow": {...}, "workers" | "worker_ids":
[...], "client_id": str, "job_id"?: str, ...}; strict errors name the
offending field.

Scheduler additions: an optional `tenant` (fair-share accounting key;
defaults to "default") and `lane` (admission priority class; unknown
lanes fall back server-side) thread the multi-tenant control plane
through the payload — see scheduler/queue.py and docs/scheduler.md.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ..utils.exceptions import DistributedError


class QueueRequestError(DistributedError):
    pass


DEFAULT_TENANT = "default"


@dataclasses.dataclass
class QueueRequestPayload:
    prompt: dict[str, Any]
    client_id: str
    worker_ids: list[str]
    trace_id: str | None = None
    tenant: str = DEFAULT_TENANT
    lane: str | None = None
    # End-to-end deadline in seconds, counted from request arrival
    # (body field `deadline_s` or the `X-CDT-Deadline` header): gates
    # admission, rides the job record, and expires overdue work.
    deadline_s: float | None = None
    # Adapter plan: [{"name", "strength"}] — per-request LoRA
    # personalization (adapters/). Validated here; the queue route
    # resolves names to content hashes against the catalog before the
    # plan rides the job record (docs/personalization.md).
    adapters: list[Any] = dataclasses.field(default_factory=list)
    extra: dict[str, Any] = dataclasses.field(default_factory=dict)


def parse_deadline_seconds(value: Any) -> float | None:
    """Validate one deadline value (body or header): positive finite
    seconds, clamped to CDT_JOB_DEADLINE_MAX when that cap is set;
    None/empty = no deadline; anything else raises."""
    from ..utils.constants import JOB_DEADLINE_MAX_SECONDS

    if value is None or value == "":
        return None
    try:
        deadline = float(value)
    except (TypeError, ValueError) as exc:
        raise QueueRequestError(
            "'deadline_s' must be a positive number of seconds"
        ) from exc
    if not deadline > 0 or deadline != deadline or deadline == float("inf"):
        raise QueueRequestError(
            "'deadline_s' must be a positive number of seconds"
        )
    if JOB_DEADLINE_MAX_SECONDS > 0:
        deadline = min(deadline, JOB_DEADLINE_MAX_SECONDS)
    return deadline


def parse_queue_request_payload(body: Any) -> QueueRequestPayload:
    if not isinstance(body, dict):
        raise QueueRequestError("request body must be a JSON object")

    prompt = body.get("prompt")
    if prompt is None and isinstance(body.get("workflow"), dict):
        prompt = body["workflow"].get("prompt", body["workflow"])
    if not isinstance(prompt, dict) or not prompt:
        raise QueueRequestError("missing or empty 'prompt'")

    client_id = body.get("client_id")
    if not isinstance(client_id, str) or not client_id:
        raise QueueRequestError("'client_id' is required")

    workers = body.get("workers", body.get("worker_ids", []))
    if workers is None:
        workers = []
    if not isinstance(workers, list) or not all(
        isinstance(w, (str, int)) for w in workers
    ):
        raise QueueRequestError("'workers' must be a list of ids")

    tenant = body.get("tenant", DEFAULT_TENANT)
    if not isinstance(tenant, str) or not tenant:
        raise QueueRequestError("'tenant' must be a non-empty string")

    lane = body.get("lane")
    if lane is not None and (not isinstance(lane, str) or not lane):
        raise QueueRequestError("'lane' must be a non-empty string")

    deadline_s = parse_deadline_seconds(body.get("deadline_s"))

    from ..adapters import AdapterError, parse_adapter_specs

    try:
        adapter_specs = parse_adapter_specs(body.get("adapters"))
    except AdapterError as exc:
        raise QueueRequestError(str(exc)) from exc

    return QueueRequestPayload(
        prompt=prompt,
        client_id=client_id,
        worker_ids=[str(w) for w in workers],
        trace_id=body.get("trace_id") or None,
        tenant=tenant,
        lane=lane,
        deadline_s=deadline_s,
        adapters=adapter_specs,
        extra={
            k: v
            for k, v in body.items()
            if k
            not in (
                "prompt",
                "workflow",
                "client_id",
                "workers",
                "worker_ids",
                "tenant",
                "lane",
                "deadline_s",
                "adapters",
            )
        },
    )
