"""Worker routes: WS dispatch, lifecycle, logs, host/topology info.

Parity with reference api/worker_routes.py (695 LoC there):
    WS   /distributed/worker_ws      — dispatch_prompt/dispatch_ack
    POST /distributed/launch_worker  — spawn a local worker process
    POST /distributed/stop_worker    — stop a managed worker
    GET  /distributed/managed        — managed process table
    GET  /distributed/worker_log/{n} — tail a worker's log file
    GET  /distributed/master_log     — in-memory master log ring
    GET  /distributed/network_info   — candidate IPs, private ranked
    GET  /distributed/system_info    — machine id, path sep, TPU topology
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
from typing import Any

from aiohttp import WSMsgType, web

from ..utils.async_helpers import run_blocking
from ..utils.logging import log


def register(app: web.Application, server) -> None:
    routes = WorkerRoutes(server)
    app.router.add_get("/distributed/worker_ws", routes.worker_ws)
    app.router.add_post("/distributed/launch_worker", routes.launch_worker)
    app.router.add_post("/distributed/stop_worker", routes.stop_worker)
    app.router.add_post(
        "/distributed/worker/clear_launching", routes.clear_launching
    )
    app.router.add_get("/distributed/managed", routes.managed)
    app.router.add_get("/distributed/worker_log/{name}", routes.worker_log)
    app.router.add_get("/distributed/master_log", routes.master_log)
    app.router.add_get("/distributed/remote_log/{worker_id}", routes.remote_log)
    app.router.add_get("/distributed/network_info", routes.network_info)
    app.router.add_get("/distributed/system_info", routes.system_info)


class WorkerRoutes:
    def __init__(self, server):
        self.server = server

    # --- websocket dispatch ------------------------------------------------

    async def worker_ws(self, request: web.Request) -> web.WebSocketResponse:
        """Server side of WS orchestration (reference
        api/worker_routes.py:43-112): the master connects and sends
        {type: dispatch_prompt, prompt, prompt_id}; we enqueue and ack
        {type: dispatch_ack, prompt_id, ok}."""
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        async for msg in ws:
            if msg.type != WSMsgType.TEXT:
                continue
            try:
                data = json.loads(msg.data)
            except json.JSONDecodeError:
                await ws.send_json({"type": "error", "error": "invalid json"})
                continue
            if data.get("type") == "dispatch_prompt":
                prompt_id = data.get("prompt_id", "")
                try:
                    self.server.queue_prompt(
                        data.get("prompt", {}),
                        prompt_id,
                        data.get("extra_data"),
                        trace_id=data.get("trace_id") or None,
                    )
                    await ws.send_json(
                        {"type": "dispatch_ack", "prompt_id": prompt_id, "ok": True}
                    )
                except Exception as exc:  # noqa: BLE001 - reported over WS
                    await ws.send_json(
                        {
                            "type": "dispatch_ack",
                            "prompt_id": prompt_id,
                            "ok": False,
                            "error": str(exc),
                        }
                    )
            elif data.get("type") == "ping":
                await ws.send_json(
                    {"type": "pong", "queue_remaining": self.server.queue_remaining}
                )
        return ws

    # --- lifecycle ---------------------------------------------------------

    async def launch_worker(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            body = {}
        worker_id = str(body.get("worker_id", ""))
        if not worker_id:
            return web.json_response({"error": "worker_id required"}, status=400)
        worker = next(
            (
                w
                for w in self.server.config.get("workers", [])
                if str(w.get("id")) == worker_id
            ),
            None,
        )
        if worker is None:
            return web.json_response({"error": "no such worker"}, status=404)

        from ..workers import get_worker_manager

        manager = get_worker_manager()
        try:
            info = await run_blocking(
                manager.launch_worker, worker, self.server.config_path
            )
        except Exception as exc:  # noqa: BLE001 - reported to client
            return web.json_response({"error": str(exc)}, status=500)
        return web.json_response({"status": "ok", **info})

    async def stop_worker(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            body = {}
        worker_id = str(body.get("worker_id", ""))
        from ..workers import get_worker_manager

        manager = get_worker_manager()
        stopped = await run_blocking(
            manager.stop_worker, worker_id, self.server.config_path
        )
        return web.json_response({"status": "ok", "stopped": stopped})

    async def clear_launching(self, request: web.Request) -> web.Response:
        """Clear a managed worker's 'launching' marker once it is
        confirmed up (reference api/worker_routes.py
        /distributed/worker/clear_launching) so a crashed launch
        cannot wedge the panel's grace state."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        worker_id = str(body.get("worker_id", "")).strip()
        if not worker_id:
            return web.json_response({"error": "worker_id required"}, status=400)
        known = any(
            str(w.get("id")) == worker_id
            for w in self.server.config.get("workers", [])
        )
        if not known:
            return web.json_response({"error": "no such worker"}, status=404)
        from ..workers import get_worker_manager

        cleared = await run_blocking(
            get_worker_manager().clear_launching,
            worker_id,
            self.server.config_path,
        )
        return web.json_response({"status": "success", "cleared": cleared})

    async def managed(self, request: web.Request) -> web.Response:
        from ..workers import get_worker_manager

        return web.json_response(
            {"managed": get_worker_manager().managed_processes(self.server.config_path)}
        )

    # --- logs --------------------------------------------------------------

    async def worker_log(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        tail = int(request.query.get("tail", 200))
        from ..workers.process_manager import worker_log_path

        path = worker_log_path(name)
        if not os.path.isfile(path):
            return web.json_response({"error": "no log"}, status=404)
        lines = await run_blocking(_tail_file, path, tail)
        return web.json_response({"name": name, "lines": lines})

    async def master_log(self, request: web.Request) -> web.Response:
        tail = int(request.query.get("tail", 200))
        return web.json_response({"lines": self.server.log_buffer[-tail:]})

    async def remote_log(self, request: web.Request) -> web.Response:
        """Proxy a remote worker's in-memory log so the panel can show
        logs of workers on other hosts (reference remote-log endpoint,
        api/worker_routes.py log proxying)."""
        worker_id = request.match_info["worker_id"]
        tail = request.query.get("tail", "200")
        worker = next(
            (
                w
                for w in self.server.config.get("workers", [])
                if str(w.get("id")) == worker_id
            ),
            None,
        )
        if worker is None:
            return web.json_response({"error": "no such worker"}, status=404)
        from ..utils.network import build_worker_url, get_client_session

        try:
            session = await get_client_session()
            url = build_worker_url(worker, f"/distributed/master_log?tail={tail}")
            async with session.get(url) as resp:
                return web.json_response(await resp.json(), status=resp.status)
        except Exception as exc:  # noqa: BLE001 - proxied errors surface
            return web.json_response({"error": str(exc)}, status=502)

    # --- host info ----------------------------------------------------------

    async def network_info(self, request: web.Request) -> web.Response:
        """Candidate IPs for reaching this host, private IPs ranked
        first (reference api/worker_routes.py network_info)."""
        candidates: list[str] = []
        try:
            hostname = socket.gethostname()
            # getaddrinfo can hit DNS: resolve through the loop's
            # executor so a slow resolver never stalls other requests
            infos = await asyncio.get_running_loop().getaddrinfo(
                hostname, None, family=socket.AF_INET
            )
            for info in infos:
                addr = info[4][0]
                if addr not in candidates:
                    candidates.append(addr)
        except OSError:
            pass
        # UDP-connect trick: the OS picks the outbound interface
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect(("10.255.255.255", 1))
                addr = s.getsockname()[0]
                if addr not in candidates:
                    candidates.insert(0, addr)
        except OSError:
            pass
        from ..utils.network import is_private_host

        ranked = sorted(
            (a for a in candidates if a != "127.0.0.1"),
            key=lambda a: (not is_private_host(a), a),
        )
        return web.json_response(
            {"candidates": ranked or candidates, "recommended": (ranked or ["127.0.0.1"])[0]}
        )

    async def system_info(self, request: web.Request) -> web.Response:
        """Machine identity + accelerator topology (the reference
        reports CUDA devices via nvidia-smi; we report the jax device
        mesh — reference api/worker_routes.py:237-274)."""
        from ..workers.detection import get_machine_id, is_docker

        info: dict[str, Any] = {
            "machine_id": get_machine_id(),
            "path_separator": os.sep,
            "platform": os.name,
            "docker": is_docker(),
            "is_worker": self.server.is_worker,
        }
        # Live telemetry snapshot for the control panel: queue depths,
        # in-flight tiles, and breaker states without making the panel
        # parse the Prometheus text surface.
        from ..resilience.health import get_health_registry

        stats = await self.server.job_store.stats()
        info["status"] = {
            "queue_remaining": self.server.queue_remaining,
            "tile_jobs": stats["tile_jobs"],
            "collector_jobs": stats["collectors"],
            "tile_queue_depth": stats["queue_depth"],
            "in_flight_tiles": stats["in_flight"],
            "breakers": get_health_registry().snapshot(),
            # advertised chip counts per worker (mesh data-axis width,
            # carried on pull/heartbeat) — the placement policy's
            # capacity inputs, surfaced for the panel and operators
            "worker_capacity": dict(self.server.job_store.worker_capacity),
        }
        # Event-bus consumer accounting: per-subscriber queue depth +
        # cumulative drops, plus the installed synchronous taps — the
        # flight recorder is an always-on tap, and its ring drops must
        # be visible here, not silent (docs/observability.md §Incidents)
        from ..telemetry import get_event_bus, peek_flight_recorder

        info["status"]["event_bus"] = get_event_bus().stats()
        recorder = peek_flight_recorder()
        info["status"]["flight"] = (
            recorder.status() if recorder is not None else {"installed": False}
        )
        incidents = getattr(self.server, "incidents", None)
        if incidents is not None:
            info["status"]["incidents"] = incidents.status()
        try:
            from ..parallel.mesh import describe_topology, serving_mesh_summary

            info["topology"] = describe_topology()
            # the mesh this process serves tile grants with (recorded
            # by the elastic loop; knob-only resolution before one has
            # run); a mesh-knob failure degrades only this key, never
            # the device enumeration above
            try:
                info["topology"]["mesh"] = serving_mesh_summary()
            except Exception as exc:  # noqa: BLE001 - best effort
                info["topology"]["mesh"] = {"error": str(exc)}
        except Exception as exc:  # noqa: BLE001 - best effort
            info["topology"] = {"error": str(exc)}
        # Tokenizer fidelity: with the committed prose-trained stand-in
        # vocab, real SD/SDXL checkpoints get wrong token ids. The
        # reference inherits the exact tokenizer from ComfyUI's bundled
        # assets (reference upscale/tile_ops.py:168); we surface the
        # degraded state so the panel can show it instead of burying it
        # in a log line (round-3 verdict item 5).
        try:
            from ..models.clip_bpe import get_bpe

            info["clip_vocab_canonical"] = await run_blocking(
                lambda: get_bpe().is_canonical
            )
        except Exception as exc:  # noqa: BLE001 - best effort
            info["clip_vocab_canonical"] = None
            info["clip_vocab_error"] = str(exc)
        # Same fidelity surface for the T5 side: Flux/SD3/WAN condition
        # through sentencepiece vocabs; without CDT_T5_SPM the fallback
        # CLIP-BPE ids are deterministic placeholders (and get folded
        # into the embedding range — models/t5_encoder.py).
        try:
            from ..models.t5_encoder import t5_vocab_canonical

            # actual tokenizer state, like the CLIP branch (and cached
            # like it — this endpoint is panel-polled)
            info["t5_vocab_canonical"] = await run_blocking(
                t5_vocab_canonical
            )
        except Exception as exc:  # noqa: BLE001 - best effort
            info["t5_vocab_canonical"] = None
            info["t5_vocab_error"] = str(exc)
        # Last bench accelerator-probe report (scripts/bench_probe via
        # bench.py writes CDT_PROBE_REPORT): backend/stage/versions so
        # operators see WHY accelerators fell back to CPU without
        # digging through BENCH notes. Absent file = key omitted.
        try:
            from ..utils.constants import probe_report_path

            probe_path = probe_report_path()
            if probe_path is not None and os.path.exists(probe_path):
                import json as json_mod

                def _read_probe() -> Any:
                    with open(probe_path, "r", encoding="utf-8") as fh:
                        return json_mod.load(fh)

                info["probe"] = await run_blocking(_read_probe)
        except Exception as exc:  # noqa: BLE001 - best effort
            info["probe"] = {"error": str(exc)}
        return web.json_response(info)


def _tail_file(path: str, n_lines: int) -> list[str]:
    """Tail-read a potentially large file without loading it whole."""
    avg = 200
    with open(path, "rb") as fh:
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        window = min(size, max(4096, n_lines * avg))
        fh.seek(size - window)
        data = fh.read().decode("utf-8", errors="replace")
    lines = data.splitlines()
    return lines[-n_lines:]
