"""Incident-bundle routes: list, fetch, manual capture.

    GET  /distributed/incidents           — newest-first bundle listing
                                            + manager/flight accounting
    GET  /distributed/incidents/{id}      — one full bundle (JSON)
    POST /distributed/incidents/capture   — manual capture (bypasses
                                            debounce, still single-flight)

Enabled on masters with ``CDT_INCIDENT_DIR`` set; otherwise every
route answers ``enabled: false`` with a hint (the journal-dir idiom).
File reads and the synchronous capture run off the event loop via
``run_blocking`` — a multi-MB bundle read must not stall serving
(cdt-lint CDT001 is the enforcement).
"""

from __future__ import annotations

from aiohttp import web

from ..utils.async_helpers import run_blocking

DISABLED_HINT = {
    "enabled": False,
    "hint": "set CDT_INCIDENT_DIR on a master to enable incident capture",
}


def register(app: web.Application, server) -> None:
    routes = IncidentRoutes(server)
    app.router.add_get("/distributed/incidents", routes.list_incidents)
    app.router.add_post("/distributed/incidents/capture", routes.capture)
    app.router.add_get(
        "/distributed/incidents/{incident_id}", routes.get_incident
    )


class IncidentRoutes:
    def __init__(self, server):
        self.server = server

    @property
    def manager(self):
        return getattr(self.server, "incidents", None)

    async def list_incidents(self, request: web.Request) -> web.Response:
        manager = self.manager
        if manager is None:
            return web.json_response(DISABLED_HINT)
        from ..telemetry.flight import peek_flight_recorder

        listing = await run_blocking(manager.list_bundles)
        recorder = peek_flight_recorder()
        return web.json_response(
            {
                "enabled": True,
                "incidents": listing,
                "manager": manager.status(),
                "flight": recorder.status() if recorder is not None else None,
            }
        )

    async def get_incident(self, request: web.Request) -> web.Response:
        manager = self.manager
        if manager is None:
            return web.json_response(DISABLED_HINT, status=404)
        incident_id = request.match_info["incident_id"]
        bundle = await run_blocking(lambda: manager.read_bundle(incident_id))
        if bundle is None:
            return web.json_response(
                {"error": f"no such incident: {incident_id}"}, status=404
            )
        return web.json_response(bundle)

    async def capture(self, request: web.Request) -> web.Response:
        """Operator-initiated capture. Optional JSON body:
        ``{"key": ..., "context": {...}}`` rides into the bundle's
        trigger section. Runs the capture synchronously off-loop and
        answers with the written bundle's id."""
        manager = self.manager
        if manager is None:
            return web.json_response(DISABLED_HINT, status=400)
        key = ""
        context: dict = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:  # noqa: BLE001 - empty/invalid body is fine
                body = None
            if isinstance(body, dict):
                key = str(body.get("key", ""))
                if isinstance(body.get("context"), dict):
                    context = body["context"]
        try:
            result = await run_blocking(
                lambda: manager.capture_now(key=key, context=context)
            )
        except Exception as exc:  # noqa: BLE001 - reported to the operator
            return web.json_response(
                {"error": f"{type(exc).__name__}: {exc}"}, status=500
            )
        return web.json_response({"captured": True, **result})
