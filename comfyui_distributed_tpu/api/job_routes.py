"""Job routes: orchestration entry + collector result ingestion.

Route parity with reference api/job_routes.py:
    POST /distributed/queue         — REST orchestration entry
    POST /distributed/job_complete  — canonical collector envelope
    POST /distributed/prepare_job   — pre-create a collector queue
    POST /distributed/clear_memory  — drop caches / free device memory
    POST /distributed/check_file    — media-sync hash check
    GET  /distributed/load_image    — serve an input image
"""

from __future__ import annotations

import asyncio
import hashlib
import os
from typing import Any

from aiohttp import web

from ..telemetry.instruments import collector_results_total
from ..utils import audio_payload as audio_utils
from ..utils import image as img_utils
from ..utils.async_helpers import run_blocking
from ..utils.constants import JOB_INIT_GRACE_SECONDS
from ..utils.exceptions import PromptValidationError
from ..utils.logging import debug_log, log
from .queue_request import QueueRequestError, parse_queue_request_payload
from .telemetry_routes import rpc_span


def register(app: web.Application, server) -> None:
    routes = JobRoutes(server)
    app.router.add_post("/distributed/queue", routes.queue)
    app.router.add_post("/distributed/cancel/{job_id}", routes.cancel_job)
    app.router.add_post("/distributed/job_complete", routes.job_complete)
    app.router.add_post("/distributed/prepare_job", routes.prepare_job)
    app.router.add_post("/distributed/clear_memory", routes.clear_memory)
    app.router.add_post("/distributed/check_file", routes.check_file)
    app.router.add_get("/distributed/load_image", routes.load_image)
    app.router.add_post("/upload/image", routes.upload_image)


class JobRoutes:
    def __init__(self, server):
        self.server = server

    async def queue(self, request: web.Request) -> web.Response:
        import time as time_mod

        arrived_at = time_mod.monotonic()
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid json"}, status=400)
        try:
            payload = parse_queue_request_payload(body)
            if payload.deadline_s is None:
                # header form of the end-to-end deadline (proxies and
                # thin clients that can't touch the JSON body)
                from .queue_request import parse_deadline_seconds

                payload.deadline_s = parse_deadline_seconds(
                    request.headers.get("X-CDT-Deadline")
                )
        except QueueRequestError as exc:
            return web.json_response({"error": str(exc)}, status=400)

        if payload.adapters:
            # Resolve adapter names → content hashes NOW, against the
            # master's catalog: an unknown adapter is a client error at
            # admission, never a mid-job worker failure. The stamped
            # hashes are the identity every downstream surface keys on.
            from ..adapters import AdapterError, get_adapter_catalog

            try:
                payload.adapters = get_adapter_catalog().resolve(
                    payload.adapters
                )
            except AdapterError as exc:
                return web.json_response({"error": str(exc)}, status=400)
        if payload.lane is None:
            # Budget tenants with no explicit lane ride the cheap lane
            # (CDT_CHEAP_LANE — the GGUF-quantized tier's admission
            # class, models/gguf.quantized_lane_info).
            from ..utils.constants import budget_tenants, cheap_lane

            if payload.tenant in budget_tenants():
                payload.lane = cheap_lane()

        import asyncio

        from ..scheduler import (
            AdmissionClosed,
            DeadlineUnmeetable,
            SchedulerOverloaded,
            SchedulerSaturated,
        )
        from ..telemetry import get_tracer
        from ..utils.constants import SCHED_GRANT_TIMEOUT_SECONDS
        from ..utils.trace_logger import generate_trace_id
        from .orchestration.queue_orchestration import (
            orchestrate_distributed_execution,
        )

        scheduler = getattr(self.server, "scheduler", None)
        ticket = None
        if scheduler is not None:
            # The trace id is fixed here (not in orchestration) so the
            # sched.wait span and the execution share one span tree —
            # perf_report's queue-wait column pairs them.
            payload.trace_id = payload.trace_id or generate_trace_id()
            try:
                ticket = scheduler.submit_payload(payload)
            except DeadlineUnmeetable as exc:
                return web.json_response(
                    {
                        "error": str(exc),
                        "lane": exc.lane,
                        "reason": "deadline_unmeetable",
                        "deadline_s": exc.deadline_s,
                        "estimated_wait_seconds": round(exc.estimated_wait, 2),
                    },
                    status=429,
                    headers={"Retry-After": str(int(exc.retry_after))},
                )
            except SchedulerOverloaded as exc:
                return web.json_response(
                    {"error": str(exc), "lane": exc.lane, "reason": "shed"},
                    status=429,
                    headers={"Retry-After": str(int(exc.retry_after))},
                )
            except SchedulerSaturated as exc:
                return web.json_response(
                    {"error": str(exc), "lane": exc.lane},
                    status=429,
                    headers={"Retry-After": str(int(exc.retry_after))},
                )
            except AdmissionClosed as exc:
                return web.json_response(
                    {"error": str(exc)},
                    status=503,
                    headers={"Retry-After": str(int(exc.retry_after))},
                )
        # Every exit below — grant timeout, validation error, client
        # disconnect (CancelledError out of the wait or orchestration),
        # even a grant racing the timeout — must hand the ticket back:
        # still-queued tickets are withdrawn, granted ones release
        # their slot. Leaking either would permanently consume one of
        # the max_active grant slots.
        try:
            if ticket is not None:
                try:
                    with get_tracer().span(
                        "sched.wait",
                        trace_id=payload.trace_id,
                        lane=ticket.lane,
                        tenant=ticket.tenant,
                        ticket_id=ticket.ticket_id,
                    ):
                        await asyncio.wait_for(
                            ticket.granted(), SCHED_GRANT_TIMEOUT_SECONDS
                        )
                except asyncio.TimeoutError:
                    return web.json_response(
                        {
                            "error": "grant wait expired; scheduler saturated",
                            "lane": ticket.lane,
                        },
                        status=429,
                        headers={
                            "Retry-After": str(
                                int(
                                    scheduler.queue.estimate_retry_after(
                                        ticket.lane
                                    )
                                )
                            )
                        },
                    )
                if ticket.state == "cancelled":
                    # withdrawn while queued (DELETE ticket route): the
                    # parked request unwinds here instead of waiting
                    # out the grant timeout
                    return web.json_response(
                        {
                            "error": "ticket cancelled before grant",
                            "ticket_id": ticket.ticket_id,
                        },
                        status=409,
                    )

            if payload.deadline_s is not None:
                # the deadline is END-TO-END: time spent queued counts.
                # What rides into the job record is the REMAINDER; a
                # request that burned its whole budget waiting answers
                # 429 without starting doomed work.
                remaining = payload.deadline_s - (
                    time_mod.monotonic() - arrived_at
                )
                if remaining <= 0:
                    return web.json_response(
                        {
                            "error": "deadline expired while queued",
                            "reason": "deadline_expired",
                            "deadline_s": payload.deadline_s,
                        },
                        status=429,
                        headers={"Retry-After": "1"},
                    )
                payload.deadline_s = remaining

            try:
                result = await orchestrate_distributed_execution(
                    self.server, payload
                )
            except PromptValidationError as exc:
                return web.json_response(
                    {"error": str(exc), "node_errors": exc.node_errors},
                    status=400,
                )
            if ticket is not None:
                result["scheduler"] = {
                    "ticket_id": ticket.ticket_id,
                    "tenant": ticket.tenant,
                    "lane": ticket.lane,
                    "queue_wait_seconds": ticket.queue_wait_seconds,
                }
            return web.json_response(result)
        finally:
            if ticket is not None:
                if ticket.state == "queued":
                    scheduler.queue.cancel(ticket)
                else:
                    scheduler.queue.release(ticket)  # no-op unless granted

    async def cancel_job(self, request: web.Request) -> web.Response:
        """POST /distributed/cancel/{job_id} — cooperative cancellation
        of a RUNNING job: journals the terminal cancel record, refunds
        every pending + in-flight tile, notifies workers over the
        events stream (they flush what's encoded and abort between
        batches), and settles the master loop with a terminal
        `cancelled` status. Idempotent; 404 for unknown jobs.

        Pre-admission requests are cancelled through
        DELETE /distributed/queue/{ticket_id} instead."""
        import time as time_mod

        job_id = request.match_info["job_id"]
        reason = "client"
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 - body optional
            body = None
        if isinstance(body, dict) and body.get("reason"):
            reason = str(body["reason"])
        started = time_mod.monotonic()
        with rpc_span(request, "rpc.cancel_job", job_id=str(job_id)):
            accounting = await self.server.job_store.cancel_job(
                str(job_id), reason=reason
            )
        if accounting is None:
            return web.json_response({"error": "no such job"}, status=404)
        accounting["status"] = "cancelled"
        # cancel-request → all tiles refunded: the reclaim-speed number
        # the bench stamps as cancel_latency_ms
        accounting["cancel_latency_ms"] = round(
            (time_mod.monotonic() - started) * 1000.0, 3
        )
        return web.json_response(accounting)

    async def job_complete(self, request: web.Request) -> web.Response:
        """Canonical envelope {job_id, worker_id, batch_idx, image
        (base64 PNG data URL), is_last, audio?} — one request per image
        (reference api/job_routes.py:273-343)."""
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid json"}, status=400)

        errors = _validate_envelope(body)
        if errors:
            return web.json_response({"error": "; ".join(errors)}, status=400)

        try:
            tensor = img_utils.decode_image_data_url(body["image"])
        except Exception as exc:  # noqa: BLE001 - boundary validation
            return web.json_response(
                {"error": f"undecodable image: {exc}"}, status=400
            )
        audio = None
        if body.get("audio") is not None:
            try:
                audio = audio_utils.decode_audio_payload(body["audio"])
            except Exception as exc:  # noqa: BLE001
                return web.json_response(
                    {"error": f"undecodable audio: {exc}"}, status=400
                )

        with rpc_span(
            request, "rpc.job_complete",
            worker_id=str(body["worker_id"]), job_id=str(body["job_id"]),
            batch_idx=int(body["batch_idx"]),
        ):
            job = await self.server.job_store.wait_for_collector(
                body["job_id"], JOB_INIT_GRACE_SECONDS
            )
            if job is None:
                return web.json_response({"error": "no such job"}, status=404)
            await self.server.job_store.put_collector_result(
                body["job_id"],
                {
                    "tensor": tensor,
                    "worker_id": str(body["worker_id"]),
                    "batch_idx": int(body["batch_idx"]),
                    "is_last": bool(body.get("is_last", False)),
                    "empty": bool(body.get("empty", False)),
                    "audio": audio,
                },
            )
            collector_results_total().inc(worker_id=str(body["worker_id"]))
        return web.json_response({"status": "ok"})

    async def prepare_job(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid json"}, status=400)
        job_id = body.get("job_id")
        if not job_id:
            return web.json_response({"error": "missing job_id"}, status=400)
        await self.server.job_store.ensure_collector(str(job_id))
        return web.json_response({"status": "ok"})

    async def clear_memory(self, request: web.Request) -> web.Response:
        """Drop pipeline caches and device buffers (the TPU analog of
        the reference's unload-models + cuda empty_cache)."""
        self.server.execution_context.pipelines.clear()
        import gc

        gc.collect()
        try:
            import jax

            jax.clear_caches()
        except Exception as exc:  # noqa: BLE001 - best effort
            debug_log(f"clear_caches failed: {exc}")
        log("cleared pipeline caches and compilation caches")
        return web.json_response({"status": "ok"})

    async def check_file(self, request: web.Request) -> web.Response:
        """{'filename': ..., 'md5'?: ...} → exists/hash-match (media sync)."""
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": "invalid json"}, status=400)
        name = body.get("filename")
        if not name:
            return web.json_response({"error": "missing filename"}, status=400)
        from ..graph.io_dirs import get_input_dir, resolve_input_path

        try:
            path = resolve_input_path(str(name), None)
        except Exception:
            return web.json_response({"exists": False})
        if not os.path.isfile(path):
            return web.json_response({"exists": False})
        response: dict[str, Any] = {"exists": True}
        expected = body.get("md5")
        if expected:
            # digesting a multi-MB media file blocks; hash off-loop (CDT001)
            def _digest_file() -> str:
                digest = hashlib.md5()
                with open(path, "rb") as fh:
                    for chunk in iter(lambda: fh.read(1 << 20), b""):
                        digest.update(chunk)
                return digest.hexdigest()

            hexdigest = await run_blocking(_digest_file)
            response["md5"] = hexdigest
            response["matches"] = hexdigest == expected
        return web.json_response(response)

    async def load_image(self, request: web.Request) -> web.Response:
        name = request.query.get("filename", "")
        from ..graph.io_dirs import resolve_input_path

        try:
            path = resolve_input_path(name, None)
        except Exception:
            return web.json_response({"error": "bad path"}, status=400)
        if not os.path.isfile(path):
            return web.json_response({"error": "not found"}, status=404)
        return web.FileResponse(path)

    async def upload_image(self, request: web.Request) -> web.Response:
        """Multipart upload into the input dir (media sync target —
        ComfyUI /upload/image parity)."""
        from ..graph.io_dirs import get_input_dir

        reader = await request.multipart()
        saved = []
        while True:
            part = await reader.next()
            if part is None:
                break
            if part.name in ("image", "file"):
                filename = os.path.basename(part.filename or "upload.bin")
                target_dir = get_input_dir(None)
                os.makedirs(target_dir, exist_ok=True)
                target = os.path.join(target_dir, filename)
                # stream chunk-by-chunk with the open/write/close on the
                # executor: bounded memory for arbitrarily large media
                # files AND no sync file I/O on the loop (CDT001)
                fh = await run_blocking(open, target, "wb")
                try:
                    while True:
                        chunk = await part.read_chunk()
                        if not chunk:
                            break
                        await run_blocking(fh.write, chunk)
                finally:
                    await run_blocking(fh.close)
                saved.append(filename)
        return web.json_response({"name": saved[0] if saved else None, "saved": saved})


def _validate_envelope(body: Any) -> list[str]:
    errors = []
    if not isinstance(body, dict):
        return ["body must be an object"]
    for field in ("job_id", "worker_id", "batch_idx", "image"):
        if field not in body:
            errors.append(f"missing {field!r}")
    if "batch_idx" in body:
        try:
            int(body["batch_idx"])
        except (TypeError, ValueError):
            errors.append("batch_idx must be an int")
    if "image" in body and not isinstance(body["image"], str):
        errors.append("image must be a base64 data-URL string")
    return errors
