"""Tunnel routes: start/stop/status of the Cloudflare quick tunnel
(parity with reference api/tunnel_routes.py)."""

from __future__ import annotations

from aiohttp import web

from ..utils.exceptions import TunnelError
from ..utils.tunnel import TunnelManager


def register(app: web.Application, server) -> None:
    server.tunnel_manager = TunnelManager(server.config_path)
    routes = TunnelRoutes(server)
    app.router.add_post("/distributed/tunnel/start", routes.start)
    app.router.add_post("/distributed/tunnel/stop", routes.stop)
    app.router.add_get("/distributed/tunnel/status", routes.status)


class TunnelRoutes:
    def __init__(self, server):
        self.server = server

    async def start(self, request: web.Request) -> web.Response:
        try:
            url = await self.server.tunnel_manager.start(self.server.port)
        except TunnelError as exc:
            return web.json_response({"error": str(exc)}, status=503)
        return web.json_response({"status": "ok", "url": url})

    async def stop(self, request: web.Request) -> web.Response:
        stopped = await self.server.tunnel_manager.stop()
        return web.json_response({"status": "ok", "stopped": stopped})

    async def status(self, request: web.Request) -> web.Response:
        return web.json_response(self.server.tunnel_manager.status())
