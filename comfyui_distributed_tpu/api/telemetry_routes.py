"""Telemetry routes: Prometheus scrape, span trees, live event stream.

    GET /distributed/metrics            — Prometheus text exposition
    GET /distributed/trace/{trace_id}   — span tree JSON for one execution
    GET /distributed/traces             — paginated trace-id listing
    GET /distributed/events             — WebSocket live event stream
    GET /distributed/durability         — WAL/snapshot/recovery status
    GET /distributed/fleet              — fleet rollups + per-worker
                                          drill-down (+ ?since= history)
    GET /distributed/alerts             — SLO burn-rate alert states

The metrics body is the process-global registry (counters/histograms
pushed by the instrumented layers, live-state gauges filled at scrape
time by the server's collectors — telemetry/instruments.py, and JAX
runtime gauges from telemetry/runtime.py).

The event stream pushes `metric_delta`, `span_open`/`span_close`,
`health_transition`, and watchdog verdict events as JSON text frames
(one event per frame; schema in docs/observability.md). Clients filter
server-side with `?types=a,b,c` so an unfiltered metric firehose is
opt-in, not default-on.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import math
from typing import Any

from aiohttp import web

from ..telemetry import (
    TRACE_HEADER,
    get_event_bus,
    get_metrics_registry,
    get_tracer,
)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

DEFAULT_TRACE_PAGE = 50


@contextlib.contextmanager
def rpc_span(request: web.Request, name: str, **attrs: Any):
    """Attach a server-side span for one inbound RPC to the trace the
    X-CDT-Trace-Id header names; no-op (yields None) when the caller
    didn't propagate a trace. Shared by every route module that
    receives trace-propagated worker RPCs (usdu_routes, job_routes)."""
    trace_id = request.headers.get(TRACE_HEADER)
    if not trace_id:
        yield None
        return
    with get_tracer().span(name, trace_id=trace_id, **attrs) as span:
        yield span


def register(app: web.Application, server) -> None:
    routes = TelemetryRoutes(server)
    app.router.add_get("/distributed/metrics", routes.metrics)
    app.router.add_get("/distributed/trace/{trace_id}", routes.trace)
    app.router.add_get("/distributed/traces", routes.traces)
    app.router.add_get("/distributed/events", routes.events)
    app.router.add_get("/distributed/durability", routes.durability)
    app.router.add_get("/distributed/fleet", routes.fleet)
    app.router.add_get("/distributed/alerts", routes.alerts)
    app.router.add_get("/distributed/usage", routes.usage)
    app.router.add_get("/distributed/cache", routes.cache)
    app.router.add_post("/distributed/cache/clear", routes.cache_clear)


class TelemetryRoutes:
    def __init__(self, server):
        self.server = server

    async def metrics(self, request: web.Request) -> web.Response:
        body = get_metrics_registry().render()
        return web.Response(
            body=body.encode("utf-8"),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    async def durability(self, request: web.Request) -> web.Response:
        """Durable-control-plane status: journal head/segments, last
        snapshot lsn + age, post-recovery admission hold, and the last
        recovery's report (docs/durability.md; runbook §4f/§4g read
        this first in a restart/failover triage). With the HA layer the
        payload adds `role` (active | standby | deposed), the fencing
        `epoch`, replication standby counts on the active, and the
        standby's own replication lag in records and seconds."""
        manager = getattr(self.server, "durability", None)
        if manager is None:
            return web.json_response(
                {"enabled": False, "hint": "set CDT_JOURNAL_DIR to enable"}
            )
        status = manager.status()
        standby = getattr(self.server, "standby", None)
        if standby is not None and not standby.promoted:
            # this process is a warm standby: the authoritative journal
            # lives on the active master; report the replica's view
            status["role"] = "standby"
            status["standby"] = standby.status()
            replica = standby.replica.status()
            status["epoch"] = replica["source_epoch"]
            status["replication"] = {
                **status.get("replication", {}),
                "lag_records": replica["lag_records"],
                "lag_seconds": replica["lag_seconds"],
                "applied_lsn": replica["applied_lsn"],
                "synced": replica["synced"],
            }
        elif getattr(self.server, "deposed", False):
            status["role"] = "deposed"
        return web.json_response(status)

    async def fleet(self, request: web.Request) -> web.Response:
        """Fleet observability rollups + per-worker drill-down
        (docs/observability.md §Fleet). Query params:

        - ``since=SECONDS`` — adds windowed history for the retained
          series (raw 10 s tier while it covers the window, 5 min
          rollups beyond);
        - ``worker=ID`` — scopes drill-down + history to one worker.
        """
        registry = getattr(self.server, "fleet", None)
        if registry is None:
            return web.json_response(
                {"enabled": False,
                 "hint": "fleet plane runs on masters with CDT_FLEET=1"}
            )
        since_param = request.query.get("since")
        since_s: float | None = None
        if since_param is not None:
            try:
                since_s = float(since_param)
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": "since must be a number of seconds"},
                    status=400,
                )
            # NaN passes every comparison below and Infinity survives
            # them — both would serialize as non-standard JSON tokens
            # that break strict clients' JSON.parse
            if not math.isfinite(since_s) or since_s < 0:
                return web.json_response(
                    {"error": "since must be a finite number >= 0"},
                    status=400,
                )
        payload = registry.status(
            since_s=since_s, worker=request.query.get("worker")
        )
        payload["enabled"] = True
        return web.json_response(payload)

    async def usage(self, request: web.Request) -> web.Response:
        """Tenant usage metering & chip-time attribution
        (docs/observability.md §Usage metering): fleet rollup
        (per-tenant/per-lane/per-job chip-seconds, tiles, steps), the
        full waste breakdown (padding | preempt_recompute | speculation
        | poison_retry), the conservation identity, and the measured
        cost model. Query params:

        - ``since=SECONDS`` — adds windowed history for the retained
          per-tenant/waste series (two-tier retention, like the fleet
          route);
        - ``tenant=NAME`` — scopes drill-down + history to one tenant.
        """
        fleet = getattr(self.server, "fleet", None)
        aggregator = getattr(fleet, "usage", None) if fleet else None
        if aggregator is None:
            return web.json_response(
                {"enabled": False,
                 "hint": "usage metering runs on masters with CDT_FLEET=1 "
                         "and CDT_USAGE=1"}
            )
        since_param = request.query.get("since")
        since_s: float | None = None
        if since_param is not None:
            try:
                since_s = float(since_param)
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": "since must be a number of seconds"},
                    status=400,
                )
            if not math.isfinite(since_s) or since_s < 0:
                return web.json_response(
                    {"error": "since must be a finite number >= 0"},
                    status=400,
                )
        payload = aggregator.status(
            since_s=since_s, tenant=request.query.get("tenant")
        )
        return web.json_response(payload)

    async def cache(self, request: web.Request) -> web.Response:
        """Content-addressed tile cache stats (docs/caching.md): tier
        sizes, hit/miss/corrupt counters, and the derived hit rate the
        panel's Cache card renders."""
        from ..cache.store import get_tile_cache

        tile_cache = get_tile_cache()
        if tile_cache is None:
            return web.json_response(
                {"enabled": False,
                 "hint": "tile result cache runs on masters with "
                         "CDT_CACHE=1 (CDT_CACHE_DIR adds the disk tier)"}
            )
        payload = tile_cache.stats()
        payload["enabled"] = True
        return web.json_response(payload)

    async def cache_clear(self, request: web.Request) -> web.Response:
        """Drop both cache tiers (runbook §cache triage: the recovery
        lever for a suspected-stale cache — e.g. after an undeclared
        model weight edit in place). Returns what was dropped."""
        from ..cache.store import get_tile_cache

        tile_cache = get_tile_cache()
        if tile_cache is None:
            return web.json_response(
                {"enabled": False,
                 "hint": "tile result cache runs on masters with "
                         "CDT_CACHE=1"}
            )
        payload = tile_cache.clear()
        payload["enabled"] = True
        return web.json_response(payload)

    async def alerts(self, request: web.Request) -> web.Response:
        """SLO burn-rate alert engine state: every spec's current burn
        evaluation, the open alerts, and the bounded transition history
        (runbook §4i reads this first when `alert_fired` lands)."""
        engine = getattr(self.server, "slo", None)
        if engine is None:
            return web.json_response(
                {"enabled": False,
                 "hint": "SLO engine runs on masters with CDT_FLEET=1"}
            )
        payload = engine.status()
        payload["enabled"] = True
        return web.json_response(payload)

    async def trace(self, request: web.Request) -> web.Response:
        trace_id = request.match_info["trace_id"]
        tracer = get_tracer()
        spans = tracer.spans(trace_id)
        if not spans:
            return web.json_response({"error": "no such trace"}, status=404)
        return web.json_response(
            {
                "trace_id": trace_id,
                "span_count": len(spans),
                "tree": tracer.tree(trace_id, spans),
            }
        )

    async def traces(self, request: web.Request) -> web.Response:
        """Paginated listing, most-recently-active first. The page size
        is clamped to the tracer's retention bound — the listing can
        never hand out more ids than retention keeps alive."""
        tracer = get_tracer()
        try:
            limit = int(request.query.get("limit", DEFAULT_TRACE_PAGE))
            offset = int(request.query.get("offset", 0))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "limit/offset must be integers"}, status=400
            )
        if limit <= 0 or offset < 0:
            return web.json_response(
                {"error": "limit must be > 0 and offset >= 0"}, status=400
            )
        limit = min(limit, tracer.max_traces)
        ids = tracer.trace_ids()
        ids.reverse()  # storage order is LRU: last = most recently active
        return web.json_response(
            {
                "traces": ids[offset : offset + limit],
                "total": len(ids),
                "limit": limit,
                "offset": offset,
            }
        )

    async def events(self, request: web.Request) -> web.StreamResponse:
        """Live event stream over WebSocket. `?types=a,b,c` filters
        bus-side; every connection starts with a `hello` frame carrying
        a state snapshot (health + store depths) so consumers don't
        need a separate poll to initialize."""
        types_param = request.query.get("types")
        types = (
            {t.strip() for t in types_param.split(",") if t.strip()}
            if types_param
            else None
        )
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        bus = get_event_bus()
        # named per remote so /distributed/system_info's event_bus
        # stats attribute depth/drops to a specific consumer
        sub = bus.subscribe(
            types=types, name=f"ws:{request.remote or 'events'}"
        )
        from ..resilience.health import get_health_registry

        hello = {
            "type": "hello",
            "seq": None,
            "ts": None,
            "data": {
                "server": (
                    f"{'worker' if self.server.is_worker else 'master'}:"
                    f"{self.server.port}"
                ),
                "subscribed": sorted(types) if types else "all",
                "health": get_health_registry().snapshot(),
                "store": self.server.job_store.stats_unlocked(),
            },
        }
        receiver = asyncio.ensure_future(ws.receive())
        getter: asyncio.Future | None = None
        reported_drops = 0
        try:
            await ws.send_str(json.dumps(hello, default=str))
            while True:
                getter = asyncio.ensure_future(sub.get())
                done, _pending = await asyncio.wait(
                    {getter, receiver}, return_when=asyncio.FIRST_COMPLETED
                )
                if receiver in done:
                    break  # client closed (or sent anything; stream is one-way)
                event = getter.result()
                getter = None
                if sub.dropped > reported_drops:
                    # connection-local notice: schema-uniform frame
                    # shape, but no bus seq/ts (it never rode the bus)
                    await ws.send_str(
                        json.dumps(
                            {
                                "type": "events_dropped",
                                "seq": None,
                                "ts": None,
                                "data": {"count": sub.dropped - reported_drops},
                            }
                        )
                    )
                    reported_drops = sub.dropped
                await ws.send_str(json.dumps(event, default=str))
        except (ConnectionResetError, asyncio.CancelledError):
            pass  # client went away mid-send / server shutting down
        finally:
            bus.unsubscribe(sub)
            if getter is not None:
                getter.cancel()
            receiver.cancel()
            with contextlib.suppress(Exception):
                await ws.close()
        return ws
