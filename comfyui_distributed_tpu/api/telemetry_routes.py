"""Telemetry routes: Prometheus scrape + per-execution span trees.

    GET /distributed/metrics            — Prometheus text exposition
    GET /distributed/trace/{trace_id}   — span tree JSON for one execution
    GET /distributed/traces             — trace ids currently retained

The metrics body is the process-global registry (counters/histograms
pushed by the instrumented layers, live-state gauges filled at scrape
time by the server's collectors — telemetry/instruments.py).
"""

from __future__ import annotations

import contextlib
from typing import Any

from aiohttp import web

from ..telemetry import TRACE_HEADER, get_metrics_registry, get_tracer

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


@contextlib.contextmanager
def rpc_span(request: web.Request, name: str, **attrs: Any):
    """Attach a server-side span for one inbound RPC to the trace the
    X-CDT-Trace-Id header names; no-op (yields None) when the caller
    didn't propagate a trace. Shared by every route module that
    receives trace-propagated worker RPCs (usdu_routes, job_routes)."""
    trace_id = request.headers.get(TRACE_HEADER)
    if not trace_id:
        yield None
        return
    with get_tracer().span(name, trace_id=trace_id, **attrs) as span:
        yield span


def register(app: web.Application, server) -> None:
    routes = TelemetryRoutes(server)
    app.router.add_get("/distributed/metrics", routes.metrics)
    app.router.add_get("/distributed/trace/{trace_id}", routes.trace)
    app.router.add_get("/distributed/traces", routes.traces)


class TelemetryRoutes:
    def __init__(self, server):
        self.server = server

    async def metrics(self, request: web.Request) -> web.Response:
        body = get_metrics_registry().render()
        return web.Response(
            body=body.encode("utf-8"),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    async def trace(self, request: web.Request) -> web.Response:
        trace_id = request.match_info["trace_id"]
        tracer = get_tracer()
        spans = tracer.spans(trace_id)
        if not spans:
            return web.json_response({"error": "no such trace"}, status=404)
        return web.json_response(
            {
                "trace_id": trace_id,
                "span_count": len(spans),
                "tree": tracer.tree(trace_id, spans),
            }
        )

    async def traces(self, request: web.Request) -> web.Response:
        tracer = get_tracer()
        return web.json_response({"traces": tracer.trace_ids()})
