"""Serve the control-panel web UI (reference web/ sidebar equivalent,
standalone: the master serves it at / since there is no ComfyUI
frontend to embed into)."""

from __future__ import annotations

import json
import os

from aiohttp import web

from ..utils.async_helpers import run_blocking

WEB_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "web")


def _workflow_dirs() -> list[str]:
    package_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return [
        os.environ.get("CDT_WORKFLOW_DIR", ""),
        os.path.join(package_root, "workflows"),
        os.path.join(os.getcwd(), "workflows"),
    ]


def register(app: web.Application, server) -> None:
    async def index(request: web.Request) -> web.Response:
        return web.FileResponse(os.path.join(WEB_DIR, "index.html"))

    async def list_workflows(request: web.Request) -> web.Response:
        names: list[str] = []
        for directory in _workflow_dirs():
            if directory and os.path.isdir(directory):
                names.extend(
                    f for f in sorted(os.listdir(directory)) if f.endswith(".json")
                )
        return web.json_response({"workflows": sorted(set(names))})

    async def get_workflow(request: web.Request) -> web.Response:
        name = os.path.basename(request.match_info["name"])
        for directory in _workflow_dirs():
            path = os.path.join(directory, name) if directory else ""
            if path and os.path.isfile(path):
                # workflow JSON can sit on slow/network storage:
                # read+parse off the serving loop (CDT001)
                def _load(p: str = path):
                    with open(p, "r", encoding="utf-8") as fh:
                        return json.load(fh)

                return web.json_response(await run_blocking(_load))
        return web.json_response({"error": "not found"}, status=404)

    app.router.add_get("/", index)
    app.router.add_static("/web/", WEB_DIR, show_index=False)
    app.router.add_get("/distributed/workflows", list_workflows)
    app.router.add_get("/distributed/workflows/{name}", get_workflow)
